"""Performance timeline export: the engine's rings as one Perfetto trace.

PRs 1/4/16/17 built the raw material — the flight recorder's per-request
timelines, the step ledger whose segments tile each iteration's
wall-clock, the utilization ledger's dispatch→sync accounting — but every
one of those surfaces is a JSON ring an operator reads by hand. This
module renders them all into ONE Chrome trace-event JSON payload
(the format Perfetto / chrome://tracing load natively), so a step, a
request, and the device pipeline are visible on a single zoomable
timeline:

  * one track per real thread — the engine loop (``llm-engine``), the
    out-of-band callback finisher (``llm-finisher``), the HTTP acceptor —
    with the loop track annotated from graftlint's ``LOOP_ONLY_REGISTRY``
    (tpu/ownership.py), so the track metadata names exactly which
    functions are contractually pinned to it;
  * every step-ledger record as a ``B``/``E`` slice on the loop track,
    its segments tiled inside as nested child slices IN THE LEDGER'S
    CANONICAL ORDER whose durations reproduce the sum identity (segments
    == step wall, ``other`` residual included) — the ledger keeps
    per-segment totals, not per-segment stamps, so the tiling is the
    honest sequential rendering of that identity;
  * an async "device" track where each dispatch→sync busy interval from
    the utilization ledger becomes one slice (the busy-union watermark
    means slices never overlap);
  * executor cache-miss compiles as complete (``X``) events on their own
    track, captured live by chaining the executor's ``on_compile``
    callback;
  * per-request FLOW events (``s``/``t``/``f``) linking
    enqueued → admitted → first-token → finished across the HTTP, loop,
    and finisher tracks, flow-id'd by the W3C trace id when the request
    carried one (so the fleet stitcher, gofr_tpu/fleet/timeline.py, can
    join flows across replicas), plus one async "request" slice per
    request for at-a-glance lifetime;
  * flight-recorder engine events (cache growth, sheds, resets,
    incidents) as instant events on the loop track.

A DISAGG_MODE=both replica exports BOTH halves: the serving (decode)
engine's tracks plus the co-resident prefill engine's, on a second tid
block — so one payload shows prompt prefill, the KV hand-off, and the
decode continuation, and the two halves' flow events share the request's
trace id (flows are normalized per id: first event becomes ``s``, the
terminal ``finished`` becomes ``f``, everything between ``t``).

Clock discipline: every ``ts`` is the engine's monotonic clock in
microseconds. The payload carries ONE wall/mono anchor pair (the flight
recorder idiom) so cross-process consumers — the fleet stitcher aligning
several replicas into one multi-pid trace — shift monotonic
microseconds into a shared wall epoch with a single linear map.

Operator surface (install_routes / App.enable_timeline):

    GET /debug/timeline[?steps=N]  -> the trace-event payload; save the
         body to a .json file and open it in https://ui.perfetto.dev
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .obs import MetricsHook
from .ownership import LOOP_ONLY_REGISTRY
from .stepledger import SEGMENTS

# stable track ids (tids) inside the exported pid; a co-resident prefill
# engine (DISAGG_MODE=both) gets the same layout at base + PREFILL_BASE
LOOP_TID = 1
FINISHER_TID = 2
HTTP_TID = 3
DEVICE_TID = 4
COMPILE_TID = 5
REQUEST_TID = 6
PREFILL_BASE = 10

DEFAULT_STEPS = 128
MAX_COMPILE_EVENTS = 256


def _us(t_mono: float) -> float:
    """Monotonic seconds -> trace-event microseconds."""
    return round(t_mono * 1e6, 1)


class TimelineExporter:
    """Renders one engine's observability rings as trace-event JSON.

    Construction is cheap and side-effect free except for one thing: each
    rendered engine's executor ``on_compile`` callback is chained so
    compile completions are captured with timestamps (the compile table
    keeps durations but not stamps). The chained hook preserves the
    engine's own re-attribution callback."""

    def __init__(self, engine, process_name: str = "llm-server",
                 pid: int = 1, max_steps: int = DEFAULT_STEPS,
                 metrics=None):
        self.engine = engine
        self.process_name = str(process_name)
        self.pid = int(pid)
        self.max_steps = max(1, int(max_steps))
        self._obs = MetricsHook(metrics)
        self.exports_total = 0
        # per-tid-base (t_mono_end, name, seconds) compile completions
        self._compiles: Dict[int, "collections.deque"] = {}
        self._compile_lock = threading.Lock()
        for eng, base, _label in self._engines():
            self._compiles[base] = collections.deque(
                maxlen=MAX_COMPILE_EVENTS)
            self._chain_compile_hook(eng, base)

    def use_metrics(self, metrics) -> None:
        if metrics is not None:
            self._obs = MetricsHook(metrics)

    def _engines(self) -> List[Tuple[Any, int, str]]:
        """(engine, tid_base, track label prefix) for every engine this
        process runs: the serving engine, plus the co-resident prefill
        engine of a DISAGG_MODE=both replica."""
        out: List[Tuple[Any, int, str]] = [(self.engine, 0, "")]
        disagg = getattr(self.engine, "disagg_router", None)
        prefill = (getattr(disagg, "prefill_engine", None)
                   if disagg is not None else None)
        if prefill is not None and prefill is not self.engine:
            out.append((prefill, PREFILL_BASE, "prefill:"))
        return out

    # -- compile capture ------------------------------------------------------
    def _chain_compile_hook(self, engine, base: int) -> None:
        executor = getattr(engine, "executor", None)
        if executor is None:
            return
        prev = getattr(executor, "on_compile", None)

        def _on_compile(name: str, seconds: float, _prev=prev) -> None:
            self.note_compile(name, seconds, base=base)
            if _prev is not None:
                _prev(name, seconds)

        executor.on_compile = _on_compile

    def note_compile(self, name: str, seconds: float,
                     base: int = 0) -> None:
        """Record a finished compile (called from whichever thread
        compiled — the deque append is locked and O(1))."""
        try:
            with self._compile_lock:
                self._compiles[base].append(
                    (time.monotonic(), str(name), float(seconds)))
        except Exception:  # noqa: BLE001 - capture is best-effort
            pass

    # -- track metadata -------------------------------------------------------
    def _thread_names(self, engine, base: int,
                      label: str) -> Dict[int, str]:
        loop_thread = getattr(engine, "_thread", None)
        finisher = getattr(engine, "_finisher", None)
        finisher_thread = getattr(finisher, "_thread", None)
        names = {
            base + LOOP_TID: label + (getattr(loop_thread, "name", None)
                                      or "llm-engine"),
            base + FINISHER_TID: label + (
                getattr(finisher_thread, "name", None) or "llm-finisher"),
            base + HTTP_TID: label + "http-server",
            base + DEVICE_TID: label + "device",
            base + COMPILE_TID: label + "xla-compile",
            base + REQUEST_TID: label + "requests",
        }
        if base == 0:
            for t in threading.enumerate():
                if t.name.startswith("http-server"):
                    names[HTTP_TID] = t.name
                    break
        return names

    def _metadata(self) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = [{
            "ph": "M", "name": "process_name", "pid": self.pid, "tid": 0,
            "ts": 0, "args": {"name": self.process_name}}]
        for engine, base, label in self._engines():
            for tid, name in sorted(
                    self._thread_names(engine, base, label).items()):
                args: Dict[str, Any] = {"name": name}
                if tid == base + LOOP_TID:
                    # the ownership contract, attached to the track it
                    # guards: the functions graftlint pins to this thread
                    args["loop_only"] = sorted(LOOP_ONLY_REGISTRY)
                events.append({"ph": "M", "name": "thread_name",
                               "pid": self.pid, "tid": tid, "ts": 0,
                               "args": args})
        return events

    # -- sections -------------------------------------------------------------
    def _step_events(self, engine, base: int,
                     steps: int) -> List[Dict[str, Any]]:
        ledger = getattr(engine, "steps", None)
        if ledger is None or not hasattr(ledger, "records"):
            return []
        tid = base + LOOP_TID
        events: List[Dict[str, Any]] = []
        for rec in ledger.records(recent=steps):
            t0 = rec.started_at
            if rec.idle_gap_s > 0.0:
                events.append({
                    "ph": "X", "name": "idle", "cat": "idle",
                    "pid": self.pid, "tid": tid,
                    "ts": _us(t0 - rec.idle_gap_s),
                    "dur": round(rec.idle_gap_s * 1e6, 1),
                    "args": {"idle_gap_s": round(rec.idle_gap_s, 6)}})
            args: Dict[str, Any] = {
                "step": rec.seq, "wall_s": round(rec.wall_s, 6),
                "tokens": rec.tokens, "active_slots": rec.active_slots,
                "queue_depth": rec.queue_depth}
            if rec.straggler:
                args["straggler"] = True
                args["cause"] = rec.cause
            if rec.slowest_request_id is not None:
                args["slowest_request_id"] = rec.slowest_request_id
            events.append({"ph": "B", "name": f"step:{rec.phase}",
                           "cat": "step", "pid": self.pid, "tid": tid,
                           "ts": _us(t0), "args": args})
            # segments tiled sequentially in canonical order: durations
            # reproduce the ledger's sum identity (they fill the parent
            # slice exactly, `other` residual included)
            cursor = t0
            ordered = [s for s in SEGMENTS if s in rec.segments]
            ordered += sorted(s for s in rec.segments if s not in SEGMENTS)
            for seg in ordered:
                dur = rec.segments[seg]
                if dur <= 0.0:
                    continue
                events.append({"ph": "B", "name": seg, "cat": "segment",
                               "pid": self.pid, "tid": tid,
                               "ts": _us(cursor),
                               "args": {"seconds": round(dur, 6)}})
                cursor += dur
                events.append({"ph": "E", "pid": self.pid,
                               "tid": tid, "ts": _us(cursor)})
            events.append({"ph": "E", "pid": self.pid, "tid": tid,
                           "ts": _us(t0 + rec.wall_s)})
        return events

    def _device_events(self, engine, base: int,
                       label: str) -> List[Dict[str, Any]]:
        util = getattr(engine, "util", None)
        if util is None or not hasattr(util, "device_slices"):
            return []
        tid = base + DEVICE_TID
        events: List[Dict[str, Any]] = []
        for i, sl in enumerate(util.device_slices()):
            ident = f"{label}dev-{i}"
            args = {"tokens": sl["tokens"],
                    "busy_s": round(sl["busy_s"], 6),
                    "sync_wait_s": round(sl["sync_wait_s"], 6)}
            events.append({"ph": "b", "cat": "device", "id": ident,
                           "name": sl["phase"], "pid": self.pid,
                           "tid": tid, "ts": _us(sl["start"]),
                           "args": args})
            events.append({"ph": "e", "cat": "device", "id": ident,
                           "name": sl["phase"], "pid": self.pid,
                           "tid": tid, "ts": _us(sl["end"])})
        return events

    def _compile_events(self, base: int) -> List[Dict[str, Any]]:
        with self._compile_lock:
            compiles = list(self._compiles.get(base, ()))
        return [{
            "ph": "X", "name": f"compile:{name}", "cat": "compile",
            "pid": self.pid, "tid": base + COMPILE_TID,
            "ts": _us(end - seconds), "dur": round(seconds * 1e6, 1),
            "args": {"seconds": round(seconds, 6)}}
            for end, name, seconds in compiles]

    def _request_events(self, engine, base: int,
                        label: str) -> List[Dict[str, Any]]:
        recorder = getattr(engine, "recorder", None)
        if recorder is None or not hasattr(recorder, "timeline_records"):
            return []
        events: List[Dict[str, Any]] = []
        for rec in recorder.timeline_records():
            fid = rec["trace_id"] or f"req-{rec['id']}"
            args = {"request_id": rec["id"]}
            if rec["trace_id"]:
                args["trace_id"] = rec["trace_id"]
            if rec["handoff"]:
                args["handoff"] = True
            rid = f"{label}req-{rec['id']}"
            # async lifetime slice on the requests track
            events.append({"ph": "b", "cat": "request", "id": rid,
                           "name": "request", "pid": self.pid,
                           "tid": base + REQUEST_TID,
                           "ts": _us(rec["enqueued_at"]), "args": args})
            # flow origin: enqueued on the HTTP track (where submit ran);
            # _normalize_flows later rewrites s/t/f per flow id
            events.append({"ph": "s", "cat": "flow", "id": fid,
                           "name": "request", "pid": self.pid,
                           "tid": base + HTTP_TID,
                           "ts": _us(rec["enqueued_at"]),
                           "args": dict(args, milestone="enqueued")})
            for milestone, stamp in (("admitted", rec["admitted_at"]),
                                     ("first_token",
                                      rec["first_token_at"])):
                if stamp is None:
                    continue
                if milestone == "first_token" and rec["handoff"]:
                    # carried over from the prefill half; that engine's
                    # own flow step already marks it at the true site
                    continue
                events.append({"ph": "n", "cat": "request", "id": rid,
                               "name": milestone, "pid": self.pid,
                               "tid": base + REQUEST_TID,
                               "ts": _us(stamp)})
                events.append({"ph": "t", "cat": "flow", "id": fid,
                               "name": "request", "pid": self.pid,
                               "tid": base + LOOP_TID, "ts": _us(stamp),
                               "args": dict(args, milestone=milestone)})
            if rec["finished_at"] is not None:
                end_args = dict(args, milestone="finished",
                                outcome=rec["outcome"],
                                generated=rec["generated"])
                # terminal flow step on the finisher track: completion
                # callbacks are delivered out-of-band there
                events.append({"ph": "f", "bp": "e", "cat": "flow",
                               "id": fid, "name": "request",
                               "pid": self.pid,
                               "tid": base + FINISHER_TID,
                               "ts": _us(rec["finished_at"]),
                               "args": end_args})
                events.append({"ph": "e", "cat": "request", "id": rid,
                               "name": "request", "pid": self.pid,
                               "tid": base + REQUEST_TID,
                               "ts": _us(rec["finished_at"]),
                               "args": end_args})
        return events

    def _engine_events(self, engine, base: int, anchor_wall0: float,
                       anchor_mono0: float) -> List[Dict[str, Any]]:
        recorder = getattr(engine, "recorder", None)
        if recorder is None:
            return []
        try:
            snap_events = recorder.snapshot().get("engine_events", [])
        except Exception:  # noqa: BLE001 - export degrades, never fails
            return []
        events: List[Dict[str, Any]] = []
        for ev in snap_events:
            ev = dict(ev)
            t_wall = ev.pop("t", None)
            name = ev.pop("event", None)
            if t_wall is None or name is None:
                continue
            # engine events are stamped wall-side (operator-log
            # correlation); pull them into the mono domain via the anchor
            t_mono = t_wall - anchor_wall0 + anchor_mono0
            events.append({"ph": "i", "s": "t", "name": name,
                           "cat": "engine_event", "pid": self.pid,
                           "tid": base + LOOP_TID, "ts": _us(t_mono),
                           "args": ev})
        return events

    @staticmethod
    def _normalize_flows(events: List[Dict[str, Any]]) -> None:
        """Rewrite each flow id's events into a well-formed chain: the
        earliest becomes the single ``s``, a terminal ``finished``
        milestone at the end becomes the single ``f``, everything between
        is a ``t``. Needed because a hand-off pair (or router-level
        retries) contributes several raw ``s``/``f`` under one trace
        id."""
        flows: Dict[Any, List[int]] = {}
        for idx, ev in enumerate(events):
            if ev.get("cat") == "flow":
                flows.setdefault(ev.get("id"), []).append(idx)
        for idxs in flows.values():
            idxs.sort(key=lambda i: events[i]["ts"])
            last = len(idxs) - 1
            for j, i in enumerate(idxs):
                ev = events[i]
                ev.pop("bp", None)
                if j == 0:
                    ev["ph"] = "s"
                elif (j == last and ev.get("args", {}).get("milestone")
                        == "finished"):
                    ev["ph"] = "f"
                    ev["bp"] = "e"
                else:
                    ev["ph"] = "t"

    # -- the export -----------------------------------------------------------
    def export(self, steps: Optional[int] = None) -> Dict[str, Any]:
        """One trace-event JSON payload over the last `steps` ledger
        records (default `max_steps`) plus everything else currently in
        the rings. Read-only over every source; safe from any thread."""
        steps = self.max_steps if not steps else max(1, int(steps))
        # the ONE wall/mono anchor pair: fleet stitching aligns replicas
        # by mapping each payload's monotonic ts through its own anchor
        wall0 = time.time()  # lint: clock-ok the designated wall/mono anchor pair for cross-replica alignment
        mono0 = time.monotonic()
        events = self._metadata()
        for engine, base, label in self._engines():
            events += self._step_events(engine, base, steps)
            events += self._device_events(engine, base, label)
            events += self._compile_events(base)
            events += self._request_events(engine, base, label)
            events += self._engine_events(engine, base, wall0, mono0)
        self._normalize_flows(events)
        self.exports_total += 1
        self._obs.counter("app_tpu_timeline_exports_total")
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "clock_domain": "monotonic_us",
            "anchor": {"wall0": round(wall0, 6), "mono0": round(mono0, 6)},
            "pid": self.pid,
            "process": self.process_name,
            "steps_window": steps,
            "events_total": len(events),
        }


def register_timeline_metrics(metrics) -> None:
    """Idempotent registration (the register_step_metrics idiom)."""
    try:
        if metrics.get("app_tpu_timeline_exports_total") is None:
            metrics.new_counter(
                "app_tpu_timeline_exports_total",
                "trace-event timeline exports served by /debug/timeline")
    except Exception:  # noqa: BLE001 - already registered
        pass


def install_routes(app, exporter: TimelineExporter,
                   path: str = "/debug/timeline") -> None:
    """Register GET /debug/timeline on a gofr_tpu App (the step-ledger
    install_routes idiom)."""

    @app.get(path)
    def debug_timeline(ctx):  # noqa: ANN001
        try:
            steps = int(ctx.request.param("steps") or 0)
        except (TypeError, ValueError):
            steps = 0
        return exporter.export(steps=steps or None)
