"""TPU device client: the Container datasource wrapping the JAX/XLA runtime.

Parity: the reference's injected-datasource provider pattern
(pkg/gofr/datasource/mongo.go:41-74 — New(Config) + UseLogger/UseMetrics/
Connect, wired by externalDB.go:5-12) and its HealthCheck feeding
/.well-known/health (container/health.go:39-59). Where the reference's
datasource boundary is a TCP connection to a database, this one is the
process<->accelerator boundary: device enumeration, HBM usage, mesh
construction, and the TPU metric set (SURVEY.md §5: tokens/sec, TTFT/TPOT,
batch size, HBM bytes, queue depth, compile-cache hits).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..datasource import Health, STATUS_DEGRADED, STATUS_DOWN, STATUS_UP

def pin_platform_from_env(env_var: str = "JAX_PLATFORMS") -> None:
    """Make the JAX_PLATFORMS env var authoritative for model servers.

    Some environments (this one included) ship a sitecustomize that
    force-registers an accelerator PJRT plugin and overrides
    jax_platforms at interpreter start — so exporting JAX_PLATFORMS=cpu
    silently still boots against the accelerator, and when that tunnel is
    wedged the server hangs forever inside PJRT_Client_Create. Call this
    BEFORE first device use (backends initialize lazily, so a config
    re-pin after jax import wins — same mechanism as tests/conftest.py).
    No-op when the variable is unset."""
    import os

    value = os.environ.get(env_var)
    if not value:
        return
    import jax

    try:
        jax.config.update("jax_platforms", value)
    except Exception:  # noqa: BLE001 - plain jax builds have no override
        pass


TTFT_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.15, 0.25, 0.5, 1, 2.5, 5, 10)
TPOT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1)
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class TPUClient:
    """Holds the JAX device handles; everything model-facing goes through it."""

    def __init__(self, config=None, platform: Optional[str] = None):
        self.config = config
        self.platform_override = platform or (
            config.get_or_default("TPU_PLATFORM", "") if config is not None else "")
        self.logger = None
        self.metrics = None
        self._devices: List[Any] = []
        self._connected_at: Optional[float] = None
        self._jax = None
        # single-flight health probe state (see health_check); the lock
        # serializes probe start/result reads — without it two concurrent
        # health polls can both observe a dead probe thread, both reset
        # _probe_result, and one then unpacks None after join (spurious
        # DOWN flap, ADVICE r5)
        import threading

        self._probe_lock = threading.Lock()
        self._probe_thread = None
        self._probe_result = None
        # fault-injection plane (tpu/faults.py): None in production; armed
        # deployments can wedge/fail the health probe for chaos drills
        self.faults = None

    # -- provider pattern (mongo.go:142-155) ----------------------------------
    def use_logger(self, logger) -> None:
        self.logger = logger

    def use_metrics(self, metrics) -> None:
        self.metrics = metrics

    def connect(self) -> None:
        import jax

        self._jax = jax
        if self.platform_override:
            # pin the whole process to the requested platform BEFORE backends
            # initialize — environments that pre-register an accelerator
            # plugin (e.g. the axon TPU tunnel) force-set jax_platforms at
            # interpreter start, so TPU_PLATFORM=cpu must win here to keep
            # CI/dev runs off the single-tenant device
            try:
                jax.config.update("jax_platforms", self.platform_override)
            except Exception:  # noqa: BLE001
                pass
            self._devices = jax.devices(self.platform_override)
        else:
            self._devices = jax.devices()
        self._connected_at = time.monotonic()
        if self.metrics is not None:
            self.register_metrics()
        if self.logger is not None:
            kinds = {d.device_kind for d in self._devices}
            self.logger.infof("connected to %d %s device(s): %s",
                              len(self._devices), self.platform,
                              ", ".join(sorted(kinds)))

    @classmethod
    def from_config(cls, config, logger, metrics) -> "TPUClient":
        client = cls(config)
        client.use_logger(logger)
        client.use_metrics(metrics)
        client.connect()
        return client

    def register_metrics(self) -> None:
        m = self.metrics
        for name, desc in (
            ("app_tpu_compile_total", "XLA compilations performed"),
            ("app_tpu_compile_cache_hits", "executor compile-cache hits"),
            ("app_tpu_compile_disk_hits", "programs loaded from the disk cache"),
            ("app_tpu_execute_total", "device executions dispatched"),
            ("app_tpu_tokens_generated_total", "output tokens generated"),
            ("app_tpu_requests_total", "inference requests admitted"),
            ("app_tpu_spec_drafted_total", "speculative draft tokens proposed"),
            ("app_tpu_spec_accepted_total", "speculative draft tokens accepted"),
            ("app_tpu_page_waits_total", "admissions deferred on page-pool exhaustion"),
            # crash-only recovery (tpu/faults.py + engine replay)
            ("app_tpu_device_resets_total",
             "device-state resets after a failed donated-cache program"),
            ("app_tpu_request_replays_total",
             "interrupted requests requeued for replay after a device reset"),
            ("app_tpu_replayed_tokens_total",
             "already-delivered tokens re-prefilled by replay admissions"),
            ("app_tpu_requests_quarantined_total",
             "poison requests failed after repeatedly reset-looping the engine"),
            # step anatomy ledger (tpu/stepledger.py)
            ("app_tpu_step_stragglers_total",
             "engine steps flagged slower than the rolling per-phase "
             "baseline, by dominant-segment cause"),
            # incident autopsy plane (tpu/incidents.py)
            ("app_tpu_incidents_total",
             "incident evidence bundles captured, by trigger"),
            ("app_tpu_incidents_suppressed_total",
             "incident triggers suppressed by the capture rate limit "
             "(cooldown / max-per-hour), by trigger"),
            # best-effort hook self-observability (tpu/obs.py)
            ("app_obs_dropped_metrics_total",
             "metric recordings swallowed by best-effort hooks, by metric "
             "name (a non-zero series is a wiring bug)"),
        ):
            try:
                m.new_counter(name, desc)
            except Exception:  # noqa: BLE001 - re-registration on reconnect
                pass
        for name, desc in (
            ("app_tpu_queue_depth", "requests waiting for batch assembly"),
            ("app_tpu_active_slots", "occupied continuous-batching slots"),
            ("app_tpu_hbm_bytes_used", "HBM bytes in use per device"),
            ("app_tpu_hbm_bytes_limit", "HBM bytes available per device"),
            ("app_tpu_tokens_per_second", "rolling decode throughput"),
            ("app_tpu_pages_used", "KV pool pages currently owned by slots"),
            ("app_tpu_engine_stall_seconds",
             "seconds the engine loop has been stuck inside one device "
             "call (0 = healthy); scrape-time, set by a container scrape "
             "hook because a wedged loop cannot push its own metric"),
            ("app_tpu_slo_ttft_goodput",
             "fraction of recent requests meeting the TTFT target "
             "(flight recorder rolling window)"),
            ("app_tpu_slo_tpot_goodput",
             "fraction of recent requests meeting the TPOT target "
             "(flight recorder rolling window)"),
            # SLO burn-rate engine (tpu/incidents.py)
            ("app_tpu_slo_burn_rate",
             "SLO error-budget burn rate (error rate / budget) by slo "
             "and window (fast/slow)"),
            ("app_tpu_slo_alert_state",
             "SLO alert state by slo: 0 ok, 1 warn, 2 page "
             "(both-windows burn rule)"),
            # utilization ledger (tpu/utilization.py): roofline telemetry
            ("app_tpu_device_duty_cycle",
             "fraction of the rolling window the device spent executing "
             "dispatched programs"),
            ("app_tpu_host_overhead_seconds",
             "host/scheduler seconds (admission, prep, demux) in the "
             "rolling utilization window"),
            ("app_tpu_mfu",
             "model FLOPs utilization vs the platform peak, by phase"),
            ("app_tpu_mbu",
             "HBM bandwidth utilization vs the platform peak, by phase"),
            ("app_tpu_hbm_bytes",
             "HBM bytes per device (kind=in_use|limit)"),
            ("app_tpu_kv_pool_pages",
             "KV page-pool occupancy (kind=used|free)"),
            ("app_tpu_breaker_state",
             "reset-storm breaker state (0=closed, 1=half_open, 2=open)"),
        ):
            try:
                m.new_gauge(name, desc)
            except Exception:  # noqa: BLE001
                pass
        from .stepledger import STEP_SECONDS_BUCKETS

        for name, desc, buckets in (
            ("app_tpu_ttft_seconds", "time to first token", TTFT_BUCKETS),
            ("app_tpu_queue_wait_seconds", "submit-to-admission wait", TTFT_BUCKETS),
            ("app_tpu_tpot_seconds", "time per output token", TPOT_BUCKETS),
            ("app_tpu_batch_size", "assembled batch sizes", BATCH_BUCKETS),
            ("app_tpu_execute_seconds", "device execution wall time", TPOT_BUCKETS),
            ("app_tpu_step_seconds",
             "engine step time by phase and attributed segment",
             STEP_SECONDS_BUCKETS),
        ):
            try:
                m.new_histogram(name, desc, buckets)
            except Exception:  # noqa: BLE001
                pass

    # -- device surface -------------------------------------------------------
    @property
    def devices(self) -> List[Any]:
        return self._devices

    @property
    def device_count(self) -> int:
        return len(self._devices)

    @property
    def platform(self) -> str:
        return self._devices[0].platform if self._devices else "none"

    def mesh(self, axes: Dict[str, int], allow_subset: bool = False):
        """Build a jax.sharding.Mesh over the client's devices.

        axes: ordered {axis_name: size}; product must equal device_count
        (pass -1 for one axis to infer it). allow_subset=True builds the
        mesh over the FIRST product-many devices instead — for serving
        configs sharded narrower than the visible slice (e.g. TP=2 on an
        8-chip host).
        """
        import numpy as np
        from jax.sharding import Mesh

        names = list(axes.keys())
        sizes = list(axes.values())
        if -1 in sizes:
            known = int(np.prod([s for s in sizes if s != -1]))
            sizes[sizes.index(-1)] = len(self._devices) // known
        total = int(np.prod(sizes))
        if total != len(self._devices) and not (allow_subset
                                                and total < len(self._devices)):
            raise ValueError(f"mesh axes {dict(zip(names, sizes))} need {total} devices, "
                             f"have {len(self._devices)}")
        return Mesh(np.array(self._devices[:total]).reshape(sizes),
                    tuple(names))

    def memory_stats(self) -> List[Dict[str, Any]]:
        out = []
        for d in self._devices:
            try:
                stats = d.memory_stats() or {}
            except Exception:  # noqa: BLE001 - CPU backends have no stats
                stats = {}
            out.append({
                "id": d.id,
                "kind": d.device_kind,
                "bytes_in_use": stats.get("bytes_in_use", 0),
                "bytes_limit": stats.get("bytes_limit", 0),
            })
        return out

    def refresh_memory_metrics(self) -> None:
        if self.metrics is None:
            return
        for s in self.memory_stats():
            dev = str(s["id"])
            self.metrics.set_gauge("app_tpu_hbm_bytes_used", s["bytes_in_use"],
                                   device=dev)
            self.metrics.set_gauge("app_tpu_hbm_bytes_limit", s["bytes_limit"],
                                   device=dev)
            # canonical kind-labeled series (the legacy _used/_limit pair
            # stays for dashboards built on PR 0; see docs/observability.md)
            self.metrics.set_gauge("app_tpu_hbm_bytes", s["bytes_in_use"],
                                   device=dev, kind="in_use")
            self.metrics.set_gauge("app_tpu_hbm_bytes", s["bytes_limit"],
                                   device=dev, kind="limit")

    # -- health (feeds /.well-known/health) -----------------------------------
    # the device round-trip gets this long before the probe is declared
    # stuck; a wedged PJRT call can block FOREVER, and /health must answer
    # regardless (class attr so deployments/tests can tune per instance)
    HEALTH_PROBE_TIMEOUT_S = 3.0

    def _probe_device(self) -> None:
        """The actual device round-trip, run on the single-flight probe
        thread: like the SQL ping (sql/health.go:26-65), but isolated so a
        device that stops answering (r5: wedged tunnel, PJRT call never
        returns) pins ONE daemon thread instead of every health handler."""
        try:
            import jax.numpy as jnp

            if self.faults is not None:  # chaos drills: wedge/fail the probe
                self.faults.hit("device.health_probe")
            ok = float(jnp.asarray(1.0) + 1.0) == 2.0
            self._probe_result = (STATUS_UP if ok else STATUS_DEGRADED, None)
        except Exception as exc:  # noqa: BLE001
            self._probe_result = (STATUS_DOWN, str(exc))

    def health_check(self) -> Health:
        if not self._devices:
            return Health(status=STATUS_DOWN, details={"error": "no devices"})
        import threading

        # single-flight: while one probe is still blocked inside the
        # device, health polls reuse it (reporting DEGRADED) rather than
        # piling up a stuck thread per poll. Start/result are guarded by
        # _probe_lock so concurrent polls cannot double-start a probe or
        # reset the result another poll is about to read
        with self._probe_lock:
            probe = self._probe_thread
            if probe is None or not probe.is_alive():
                self._probe_result = None
                probe = threading.Thread(target=self._probe_device,
                                         name="tpu-health-probe", daemon=True)
                self._probe_thread = probe
                probe.start()
        probe.join(timeout=self.HEALTH_PROBE_TIMEOUT_S)
        with self._probe_lock:
            result = self._probe_result
        if probe.is_alive() or result is None:
            # still blocked inside the device — or finished the join race
            # without a published result yet: degraded, never a crash
            return Health(status=STATUS_DEGRADED, details={
                "platform": self.platform,
                "error": f"device probe stuck for "
                         f">{self.HEALTH_PROBE_TIMEOUT_S:.0f}s "
                         f"(runtime not answering)",
            })
        status, err = result
        if status == STATUS_DOWN:
            return Health(status=STATUS_DOWN, details={"error": err})
        self.refresh_memory_metrics()
        mem = self.memory_stats()
        return Health(status=status, details={
            "platform": self.platform,
            "devices": len(self._devices),
            "memory": mem,
            "uptime_s": round(time.monotonic() - (self._connected_at or time.monotonic()), 1),
        })

    def close(self) -> None:
        self._devices = []
