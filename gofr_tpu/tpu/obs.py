"""Shared best-effort metric recording for the TPU runtime components.

Metric failures (unregistered name in a bare test Manager, etc.) must never
take down the serving loop, so every call swallows errors — but never
SILENTLY: each swallowed failure increments the self-observability counter
``app_obs_dropped_metrics_total{name}`` (registered on demand on the same
manager) and logs once per name at debug, so a typo'd or unregistered
metric name is findable in five minutes instead of invisible forever.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

DROPPED_METRIC = "app_obs_dropped_metrics_total"


class MetricsHook:
    def __init__(self, metrics=None, logger=None):
        self.metrics = metrics
        self.logger = logger
        # names already logged as dropped — once per name keeps a hot loop
        # recording a bad name from flooding the log at dispatch rate
        self._drop_logged: set = set()

    def _dropped(self, name: str, exc: BaseException) -> None:
        """Count (and once, log) a swallowed recording failure. Best-effort
        squared: a failure HERE is swallowed for real — the drop counter
        registers itself on first use, so the only way to lose a drop is a
        manager too broken to register a counter."""
        m = self.metrics
        try:
            inst = m.get(DROPPED_METRIC)
            if inst is None:
                m.new_counter(
                    DROPPED_METRIC,
                    "metric recordings swallowed by best-effort hooks, "
                    "by metric name (a non-zero series is a wiring bug)")
                inst = m.get(DROPPED_METRIC)
            # direct instrument add: increment_counter(name=...) would
            # collide with the method's own `name` parameter
            inst.add(1.0, name=name)
        except Exception:  # noqa: BLE001 - self-observability stays best-effort
            pass
        if name not in self._drop_logged:
            self._drop_logged.add(name)
            if self.logger is not None:
                try:
                    self.logger.debugf("metric %s dropped: %s", name, exc)
                except Exception:  # noqa: BLE001
                    pass

    def counter(self, name: str, value: float = 1.0, **labels) -> None:
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(name, value, **labels)
            except Exception as exc:  # noqa: BLE001
                self._dropped(name, exc)

    def gauge(self, name: str, value, **labels) -> None:
        if self.metrics is not None:
            try:
                self.metrics.set_gauge(name, value, **labels)
            except Exception as exc:  # noqa: BLE001
                self._dropped(name, exc)

    def hist(self, name: str, value,
             exemplar: Optional[Dict[str, Any]] = None, **labels) -> None:
        # exemplar rides only when present so duck-typed managers without
        # the kwarg (test fakes, adapters) keep working unchanged
        if self.metrics is not None:
            try:
                if exemplar is not None:
                    self.metrics.record_histogram(name, value,
                                                  exemplar=exemplar, **labels)
                else:
                    self.metrics.record_histogram(name, value, **labels)
            except Exception as exc:  # noqa: BLE001
                self._dropped(name, exc)

    def hist_n(self, name: str, value, n: int,
               exemplar: Optional[Dict[str, Any]] = None, **labels) -> None:
        """n identical observations in one call (hot-loop batching)."""
        if self.metrics is not None:
            try:
                if exemplar is not None:
                    self.metrics.record_histogram_n(name, value, n,
                                                    exemplar=exemplar,
                                                    **labels)
                else:
                    self.metrics.record_histogram_n(name, value, n, **labels)
            except Exception as exc:  # noqa: BLE001
                self._dropped(name, exc)
