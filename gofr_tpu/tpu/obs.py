"""Shared best-effort metric recording for the TPU runtime components.

Metric failures (unregistered name in a bare test Manager, etc.) must never
take down the serving loop, so every call swallows errors.
"""

from __future__ import annotations


class MetricsHook:
    def __init__(self, metrics=None):
        self.metrics = metrics

    def counter(self, name: str, value: float = 1.0, **labels) -> None:
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(name, value, **labels)
            except Exception:  # noqa: BLE001
                pass

    def gauge(self, name: str, value, **labels) -> None:
        if self.metrics is not None:
            try:
                self.metrics.set_gauge(name, value, **labels)
            except Exception:  # noqa: BLE001
                pass

    def hist(self, name: str, value, **labels) -> None:
        if self.metrics is not None:
            try:
                self.metrics.record_histogram(name, value, **labels)
            except Exception:  # noqa: BLE001
                pass

    def hist_n(self, name: str, value, n: int, **labels) -> None:
        """n identical observations in one call (hot-loop batching)."""
        if self.metrics is not None:
            try:
                self.metrics.record_histogram_n(name, value, n, **labels)
            except Exception:  # noqa: BLE001
                pass
