"""Thread-ownership markers: the `@loop_only` convention, formalized.

PRs 4/6 scattered "loop-thread-only" comments across engine.py,
stepledger.py, paging.py and prefixcache.py — true statements nothing
enforced. `@loop_only` turns each comment into a machine-checkable
contract: graftlint's ownership pass (tools/analysis/passes/ownership.py)
verifies that marked methods — and writes to the instance fields they
declare via ``fields=(...)`` — are only reached from loop-rooted call
paths (functions named ``_loop`` or themselves marked ``@loop_only``).

The decorator is deliberately zero-cost at runtime: it stamps two
attributes and returns the function unwrapped, so the engine hot loop
pays nothing. ``__init__`` is always exempt from field-ownership (the
constructing thread owns the object before the loop exists); any other
off-loop access is either a bug, a pragma with a reason, or a baselined
finding — never silent.

    class PageAllocator:
        @loop_only(fields=("_free", "_refs"))
        def alloc(self, n): ...

A registry of every marked function is kept for introspection
(`/debug`-style tooling, tests); it is not consulted on any hot path.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

# qualname -> declared owned fields, for introspection and tests
LOOP_ONLY_REGISTRY: Dict[str, Tuple[str, ...]] = {}


def loop_only(fn: Optional[Callable] = None, *,
              fields: Tuple[str, ...] = ()):
    """Mark a function as engine-loop-thread-only. Usable bare
    (``@loop_only``) or with owned fields
    (``@loop_only(fields=("_slots",))``). Returns the function object
    itself — no wrapper, no per-call overhead."""

    def mark(f: Callable) -> Callable:
        f.__loop_only__ = True
        f.__loop_owned_fields__ = tuple(fields)
        LOOP_ONLY_REGISTRY[f"{f.__module__}.{f.__qualname__}"] = \
            tuple(fields)
        return f

    return mark(fn) if fn is not None else mark


def is_loop_only(fn: Callable) -> bool:
    return bool(getattr(fn, "__loop_only__", False))
