"""Executor: AOT compile cache keyed by (program, shapes), shape bucketing.

The TPU-first design constraint this enforces (SURVEY.md §7 hard parts):
everything under jit is traced once and compiled; dynamic request shapes must
be bucketed to a small, fixed set so XLA compiles a bounded number of
programs. The cache is the analog of the reference keeping its expensive init
(DB connect) in the container, not per request (gofr.go:63-97).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)


def next_bucket(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket >= n. Raises if n exceeds the largest bucket."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"size {n} exceeds largest bucket {buckets[-1]}")


def pad_to(array, size: int, axis: int = 0, value=0):
    """Pad `array` along `axis` up to `size` with `value` (no-op if already there)."""
    import jax.numpy as jnp
    import numpy as np

    xp = jnp if not isinstance(array, np.ndarray) else np
    current = array.shape[axis]
    if current == size:
        return array
    if current > size:
        raise ValueError(f"array dim {current} larger than target {size}")
    widths = [(0, 0)] * array.ndim
    widths[axis] = (0, size - current)
    return xp.pad(array, widths, constant_values=value)


def _abstract_key(tree) -> Tuple:
    """Hashable (shape, dtype) signature of an argument pytree."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            sig.append((type(leaf).__name__, repr(leaf)))
    return tuple(sig)


class CompiledProgram:
    def __init__(self, compiled, name: str, key: Tuple):
        self.compiled = compiled
        self.name = name
        self.key = key
        self.executions = 0

    def __call__(self, *args):
        self.executions += 1
        return self.compiled(*args)


class Executor:
    """Compile-once execute-many wrapper around jax.jit with an explicit cache.

    compile(name, fn, args, ...) AOT-lowers + compiles for the exact arg
    shapes; subsequent calls with the same shapes hit the cache. `run` is the
    one-call convenience: bucket -> compile-or-hit -> execute.
    """

    def __init__(self, tpu_client=None, logger=None, metrics=None):
        self.tpu = tpu_client
        self.logger = logger if logger is not None else getattr(tpu_client, "logger", None)
        self.metrics = metrics if metrics is not None else getattr(tpu_client, "metrics", None)
        self._cache: Dict[Tuple, CompiledProgram] = {}
        self._lock = threading.Lock()

    def _observe_compile(self, name: str, seconds: float, hit: bool) -> None:
        if self.metrics is not None:
            try:
                if hit:
                    self.metrics.increment_counter("app_tpu_compile_cache_hits")
                else:
                    self.metrics.increment_counter("app_tpu_compile_total")
            except Exception:  # noqa: BLE001 - metrics may not be registered in tests
                pass
        if not hit and self.logger is not None:
            self.logger.infof("compiled %s in %.2fs", name, seconds)

    def compile(self, name: str, fn: Callable, args: Tuple,
                static_argnums: Tuple[int, ...] = (),
                donate_argnums: Tuple[int, ...] = (),
                in_shardings=None, out_shardings=None) -> CompiledProgram:
        import jax

        key = (name, _abstract_key([a for i, a in enumerate(args) if i not in static_argnums]),
               tuple(static_argnums), tuple(donate_argnums))
        with self._lock:
            cached = self._cache.get(key)
        if cached is not None:
            self._observe_compile(name, 0.0, hit=True)
            return cached

        start = time.time()
        kwargs: Dict[str, Any] = {}
        if static_argnums:
            kwargs["static_argnums"] = static_argnums
        if donate_argnums:
            kwargs["donate_argnums"] = donate_argnums
        if in_shardings is not None:
            kwargs["in_shardings"] = in_shardings
        if out_shardings is not None:
            kwargs["out_shardings"] = out_shardings
        jitted = jax.jit(fn, **kwargs)
        compiled = jitted.lower(*args).compile()
        program = CompiledProgram(compiled, name, key)
        elapsed = time.time() - start
        with self._lock:
            # a racing thread may have compiled the same key; keep the first
            program = self._cache.setdefault(key, program)
        self._observe_compile(name, elapsed, hit=False)
        return program

    def run(self, name: str, fn: Callable, *args, **compile_kwargs):
        program = self.compile(name, fn, args, **compile_kwargs)
        start = time.time()
        out = program(*args)
        if self.metrics is not None:
            try:
                self.metrics.increment_counter("app_tpu_execute_total")
                self.metrics.record_histogram("app_tpu_execute_seconds", time.time() - start)
            except Exception:  # noqa: BLE001
                pass
        return out

    def warmup(self, name: str, fn: Callable, example_args: Tuple, **kw) -> None:
        """Pre-compile at boot so the first request doesn't pay compile latency
        (the expensive-init-in-container rule, SURVEY.md §3.1)."""
        self.compile(name, fn, example_args, **kw)

    @property
    def cache_size(self) -> int:
        return len(self._cache)

    def cache_info(self) -> Dict[str, int]:
        with self._lock:
            return {prog.name: prog.executions for prog in self._cache.values()}
