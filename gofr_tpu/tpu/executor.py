"""Executor: AOT compile cache keyed by (program, shapes), shape bucketing.

The TPU-first design constraint this enforces (SURVEY.md §7 hard parts):
everything under jit is traced once and compiled; dynamic request shapes must
be bucketed to a small, fixed set so XLA compiles a bounded number of
programs. The cache is the analog of the reference keeping its expensive init
(DB connect) in the container, not per request (gofr.go:63-97).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)


def next_bucket(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket >= n. Raises if n exceeds the largest bucket."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"size {n} exceeds largest bucket {buckets[-1]}")


def pad_to(array, size: int, axis: int = 0, value=0):
    """Pad `array` along `axis` up to `size` with `value` (no-op if already there)."""
    import jax.numpy as jnp
    import numpy as np

    xp = jnp if not isinstance(array, np.ndarray) else np
    current = array.shape[axis]
    if current == size:
        return array
    if current > size:
        raise ValueError(f"array dim {current} larger than target {size}")
    widths = [(0, 0)] * array.ndim
    widths[axis] = (0, size - current)
    return xp.pad(array, widths, constant_values=value)


def _abstract_key(tree) -> Tuple:
    """Hashable (shape, dtype) signature of an argument pytree."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            sig.append((type(leaf).__name__, repr(leaf)))
    return tuple(sig)


class CompiledProgram:
    def __init__(self, compiled, name: str, key: Tuple):
        self.compiled = compiled
        self.name = name
        self.key = key
        self.executions = 0
        # compile-table bookkeeping (Executor.compile_table): how this
        # program came to exist, what it cost, how often the cache served it
        self.compile_seconds = 0.0
        self.hits = 0
        self.source = "compiled"        # "compiled" | "disk"

    def __call__(self, *args):
        self.executions += 1
        return self.compiled(*args)


class Executor:
    """Compile-once execute-many wrapper around jax.jit with an explicit cache.

    compile(name, fn, args, ...) AOT-lowers + compiles for the exact arg
    shapes; subsequent calls with the same shapes hit the cache. `run` is the
    one-call convenience: bucket -> compile-or-hit -> execute.
    """

    def __init__(self, tpu_client=None, logger=None, metrics=None,
                 cache_dir: Optional[str] = None):
        self.tpu = tpu_client
        self.logger = logger if logger is not None else getattr(tpu_client, "logger", None)
        self.metrics = metrics if metrics is not None else getattr(tpu_client, "metrics", None)
        self._cache: Dict[Tuple, CompiledProgram] = {}
        self._lock = threading.Lock()
        # fault-injection plane (tpu/faults.py): None in production; armed
        # deployments can add latency to (or fail) compile lookups
        self.faults = None
        # step-ledger attribution (tpu/stepledger.py): called with
        # (name, seconds) after every cache-MISS compile so the engine can
        # re-attribute compile time out of the segment it happened under.
        # One callback per executor — an executor shared across engines
        # reports to whichever engine bound it last (attribution only;
        # correctness never depends on it)
        self.on_compile = None
        # compiled-program persistence (SURVEY §2.5 item 2): serialized PJRT
        # executables keyed by (program, shapes, backend); a second boot
        # loads them instead of re-tracing + re-compiling
        self.cache_dir = cache_dir
        self.disk_hits = 0
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            self._prune_stale_artifacts()

    # artifacts whose fingerprint can no longer be produced (code changed,
    # topology changed, fingerprint schema evolved) are never matched and
    # never hit the failed-load cleanup — age them out so the cache dir
    # stays bounded. Loads touch mtime, so live artifacts survive.
    PRUNE_AGE_S = 30 * 86400

    def _prune_stale_artifacts(self) -> None:
        now = time.time()  # lint: clock-ok compared against file mtimes, which are wall-clock
        try:
            for fname in os.listdir(self.cache_dir):
                if fname.endswith(".jexec"):
                    cutoff = now - self.PRUNE_AGE_S
                elif ".jexec.tmp." in fname:
                    # crash-during-persist leftovers (the atomic-replace
                    # staging files); an hour covers any live writer
                    cutoff = now - 3600
                else:
                    continue
                path = os.path.join(self.cache_dir, fname)
                try:
                    if os.path.getmtime(path) < cutoff:
                        os.remove(path)
                except OSError:
                    pass
        except OSError:
            pass

    def _observe_compile(self, name: str, seconds: float, hit: bool) -> None:
        if self.metrics is not None:
            try:
                if hit:
                    self.metrics.increment_counter("app_tpu_compile_cache_hits")
                else:
                    self.metrics.increment_counter("app_tpu_compile_total")
            except Exception:  # noqa: BLE001 - metrics may not be registered in tests
                pass
        if not hit and self.logger is not None:
            self.logger.infof("compiled %s in %.2fs", name, seconds)

    @staticmethod
    def _args_device_sig(args) -> Tuple:
        """ORDERED device ids the example args are committed to — part of
        the disk fingerprint so a tp=8 artifact can never be resurrected
        by a single-device engine with identical shapes, and a mesh over
        the same devices in a DIFFERENT order gets its own artifact (the
        restore pins the recorded order; an order mismatch would fail on
        every call with no recompile fallback)."""
        import jax

        ids = set()
        for leaf in jax.tree_util.tree_leaves(args):
            sharding = getattr(leaf, "sharding", None)
            if sharding is None:
                continue
            assignment = getattr(sharding, "_device_assignment", None)
            if assignment and len(assignment) > 1:
                return tuple(d.id for d in assignment)
            mesh = getattr(sharding, "mesh", None)
            devices = getattr(mesh, "devices", None)
            if devices is not None and getattr(devices, "size", 1) > 1:
                return tuple(d.id for d in devices.flat)
            device_set = getattr(sharding, "device_set", None)
            if device_set:
                ids |= {d.id for d in device_set}
        return tuple(sorted(ids))   # single-device / uncommitted args

    def _disk_path(self, key: Tuple, fn: Callable,
                   dev_sig: Tuple = ()) -> Optional[str]:
        if not self.cache_dir:
            return None
        import jax

        try:
            device = jax.devices()[0]
            # the full marshalled code object (bytecode + consts + names +
            # nested code) AND the closure cell values go into the
            # fingerprint: co_code alone is identical for `x+1` vs `x+2`
            # (constants live in co_consts), and engine program factories
            # close over the model config — neither may resurrect a stale
            # executable. Address-bearing reprs (plain objects) are reduced
            # to their type name so the digest is stable across processes.
            import marshal
            import re

            code = getattr(fn, "__code__", None)
            code_bytes = marshal.dumps(code) if code is not None else b""
            cells = []
            for cell in (getattr(fn, "__closure__", None) or ()):
                try:
                    text = repr(cell.cell_contents)
                except Exception:  # noqa: BLE001
                    text = "?"
                if " at 0x" in text:
                    text = type(cell.cell_contents).__name__
                cells.append(re.sub(r"0x[0-9a-f]+", "", text))
            fingerprint = (key, jax.__version__, device.platform,
                           device.device_kind, dev_sig,
                           hashlib.sha256(code_bytes).hexdigest(),
                           tuple(cells))
        except Exception:  # noqa: BLE001
            return None
        digest = hashlib.sha256(repr(fingerprint).encode()).hexdigest()[:32]
        return os.path.join(self.cache_dir, f"{digest}.jexec")

    def _load_from_disk(self, name: str, key: Tuple, fn: Callable,
                        dev_sig: Tuple = ()) -> Optional[CompiledProgram]:
        path = self._disk_path(key, fn, dev_sig)
        if path is None or not os.path.exists(path):
            return None
        import jax
        from jax.experimental import serialize_executable

        try:
            with open(path, "rb") as fp:
                blob, in_tree, out_tree, device_ids = pickle.load(fp)
            # the artifact records the mesh's DEVICE ORDER (a device count
            # cannot reconstruct an assignment; a wrong order would
            # silently mis-shard). Restore exactly that ordering — if any
            # recorded device is gone, the topology changed: discard
            by_id = {d.id: d for d in jax.devices()}
            if device_ids and not all(i in by_id for i in device_ids):
                raise ValueError(f"device ids {device_ids} not all present")
            execution_devices = ([by_id[i] for i in device_ids]
                                 if device_ids else jax.devices()[:1])
            import inspect
            params = inspect.signature(
                serialize_executable.deserialize_and_load).parameters
            if "execution_devices" in params:
                compiled = serialize_executable.deserialize_and_load(
                    blob, in_tree, out_tree,
                    execution_devices=execution_devices)
            else:
                # jax 0.4.x: no execution_devices kwarg — the PJRT blob
                # carries its own device assignment, which load() restores
                # through the backend client; the device-id presence check
                # above still discards artifacts from a changed topology
                compiled = serialize_executable.deserialize_and_load(
                    blob, in_tree, out_tree,
                    backend=execution_devices[0].client)
        except Exception as exc:  # noqa: BLE001 - stale/foreign artifact
            if self.logger is not None:
                self.logger.warnf("discarding persisted program %s: %s",
                                  path, exc)
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        if self.metrics is not None:
            try:
                self.metrics.increment_counter("app_tpu_compile_disk_hits")
            except Exception:  # noqa: BLE001
                pass
        self.disk_hits += 1
        try:
            os.utime(path)   # keep hot artifacts out of the age-out prune
        except OSError:
            pass
        if self.logger is not None:
            self.logger.infof("loaded %s from program cache", name)
        program = CompiledProgram(compiled, name, key)
        program.source = "disk"
        return program

    @staticmethod
    def _device_order(compiled):
        """The compiled executable's ordered device assignment, or None if
        it cannot be determined (then multi-device persist is skipped)."""
        import jax

        for s in jax.tree_util.tree_leaves(compiled.input_shardings):
            assignment = getattr(s, "_device_assignment", None)
            if assignment:
                return list(assignment)
            mesh = getattr(s, "mesh", None)
            if mesh is not None:
                try:
                    return list(mesh.devices.flat)
                except Exception:  # noqa: BLE001
                    pass
        return None

    def _save_to_disk(self, key: Tuple, fn: Callable, compiled,
                      dev_sig: Tuple = ()) -> None:
        path = self._disk_path(key, fn, dev_sig)
        if path is None:
            return
        import jax
        from jax.experimental import serialize_executable

        try:
            devices = set()
            for s in jax.tree_util.tree_leaves(compiled.input_shardings):
                devices |= getattr(s, "device_set", set())
            if len(devices) > 1:
                # multi-device (mesh) program: persist the mesh's device
                # ORDERING alongside the blob so a later boot restores the
                # exact assignment (VERDICT r3 weak #5 — TP programs used
                # to recompile every restart). Order unknown -> skip
                order = self._device_order(compiled)
                if order is None or len(order) != len(devices):
                    return
                device_ids = [d.id for d in order]
            elif devices:
                # single-device too: a program committed to device 3 must
                # not reload pinned to device 0 (it would fail on every
                # call with a device mismatch, with no recompile fallback)
                device_ids = [next(iter(devices)).id]
            else:
                device_ids = []   # uncommitted: default device at load
            blob, in_tree, out_tree = serialize_executable.serialize(compiled)
            payload = pickle.dumps((blob, in_tree, out_tree, device_ids))
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fp:
                fp.write(payload)
            os.replace(tmp, path)
        except Exception as exc:  # noqa: BLE001 - persistence is best-effort
            if self.logger is not None:
                self.logger.debugf("could not persist program: %s", exc)

    def compile(self, name: str, fn: Callable, args: Tuple,
                static_argnums: Tuple[int, ...] = (),
                donate_argnums: Tuple[int, ...] = (),
                in_shardings=None, out_shardings=None) -> CompiledProgram:
        import jax

        import re as _re

        if self.faults is not None:  # chaos drills: slow/failed compiles
            self.faults.hit("executor.compile", name=name)

        shard_sig = ""
        if in_shardings is not None or out_shardings is not None:
            # explicit shardings change the compiled program for identical
            # arg shapes; scrub addresses so the signature is stable
            shard_sig = _re.sub(r"0x[0-9a-f]+", "",
                                repr((in_shardings, out_shardings)))
        # the device signature is part of the IN-MEMORY key too: two engines
        # sharing one Executor with identical names/shapes but different
        # meshes (or devices) must not be handed each other's programs
        # (ADVICE r4) — the same topology identity that keys disk artifacts
        dev_sig = self._args_device_sig(args)
        key = (name, _abstract_key([a for i, a in enumerate(args) if i not in static_argnums]),
               tuple(static_argnums), tuple(donate_argnums), shard_sig,
               dev_sig)
        with self._lock:
            cached = self._cache.get(key)
        if cached is not None:
            cached.hits += 1
            self._observe_compile(name, 0.0, hit=True)
            return cached

        loaded = self._load_from_disk(name, key, fn, dev_sig)
        if loaded is not None:
            with self._lock:
                loaded = self._cache.setdefault(key, loaded)
            return loaded

        start = time.monotonic()
        kwargs: Dict[str, Any] = {}
        if static_argnums:
            kwargs["static_argnums"] = static_argnums
        if donate_argnums:
            kwargs["donate_argnums"] = donate_argnums
        if in_shardings is not None:
            kwargs["in_shardings"] = in_shardings
        if out_shardings is not None:
            kwargs["out_shardings"] = out_shardings
        jitted = jax.jit(fn, **kwargs)
        compiled = jitted.lower(*args).compile()
        program = CompiledProgram(compiled, name, key)
        elapsed = time.monotonic() - start
        program.compile_seconds = elapsed
        self._save_to_disk(key, fn, compiled, dev_sig)
        with self._lock:
            # a racing thread may have compiled the same key; keep the first
            program = self._cache.setdefault(key, program)
        self._observe_compile(name, elapsed, hit=False)
        if self.on_compile is not None:
            try:
                self.on_compile(name, elapsed)
            except Exception:  # noqa: BLE001 - attribution is best-effort
                pass
        return program

    def run(self, name: str, fn: Callable, *args, **compile_kwargs):
        program = self.compile(name, fn, args, **compile_kwargs)
        start = time.monotonic()
        out = program(*args)
        if self.metrics is not None:
            try:
                self.metrics.increment_counter("app_tpu_execute_total")
                self.metrics.record_histogram("app_tpu_execute_seconds", time.monotonic() - start)
            except Exception:  # noqa: BLE001
                pass
        return out

    def warmup(self, name: str, fn: Callable, example_args: Tuple, **kw) -> None:
        """Pre-compile at boot so the first request doesn't pay compile latency
        (the expensive-init-in-container rule, SURVEY.md §3.1)."""
        self.compile(name, fn, example_args, **kw)

    @property
    def cache_size(self) -> int:
        return len(self._cache)

    def cache_info(self) -> Dict[str, int]:
        with self._lock:
            return {prog.name: prog.executions for prog in self._cache.values()}

    def compile_table(self) -> Dict[str, Any]:
        """The compile cache as an operator table (/debug/engine): one row
        per program NAME (shape/K variants of the same program aggregate,
        with a `variants` count), plus cache-wide totals. The hit ratio is
        in-memory hits over all compile() lookups — disk loads count as
        misses for the in-memory cache but are reported separately."""
        with self._lock:
            programs = list(self._cache.values())
        by_name: Dict[str, Dict[str, Any]] = {}
        for prog in programs:
            row = by_name.setdefault(prog.name, {
                "name": prog.name, "variants": 0, "executions": 0,
                "cache_hits": 0, "compile_seconds": 0.0,
                "disk_loads": 0})
            row["variants"] += 1
            row["executions"] += prog.executions
            row["cache_hits"] += prog.hits
            row["compile_seconds"] += prog.compile_seconds
            row["disk_loads"] += 1 if prog.source == "disk" else 0
        rows = sorted(by_name.values(),
                      key=lambda r: (-r["compile_seconds"], r["name"]))
        for row in rows:
            row["compile_seconds"] = round(row["compile_seconds"], 3)
        hits = sum(r["cache_hits"] for r in rows)
        lookups = hits + len(programs)
        return {
            "programs": rows,
            "distinct_programs": len(programs),
            "compile_seconds_total": round(
                sum(p.compile_seconds for p in programs), 3),
            "cache_hits_total": hits,
            "disk_hits_total": self.disk_hits,
            "hit_ratio": round(hits / lookups, 4) if lookups else 0.0,
        }
