"""Disaggregated prefill/decode serving: two engines, one stream.

Interleaved prefill is the dominant TPOT poison a colocated engine
exhibits (every admitted prompt steals a sync window from in-flight
decodes — /debug/steps attributes exactly how much). This module removes
the interference ARCHITECTURALLY, the DistServe / vLLM-disagg split:

  client ── DisaggRouter.submit ──> prefill engine (disagg_role="prefill")
               │                        runs chunked prefill at full MFU,
               │                        emits the FIRST token (TTFT owned
               │                        here), exports the finished KV as
               │                        kvtier.PageBlob slices (async D2H)
               │                        and evacuates the slot — it never
               │                        dispatches a decode step
               │
               │   bounded in-proc queue (default) or gofr_tpu/pubsub
               ▼
          DecodeCoordinator ──> decode engine (disagg_role="decode")
                                    restores the shipped KV with the
                                    donated H2D scatter (``kv_handoff``
                                    step segment) and binds straight into
                                    decode — it never runs a prefill, so
                                    TPOT is pure decode cadence.

The stream never changes hands from the client's point of view: the
hand-off shares the prefill-side request's out_queue and cancel event, so
tokens keep flowing from the same GenerationRequest the router returned.

Failure semantics reuse the replay-after-reset contract (PR 3): ANY lost,
corrupt, rejected, or orphaned hand-off degrades to a blob-less
``submit_handoff`` on the decode pool — a local recompute of
``prompt + emitted`` — never a failed stream. The router's registry is
the exactly-once gate: every terminal path (coordinator consume, export
failure, prefill-failure hook, stale-hand-off reaper, worker-death sweep)
must CLAIM the request by popping its registry entry first; whoever pops
it owns routing, everyone else drops.

Wire contract (``encode_handoff``/``decode_handoff``): a versioned JSON
envelope carrying the admission spec (the admission-plane ``_spec``
shape), the emitted-token replay ledger, the traceparent (one trace
across the hop — the decode side synthesizes an ``engine.handoff`` span
under it), and one ``kvtier.encode_blob`` string per exported page (crc32
+ content verification happen at the decode pool's admission, exactly the
tier-restore trust model).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from .kvtier import PageBlob, decode_blob, encode_blob
from .obs import MetricsHook

HANDOFF_VERSION = 1

# every fallback increments app_tpu_disagg_fallback_total{reason=...};
# the engine/paging layers add: export, page_count, shape, content, restore
FALLBACK_TOTAL = "app_tpu_disagg_fallback_total"


def _span_traceparent(span) -> Optional[str]:
    """Best-effort W3C traceparent from a live tracer span, so the decode
    pool's spans land on the SAME trace even when the client sent no
    traceparent header (the prefill-side gen_span then roots the trace).
    Tracer backends differ; probe the common shapes and give up quietly."""
    if span is None:
        return None
    try:
        ctx = getattr(span, "context", None) or span
        trace_id = getattr(ctx, "trace_id", None)
        span_id = getattr(ctx, "span_id", None)
        if trace_id is None or span_id is None:
            return None
        if isinstance(trace_id, int):
            trace_id = f"{trace_id:032x}"
        if isinstance(span_id, int):
            span_id = f"{span_id:016x}"
        return f"00-{trace_id}-{span_id}-01"
    except Exception:  # noqa: BLE001 - tracing is never load-bearing
        return None


def encode_handoff(request, blobs: Optional[Sequence[PageBlob]],
                   n_ctx: int) -> str:
    """Serialize one hand-off. ``blobs=None`` encodes the degraded
    (recompute) form — same envelope, no KV payload."""
    spec: Dict[str, Any] = {
        "id": request.id,
        "prompt": list(request.prompt_tokens),
        "emitted": list(request.emitted),
        "max_new": request.max_new_tokens,
        "temp": request.temperature,
        "stop": sorted(request.stop_tokens),
        "prio": request.priority,
        "min": request.min_tokens,
        "top_p": request.top_p,
        "top_k": request.top_k,
        # QoS identity crosses the hop for accounting; prio already
        # carries the band and hand-offs outrank everything anyway
        "qos": getattr(request, "qos_class", None),
        "tenant": getattr(request, "tenant", ""),
    }
    traceparent = request.traceparent or _span_traceparent(request.gen_span)
    return json.dumps({
        "v": HANDOFF_VERSION,
        "rid": request.id,
        "spec": spec,
        "n_ctx": int(n_ctx),
        "traceparent": traceparent,
        # single-host hop: monotonic stamps are comparable across threads
        "sent_at": time.monotonic(),
        "blobs": None if blobs is None else [encode_blob(b) for b in blobs],
    })


def decode_handoff(raw) -> Optional[Dict[str, Any]]:
    """Parse the envelope (NOT the blobs — those stay encoded until the
    coordinator decides per-blob, so one corrupt page cannot take down the
    whole parse). None on any structural failure; the caller cannot even
    learn the request id from a torn envelope, so envelope integrity is
    the transport's job — per-page integrity is crc32's."""
    try:
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8")
        body = json.loads(raw)
        if body.get("v") != HANDOFF_VERSION:
            return None
        if "rid" not in body or "spec" not in body:
            return None
        return body
    except Exception:  # noqa: BLE001 - torn payload == lost payload
        return None


# -- transports ---------------------------------------------------------------


class QueueTransport:
    """Default hand-off transport: a bounded in-process queue. publish()
    is non-blocking — a full queue returns False, which the prefill side
    turns into a recompute fallback rather than stalling its loop (the
    decode pool is the bottleneck at that moment; shipping more KV at it
    would not help)."""

    def __init__(self, maxsize: int = 64):
        self._q: "queue.Queue[str]" = queue.Queue(maxsize=maxsize)

    def publish(self, payload: str) -> bool:
        try:
            self._q.put_nowait(payload)
        except queue.Full:
            return False
        return True

    def poll(self, timeout_s: float) -> Optional[str]:
        try:
            return self._q.get(timeout=timeout_s)
        except queue.Empty:
            return None

    def depth(self) -> int:
        return self._q.qsize()


class PubSubTransport:
    """Hand-off over a gofr_tpu/pubsub broker (config-selected): the same
    envelope published to a topic, consumed commit-to-advance by the
    decode side's group. Lets the split pair ride whatever broker the app
    already wires (in-proc for tests, Kafka-shaped for real deployments).
    Payload loss/duplication then follows the broker's delivery contract;
    the router's registry claim keeps duplicates harmless."""

    def __init__(self, broker, topic: str = "gofr.disagg.handoff",
                 group: str = "decode-pool"):
        self.broker = broker
        self.topic = topic
        self.group = group

    def publish(self, payload: str) -> bool:
        try:
            self.broker.publish(self.topic, payload.encode("utf-8"))
        except Exception:  # noqa: BLE001 - broker down == hand-off lost
            return False
        return True

    def poll(self, timeout_s: float) -> Optional[str]:
        try:
            msg = self.broker.subscribe(self.topic, self.group,
                                        timeout_s=timeout_s)
        except Exception:  # noqa: BLE001
            return None
        if msg is None:
            return None
        msg.commit()
        value = msg.value
        return value.decode("utf-8") if isinstance(value, bytes) else value

    def depth(self) -> int:
        return 0  # broker-side depth is the broker's own metric


# -- prefill side -------------------------------------------------------------


class PrefillWorker:
    """Owns the prefill engine's two disagg hooks. ``_export`` runs on the
    ENGINE LOOP thread (inside the ``kv_handoff`` step segment) right
    after the first token emits; ``_on_fail`` intercepts every would-be
    request failure and re-routes it to the decode pool instead.

    kill() is the chaos hook soak exercises: abrupt worker death must
    surface ONLY as fallback_total increments and replay events — never
    as a failed client stream."""

    def __init__(self, engine, router: "DisaggRouter"):
        if getattr(engine, "disagg_role", "") != "prefill":
            raise ValueError("PrefillWorker needs an engine built with "
                             "disagg_role='prefill'")
        self.engine = engine
        self.router = router
        self.alive = True
        engine._handoff_sink = self._export
        engine._handoff_fail = self._on_fail
        if getattr(engine, "util", None) is not None:
            engine.util.pool = "prefill"

    # engine loop thread
    def _export(self, request, blobs, n_ctx: int) -> bool:
        router = self.router
        if not self.alive:
            preq = router._claim(request.id)
            if preq is not None:
                router._fallback(preq, "worker_death")
            return False  # fallback arranged (or someone else claimed)
        with router._lock:
            entry = router._registry.get(request.id)
        if entry is None:
            # not routed through this router (or already claimed by a
            # sweep): raising keeps the slot bound so the prefill engine
            # decodes it locally — the never-a-lost-stream last resort
            raise RuntimeError(f"request {request.id} is not registered "
                               f"with the disagg router")
        payload = encode_handoff(request, blobs, n_ctx)
        if not self.router.transport.publish(payload):
            preq = router._claim(request.id)
            if preq is not None:
                router._fallback(preq, "queue_full")
            return False
        router._obs.counter("app_tpu_disagg_handoff_bytes_total",
                            float(len(payload)))
        router._obs.gauge("app_tpu_disagg_queue_depth",
                          self.router.transport.depth())
        # informational only — the kill sweep and the stale reaper key off
        # this state+stamp; a racing consume has already popped the entry
        # and mutating the dead list is harmless
        entry[1] = "queued"
        entry[2] = time.monotonic()
        return True

    # engine loop thread, via _fail_request
    def _on_fail(self, request, exc) -> bool:
        """Re-route a dying prefill-side request to the decode pool.
        True == handled (no error surfaces, no terminal None here — the
        decode side now owns the stream). Client cancels are NOT ours:
        declining lets the normal cancel path close the stream."""
        if request.cancelled.is_set():
            self.router._claim(request.id)  # drop the registry entry
            return False
        preq = self.router._claim(request.id)
        if preq is None:
            return False
        if preq.max_new_tokens - len(preq.emitted) <= 0:
            # budget already delivered; nothing to resume — just close
            preq.out_queue.put(None)
            return True
        try:
            self.router._fallback(preq, "prefill_error")
            return True
        except Exception:  # noqa: BLE001 - decode pool also unusable
            return False  # surface the original failure

    def kill(self) -> None:
        """Chaos: abrupt prefill-worker death. Stops the engine (its drain
        fails every queued request THROUGH the _on_fail hook, each one
        re-routing to the decode pool), then sweeps whatever the registry
        still holds — active-slot requests the dead loop abandoned and
        queued payloads that die with the worker's transport. Exactly-once
        is the registry pop: a coordinator racing on an already-swept
        payload claims nothing and drops it."""
        if not self.alive:
            return
        # under the submit gate: an in-flight router.submit finishes its
        # registry insert before death lands, so the drain below can
        # re-route it; later submits see alive=False and go straight to
        # the decode pool
        with self.router._submit_gate:
            self.alive = False
        try:
            self.engine.stop()
        finally:
            self.router._sweep("worker_death")


# -- decode side --------------------------------------------------------------


class DecodeCoordinator:
    """Consumer thread: polls the transport, decodes envelopes, claims the
    request from the router registry, and admits it into the decode pool
    via submit_handoff — with the shipped blobs when every page survives
    decode_blob's crc, blob-less (recompute) otherwise. Also reaps stale
    hand-offs: an entry stuck in "queued" past handoff_timeout_s means
    the payload was lost in flight; its stream falls back rather than
    hanging until the client's own timeout."""

    POLL_S = 0.1

    def __init__(self, engine, router: "DisaggRouter"):
        if getattr(engine, "disagg_role", "") != "decode":
            raise ValueError("DecodeCoordinator needs an engine built with "
                             "disagg_role='decode'")
        self.engine = engine
        self.router = router
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.consumed_total = 0
        if getattr(engine, "util", None) is not None:
            engine.util.pool = "decode"

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="disagg-decode-coordinator",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            payload = self.router.transport.poll(self.POLL_S)
            if payload is not None:
                try:
                    self._consume(payload)
                except Exception:  # noqa: BLE001 - keep consuming
                    pass
            self.router._reap_stale()

    def _consume(self, payload: str) -> None:
        router = self.router
        body = decode_handoff(payload)
        if body is None:
            # torn envelope: the rid is unreadable, so the stream cannot
            # be re-routed from here — the stale reaper rescues it
            router._count_fallback("envelope")
            return
        preq = router._claim(body["rid"])
        if preq is None:
            return  # swept/cancelled already; exactly-once says drop
        router._obs.gauge("app_tpu_disagg_queue_depth",
                          router.transport.depth())
        sent_at = body.get("sent_at")
        if isinstance(sent_at, (int, float)):
            router._obs.hist("app_tpu_disagg_handoff_seconds",
                             max(0.0, time.monotonic() - float(sent_at)))
        blobs: Optional[List[PageBlob]] = None
        raw_blobs = body.get("blobs")
        if raw_blobs is not None:
            decoded = [decode_blob(raw) for raw in raw_blobs]
            if all(b is not None for b in decoded):
                blobs = decoded
            else:
                # crc/structure failure on any page poisons the whole
                # hand-off: recompute is cheaper than a wrong KV read
                router._count_fallback("corrupt")
                if self.engine.recorder is not None:
                    self.engine.recorder.record_engine_event(
                        "disagg_corrupt_handoff", rid=body["rid"],
                        pages=len(raw_blobs))
        spec = body["spec"]
        try:
            self.engine.submit_handoff(
                spec["prompt"], spec["emitted"],
                max_new_tokens=spec["max_new"],
                temperature=spec["temp"],
                stop_tokens=set(spec["stop"]),
                priority=spec["prio"],
                min_tokens=spec["min"],
                top_p=spec["top_p"], top_k=spec["top_k"],
                traceparent=body.get("traceparent"),
                out_queue=preq.out_queue,
                cancelled=preq.cancelled,
                blobs=blobs,
                qos_class=spec.get("qos"),
                tenant=spec.get("tenant", ""))
            self.consumed_total += 1
        except Exception as exc:  # noqa: BLE001
            # decode pool refused outright (draining/shedding/never-fits):
            # both pools are unusable for this request — terminate the
            # stream explicitly rather than leaving the client hanging
            router._count_fallback("rejected")
            preq.error = exc
            preq.out_queue.put(None)


# -- the router ---------------------------------------------------------------


class DisaggRouter:
    """Front end of the split pair: clients submit here and stream from
    the returned request exactly as they would against one engine. Holds
    the rid -> [request, state, queued_at] registry that makes every
    hand-off terminal path exactly-once (see module docstring)."""

    def __init__(self, prefill_engine, decode_engine, *, metrics=None,
                 transport=None, queue_depth: int = 64,
                 handoff_timeout_s: float = 10.0):
        self.prefill_engine = prefill_engine
        self.decode_engine = decode_engine
        self.transport = transport or QueueTransport(queue_depth)
        self.handoff_timeout_s = float(handoff_timeout_s)
        self._obs = MetricsHook(metrics)
        self._lock = threading.Lock()
        self._registry: Dict[int, List[Any]] = {}
        # serializes submit's {alive-check, prefill submit, registry
        # insert} against kill(): without it a request could enter the
        # prefill engine after the death sweep but before its registry
        # entry exists, and the drain's failure hook — finding no entry —
        # would fail the stream instead of re-routing it
        self._submit_gate = threading.Lock()
        self.fallbacks_total = 0
        self.worker = PrefillWorker(prefill_engine, self)
        self.coordinator = DecodeCoordinator(decode_engine, self)

    @property
    def admission_limit(self) -> int:
        """The binding context limit across the pair (engine.submit
        parity — callers size prompts against the front door)."""
        return min(self.prefill_engine.admission_limit,
                   self.decode_engine.admission_limit)

    def start(self) -> None:
        self.coordinator.start()

    def stop(self) -> None:
        self.coordinator.stop()

    # -- client API -----------------------------------------------------------

    def submit(self, prompt_tokens: Sequence[int],
               max_new_tokens: int = 128, temperature: float = 0.0,
               stop_tokens=None, span=None, priority: int = 0,
               min_tokens: int = 0, top_p: float = 0.0, top_k: int = 0,
               traceparent: Optional[str] = None,
               qos_class: Optional[str] = None, tenant: str = ""):
        """engine.submit()'s signature, against the split pair. Returns
        the request whose stream() carries the whole generation.
        qos_class/tenant hit the PREFILL engine's QoS gate (banding,
        ladder door check); the dead-pool fallback carries them through
        for accounting only, like any hand-off."""
        with self._submit_gate:
            if self.worker.alive:
                preq = self.prefill_engine.submit(
                    prompt_tokens, max_new_tokens=max_new_tokens,
                    temperature=temperature, stop_tokens=stop_tokens,
                    span=span, priority=priority, min_tokens=min_tokens,
                    top_p=top_p, top_k=top_k, traceparent=traceparent,
                    qos_class=qos_class, tenant=tenant)
                with self._lock:
                    self._registry[preq.id] = [preq, "prefill", 0.0]
                return preq
        # dead prefill pool: the decode pool recomputes (degraded but
        # available — the soak chaos arc runs through here)
        self._count_fallback("worker_death")
        return self.decode_engine.submit_handoff(
            list(prompt_tokens), [], max_new_tokens=max_new_tokens,
            temperature=temperature, stop_tokens=stop_tokens,
            priority=priority, min_tokens=min_tokens,
            top_p=top_p, top_k=top_k, traceparent=traceparent,
            blobs=None, qos_class=qos_class, tenant=tenant)

    def stats(self) -> Dict[str, Any]:
        """/debug/disagg payload: the hand-off plane's health plus both
        pools' engine snapshots (lazy import: utilization pulls jax)."""
        from .utilization import engine_snapshot
        with self._lock:
            pending = len(self._registry)
            queued = sum(1 for e in self._registry.values()
                         if e[1] == "queued")
        return {
            "worker_alive": self.worker.alive,
            "queue_depth": self.transport.depth(),
            "pending_handoffs": pending,
            "handoffs_in_flight": queued,
            "handoffs_total": getattr(self.prefill_engine,
                                      "handoffs_total", 0),
            "handoffs_consumed": self.coordinator.consumed_total,
            "fallbacks_total": self.fallbacks_total
            + getattr(self.prefill_engine, "handoff_fallbacks_total", 0)
            + getattr(self.decode_engine, "handoff_fallbacks_total", 0),
            "handoff_timeout_s": self.handoff_timeout_s,
            "prefill": engine_snapshot(self.prefill_engine),
            "decode": engine_snapshot(self.decode_engine),
        }

    # -- exactly-once plumbing ------------------------------------------------

    def _claim(self, rid: int):
        """Pop-and-own: whoever claims the entry routes the stream; every
        later claimer gets None and must drop."""
        with self._lock:
            entry = self._registry.pop(rid, None)
        return entry[0] if entry is not None else None

    def _count_fallback(self, reason: str) -> None:
        self.fallbacks_total += 1
        self._obs.counter(FALLBACK_TOTAL, reason=reason)

    def _fallback(self, preq, reason: str) -> None:
        """Degrade one CLAIMED request to a decode-pool recompute of its
        resume window. Terminates the stream explicitly if even that is
        impossible — a fallback may degrade latency, never deliverability."""
        self._count_fallback(reason)
        recorder = self.decode_engine.recorder
        if recorder is not None:
            recorder.record_engine_event("disagg_fallback", rid=preq.id,
                                         reason=reason)
        try:
            self.decode_engine.submit_handoff(
                preq.prompt_tokens, list(preq.emitted),
                max_new_tokens=preq.max_new_tokens,
                temperature=preq.temperature,
                stop_tokens=set(preq.stop_tokens),
                priority=preq.priority, min_tokens=preq.min_tokens,
                top_p=preq.top_p, top_k=preq.top_k,
                traceparent=preq.traceparent
                or _span_traceparent(preq.gen_span),
                out_queue=preq.out_queue, cancelled=preq.cancelled,
                blobs=None, qos_class=getattr(preq, "qos_class", None),
                tenant=getattr(preq, "tenant", ""))
        except Exception as exc:  # noqa: BLE001
            preq.error = exc
            preq.out_queue.put(None)
            raise

    def _sweep(self, reason: str) -> None:
        """Claim EVERYTHING and fall each request back — worker death."""
        with self._lock:
            entries = list(self._registry.values())
            self._registry.clear()
        for entry in entries:
            try:
                self._fallback(entry[0], reason)
            except Exception:  # noqa: BLE001 - stream already terminated
                pass

    def _reap_stale(self) -> None:
        """Rescue hand-offs lost in flight: queued past the timeout means
        the payload will never arrive (dropped by a lossy transport or a
        crashed consumer) — recompute instead of hanging the stream."""
        now = time.monotonic()
        stale = []
        with self._lock:
            for rid, entry in list(self._registry.items()):
                if (entry[1] == "queued"
                        and now - entry[2] > self.handoff_timeout_s):
                    self._registry.pop(rid)
                    stale.append(entry[0])
        for preq in stale:
            try:
                self._fallback(preq, "lost")
            except Exception:  # noqa: BLE001
                pass


# -- observability wiring -----------------------------------------------------


def register_disagg_metrics(metrics) -> None:
    """Register every app_tpu_disagg_* series on a metrics Manager
    (idempotent; the engine/paging/utilization layers record some of
    these, this module the rest)."""
    for name, desc in (
        ("app_tpu_disagg_queue_depth",
         "hand-off payloads waiting between the prefill and decode pools"),
        ("app_tpu_disagg_pool_duty_cycle",
         "per-pool device duty cycle of the disaggregated pair "
         "(pool=prefill|decode)"),
    ):
        try:
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        except Exception:  # noqa: BLE001 - already registered
            pass
    for name, desc in (
        ("app_tpu_disagg_handoffs_total",
         "KV hand-offs exported by the prefill pool"),
        ("app_tpu_disagg_fallback_total",
         "hand-offs degraded to a decode-pool recompute, by reason"),
        ("app_tpu_disagg_handoff_bytes_total",
         "encoded hand-off payload bytes shipped over the transport"),
    ):
        try:
            if metrics.get(name) is None:
                metrics.new_counter(name, desc)
        except Exception:  # noqa: BLE001
            pass
    try:
        if metrics.get("app_tpu_disagg_handoff_seconds") is None:
            metrics.new_histogram(
                "app_tpu_disagg_handoff_seconds",
                "transport latency of one hand-off, export to consume")
    except Exception:  # noqa: BLE001
        pass


def install_routes(app, router: DisaggRouter,
                   path: str = "/debug/disagg") -> None:
    """Mount the hand-off plane's debug endpoint on a gofr app."""

    @app.get(path)
    def _disagg_stats(ctx):  # noqa: ANN001 - gofr handler shape
        return router.stats()
