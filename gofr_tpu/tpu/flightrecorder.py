"""Per-request flight recorder: engine lifecycle timelines for operators.

The aggregate surfaces (metrics histograms, the HTTP span) answer "how is
the fleet doing"; this module answers "where did THIS request spend its
time" — the question a blown TTFT budget raises. It keeps a bounded,
thread-safe ring of per-request event timelines covering the engine
lifecycle the HTTP trace cannot see (enqueued → admitted → prefill →
first token → decode blocks → finished/aborted), and on completion:

  * synthesizes engine child spans (``engine.queue`` / ``engine.prefill``
    / ``engine.decode``) through the existing tracing.Tracer, parented
    under the request's inbound trace context — so every configured
    exporter (InMemory/Zipkin/OTLP) sees engine-level spans that share
    the HTTP request's trace id, not just the transport span;
  * folds the request into a rolling SLO window and publishes goodput
    gauges (``app_tpu_slo_ttft_goodput`` / ``app_tpu_slo_tpot_goodput``):
    the fraction of recent requests meeting the configured TTFT/TPOT
    targets — the north-star SLO as a live number instead of a quantile
    read off a histogram.

Recording discipline (the MetricsHook posture, tpu/obs.py): every public
call is best-effort — it takes one short lock, does O(1) work, and
swallows its own failures, so recording can never take down the serving
loop. Decode-step events are batched per executed dispatch sync (the
engine already demuxes per slot there), never per token; memory is capped
by ``capacity`` completed records × ``max_events`` events each.

Operator surface (install_routes / App.enable_flight_recorder):

    GET /debug/requests        -> in-flight + recent completions with
                                  phase timings + SLO goodput + engine
                                  events (cache growth, resets, sheds)
    GET /debug/requests/{id}   -> one request's full event timeline
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from .obs import MetricsHook

# north-star defaults (ROADMAP.md): p50 TTFT < 150 ms; TPOT sized for
# ~20 tok/s/stream — deployments tune both via enable_flight_recorder
DEFAULT_TTFT_TARGET_S = 0.150
DEFAULT_TPOT_TARGET_S = 0.050


class RequestRecord:
    """One request's lifecycle: identity, phase stamps, bounded events.

    Clock discipline: every stamp is ``time.monotonic()`` (the engine's
    clock domain — NTP can never corrupt the interval math), plus ONE
    wall/monotonic anchor pair captured at enqueue. Epoch timestamps are
    derived through the anchor only where they leave the process: the
    summary/detail display and synthesized spans."""

    __slots__ = ("id", "prompt_tokens", "max_new_tokens", "priority",
                 "trace_id", "parent_span_id", "enqueued_at", "admitted_at",
                 "first_token_at", "finished_at", "generated", "outcome",
                 "error", "slot", "bucket", "batch_id", "chunked", "handoff",
                 "events", "events_dropped", "wall0", "mono0")

    def __init__(self, request) -> None:
        self.id = request.id
        self.prompt_tokens = len(request.prompt_tokens)
        self.max_new_tokens = request.max_new_tokens
        self.priority = request.priority
        self.trace_id: Optional[str] = None
        self.parent_span_id: Optional[str] = None
        self.enqueued_at = request.enqueued_at
        # wall/monotonic anchor: the ONE place both clocks are read
        # together; every displayed epoch is enqueue-wall + monotonic delta
        self.wall0 = time.time()  # lint: clock-ok the designated wall/mono anchor pair
        self.mono0 = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.generated = 0
        self.outcome: Optional[str] = None
        self.error: Optional[str] = None
        self.slot: Optional[int] = None
        self.bucket: Optional[int] = None
        self.batch_id: Optional[int] = None
        self.chunked = False
        # disaggregated hand-off (tpu/disagg.py): this record covers the
        # DECODE half of a request whose prefill (and first token) ran on
        # another engine — the first-token stamp carried over anchors the
        # decode-side TPOT at hand-off receipt, and span synthesis swaps
        # queue/prefill for a single engine.handoff span on the same trace
        self.handoff = bool(getattr(request, "disagg_handoff", False))
        if self.handoff and getattr(request, "first_token_at", None):
            self.first_token_at = request.first_token_at
        self.events: List[tuple] = [(self.enqueued_at, "enqueued", None)]
        self.events_dropped = 0

    def wall(self, t_mono: float) -> float:
        """Epoch rendering of a monotonic stamp through the anchor."""
        return self.wall0 + (t_mono - self.mono0)

    def add_event(self, name: str, data: Optional[Dict[str, Any]],
                  cap: int, t: Optional[float] = None) -> None:
        if len(self.events) >= cap:
            self.events_dropped += 1
            return
        self.events.append((t if t is not None else time.monotonic(),
                            name, data))

    def has_event(self, name: str) -> bool:
        return any(e[1] == name for e in self.events)

    def phases(self) -> Dict[str, float]:
        """Monotonic, non-overlapping phase durations: queue is
        enqueued→admitted, prefill is admitted→first token, decode is
        first token→finish. A phase a request never reached is absent."""
        out: Dict[str, float] = {}
        if self.admitted_at is not None:
            out["queue_s"] = max(0.0, self.admitted_at - self.enqueued_at)
            if self.first_token_at is not None:
                out["prefill_s"] = max(
                    0.0, self.first_token_at - self.admitted_at)
                if self.finished_at is not None:
                    out["decode_s"] = max(
                        0.0, self.finished_at - self.first_token_at)
        if self.finished_at is not None:
            out["total_s"] = max(0.0, self.finished_at - self.enqueued_at)
        return out

    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return max(0.0, self.first_token_at - self.enqueued_at)

    def tpot_s(self) -> Optional[float]:
        """Mean decode-phase seconds per token past the first; None until
        a request has finished with at least two tokens."""
        if (self.finished_at is None or self.first_token_at is None
                or self.generated < 2):
            return None
        return max(0.0, (self.finished_at - self.first_token_at)
                   / (self.generated - 1))

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "id": self.id,
            "prompt_tokens": self.prompt_tokens,
            "max_new_tokens": self.max_new_tokens,
            "generated": self.generated,
            # displayed as epoch via the anchor (stored stamp is monotonic)
            "enqueued_at": round(self.wall(self.enqueued_at), 6),
            "phases": self.phases(),
        }
        for key in ("outcome", "error", "slot", "bucket", "batch_id",
                    "trace_id"):
            value = getattr(self, key)
            if value is not None:
                out[key] = value
        if self.priority:
            out["priority"] = self.priority
        if self.chunked:
            out["chunked"] = True
        if self.handoff:
            out["handoff"] = True
        ttft = self.ttft_s()
        if ttft is not None:
            out["ttft_s"] = round(ttft, 6)
        tpot = self.tpot_s()
        if tpot is not None:
            out["tpot_s"] = round(tpot, 6)
        return out

    def detail(self) -> Dict[str, Any]:
        out = self.summary()
        out["events"] = [
            {"t": round(self.wall(t), 6), "event": name, **(data or {})}
            for t, name, data in self.events
        ]
        if self.events_dropped:
            out["events_dropped"] = self.events_dropped
        return out


class FlightRecorder:
    """Bounded, thread-safe per-request timeline store (see module doc).

    One instance per engine, shared with the /debug/requests routes. All
    ``record_*`` methods are hot-path safe: O(1) under one lock and
    best-effort (a recording failure is swallowed, like MetricsHook)."""

    def __init__(self, capacity: int = 256, max_events: int = 512,
                 slo_ttft_s: float = DEFAULT_TTFT_TARGET_S,
                 slo_tpot_s: float = DEFAULT_TPOT_TARGET_S,
                 slo_window: int = 256, metrics=None, tracer=None):
        self.capacity = max(1, int(capacity))
        self.max_events = max(8, int(max_events))
        self.slo_ttft_s = float(slo_ttft_s)
        self.slo_tpot_s = float(slo_tpot_s)
        self._lock = threading.Lock()
        self._live: Dict[int, RequestRecord] = {}
        self._done: "collections.deque[RequestRecord]" = collections.deque(
            maxlen=self.capacity)
        # (ttft_s|None, tpot_s|None) of recent completions — the goodput
        # window; sized independently of the ring so a small ring can
        # still back a stable gauge
        self._slo: "collections.deque" = collections.deque(
            maxlen=max(1, int(slo_window)))
        # engine-level happenings not owned by one request (cache growth,
        # device resets, stall sheds) — small and recent-only
        self._engine_events: "collections.deque" = collections.deque(
            maxlen=64)
        self._obs = MetricsHook(metrics)
        self.tracer = tracer
        # SLO burn-rate engine (tpu/incidents.py): when wired, every
        # completion and every shed feeds its error-budget windows — the
        # recorder already owns the TTFT/TPOT measurements and sees the
        # shed engine events, so it is the one natural tap point
        self.burn = None
        # terminal events ever recorded — ring eviction never decrements
        # it, so tests (and operators) can assert none were lost
        self.finished_total = 0

    # -- wiring (late binding for injected engines) ---------------------------
    def use_metrics(self, metrics) -> None:
        if metrics is not None:
            self._obs = MetricsHook(metrics)

    def use_tracer(self, tracer) -> None:
        if tracer is not None:
            self.tracer = tracer

    def use_burn_engine(self, burn) -> None:
        if burn is not None:
            self.burn = burn

    # -- recording (engine-facing, best-effort) -------------------------------
    def record_enqueued(self, request) -> None:
        try:
            rec = RequestRecord(request)
            # inbound trace context, most specific first: the engine's own
            # tpu.generate span (child of the HTTP span, so it carries the
            # inbound trace id), the HTTP span itself, or a raw W3C
            # traceparent header propagated through GenerationRequest
            span = getattr(request, "gen_span", None) or request.span
            if span is not None:
                rec.trace_id = span.trace_id
                rec.parent_span_id = span.span_id
            else:
                header = getattr(request, "traceparent", None)
                if header:
                    from ..tracing import parse_traceparent

                    parsed = parse_traceparent(header)
                    if parsed:
                        rec.trace_id, rec.parent_span_id = parsed
            with self._lock:
                self._live[request.id] = rec
        except Exception:  # noqa: BLE001 - recording is best-effort
            pass

    def record_admitted(self, request, slot: int, bucket: int,
                        batch_id: Optional[int] = None,
                        chunked: bool = False) -> None:
        try:
            with self._lock:
                rec = self._live.get(request.id)
                if rec is None:
                    return
                if batch_id is not None:
                    rec.batch_id = batch_id
                if rec.admitted_at is not None:
                    return  # chunk path: admitted at chunk 1, bound later
                rec.admitted_at = request.admitted_at or time.monotonic()
                rec.slot = slot
                rec.bucket = bucket
                rec.chunked = chunked
                rec.add_event("admitted", {"slot": slot, "bucket": bucket},
                              self.max_events, t=rec.admitted_at)
        except Exception:  # noqa: BLE001
            pass

    def record_event(self, request_id: int, name: str, once: bool = False,
                     **data) -> None:
        try:
            with self._lock:
                rec = self._live.get(request_id)
                if rec is None or (once and rec.has_event(name)):
                    return
                rec.add_event(name, data or None, self.max_events)
        except Exception:  # noqa: BLE001
            pass

    def record_first_token(self, request) -> None:
        try:
            with self._lock:
                rec = self._live.get(request.id)
                if rec is None or rec.first_token_at is not None:
                    return
                rec.first_token_at = (request.first_token_at
                                      or time.monotonic())
                rec.add_event("first_token", None, self.max_events,
                              t=rec.first_token_at)
        except Exception:  # noqa: BLE001
            pass

    def record_decode_block(self, request_id: int, tokens: int,
                            step_s: float) -> None:
        """One event per request per dispatch SYNC (a whole executed block
        of decode steps), never per token — the hot-path batching rule."""
        try:
            with self._lock:
                rec = self._live.get(request_id)
                if rec is None:
                    return
                rec.add_event("decode_block",
                              {"tokens": int(tokens),
                               "step_s": round(float(step_s), 6)},
                              self.max_events)
        except Exception:  # noqa: BLE001
            pass

    def record_finished(self, request, reason: str) -> None:
        try:
            with self._lock:
                rec = self._live.pop(request.id, None)
                if rec is None:
                    return
                rec.finished_at = request.finished_at or time.monotonic()
                rec.generated = request.generated
                rec.outcome = reason
                if request.error is not None:
                    rec.error = str(request.error)
                rec.add_event("finished", {"reason": reason},
                              self.max_events, t=rec.finished_at)
                self.finished_total += 1
                self._done.append(rec)
                self._slo.append((rec.ttft_s(), rec.tpot_s()))
                stats = self._slo_stats_locked()
            if stats["ttft_goodput"] is not None:
                self._obs.gauge("app_tpu_slo_ttft_goodput",
                                stats["ttft_goodput"])
            if stats["tpot_goodput"] is not None:
                self._obs.gauge("app_tpu_slo_tpot_goodput",
                                stats["tpot_goodput"])
            if self.burn is not None:
                # outcome "error"/"aborted" spends availability budget; a
                # cancel is the client's choice, not a served failure
                self.burn.observe_request(
                    rec.ttft_s(), rec.tpot_s(),
                    error=(rec.error is not None
                           or reason in ("error", "aborted")))
            self._emit_spans(rec)
        except Exception:  # noqa: BLE001
            pass

    def record_engine_event(self, name: str, **data) -> None:
        try:
            with self._lock:
                self._engine_events.append(
                    # lint: clock-ok operator-facing event timestamp, correlated with external logs
                    {"t": time.time(), "event": name, **data})
            if self.burn is not None and name in ("stall_shed",
                                                  "breaker_shed"):
                # a shed request never reaches record_finished: count the
                # refusal against the availability budget here
                self.burn.observe_shed()
        except Exception:  # noqa: BLE001
            pass

    # -- span synthesis -------------------------------------------------------
    def _emit_spans(self, rec: RequestRecord) -> None:
        """Child spans for the phases the request actually reached, in
        phase order, sharing the inbound trace id. Runs once, after the
        record went terminal (outside the recorder lock)."""
        tracer = self.tracer
        if tracer is None or rec.trace_id is None:
            return
        # spans leave the process: render the monotonic stamps as epochs
        # through the record's anchor (one linear shift, so phase
        # boundaries stay exactly contiguous)
        end = rec.wall(rec.finished_at if rec.finished_at is not None
                       else time.monotonic())
        attrs = {"request.id": rec.id}
        if rec.batch_id is not None:
            attrs["batch.id"] = rec.batch_id
        if rec.slot is not None:
            attrs["tpu.slot"] = rec.slot
        queue_end = (rec.wall(rec.admitted_at)
                     if rec.admitted_at is not None else end)
        if rec.handoff:
            # disaggregated decode pool: prefill (and the queue the client
            # saw) ran on the OTHER engine, whose recorder already emitted
            # those spans on this same trace id. This record's pre-admit
            # window is the hop itself — receipt, blob validation, the
            # H2D landing — so synthesize it as engine.handoff, then the
            # decode span; an engine.queue/engine.prefill pair here would
            # double-count phases the request never spent on this pool
            tracer.span_at("engine.handoff", rec.wall(rec.enqueued_at),
                           queue_end, trace_id=rec.trace_id,
                           parent_id=rec.parent_span_id,
                           attributes=dict(attrs,
                                           outcome=rec.outcome or ""))
            if rec.admitted_at is None:
                return
            tracer.span_at("engine.decode", rec.wall(rec.admitted_at), end,
                           trace_id=rec.trace_id,
                           parent_id=rec.parent_span_id,
                           attributes=dict(attrs, **{
                               "tpu.tokens": rec.generated,
                               "outcome": rec.outcome or ""}))
            return
        tracer.span_at("engine.queue", rec.wall(rec.enqueued_at), queue_end,
                       trace_id=rec.trace_id, parent_id=rec.parent_span_id,
                       attributes=dict(attrs, outcome=rec.outcome or ""))
        if rec.admitted_at is None:
            return
        prefill_end = (rec.wall(rec.first_token_at)
                       if rec.first_token_at is not None else end)
        pattrs = dict(attrs)
        if rec.bucket is not None:
            pattrs["tpu.prefill_bucket"] = rec.bucket
        if rec.chunked:
            pattrs["tpu.chunked"] = True
        tracer.span_at("engine.prefill", rec.wall(rec.admitted_at),
                       prefill_end,
                       trace_id=rec.trace_id, parent_id=rec.parent_span_id,
                       attributes=pattrs)
        if rec.first_token_at is None:
            return
        tracer.span_at("engine.decode", rec.wall(rec.first_token_at), end,
                       trace_id=rec.trace_id, parent_id=rec.parent_span_id,
                       attributes=dict(attrs, **{
                           "tpu.tokens": rec.generated,
                           "outcome": rec.outcome or ""}))

    # -- operator surface -----------------------------------------------------
    def _slo_stats_locked(self) -> Dict[str, Any]:
        ttfts = [t for t, _ in self._slo if t is not None]
        tpots = [t for _, t in self._slo if t is not None]
        return {
            "window": len(self._slo),
            "ttft_target_s": self.slo_ttft_s,
            "tpot_target_s": self.slo_tpot_s,
            "ttft_goodput": (round(sum(
                1 for t in ttfts if t <= self.slo_ttft_s) / len(ttfts), 4)
                if ttfts else None),
            "tpot_goodput": (round(sum(
                1 for t in tpots if t <= self.slo_tpot_s) / len(tpots), 4)
                if tpots else None),
        }

    def slo_stats(self) -> Dict[str, Any]:
        with self._lock:
            return self._slo_stats_locked()

    def snapshot(self) -> Dict[str, Any]:
        """The /debug/requests payload: in-flight + recent completions
        (newest first) with phase timings, SLO goodput, engine events."""
        with self._lock:
            live = sorted(self._live.values(), key=lambda r: r.enqueued_at)
            return {
                "in_flight": [r.summary() for r in live],
                "recent": [r.summary() for r in reversed(self._done)],
                "slo": self._slo_stats_locked(),
                "engine_events": list(self._engine_events),
                "capacity": self.capacity,
                "finished_total": self.finished_total,
            }

    def timeline_records(self) -> List[Dict[str, Any]]:
        """Request milestones in the RAW monotonic domain (no wall
        rendering), completed then live, for the timeline exporter's flow
        events (tpu/timeline.py). detail()/summary() render epochs for
        humans; trace-event ``ts`` stays monotonic so one payload-level
        anchor aligns everything at the stitching boundary."""
        with self._lock:
            recs = list(self._done) + sorted(self._live.values(),
                                             key=lambda r: r.enqueued_at)
            return [{
                "id": r.id,
                "trace_id": r.trace_id,
                "enqueued_at": r.enqueued_at,
                "admitted_at": r.admitted_at,
                "first_token_at": r.first_token_at,
                "finished_at": r.finished_at,
                "generated": r.generated,
                "outcome": r.outcome,
                "handoff": r.handoff,
            } for r in recs]

    def lookup(self, request_id: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._live.get(request_id)
            if rec is None:
                for done in self._done:
                    if done.id == request_id:
                        rec = done
                        break
            return rec.detail() if rec is not None else None

    def lookup_trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """Every record (live + done) sharing a W3C trace id, oldest
        first. A trace can own several records on one recorder (retried
        requests) and across recorders (the disagg prefill/decode halves
        each record the same inbound trace) — the journey assembler
        (tpu/journey.py, fleet/journey.py) stitches them by this key."""
        if not trace_id:
            return []
        with self._lock:
            records = [r for r in self._done if r.trace_id == trace_id]
            records.extend(r for r in self._live.values()
                           if r.trace_id == trace_id)
            records.sort(key=lambda r: r.wall(r.enqueued_at))
            return [r.detail() for r in records]

    def __len__(self) -> int:
        with self._lock:
            return len(self._live) + len(self._done)


def register_slo_gauges(metrics) -> None:
    """Register the goodput gauges on a metrics Manager (idempotent)."""
    for name, desc in (
        ("app_tpu_slo_ttft_goodput",
         "fraction of recent requests meeting the TTFT target"),
        ("app_tpu_slo_tpot_goodput",
         "fraction of recent requests meeting the TPOT target"),
    ):
        try:
            if metrics.get(name) is None:  # TPUClient may have registered
                metrics.new_gauge(name, desc)
        except Exception:  # noqa: BLE001 - already registered
            pass


def install_routes(app, recorder: FlightRecorder,
                   path: str = "/debug/requests") -> None:
    """Register the flight-recorder endpoints on a gofr_tpu App (the
    profiler.install_routes idiom, tpu/profiler.py)."""
    from ..http.errors import HTTPError

    @app.get(path)
    def flight_requests(ctx):  # noqa: ANN001
        return recorder.snapshot()

    @app.get(path + "/{id}")
    def flight_request_detail(ctx):  # noqa: ANN001
        raw = ctx.request.path_param("id")
        try:
            request_id = int(raw)
        except (TypeError, ValueError) as exc:
            raise HTTPError(f"invalid request id {raw!r}",
                            status_code=400) from exc
        detail = recorder.lookup(request_id)
        if detail is None:
            raise HTTPError(
                f"request {request_id} not in the flight recorder "
                f"(ring keeps the last {recorder.capacity} completions)",
                status_code=404)
        return detail
