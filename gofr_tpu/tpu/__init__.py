"""TPU serving runtime: device client, AOT executor, batching schedulers.

This is the layer-3 datasource + layer-7 runtime the SURVEY.md TPU mapping
calls for: the device client is a Container datasource (like SQL/KV), and the
schedulers bridge HTTP/gRPC/pub-sub ingress to padded XLA executions.
"""

from .device import TPUClient
from .executor import Executor, next_bucket, pad_to

__all__ = ["TPUClient", "Executor", "next_bucket", "pad_to"]
