"""Prefix cache: refcounted shared KV pages keyed by prompt content.

Serving traffic repeats prompt prefixes constantly — the OpenAI chat
surface re-sends the same system prompt on every request — and without
sharing, every admission re-prefills it from scratch. The paged pool's
block tables are exactly the substrate for fixing that (VERDICT r3
missing #3): a page is an immutable chunk of KV once written, so two
requests whose prompts agree on a whole page can point their tables at
the SAME page.

Design (vLLM-style block hashing, hardened):

  - FULL pages only. A page is shareable iff the prompt covers every one
    of its `page_size` positions; the partial tail page is always private
    (decode writes continue into it), so there is no copy-on-write to
    implement — sharing is read-only by construction. At least one tail
    token is always recomputed (the last prompt position's logits are
    needed to sample), enforced by the matcher.
  - CUMULATIVE keys. Page i's key covers tokens [0, (i+1)*ps), so a hit
    on page i implies hits on all earlier pages, and matching is a walk
    from page 0 until the first miss. Keys verify the actual token
    content (stored alongside), so a hash collision degrades to a miss,
    never to silently serving another prompt's KV.
  - REFCOUNTS, not ownership. `refs[page]` counts the slots currently
    mapping the page. A resident page with refs == 0 is evictable (LRU);
    eviction hands the page id back to the allocator's free list. The
    engine routes a finished slot's pages here first — pages the cache
    owns are unref'd and stay resident; only unknown pages free.

Host-side and loop-thread-only, like the PageAllocator it feeds.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from .ownership import loop_only


class PrefixCache:
    def __init__(self, page_size: int):
        self.page_size = page_size
        # cumulative key -> (page_id, token_tuple); insertion order = LRU
        self._entries: "OrderedDict[int, Tuple[int, tuple]]" = OrderedDict()
        self._key_of_page: Dict[int, int] = {}
        self._refs: Dict[int, int] = {}
        # chain structure for LEAF-FIRST eviction: evicting page i while
        # page i+1's entry survives would strand the child (match() walks
        # from page 0 and breaks at the missing link) as unreachable-but-
        # resident. Entries therefore only evict when childless
        self._parent: Dict[int, Optional[int]] = {}   # key -> parent key
        self._nchildren: Dict[int, int] = {}
        self.hit_pages = 0
        self.miss_pages = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    # -- introspection -------------------------------------------------------
    @property
    def resident_pages(self) -> int:
        return len(self._entries)

    def owns(self, page_id: int) -> bool:
        return page_id in self._key_of_page

    def stats(self) -> Dict[str, int]:
        lookups = self.hit_pages + self.miss_pages
        return {
            "resident_pages": self.resident_pages,
            "hit_pages": self.hit_pages,
            "miss_pages": self.miss_pages,
            "hit_rate": round(self.hit_pages / lookups, 4) if lookups else 0.0,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
        }

    def digest(self, k: int = 16) -> List[str]:
        """Bounded O(k) list of the hottest (MRU-end) chain-key hashes,
        hex-encoded.  The cheap probe payload for fleet routers — never
        the full entry table, which is O(pool)."""
        hot = list(self._entries)[-max(0, k):]
        return [format(key & 0xFFFFFFFFFFFFFFFF, "016x") for key in hot]

    # -- key construction ----------------------------------------------------
    def _keys_for(self, tokens: Sequence[int], n_pages: int) -> List[int]:
        """Cumulative chain keys for the first n_pages full pages."""
        keys = []
        h = 0
        ps = self.page_size
        for i in range(n_pages):
            h = hash((h, tuple(tokens[i * ps:(i + 1) * ps])))
            keys.append(h)
        return keys

    def keys_for(self, tokens: Sequence[int], n_pages: int) -> List[int]:
        """Public chain keys: the host/Redis KV tiers address spilled page
        blobs by the SAME cumulative keys, so a tier lookup for page i of
        a prompt is exactly keys_for(prompt, i+1)[-1]."""
        return self._keys_for(tokens, n_pages)

    # -- the serving protocol ------------------------------------------------
    @loop_only(fields=("_entries", "_key_of_page", "_refs", "_parent",
                       "_nchildren"))
    def match(self, tokens: Sequence[int]) -> List[int]:
        """Longest run of cached full pages from page 0, with at least one
        tail token left unmatched. Acquires a ref on every matched page
        (release via unref when the slot finishes / admission aborts)."""
        ps = self.page_size
        matchable = max(0, (len(tokens) - 1) // ps)
        pages: List[int] = []
        for i, key in enumerate(self._keys_for(tokens, matchable)):
            entry = self._entries.get(key)
            if entry is None:
                break
            page_id, content = entry
            if content != tuple(tokens[i * ps:(i + 1) * ps]):
                break  # hash collision: treat as a miss, never share
            pages.append(page_id)
            self._entries.move_to_end(key)  # LRU touch
        for page_id in pages:
            self._refs[page_id] += 1
        self.hit_pages += len(pages)
        self.miss_pages += matchable - len(pages)
        return pages

    @loop_only
    def insert(self, tokens: Sequence[int], table_pages: Sequence[int]) -> None:
        """Register a freshly-prefilled prompt's full pages. table_pages is
        the slot's page list in table order (shared prefix pages first);
        already-cached pages are skipped, new ones gain a ref for the
        OWNING slot (the engine unrefs every cache-owned page at slot
        finish, so ownership and sharing release through one path)."""
        ps = self.page_size
        n_full = min(max(0, (len(tokens) - 1) // ps), len(table_pages))
        prev_key: Optional[int] = None
        for i, key in enumerate(self._keys_for(tokens, n_full)):
            page_id = table_pages[i]
            if key in self._entries:
                prev_key = key   # existing chain link (the shared prefix)
                continue
            if page_id in self._key_of_page:
                prev_key = None  # page registered under another key: the
                continue         # chain is broken here, stop linking
            self._entries[key] = (page_id, tuple(tokens[i * ps:(i + 1) * ps]))
            self._key_of_page[page_id] = key
            self._refs[page_id] = self._refs.get(page_id, 0) + 1
            self._parent[key] = prev_key
            self._nchildren.setdefault(key, 0)
            if prev_key is not None:
                self._nchildren[prev_key] = self._nchildren.get(prev_key,
                                                                0) + 1
            prev_key = key
            self.inserted_pages += 1

    @loop_only
    def unref(self, page_id: int) -> None:
        # a loud error, not assert: under python -O a silent negative ref
        # would make the page permanently fail the refs==0 eviction check —
        # an unevictable leak (ADVICE r4)
        refs = self._refs[page_id] - 1
        if refs < 0:
            raise RuntimeError(f"prefix page {page_id} over-released")
        self._refs[page_id] = refs

    @loop_only
    def evict(self, n: int) -> List[int]:
        """Reclaim up to n LRU pages with no active refs AND no resident
        children (leaf-first: a chain evicts tail-inward, never stranding
        a descendant); returns the page ids for the allocator's free
        list."""
        return [page_id for _, page_id, _ in self.evict_entries(n)]

    @loop_only
    def evict_entries(self, n: int) -> List[Tuple[int, int, tuple]]:
        """evict() with full entry detail: (chain_key, page_id, tokens)
        per reclaimed page. The tiered KV cache needs all three to spill
        the page's content to host RAM under its content-verified key
        BEFORE the page id returns to the allocator and the pool slot is
        overwritten."""
        freed: List[Tuple[int, int, tuple]] = []
        if n <= 0:
            return freed
        progress = True
        while progress and len(freed) < n:
            progress = False
            for key in list(self._entries):
                if len(freed) >= n:
                    break
                page_id, content = self._entries[key]
                if (self._refs.get(page_id, 0) != 0
                        or self._nchildren.get(key, 0) != 0):
                    continue
                parent = self._parent.pop(key, None)
                if parent is not None and parent in self._nchildren:
                    self._nchildren[parent] -= 1
                self._nchildren.pop(key, None)
                del self._entries[key]
                del self._key_of_page[page_id]
                del self._refs[page_id]
                freed.append((key, page_id, content))
                self.evicted_pages += 1
                progress = True
        return freed

    @loop_only
    def drop_all_idle(self) -> List[int]:
        """Evict every idle page (device-state reset path)."""
        return self.evict(len(self._entries))
