"""Live-traffic multi-host admission: rank 0 decides, every rank replays.

Multi-controller serving (SURVEY.md §5 distributed backend, BASELINE
config 5) requires every process in the job to issue an IDENTICAL dispatch
sequence — the compiled programs are SPMD collectives, so a wave admitted
on one rank but not another deadlocks the slice. The first multi-host
serving test satisfied that by contract (every request queued before the
loop started, tests/multihost_serving_worker.py); production traffic does
not arrive that way. This module replaces the contract with a protocol:

  * Rank 0 (the LEADER) is the single ingress: `submit()` is only legal
    there. At each engine-loop iteration the leader drains its local
    arrival queue, freezes the wave composition — request tokens, sampling
    params, priorities, plus any cancellations observed since the last
    wave — and publishes it as wave N over the jax.distributed
    coordination-service KV store: the same DCN control plane that formed
    the global device set (parallel/multihost.py), so no extra transport
    or port is needed.
  * Every FOLLOWER blocks on wave N, reconstructs shadow requests that
    reuse the leader's request ids (so the (priority, id) admission-heap
    order is bit-identical), and feeds them to the unchanged admission
    logic. From there on, both ranks' engine state evolves in lock-step:
    slot assignment, prefill buckets, page allocation, speculation EMA —
    all derived from the same wave stream.
  * Cancellation is part of the wave, not a local event: the engine reads
    `_is_cancelled` (membership in the synced set) instead of the live
    threading.Event whenever a plane is installed, so a cancel takes
    effect at the same loop iteration on every rank.
  * When nothing is active and nothing arrived, the leader publishes
    nothing and followers park in a blocking get — the idle engine costs
    no KV churn and wakes every rank on the same wave.

Reference analog: the reference reaches peer processes through its service
client (/root/reference/pkg/gofr/service/new.go:68-87) — one process acts
as ingress and fans work out over an RPC plane. Re-designed here for SPMD
lock-step: instead of load-balancing independent requests, the "RPC" is a
deterministic replay log that keeps multi-controller JAX processes
convergent.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import List, Optional, Tuple

# Waves older than this are deleted from the coordination store. Wave
# cadence exists only while dispatching (the engine passes has_work =
# dispatching work, not parked requests), and every dispatching iteration
# ends in a sync that blocks on the follower joining the collective — so
# the leader can run at most ~pipeline_depth waves ahead of any follower,
# and a generous constant bounds store growth without an ack channel.
_DELETE_LAG = 256


class InProcKV:
    """Dict-backed KV with blocking gets: the single-process test double
    for the coordination service (two planes in one process share one)."""

    def __init__(self):
        self._data = {}
        self._cond = threading.Condition()

    def set(self, key: str, value: str) -> None:
        with self._cond:
            self._data[key] = value
            self._cond.notify_all()

    def get_blocking(self, key: str, timeout_s: float) -> str:
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while key not in self._data:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(key)
                self._cond.wait(timeout=left)
            return self._data[key]

    def delete(self, key: str) -> None:
        with self._cond:
            self._data.pop(key, None)


class CoordinationKV:
    """The jax.distributed coordination-service KV store.

    Uses the internal client handle (jax._src.distributed.global_state) —
    the same store jax.experimental.multihost_utils rides for its
    barriers; tests/test_multihost_exec.py exercises it for real across
    two processes, so a jax upgrade that moves the handle fails loudly
    there rather than silently here.
    """

    def __init__(self):
        from jax._src import distributed

        client = getattr(distributed.global_state, "client", None)
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized — call "
                "parallel.multihost.initialize_from_config() first")
        self._client = client

    def set(self, key: str, value: str) -> None:
        self._client.key_value_set(key, value)

    def get_blocking(self, key: str, timeout_s: float) -> str:
        try:
            return self._client.blocking_key_value_get(
                key, int(timeout_s * 1000))
        except Exception as exc:  # jaxlib surfaces DEADLINE_EXCEEDED as XlaRuntimeError
            raise TimeoutError(f"{key}: {exc}") from exc

    def delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(key)
        except Exception:  # noqa: BLE001 - cleanup is best-effort
            pass


class _DiscardQueue:
    """Shadow requests have no consumer; their token stream must not
    accumulate. Swapped in for out_queue unless a shadow hook opts in."""

    def put(self, item) -> None:
        pass

    def get(self, timeout=None):
        raise queue.Empty

    def get_nowait(self):
        raise queue.Empty

    def qsize(self) -> int:
        return 0


def _spec(request) -> dict:
    return {"id": request.id, "prompt": request.prompt_tokens,
            "max_new": request.max_new_tokens, "temp": request.temperature,
            "stop": sorted(request.stop_tokens), "prio": request.priority,
            "min": request.min_tokens, "top_p": request.top_p,
            "top_k": request.top_k,
            # QoS identity rides the wave so follower shadows account
            # classes identically; prio already carries the band
            "qos": getattr(request, "qos_class", None),
            "tenant": getattr(request, "tenant", "")}


class AdmissionPlane:
    """One per engine per process. Leader publishes waves; followers replay.

    The engine calls `exchange()` once per loop iteration (under its state
    lock) and consults `synced_cancelled` instead of per-request live
    cancel events. `close()` publishes a stop sentinel so idle followers
    unpark promptly at shutdown.
    """

    def __init__(self, process_id: Optional[int] = None, kv=None,
                 prefix: str = "gofr/admit", wave_timeout_s: float = 120.0):
        if process_id is None:
            import jax

            process_id = jax.process_index()
        self.process_id = process_id
        self.kv = kv if kv is not None else CoordinationKV()
        self.prefix = prefix
        self.wave_timeout_s = wave_timeout_s
        self._seq = 0
        self._live = {}  # id -> request (leader: real; follower: shadow)
        self.synced_cancelled = set()
        self._closed = False
        self._drain_sent = False
        # the engine wires its stop event here so a parked follower can
        # abandon the wave wait when its own process shuts down first
        self.stop_event: Optional[threading.Event] = None
        # follower test/consumer hook: called with each shadow request
        # BEFORE admission; when set, shadows keep a real out_queue so the
        # hook's owner can read the mirrored token stream
        self.on_shadow = None

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    @property
    def closed(self) -> bool:
        return self._closed

    def _key(self, seq: int) -> str:
        return f"{self.prefix}/{seq}"

    def exchange(self, drained: List[Tuple[int, int, object]],
                 has_work: bool,
                 draining: bool = False) -> Tuple[List[Tuple[int, int, object]], bool]:
        """One admission wave. `drained` is what the leader pulled from its
        local queue this iteration (followers pass []); `has_work` is
        whether mirrored engine state has anything in flight — it must be
        computed from state every rank shares, because it decides whether
        this iteration carries a wave at all; `draining` (leader-local
        decision) rides the wave so every rank fails its parked heap at
        the same iteration. Returns (heap entries to admit, drain flag) —
        identical on every rank."""
        self._prune()
        if self._closed:
            return [], False
        if self.is_leader:
            return self._publish(drained, has_work, draining)
        return self._consume(has_work)

    def _publish(self, drained, has_work, draining):
        cancels = [rid for rid, req in self._live.items()
                   if req.cancelled.is_set()
                   and rid not in self.synced_cancelled]
        if draining:
            # drain cadence: every iteration while work remains (followers
            # are in lock-step consuming), then once more so a PARKED
            # follower learns the drain too; after that, silence until
            # close() — an idle draining loop must not flood the store
            if not has_work and not cancels and self._drain_sent:
                return [], True
            payload = {"drain": True, "cancel": cancels}
            self._drain_sent = True
        else:
            if not drained and not cancels and not has_work:
                return [], False  # idle, nothing new: followers stay parked
            payload = {"reqs": [_spec(entry[2]) for entry in drained],
                       "cancel": cancels}
        self.kv.set(self._key(self._seq), json.dumps(payload))
        if self._seq >= _DELETE_LAG:
            self.kv.delete(self._key(self._seq - _DELETE_LAG))
        self._seq += 1
        self.synced_cancelled.update(cancels)
        for _, rid, request in drained:
            self._live[rid] = request
        return drained, draining

    def _consume(self, has_work):
        deadline = time.monotonic() + self.wave_timeout_s
        while True:
            try:
                raw = self.kv.get_blocking(self._key(self._seq), 0.5)
                break
            except TimeoutError:
                if self.stop_event is not None and self.stop_event.is_set():
                    return [], False
                if not has_work:
                    # idle: yield back to the engine loop instead of
                    # parking here — exchange() runs under the engine's
                    # state lock, and an indefinite in-lock wait would
                    # hang every other lock-taking API on this rank
                    # (drain timeouts, stats). _seq is untouched, so the
                    # next call resumes waiting on the same wave.
                    return [], False
                if time.monotonic() > deadline:
                    # active work on every rank but no wave: the leader is
                    # gone or wedged — surface it instead of hanging the slice
                    raise RuntimeError(
                        f"admission wave {self._seq} never arrived "
                        f"({self.wave_timeout_s}s); leader unreachable")
        self._seq += 1
        payload = json.loads(raw)
        if payload.get("stop"):
            self._closed = True
            return [], False
        entries = []
        for spec in payload.get("reqs", ()):
            request = self._shadow(spec)
            self._live[request.id] = request
            if self.on_shadow is not None:
                self.on_shadow(request)
            entries.append((request.priority, request.id, request))
        for rid in payload["cancel"]:
            self.synced_cancelled.add(rid)
            shadow = self._live.get(rid)
            if shadow is not None:
                shadow.cancelled.set()
        return entries, bool(payload.get("drain"))

    def _shadow(self, spec):
        from .engine import GenerationRequest

        request = GenerationRequest(
            spec["prompt"], max_new_tokens=spec["max_new"],
            temperature=spec["temp"], stop_tokens=set(spec["stop"]),
            priority=spec["prio"], min_tokens=spec["min"],
            top_p=spec["top_p"], top_k=spec["top_k"],
            qos_class=spec.get("qos"), tenant=spec.get("tenant", ""))
        # the leader's id keeps (priority, id) heap order bit-identical
        request.id = spec["id"]
        if self.on_shadow is None:
            request.out_queue = _DiscardQueue()
        return request

    def _prune(self) -> None:
        """Drop finished requests from the live registry. Terminal state
        (finished_at / error) is set by engine transitions that happen at
        the same loop iteration on every rank, so pruning stays symmetric."""
        done = [rid for rid, req in self._live.items()
                if req.finished_at is not None or req.error is not None]
        for rid in done:
            del self._live[rid]
            self.synced_cancelled.discard(rid)

    def close(self) -> None:
        """Leader: publish the stop sentinel so parked followers unblock.
        Follower: stop consuming. Idempotent."""
        if self.is_leader and not self._closed:
            self.kv.set(self._key(self._seq), json.dumps({"stop": True}))
            self._seq += 1
        self._closed = True
