"""Dynamic batching scheduler: time/size-windowed batch assembly over a queue.

This is the layer-7 runtime from SURVEY.md §1's TPU mapping: HTTP/gRPC/pub-sub
handlers enqueue {input, future} and block on the future (the reference's
per-request-goroutine model, handler.go:58-63, maps to a thread waiting on a
Future); the scheduler's device loop assembles padded batches and demuxes
results. Batch dim is padded to power-of-two buckets to bound XLA compilation
count; sequence dim likewise when `seq_axis` is set.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional, Sequence

from .executor import Executor, next_bucket, pad_to
from .obs import MetricsHook
from .qos import normalize_class

BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class _WorkItem:
    __slots__ = ("payload", "future", "enqueued_at", "qos_class", "tenant")

    def __init__(self, payload, qos_class=None, tenant=""):
        self.payload = payload
        self.future: Future = Future()
        # monotonic like the engine's request stamps: TTFT math must not
        # bend under an NTP step
        self.enqueued_at = time.monotonic()
        # QoS accounting (tpu/qos.py): the batcher assembles batches FIFO
        # (no class reordering — items share one padded dispatch), but
        # the class still rides along validated so mixed surfaces report
        # per-class latency consistently with the engine path
        self.qos_class = qos_class
        self.tenant = tenant


class DynamicBatcher:
    """Batches single-example payloads into padded model calls.

    model_fn(batch) -> batch of outputs. Payloads are numpy/JAX arrays whose
    leading axis is the example (so a payload of shape [T, ...] becomes row b
    of a [B, T, ...] batch). When examples vary along `seq_axis`, each is
    padded to the batch's sequence bucket.
    """

    def __init__(
        self,
        model_fn: Callable,
        executor: Optional[Executor] = None,
        max_batch: int = 32,
        window_s: float = 0.005,
        batch_buckets: Sequence[int] = BATCH_BUCKETS,
        seq_axis: Optional[int] = None,
        seq_buckets: Sequence[int] = (16, 32, 64, 128, 256, 512, 1024, 2048),
        pad_value=0,
        name: str = "dynamic-batcher",
        metrics=None,
        logger=None,
    ):
        self.model_fn = model_fn
        self.executor = executor or Executor()
        self.max_batch = max_batch
        self.window_s = window_s
        self.batch_buckets = tuple(b for b in batch_buckets if b <= max_batch) or (max_batch,)
        self.seq_axis = seq_axis
        self.seq_buckets = seq_buckets
        self.pad_value = pad_value
        self.name = name
        self.metrics = metrics if metrics is not None else self.executor.metrics
        self.logger = logger
        self._obs = MetricsHook(self.metrics)
        self._queue: "queue.Queue[_WorkItem]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ingress --------------------------------------------------------------
    def submit(self, payload, qos_class=None, tenant: str = "") -> Future:
        if self._stop.is_set():
            raise RuntimeError("batcher is stopped")
        # unknown class strings die here with a typed 400 (InvalidParam),
        # never a silent default — same contract as engine.submit
        qos_class = normalize_class(qos_class)
        if self.seq_axis is not None and hasattr(payload, "shape"):
            # reject oversized payloads here so one bad request can't fail
            # the whole co-assembled batch in _run_batch
            seq_len = payload.shape[self.seq_axis]
            if seq_len > self.seq_buckets[-1]:
                raise ValueError(f"sequence of {seq_len} exceeds the largest "
                                 f"bucket ({self.seq_buckets[-1]})")
        item = _WorkItem(payload, qos_class=qos_class, tenant=tenant)
        self._queue.put(item)
        self._obs.gauge("app_tpu_queue_depth", self._queue.qsize())
        return item.future

    def infer(self, payload, timeout_s: Optional[float] = None):
        """Blocking convenience: submit and wait (what HTTP handlers call)."""
        return self.submit(payload).result(timeout=timeout_s)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, name=self.name, daemon=True)
        self._thread.start()

    # stop() waits this long for the loop thread before declaring it stuck
    # (class attr so tests can tighten it)
    STOP_JOIN_S = 5.0

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=self.STOP_JOIN_S)
            if thread.is_alive():
                # still inside _run_batch (device call): failing the queue
                # here would race the live loop's own future completion —
                # double-completing a Future raises InvalidStateError in
                # whichever thread loses. The live loop drains the queue
                # itself when it exits; just shout and leave it to it.
                if self.logger is not None:
                    self.logger.errorf(
                        "batcher %s loop still running after %.0fs; leaving "
                        "queue draining to the live loop", self.name,
                        self.STOP_JOIN_S)
                return
            self._thread = None
        self._fail_queued(RuntimeError("batcher stopped"))

    def _fail_queued(self, exc: BaseException) -> None:
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if not item.future.done():
                item.future.set_exception(exc)

    # -- device loop ----------------------------------------------------------
    def _collect(self) -> list:
        """Block for the first item, then fill the batch inside the window."""
        try:
            first = self._queue.get(timeout=0.1)
        except queue.Empty:
            return []
        items = [first]
        deadline = time.monotonic() + self.window_s
        while len(items) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                items.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                break
        return items

    def _loop(self) -> None:
        while not self._stop.is_set():
            items = self._collect()
            if not items:
                continue
            try:
                self._run_batch(items)
            except Exception as exc:  # noqa: BLE001 - fail the batch, keep serving
                if self.logger is not None:
                    self.logger.errorf("batch failed: %s", exc)
                for item in items:
                    if not item.future.done():
                        item.future.set_exception(exc)
        # the loop owns queue draining on the way out: when stop() timed
        # out waiting (loop was mid-batch), items that queued behind that
        # batch still need a terminal outcome — and completing them HERE
        # (the only thread that also completes batch futures) is what makes
        # the stop()/_run_batch race impossible by construction
        self._fail_queued(RuntimeError("batcher stopped"))

    def _run_batch(self, items: list) -> None:
        import jax.numpy as jnp
        import numpy as np

        n = len(items)
        bucket = next_bucket(n, self.batch_buckets)
        payloads = [item.payload for item in items]

        if self.seq_axis is not None:
            max_len = max(p.shape[self.seq_axis] for p in payloads)
            seq_bucket = next_bucket(max_len, self.seq_buckets)
            payloads = [pad_to(p, seq_bucket, axis=self.seq_axis, value=self.pad_value)
                        for p in payloads]

        batch = np.stack([np.asarray(p) for p in payloads])
        if bucket > n:  # pad batch dim with copies of row 0 (cheap, discarded)
            fill = np.broadcast_to(batch[:1], (bucket - n,) + batch.shape[1:])
            batch = np.concatenate([batch, fill], axis=0)

        outputs = self.executor.run(self.name, self.model_fn, jnp.asarray(batch))

        self._obs.hist("app_tpu_batch_size", n)
        self._obs.gauge("app_tpu_queue_depth", self._queue.qsize())
        outputs = np.asarray(outputs)  # lint: hotloop-ok the batch lane's designated materialization; rows return to waiters via futures
        now = time.monotonic()
        for i, item in enumerate(items):
            if not item.future.done():
                item.future.set_result(outputs[i])
            self._obs.hist("app_tpu_ttft_seconds", now - item.enqueued_at)

