"""Replica-side elasticity: lifecycle advertisement, drain-with-migration,
and warm-boot pre-warm.

A fleet replica is more than UP/DOWN once the fleet breathes
(fleet/elastic.py): it boots ``warming`` (compile cache + prefix pre-warm
running, router must not send cold-TTFT traffic), serves as ``serving``,
and leaves through ``draining`` — no new sessions, in-flight streams
finish, and still-LIVE sessions *migrate* to a peer instead of holding the
replica hostage for their full generation.

Migration reuses two existing contracts end to end:

- the PR 9 hand-off envelope (tpu/disagg.py encode/decode_handoff): the
  engine exports each live slot's KV as page blobs at a quiesced step
  boundary (engine.request_migration), the coordinator ships
  ``POST /migrate`` to a peer, and the peer lands it via submit_handoff —
  the same donated H2D restore the disagg decode pool runs.
- the crash-only replay ladder (PR 3): every failure ANYWHERE degrades,
  never drops. Peer rejects the blobs → peer recomputes prompt+emitted
  (its own _handoff_fallback). Peer unreachable → next peer → local
  resume on this engine (it is not draining yet — migration runs BEFORE
  engine.drain). Peer dies mid-relay → the relayed tokens are already in
  ``request.emitted``, so a blob-less local resume continues the stream
  exactly where it broke. The only terminal error is an engine that can
  no longer serve at all.

The stream never changes hands from the client's point of view: relayed
tokens land on the original request's out_queue, exactly like a disagg
hand-off.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .disagg import decode_handoff, encode_handoff
from .kvtier import decode_blob
from .obs import MetricsHook

LIFECYCLE_STATES = ("warming", "serving", "draining")

MIGRATIONS_TOTAL = "app_tpu_elastic_migrations_total"


class Lifecycle:
    """Thread-safe replica lifecycle state, advertised in the /stats
    fleet digest so routers (fleet/registry.py) gate routing on it:
    ``warming`` and ``draining`` replicas receive no new sessions."""

    def __init__(self, state: str = "serving", clock=time.monotonic):
        if state not in LIFECYCLE_STATES:
            raise ValueError(f"lifecycle state must be one of "
                             f"{LIFECYCLE_STATES}, got {state!r}")
        self._clock = clock
        self._lock = threading.Lock()
        self._state = state
        self._since = clock()
        self._trail: List[Dict[str, Any]] = []

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def to(self, state: str) -> bool:
        """Transition; False when already there. draining is terminal —
        a draining replica never un-drains (restart it instead: the
        generation bump tells routers it is a fresh boot)."""
        if state not in LIFECYCLE_STATES:
            raise ValueError(f"unknown lifecycle state {state!r}")
        with self._lock:
            if self._state == state or self._state == "draining":
                return False
            self._trail.append({"from": self._state, "to": state,
                                "t": self._clock()})
            self._state = state
            self._since = self._clock()
            return True

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self._state,
                    "since_s": round(self._clock() - self._since, 1),
                    "trail": list(self._trail)}


def admit_migration(engine, envelope: Dict[str, Any]):
    """Land one shipped migration on THIS engine — the peer half of
    drain-with-migration, sharing the disagg decode pool's trust model:
    any page that fails decode_blob's crc poisons the whole hand-off
    down to a blob-less recompute; submit_handoff's admission then
    content-verifies surviving blobs against the resume window. Returns
    the GenerationRequest whose stream() carries the continuation.
    Raises ValueError on a structurally-bad spec (transport 400s) and
    lets shed errors (503-shaped) propagate."""
    spec = envelope.get("spec")
    if not isinstance(spec, dict):
        raise ValueError("envelope has no spec")
    blobs = None
    raw_blobs = envelope.get("blobs")
    if raw_blobs is not None and getattr(engine, "_lands_handoffs", False):
        decoded = [decode_blob(raw) for raw in raw_blobs]
        if all(b is not None for b in decoded):
            blobs = decoded
        # else: corrupt in flight — recompute is cheaper than wrong KV
    try:
        return engine.submit_handoff(
            spec["prompt"], spec["emitted"],
            max_new_tokens=spec["max_new"],
            temperature=spec["temp"],
            stop_tokens=set(spec["stop"]),
            priority=spec["prio"],
            min_tokens=spec["min"],
            top_p=spec["top_p"], top_k=spec["top_k"],
            traceparent=envelope.get("traceparent"),
            blobs=blobs,
            qos_class=spec.get("qos"),
            tenant=spec.get("tenant", ""))
    except (KeyError, TypeError) as exc:
        raise ValueError(f"malformed migration spec: {exc}") from exc


class MigrationCoordinator:
    """Owns one replica's drain: flip the lifecycle, export live sessions
    from the engine, ship each to a peer's /migrate and relay the
    continuation into the original client stream, then drain the engine
    for whatever chose to finish locally.

    begin_drain() is idempotent (the double-drain operator fat-finger is
    a no-op returning current status) and returns immediately; the drain
    runs on its own thread and status() reports progress — the shape the
    router-side DrainOrchestrator polls before terminating the process."""

    def __init__(self, engine, lifecycle: Optional[Lifecycle] = None, *,
                 metrics=None, logger=None,
                 client_factory: Optional[Callable[[str], Any]] = None,
                 ship_timeout_s: float = 60.0):
        self.engine = engine
        self.lifecycle = lifecycle or Lifecycle()
        self.logger = logger
        self._obs = MetricsHook(metrics, logger)
        self.ship_timeout_s = float(ship_timeout_s)
        self._client_factory = client_factory or self._default_client
        self._lock = threading.Lock()
        self._drain_started = False
        self._drain_thread: Optional[threading.Thread] = None
        self._engine_drained: Optional[bool] = None
        self._relays_live = 0
        # outcome ledger (plain dict under _lock): exported sessions by
        # how their stream continued
        self.outcomes: Dict[str, int] = {
            "shipped": 0,        # peer restored (blobs or recompute)
            "local_resume": 0,   # every peer refused; resumed here
            "relay_break": 0,    # peer died mid-relay; resumed here
            "cancelled": 0,      # client cancelled during the hop
            "failed": 0,         # nothing could continue the stream
        }
        self.sessions: List[Dict[str, Any]] = []

    def _default_client(self, address: str):
        from ..service import HTTPService

        return HTTPService(address, logger=self.logger,
                           timeout_s=self.ship_timeout_s)

    # -- operator surface -----------------------------------------------------

    def begin_drain(self, peers: Sequence[str] = (), *,
                    timeout_s: float = 30.0,
                    migrate: bool = True) -> Dict[str, Any]:
        """Start (or observe, when already started) this replica's drain.
        peers: base URLs eligible to receive live sessions, tried in
        order per session. migrate=False skips the export round — pure
        connection-drain, in-flight streams finish locally."""
        with self._lock:
            already = self._drain_started
            self._drain_started = True
        if already:
            return self.status()
        self.lifecycle.to("draining")
        peers = [str(p).rstrip("/") for p in peers if p]
        thread = threading.Thread(
            target=self._run_drain, args=(peers, float(timeout_s), migrate),
            name="elastic-drain", daemon=True)
        self._drain_thread = thread
        thread.start()
        return self.status()

    def status(self) -> Dict[str, Any]:
        lifecycle = self.lifecycle.snapshot()  # before _lock: no nesting
        migrations = getattr(self.engine, "migrations_total", 0)
        with self._lock:
            out = {
                "lifecycle": lifecycle,
                "drain_started": self._drain_started,
                "engine_drained": self._engine_drained,
                "relays_live": self._relays_live,
                "outcomes": dict(self.outcomes),
                "sessions": list(self.sessions),
                "migrations_total": migrations,
            }
        out["drained"] = (out["engine_drained"] is True
                          and out["relays_live"] == 0)
        return out

    # -- drain machinery (its own thread) -------------------------------------

    def _run_drain(self, peers: List[str], timeout_s: float,
                   migrate: bool) -> None:
        exported: List[tuple] = []
        if migrate and peers and getattr(self.engine, "_plane", None) is None:
            def sink(request, blobs, n_ctx) -> bool:
                exported.append((request, blobs, n_ctx))
                return True  # ownership taken: the ship ladder below
                # guarantees the stream continues somewhere

            try:
                self.engine.request_migration(sink)
            except RuntimeError:
                pass  # multi-controller engine: plain drain below
            else:
                deadline = time.monotonic() + min(timeout_s, 15.0)
                while (self.engine.migration_pending
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
        relays = []
        for request, blobs, n_ctx in exported:
            t = threading.Thread(
                target=self._ship_session,
                args=(request, blobs, n_ctx, peers),
                name=f"elastic-relay-{request.id}", daemon=True)
            with self._lock:
                self._relays_live += 1
            t.start()
            relays.append(t)
        # relays must settle BEFORE engine.drain(): a failed ship
        # local-resumes via submit_handoff, which a draining engine
        # would shed — the resume floor only holds while admission is
        # open.  (A resumed session then decodes as an ACTIVE slot,
        # which drain below waits out.)
        for t in relays:
            t.join(timeout=self.ship_timeout_s)
        # whatever stayed (sink refused / admitted after the round /
        # local resume / the engine could not export) finishes locally
        # under the drain
        drained = False
        try:
            drained = bool(self.engine.drain(timeout_s))
        except Exception:  # noqa: BLE001 - a broken drain still reports
            drained = False
        with self._lock:
            self._engine_drained = drained

    def _note(self, outcome: str, request, peer: Optional[str],
              gap_s: Optional[float]) -> None:
        with self._lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
            self.sessions.append({
                "rid": request.id, "outcome": outcome, "peer": peer,
                "emitted": len(request.emitted),
                # TTFT evidence for the migrated stream: seconds between
                # the export (last local token possible) and the first
                # token the peer produced
                "gap_s": None if gap_s is None else round(gap_s, 3),
            })
        self._obs.counter(MIGRATIONS_TOTAL, phase=outcome)
        if gap_s is not None:
            self._obs.hist("app_tpu_elastic_migration_gap_seconds", gap_s)

    def _ship_session(self, request, blobs, n_ctx: int,
                      peers: List[str]) -> None:
        try:
            payload = encode_handoff(request, blobs, n_ctx)
            for peer in peers:
                if request.cancelled.is_set():
                    request.out_queue.put(None)
                    self._note("cancelled", request, peer, None)
                    return
                outcome, gap_s = self._relay_via_peer(request, payload,
                                                      peer)
                if outcome == "shipped":
                    self._note("shipped", request, peer, gap_s)
                    return
                if outcome == "cancelled":
                    self._note("cancelled", request, peer, gap_s)
                    return
                if outcome == "broken":
                    # tokens already relayed live in request.emitted, so
                    # a blob-less local resume continues exactly past the
                    # break — the blobs only cover the exported n_ctx and
                    # are stale now
                    self._local_resume(request, None, "relay_break")
                    return
                # "unstarted": nothing reached the client; next peer
            self._local_resume(request, blobs, "local_resume")
        except Exception as exc:  # noqa: BLE001 - the relay thread must
            # never die with the stream still open
            self._fail_stream(request, exc)
        finally:
            with self._lock:
                self._relays_live -= 1

    def _relay_via_peer(self, request, payload: str, peer: str):
        """One attempt: POST the envelope, relay the SSE token stream
        into the client's queue. Returns (outcome, first_token_gap_s):
        'shipped' (terminal done relayed), 'cancelled', 'broken' (died
        AFTER tokens flowed), 'unstarted' (safe to retry elsewhere)."""
        try:
            client = self._client_factory(peer)
            resp = client.request(
                None, "POST", "/migrate", body=payload,
                headers={"Content-Type": "application/json"},
                stream=True, timeout_s=self.ship_timeout_s)
        except Exception:  # noqa: BLE001 - connect refusal == unstarted
            return "unstarted", None
        if resp.status_code != 200:
            resp.close()
            return "unstarted", None
        started = False
        gap_s = None
        exported_at = request.finished_at  # stamped by the export round
        try:
            for event in _iter_sse(resp):
                if request.cancelled.is_set():
                    request.out_queue.put(None)
                    return "cancelled", gap_s
                if "t" in event:
                    token = int(event["t"])
                    if not started:
                        started = True
                        if exported_at is not None:
                            gap_s = max(0.0,
                                        time.monotonic() - exported_at)
                    # the replay ledger grows with the relay so a
                    # mid-relay break resumes past every delivered token
                    request.emitted.append(token)
                    request.generated = len(request.emitted)
                    request.out_queue.put(token)
                elif event.get("done"):
                    request.out_queue.put(None)
                    return "shipped", gap_s
                elif "error" in event:
                    break  # peer engine failed the continuation
        except Exception:  # noqa: BLE001 - transport death mid-stream
            pass
        finally:
            resp.close()
        return ("broken" if started else "unstarted"), gap_s

    def _local_resume(self, request, blobs, outcome: str) -> None:
        """Continue the stream on THIS engine. Always legal during the
        migration window: engine.drain() runs after the export round, so
        the engine is not draining yet; a hand-off outranks everything
        in admission, so the resume lands ahead of any stragglers."""
        if request.max_new_tokens - len(request.emitted) <= 0:
            request.out_queue.put(None)  # budget fully delivered
            self._note(outcome, request, None, None)
            return
        try:
            resumed = self.engine.submit_handoff(
                request.prompt_tokens, list(request.emitted),
                max_new_tokens=request.max_new_tokens,
                temperature=request.temperature,
                stop_tokens=set(request.stop_tokens),
                priority=request.priority,
                min_tokens=request.min_tokens,
                top_p=request.top_p, top_k=request.top_k,
                traceparent=request.traceparent,
                out_queue=request.out_queue,
                cancelled=request.cancelled,
                blobs=blobs if getattr(self.engine, "_lands_handoffs",
                                       False) else None,
                qos_class=getattr(request, "qos_class", None),
                tenant=getattr(request, "tenant", ""))
            # submit_handoff only QUEUES the resume; admission runs on
            # the loop thread.  engine.drain() (which _run_drain calls
            # once every relay settles) fails queued work fast, so hold
            # this relay open until the resume binds a slot — or
            # terminates on its own — before letting the drain proceed.
            deadline = time.monotonic() + min(10.0, self.ship_timeout_s)
            while time.monotonic() < deadline:
                if (resumed.error is not None
                        or resumed.finished_at is not None
                        or any(s.request is resumed for s in
                               getattr(self.engine, "slots", ()))):
                    break
                time.sleep(0.01)
            self._note(outcome, request, None, None)
        except Exception as exc:  # noqa: BLE001 - the floor gave way
            self._fail_stream(request, exc)

    def _fail_stream(self, request, exc: BaseException) -> None:
        if self.logger is not None:
            try:
                self.logger.errorf("migration of %s failed terminally: %s",
                                   request.id, exc)
            except Exception:  # noqa: BLE001
                pass
        request.error = exc
        request.out_queue.put(None)
        self._note("failed", request, None, None)


def _iter_sse(resp):
    """Incremental SSE parse over a streamed ServiceResponse: yields each
    ``data: {...}`` JSON payload as it arrives."""
    buf = b""
    for chunk in resp.iter_chunks():
        if not chunk:
            continue
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            line = line.strip()
            if line.startswith(b"data:"):
                try:
                    yield json.loads(line[5:].strip())
                except Exception:  # noqa: BLE001 - torn frame, skip
                    continue


def prewarm_from_peers(engine, peers: Sequence[str], *,
                       limit: int = 64, logger=None,
                       client_factory: Optional[Callable] = None) -> int:
    """Warm-boot pre-warm: pull peer /debug/kvtier inventories and warm
    this engine's host tier through tier.get() (shared Redis cold-tier
    hits promote into host RAM, content-verified). Best-effort by
    design — a missing peer or absent tier warms nothing and the boot
    continues; pages the shared tier no longer holds are simply misses."""
    warm = getattr(engine, "prewarm_from_tier", None)
    if warm is None or getattr(engine, "kv_tier", None) is None:
        return 0
    factory = client_factory
    if factory is None:
        from ..service import HTTPService

        factory = lambda addr: HTTPService(addr, logger=logger,  # noqa: E731
                                           timeout_s=5.0)
    warmed = 0
    for peer in peers:
        if warmed >= limit:
            break
        try:
            resp = factory(str(peer).rstrip("/")).request(
                None, "GET", "/debug/kvtier")
            if resp.status_code != 200:
                continue
            rows = (resp.json() or {}).get("pages", [])
        except Exception:  # noqa: BLE001 - peer gone == nothing to warm
            continue
        warmed += warm(rows, limit=limit - warmed)
    if logger is not None and warmed:
        try:
            logger.infof("pre-warmed %d KV pages from %d peer(s)",
                         warmed, len(list(peers)))
        except Exception:  # noqa: BLE001
            pass
    return warmed


def register_migration_metrics(metrics) -> None:
    """Idempotent registration of the replica-side app_tpu_elastic_*
    series (the fleet side registers its own in fleet/elastic.py)."""
    for name, desc in (
        (MIGRATIONS_TOTAL,
         "drain-with-migration sessions by phase: export (engine "
         "evacuated the slot), then one stream outcome — shipped, "
         "local_resume, relay_break, cancelled, failed"),
        ("app_tpu_elastic_prewarm_pages_total",
         "KV pages promoted into host RAM by warm-boot pre-warm"),
    ):
        try:
            if metrics.get(name) is None:
                metrics.new_counter(name, desc)
        except Exception:  # noqa: BLE001 - already registered
            pass
    try:
        if metrics.get("app_tpu_elastic_migration_gap_seconds") is None:
            metrics.new_histogram(
                "app_tpu_elastic_migration_gap_seconds",
                "stream gap a migrated session observed: export to first "
                "peer-produced token (the migrated-TTFT evidence)")
    except Exception:  # noqa: BLE001
        pass


def install_migration_routes(app, engine,
                             coordinator: MigrationCoordinator) -> None:
    """Replica-side elastic surface:

    - ``POST /migrate`` — land a peer's exported session (SSE stream of
      raw token ids ``{"t": id}`` then ``{"done": true}``; raw ids, not
      decoded text, so the relay is token-exact across the hop).
    - ``POST /debug/drain`` — begin drain-with-migration
      (body: ``{"peers": [...], "timeout_s": 30, "migrate": true}``).
    - ``GET /debug/drain`` — drain/migration status.
    - ``GET /debug/kvtier`` — bounded host-tier page inventory for
      peers' warm-boot pre-warm.
    """
    from .. import Stream
    from ..http.errors import InvalidParam, ServiceUnavailable

    @app.post("/migrate")
    def _migrate(ctx):
        envelope = decode_handoff(json.dumps(ctx.bind() or {}))
        if envelope is None:
            raise InvalidParam(["envelope"])
        try:
            request = admit_migration(engine, envelope)
        except ValueError as exc:
            raise InvalidParam([str(exc)]) from exc
        except Exception as exc:  # noqa: BLE001 - sheds → 503 + Retry-After
            if getattr(exc, "status_code", None) == 503:
                raise ServiceUnavailable(
                    str(exc),
                    retry_after_s=getattr(exc, "retry_after_s", None)
                    or 1.0) from exc
            raise

        def chunks():
            count = 0
            for token in request.stream():
                count += 1
                yield {"t": int(token)}
            yield {"done": True, "tokens": count}

        return Stream(chunks(), sse=True, on_close=request.cancel)

    @app.post("/debug/drain")
    def _drain(ctx):
        body = ctx.bind() or {}
        peers = body.get("peers") or []
        if not isinstance(peers, list):
            raise InvalidParam(["peers"])
        return coordinator.begin_drain(
            [str(p) for p in peers],
            timeout_s=float(body.get("timeout_s", 30.0)),
            migrate=bool(body.get("migrate", True)))

    @app.get("/debug/drain")
    def _drain_status(ctx):  # noqa: ARG001 - gofr handler shape
        return coordinator.status()

    @app.get("/debug/kvtier")
    def _kvtier(ctx):
        limit = 64
        try:
            limit = int(ctx.request.param("limit") or 64)
        except (TypeError, ValueError):
            pass
        inv = getattr(engine, "tier_inventory", None)
        return {"pages": inv(limit) if inv is not None else []}
