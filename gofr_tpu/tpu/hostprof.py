"""Always-on host sampling profiler: which Python frames eat the loop.

The step ledger (tpu/stepledger.py) measures HOW MUCH host time each
engine iteration burns — ``loop_host_share`` in bench artifacts, the
``host_prep``/``demux``/``emit`` segments on /debug/steps — but nothing
attributes that time to CODE: when host overhead blows the step budget,
no surface says which frames the loop was sitting in. This module closes
that gap with a stdlib-only sampling profiler, cheap enough to leave on
in production:

  * a daemon thread wakes at ``HOSTPROF_HZ`` (default 50 Hz) and walks
    ``sys._current_frames()`` — one bounded dict read plus pure frame
    traversal, no tracing hooks, no interpreter slowdown between samples;
  * each sampled thread is classified via its name and graftlint's
    ownership registry (tpu/ownership.py): a thread named ``llm-engine``
    — or one whose stack contains any ``@loop_only``-marked function —
    is the engine loop; ``llm-finisher`` the finisher; the HTTP
    acceptor/handler threads http; everything else other;
  * per-class collapsed stacks (``root;caller;leaf``) aggregate into a
    bounded dict (``max_stacks`` distinct stacks per class, overflow
    counted, never grown), so memory stays O(configured) forever;
  * the sampler measures ITS OWN cost — the wall time spent inside
    sampling iterations — and reports it in its output, so "is the
    profiler cheap enough" is answered by the profiler
    (acceptance: < 2% of loop wall-clock at the default rate).

Operator surface (install_routes / App.enable_hostprof):

    GET /debug/hostprof  -> per-class top stacks + sample counts +
         measured self-overhead + collapsed text (``?collapsed=1`` for
         the raw flamegraph.pl / speedscope format)

Incident integration: IncidentManager bundles embed
``top_loop_stacks()`` so a 3 a.m. capture answers "what was the engine
loop doing" without a live process to attach to.

The sampler thread itself holds no engine state and calls no
``@loop_only`` function — it only READS foreign frames — so it is clean
under the ownership pass by construction; its stamps are all
``time.monotonic()`` so the clock pass has nothing to flag.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, List, Optional

from .obs import MetricsHook
from .ownership import LOOP_ONLY_REGISTRY

DEFAULT_HZ = 50.0
DEFAULT_MAX_STACKS = 256
DEFAULT_TOP_K = 5
MAX_DEPTH = 32
# the duty-cycle governor's ceiling on self_s/wall: when a sample gets
# expensive (many live threads, GIL contention) the sampler stretches its
# interval so the measured share converges below this, half the 2%
# always-on acceptance bound
OVERHEAD_BUDGET = 0.01

CLASSES = ("loop", "finisher", "http", "other")


class HostProfiler:
    """Bounded collapsed-stack sampler over ``sys._current_frames()``.

    start()/stop() follow the MemorySampler idiom (tpu/utilization.py):
    a daemon thread parked on an Event, stopped via App.on_shutdown.
    ``snapshot()`` is safe from any thread; aggregation state is guarded
    by one short lock the sampler holds only while folding a sample."""

    def __init__(self, hz: float = DEFAULT_HZ,
                 max_stacks: int = DEFAULT_MAX_STACKS,
                 top_k: int = DEFAULT_TOP_K, max_depth: int = MAX_DEPTH,
                 overhead_budget: float = OVERHEAD_BUDGET,
                 metrics=None, logger=None):
        self.hz = max(0.1, float(hz))
        self.interval_s = 1.0 / self.hz
        self.overhead_budget = max(1e-4, float(overhead_budget))
        self.max_stacks = max(8, int(max_stacks))
        self.top_k = max(1, int(top_k))
        self.max_depth = max(4, int(max_depth))
        self._obs = MetricsHook(metrics, logger=logger)
        self.logger = logger
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # class -> {collapsed stack -> samples}, bounded per class
        self._stacks: Dict[str, Dict[str, int]] = {c: {} for c in CLASSES}
        self._class_samples: Dict[str, int] = {c: 0 for c in CLASSES}
        self._dropped: Dict[str, int] = {c: 0 for c in CLASSES}
        self.samples_total = 0
        self._self_s = 0.0
        self._cost_ema = 0.0      # EMA of per-sample cost, feeds the governor
        self._throttled = 0       # intervals the governor stretched
        self._interval_eff = self.interval_s
        self._started_mono: Optional[float] = None

    def use_metrics(self, metrics) -> None:
        if metrics is not None:
            self._obs = MetricsHook(metrics, logger=self.logger)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._started_mono = time.monotonic()
        self._thread = threading.Thread(target=self._run,
                                        name="hostprof-sampler",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 2.0) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout_s)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop.wait(self._next_interval()):
            try:
                self.sample_once()
            except Exception as exc:  # noqa: BLE001 - keep sampling
                if self.logger is not None:
                    try:
                        self.logger.debugf("hostprof sample failed: %s",
                                           exc)
                    except Exception:  # noqa: BLE001
                        pass

    def _next_interval(self) -> float:
        """Duty-cycle governor: the sleep that keeps steady-state
        self-overhead at or below the budget even when one sample is
        expensive (many live threads, a contended GIL). At the configured
        hz the duty cycle is cost/interval; when that exceeds the budget,
        stretch the interval so cost/interval == budget."""
        with self._lock:
            cost = self._cost_ema
        wait = self.interval_s
        if cost > 0.0:
            wait = max(wait, cost / self.overhead_budget)
        with self._lock:
            if wait > self.interval_s * 1.01:
                self._throttled += 1
            self._interval_eff = wait
        return wait

    # -- sampling -------------------------------------------------------------
    def _classify(self, name: str, stack: List[str]) -> str:
        if name.startswith("llm-engine"):
            return "loop"
        if name.startswith("llm-finisher"):
            return "finisher"
        if name.startswith(("http-server", "Thread-", "grpc-")):
            return "http"
        # ownership registry fallback: a renamed/embedded engine loop is
        # still recognizable by the @loop_only functions on its stack
        if any(frame in LOOP_ONLY_REGISTRY for frame in stack):
            return "loop"
        return "other"

    def sample_once(self) -> None:
        """One sampling iteration (public so tests can drive the
        aggregation deterministically without the timer thread)."""
        t0 = time.monotonic()
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()  # noqa: SLF001 - the documented profiler API
        folded: List[tuple] = []
        for ident, frame in frames.items():
            if ident == me:
                continue  # never profile the profiler
            stack: List[str] = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                code = f.f_code
                qual = getattr(code, "co_qualname", code.co_name)
                stack.append(f"{f.f_globals.get('__name__', '?')}.{qual}")
                f = f.f_back
            stack.reverse()  # root-first, the collapsed-stack convention
            cls = self._classify(names.get(ident, ""), stack)
            folded.append((cls, ";".join(stack)))
        del frames  # frame refs pin entire stacks; drop them eagerly
        with self._lock:
            for cls, collapsed in folded:
                self._class_samples[cls] += 1
                bucket = self._stacks[cls]
                if collapsed in bucket:
                    bucket[collapsed] += 1
                elif len(bucket) < self.max_stacks:
                    bucket[collapsed] = 1
                else:
                    self._dropped[cls] += 1
            self.samples_total += 1
            dt = time.monotonic() - t0
            self._self_s += dt
            self._cost_ema = (dt if self._cost_ema == 0.0
                              else 0.2 * dt + 0.8 * self._cost_ema)
        self._obs.counter("app_tpu_hostprof_samples_total")

    # -- read-out -------------------------------------------------------------
    def _top_locked(self, cls: str, k: int) -> List[Dict[str, Any]]:
        ranked = sorted(self._stacks[cls].items(), key=lambda kv: -kv[1])
        return [{"stack": stack, "samples": count}
                for stack, count in ranked[:k]]

    def top_loop_stacks(self, k: Optional[int] = None) -> List[Dict[str, Any]]:
        """Top-K loop-thread collapsed stacks (the incident-bundle embed:
        what WAS the engine loop doing)."""
        with self._lock:
            return self._top_locked("loop", k or self.top_k)

    def collapsed(self, per_class: int = 64) -> str:
        """Flamegraph-tool text: one ``class;frame;frame count`` line per
        aggregated stack, heaviest first per class."""
        with self._lock:
            lines = [f"{cls};{entry['stack']} {entry['samples']}"
                     for cls in CLASSES
                     for entry in self._top_locked(cls, per_class)]
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self, top_k: Optional[int] = None) -> Dict[str, Any]:
        """The /debug/hostprof payload: per-class sample counts + top
        stacks, plus the sampler's measured self-overhead — reported by
        the sampler itself so its cost is never a matter of faith."""
        k = top_k or self.top_k
        now = time.monotonic()
        with self._lock:
            wall = (max(1e-9, now - self._started_mono)
                    if self._started_mono is not None else 0.0)
            threads = {cls: {
                "samples": self._class_samples[cls],
                "distinct_stacks": len(self._stacks[cls]),
                "dropped_stacks": self._dropped[cls],
                "top": self._top_locked(cls, k),
            } for cls in CLASSES}
            overhead = {
                "self_s": round(self._self_s, 6),
                "share": (round(self._self_s / wall, 6) if wall else 0.0),
                "budget": self.overhead_budget,
                "interval_s": round(self._interval_eff, 6),
                "throttled": self._throttled,
            }
            samples_total = self.samples_total
        self._obs.gauge("app_tpu_hostprof_overhead_share",
                        overhead["share"])
        return {
            "hz": self.hz,
            "running": self.running,
            "samples_total": samples_total,
            "wall_s": round(wall, 3),
            "max_stacks": self.max_stacks,
            "overhead": overhead,
            "threads": threads,
        }


def register_hostprof_metrics(metrics) -> None:
    """Idempotent registration (the register_step_metrics idiom)."""
    try:
        if metrics.get("app_tpu_hostprof_samples_total") is None:
            metrics.new_counter(
                "app_tpu_hostprof_samples_total",
                "host sampling-profiler iterations taken")
    except Exception:  # noqa: BLE001 - already registered
        pass
    try:
        if metrics.get("app_tpu_hostprof_overhead_share") is None:
            metrics.new_gauge(
                "app_tpu_hostprof_overhead_share",
                "fraction of wall-clock the sampler spent sampling "
                "(its measured self-overhead)")
    except Exception:  # noqa: BLE001
        pass


def install_routes(app, profiler: HostProfiler,
                   path: str = "/debug/hostprof") -> None:
    """Register GET /debug/hostprof on a gofr_tpu App. ``?collapsed=1``
    returns the raw flamegraph text instead of the JSON snapshot."""
    from ..http.responder import Response

    @app.get(path)
    def debug_hostprof(ctx):  # noqa: ANN001
        if (ctx.request.param("collapsed") or "") in ("1", "true"):
            return Response(
                status=200,
                headers={"Content-Type": "text/plain; charset=utf-8"},
                body=profiler.collapsed().encode())
        try:
            top_k = int(ctx.request.param("top") or 0)
        except (TypeError, ValueError):
            top_k = 0
        return profiler.snapshot(top_k=top_k or None)
