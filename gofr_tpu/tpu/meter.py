"""Capacity observatory, replica half: who consumes the device, and how
much load until it falls over.

Two coupled instruments, wired by ``App.enable_capacity``:

  * **TPUMeter** — the attribution ledger. On every step sync the engine
    stashes the synced batch's rows; when the step ledger closes the
    iteration (`_finish_step`) the meter apportions that step's measured
    device time (the ledger's ``device_sync`` + ``dispatch`` segment
    timings) across the rows, weighted by tokens processed per row, and
    charges each row's analytic FLOPs (tpu/utilization.py's 2·P·token
    math) and KV page-seconds (pages held × seconds since the row's
    previous sync, pages from ``capacity.py``'s per-token KV footprint).
    Per-request totals roll into per-(tenant, class) accounts — bounded
    tenant table + overflow pool, the PR 11 `_ClassLedger` label
    plumbing — published as the
    ``app_tpu_meter_{device_seconds,flops,page_seconds,queue_seconds}_total
    {class,tenant,phase}`` counters and served at ``GET /debug/capacity``
    with a top-K-tenants table. Conservation is by construction: the
    per-row weights sum to 1, so each step's attributed device-seconds
    sum to the step ledger's measured device segments (the property
    tests/test_meter.py proves over a live multi-tenant run).
  * **HeadroomForecaster** — the queueing model over signals the stack
    already keeps: arrival rate λ from an admission-door window (every
    ``engine.submit`` stamps an arrival), service rate μ as tokens per
    device-busy-second from the utilization ledger's rolling window (the
    replica's capacity at its CURRENT batch shape), utilization
    ρ = λ/μ, headroom μ−λ, and a fluid-model TTFT prediction
    (base prefill service + backlog/μ). A queueing-collapse
    early-warning arms when the queue depth grows monotonically across
    consecutive evaluations while ρ is near 1 — the knee where waiting
    time diverges — *before* TTFT blows past the SLO. Published as the
    ``app_tpu_capacity_{rho,headroom_tok_s,predicted_ttft_ms}`` gauges
    from the metrics scrape hook, so an idle replica's forecast decays
    to zero instead of freezing at the last burst's value.

The fleet half (rollup + ``replicas_needed``) lives in
``gofr_tpu/fleet/capacity.py``; the math and the autoscaler contract
are documented in docs/capacity.md.

Threading: ``account_step`` runs on the engine loop thread,
``note_arrival`` on submit (caller) threads, ``note_finished`` on the
off-loop finisher, ``snapshot``/``publish`` on handler/scrape threads —
one short lock each, O(rows) work, failures swallowed at the metrics
sink (MetricsHook), the zero-overhead contract when disabled
(``engine.meter is None``).
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .obs import MetricsHook
from .qos import _MAX_TENANTS, _TENANT_OVERFLOW, effective_class
from .utilization import decode_flops, prefill_flops

DEFAULT_PAGE_TOKENS = 16      # dense engines: KV billed in 16-token pages
DEFAULT_WINDOW_S = 300.0      # bounded-window spend horizon
DEFAULT_DONE_CAPACITY = 512   # finished per-request rows retained
DEFAULT_STEPS_CAPACITY = 256  # per-step attribution rows retained
DEFAULT_TOP_K = 10            # tenants shown in the /debug/capacity table


class _RequestAccount:
    """Lifetime spend of one request, folded into its tenant account at
    the same instant it accrues — tenant totals always equal the sum of
    their request accounts, exactly."""

    __slots__ = ("id", "tenant", "cls", "device_s", "flops", "page_s",
                 "queue_s", "tokens", "first_seen", "last_seen",
                 "finished_at", "ok")

    def __init__(self, request_id: int, tenant: str, cls: str,
                 now: float) -> None:
        self.id = request_id
        self.tenant = tenant
        self.cls = cls
        self.device_s = 0.0
        self.flops = 0.0
        self.page_s = 0.0
        self.queue_s = 0.0
        self.tokens: Dict[str, int] = {}
        self.first_seen = now
        self.last_seen = now
        self.finished_at: Optional[float] = None
        self.ok: Optional[bool] = None

    def row(self) -> Dict[str, Any]:
        return {
            "id": self.id, "tenant": self.tenant, "class": self.cls,
            "device_s": round(self.device_s, 6),
            "flops": self.flops,
            "page_s": round(self.page_s, 6),
            "queue_s": round(self.queue_s, 6),
            "tokens": dict(self.tokens),
            "finished": self.finished_at is not None,
            "ok": self.ok,
        }


class _TenantAccount:
    """Lifetime + bounded-window spend of one (tenant, class) pair."""

    __slots__ = ("tenant", "cls", "device_s", "flops", "page_s",
                 "queue_s", "tokens", "requests", "finished", "window")

    def __init__(self, tenant: str, cls: str) -> None:
        self.tenant = tenant
        self.cls = cls
        self.device_s = 0.0
        self.flops = 0.0
        self.page_s = 0.0
        self.queue_s = 0.0
        self.tokens: Dict[str, int] = {}
        self.requests = 0
        self.finished = 0
        # bounded recent-spend window: (finished_at, device_s) per
        # finished request — the `_ClassLedger` rolling-window idiom
        self.window: "collections.deque" = collections.deque(maxlen=128)

    def row(self, now: float, window_s: float) -> Dict[str, Any]:
        recent = sum(d for t, d in self.window if now - t <= window_s)
        return {
            "tenant": self.tenant, "class": self.cls,
            "device_s": round(self.device_s, 6),
            "flops": self.flops,
            "page_s": round(self.page_s, 6),
            "queue_s": round(self.queue_s, 6),
            "tokens": dict(self.tokens),
            "requests": self.requests,
            "finished": self.finished,
            "window_device_s": round(recent, 6),
        }


class TPUMeter:
    """Per-tenant device-time / FLOPs / page-seconds attribution ledger
    (module docstring has the model; docs/capacity.md the worked math)."""

    def __init__(self, cfg=None, page_tokens: int = DEFAULT_PAGE_TOKENS,
                 window_s: float = DEFAULT_WINDOW_S,
                 done_capacity: int = DEFAULT_DONE_CAPACITY,
                 steps_capacity: int = DEFAULT_STEPS_CAPACITY,
                 top_k: int = DEFAULT_TOP_K,
                 metrics=None, logger=None) -> None:
        self.cfg = cfg
        self.page_tokens = max(1, int(page_tokens))
        self.window_s = max(1.0, float(window_s))
        self.top_k = max(1, int(top_k))
        self._obs = MetricsHook(metrics, logger=logger)
        self.logger = logger
        # forecaster ride-along: engine.submit calls note_arrival on the
        # ONE engine.meter attribute; the meter forwards
        self.forecaster: Optional["HeadroomForecaster"] = None
        self._lock = threading.Lock()
        self._live: Dict[int, _RequestAccount] = {}
        self._done: "collections.deque" = collections.deque(
            maxlen=max(16, int(done_capacity)))
        # late-attribution map: the off-loop finisher can fold a request
        # before the loop thread delivers the SAME step's staged rows
        # (note_finished races _finish_step). Keep finished accounts
        # addressable so the late share lands on the real account instead
        # of resurrecting a ghost in _live.
        self._recent_done: "collections.OrderedDict" = \
            collections.OrderedDict()
        # (tenant, class) -> account; tenant table bounded per class by
        # the qos overflow idiom so a tenant-id cardinality attack cannot
        # grow the ledger (or the metric label space) unbounded
        self._accounts: Dict[Tuple[str, str], _TenantAccount] = {}
        self._tenants_per_class: Dict[str, set] = {}
        # per-step attribution evidence ring: the conservation property
        # (attributed == ledger-measured device time) is checkable here
        self._steps: "collections.deque" = collections.deque(
            maxlen=max(16, int(steps_capacity)))
        self.steps_total = 0
        self.requests_total = 0

    def use_metrics(self, metrics) -> None:
        self._obs = MetricsHook(metrics, logger=self.logger)

    # -- label plumbing -------------------------------------------------------
    def _tenant_key(self, cls: str, tenant: str) -> str:
        """Bound the per-class tenant table at _MAX_TENANTS; excess
        tenants pool under the overflow label (the PR 11 idiom)."""
        tenant = tenant or "-"
        table = self._tenants_per_class.setdefault(cls, set())
        if tenant not in table:
            if len(table) >= _MAX_TENANTS:
                return _TENANT_OVERFLOW
            table.add(tenant)
        return tenant

    def _account(self, tenant: str, cls: str) -> _TenantAccount:
        key = (tenant, cls)
        acct = self._accounts.get(key)
        if acct is None:
            acct = _TenantAccount(tenant, cls)
            self._accounts[key] = acct
        return acct

    # -- intake (engine hooks) ------------------------------------------------
    def note_arrival(self, request) -> None:
        """submit-side arrival stamp (caller threads): forwards to the
        forecaster's λ window. Best-effort — never raises into submit."""
        fc = self.forecaster
        if fc is not None:
            try:
                fc.note_arrival(len(request.prompt_tokens),
                                request.max_new_tokens)
            except Exception:  # noqa: BLE001 - accounting is best-effort
                pass

    def account_step(self, rec, phase: str, rows, queued=None) -> None:
        """One closed engine step (loop thread): apportion the step
        ledger's measured device time across the synced batch.

        rec     — the StepRecord `step_end` returned (segment timings)
        phase   — sync kind: prefill | verify | decode
        rows    — [(request, tokens_processed, kv_tokens_held)]
        queued  — [(request, queue_wait_s)] for first-service rows
        """
        if not rows and not queued:
            return
        now = time.monotonic()
        # the step's measured device time: what the device-facing
        # segments of THIS iteration cost, per the step ledger. wall_s
        # is the fallback for ledgers configured without segments.
        segs = getattr(rec, "segments", None) or {}
        device_s = segs.get("device_sync", 0.0) + segs.get("dispatch", 0.0)
        if device_s <= 0.0:
            device_s = getattr(rec, "wall_s", 0.0) or 0.0
        total_tokens = sum(max(0, t) for _, t, _ in rows)
        # per-(tenant, class) deltas batched into ONE counter bump per
        # family per step — the hot path stays O(rows), not O(rows·sinks)
        deltas: Dict[Tuple[str, str], List[float]] = {}
        with self._lock:
            self.steps_total += 1
            attributed = 0.0
            for request, tokens, kv_tokens in rows:
                acct = self._touch_locked(request, now)
                weight = (tokens / total_tokens) if total_tokens else (
                    1.0 / len(rows))
                share = device_s * weight
                attributed += share
                if phase == "prefill":
                    flops = prefill_flops(self.cfg, tokens) if self.cfg \
                        else 0.0
                else:
                    flops = decode_flops(self.cfg, 1, tokens) if self.cfg \
                        else 0.0
                # page-seconds accrue between consecutive metered syncs:
                # pages held × elapsed wall time since this row was last
                # billed (first sight bills zero — nothing was held yet)
                pages = math.ceil(max(0, kv_tokens) / self.page_tokens)
                page_s = pages * max(0.0, now - acct.last_seen)
                acct.last_seen = now
                acct.device_s += share
                acct.flops += flops
                acct.page_s += page_s
                acct.tokens[phase] = acct.tokens.get(phase, 0) + max(0,
                                                                     tokens)
                tacct = self._account(acct.tenant, acct.cls)
                tacct.device_s += share
                tacct.flops += flops
                tacct.page_s += page_s
                tacct.tokens[phase] = tacct.tokens.get(phase, 0) + max(
                    0, tokens)
                d = deltas.setdefault((acct.tenant, acct.cls),
                                      [0.0, 0.0, 0.0, 0.0])
                d[0] += share
                d[1] += flops
                d[2] += page_s
            for request, wait_s in queued or ():
                acct = self._touch_locked(request, now)
                wait_s = max(0.0, wait_s)
                acct.queue_s += wait_s
                tacct = self._account(acct.tenant, acct.cls)
                tacct.queue_s += wait_s
                d = deltas.setdefault((acct.tenant, acct.cls),
                                      [0.0, 0.0, 0.0, 0.0])
                d[3] += wait_s
            self._steps.append({
                "seq": getattr(rec, "seq", None), "phase": phase,
                "rows": len(rows), "tokens": total_tokens,
                "device_s": round(device_s, 9),
                "attributed_s": round(attributed, 9),
                "wall_s": round(getattr(rec, "wall_s", 0.0) or 0.0, 9),
            })
        for (tenant, cls), (dev, flops, page, queue) in deltas.items():
            labels = {"class": cls, "tenant": tenant, "phase": phase}
            if dev:
                self._obs.counter("app_tpu_meter_device_seconds_total",
                                  dev, **labels)
            if flops:
                self._obs.counter("app_tpu_meter_flops_total", flops,
                                  **labels)
            if page:
                self._obs.counter("app_tpu_meter_page_seconds_total",
                                  page, **labels)
            if queue:
                self._obs.counter("app_tpu_meter_queue_seconds_total",
                                  queue, **{"class": cls, "tenant": tenant,
                                            "phase": "queue"})
        fc = self.forecaster
        if fc is not None and phase == "prefill" and rows:
            # base TTFT service sample: what one prefill dispatch costs
            # at the current batch shape (the no-queue floor)
            fc.note_prefill(device_s)

    def _touch_locked(self, request, now: float) -> _RequestAccount:
        acct = self._live.get(request.id)
        if acct is None:
            acct = self._recent_done.get(request.id)
        if acct is None:
            cls = effective_class(request)
            tenant = self._tenant_key(cls, getattr(request, "tenant", ""))
            acct = _RequestAccount(request.id, tenant, cls, now)
            self._live[request.id] = acct
            self.requests_total += 1
            tacct = self._account(tenant, cls)
            tacct.requests += 1
        return acct

    def note_finished(self, request, ok: bool) -> None:
        """Fold a finished request's account into the done ring and its
        tenant's bounded window (finisher thread). Unknown ids (shed
        before any sync) are ignored — they consumed no device time."""
        now = time.monotonic()
        with self._lock:
            acct = self._live.pop(request.id, None)
            if acct is None:
                return
            acct.finished_at = now
            acct.ok = ok
            self._done.append(acct)
            self._recent_done[acct.id] = acct
            while len(self._recent_done) > (self._done.maxlen or 16):
                self._recent_done.popitem(last=False)
            tacct = self._account(acct.tenant, acct.cls)
            tacct.finished += 1
            tacct.window.append((now, acct.device_s))
        fc = self.forecaster
        if fc is not None:
            try:
                fc.note_finished(len(request.prompt_tokens),
                                 len(request.emitted))
            except Exception:  # noqa: BLE001 - accounting is best-effort
                pass

    # -- operator surface -----------------------------------------------------
    def snapshot(self, top_k: Optional[int] = None) -> Dict[str, Any]:
        """The GET /debug/capacity payload: totals, the top-K tenant
        table, per-(tenant, class) accounts, recent requests, per-step
        attribution evidence, and the forecaster readout."""
        now = time.monotonic()
        k = top_k if top_k is not None else self.top_k
        with self._lock:
            accounts = [acct.row(now, self.window_s)
                        for acct in self._accounts.values()]
            requests = [a.row() for a in self._live.values()]
            requests += [a.row() for a in list(self._done)[-32:]]
            steps = list(self._steps)[-32:]
            steps_total = self.steps_total
            requests_total = self.requests_total
        accounts.sort(key=lambda r: r["device_s"], reverse=True)
        tenants: Dict[str, Dict[str, Any]] = {}
        for row in accounts:
            t = tenants.setdefault(row["tenant"], {
                "device_s": 0.0, "flops": 0.0, "page_s": 0.0,
                "queue_s": 0.0, "requests": 0, "window_device_s": 0.0})
            for field in ("device_s", "flops", "page_s", "queue_s",
                          "requests", "window_device_s"):
                t[field] = round(t[field] + row[field], 6)
        top = sorted(tenants.items(), key=lambda kv: kv[1]["device_s"],
                     reverse=True)[:k]
        totals = {
            "device_s": round(sum(r["device_s"] for r in accounts), 6),
            "flops": sum(r["flops"] for r in accounts),
            "page_s": round(sum(r["page_s"] for r in accounts), 6),
            "queue_s": round(sum(r["queue_s"] for r in accounts), 6),
        }
        out: Dict[str, Any] = {
            "totals": totals,
            "tenants": [{"tenant": name, **row} for name, row in top],
            "accounts": accounts,
            "requests": requests,
            "steps": steps,
            "steps_total": steps_total,
            "requests_total": requests_total,
            "page_tokens": self.page_tokens,
            "window_s": self.window_s,
        }
        fc = self.forecaster
        if fc is not None:
            out["forecast"] = fc.evaluate(now)
        return out


class HeadroomForecaster:
    """λ/μ/ρ queueing readout + fluid TTFT prediction + collapse
    early-warning (module docstring; worked example in
    docs/capacity.md)."""

    def __init__(self, engine=None, window_s: float = 60.0,
                 rho_warn: float = 0.85, collapse_evals: int = 3,
                 depth_warn: Optional[int] = None,
                 default_prompt_tokens: int = 128,
                 metrics=None, logger=None) -> None:
        self.engine = engine
        self.window_s = max(1.0, float(window_s))
        self.rho_warn = float(rho_warn)
        self.collapse_evals = max(2, int(collapse_evals))
        # depth corroboration for the collapse warning: a backlog this
        # many requests deep (two full batch waves) that is STILL
        # growing is saturation wherever the bottleneck sits — device-rho
        # alone is blind to a host- or scheduler-bound collapse
        if depth_warn is None:
            depth_warn = 2 * int(getattr(engine, "n_slots", 0) or 8)
        self.depth_warn = max(8, int(depth_warn))
        self.default_prompt_tokens = max(1, int(default_prompt_tokens))
        self._obs = MetricsHook(metrics, logger=logger)
        self.logger = logger
        self._lock = threading.Lock()
        # admission-door arrivals: (t, prompt_tokens, max_new)
        self._arrivals: "collections.deque" = collections.deque()
        self._created_at = time.monotonic()
        # EWMAs observed from served traffic (None until the first sample)
        self._ewma_prompt: Optional[float] = None
        self._ewma_decode: Optional[float] = None
        self._ewma_prefill_s: Optional[float] = None
        self._alpha = 0.2
        # collapse detector state: recent (t, queue_depth) eval samples
        self._depth_samples: "collections.deque" = collections.deque(
            maxlen=self.collapse_evals)
        self._collapse = False
        self.collapse_events = 0

    # -- intake ---------------------------------------------------------------
    def note_arrival(self, prompt_tokens: int, max_new_tokens: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._arrivals.append((now, int(prompt_tokens),
                                   int(max_new_tokens)))
            self._prune_locked(now)

    def note_prefill(self, service_s: float) -> None:
        if service_s <= 0:
            return
        with self._lock:
            self._ewma_prefill_s = service_s if self._ewma_prefill_s is None \
                else (1 - self._alpha) * self._ewma_prefill_s \
                + self._alpha * service_s

    def note_finished(self, prompt_tokens: int, generated: int) -> None:
        with self._lock:
            self._ewma_prompt = float(prompt_tokens) \
                if self._ewma_prompt is None \
                else (1 - self._alpha) * self._ewma_prompt \
                + self._alpha * prompt_tokens
            self._ewma_decode = float(generated) \
                if self._ewma_decode is None \
                else (1 - self._alpha) * self._ewma_decode \
                + self._alpha * generated

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        while self._arrivals and self._arrivals[0][0] < horizon:
            self._arrivals.popleft()

    # -- the model ------------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One λ/μ/ρ readout. Called on every scrape and on every
        /debug/capacity GET — pure host arithmetic over bounded state."""
        now = now if now is not None else time.monotonic()
        with self._lock:
            self._prune_locked(now)
            span = max(1e-9, min(self.window_s, now - self._created_at))
            n = len(self._arrivals)
            lam_req = n / span
            decode_est = self._ewma_decode
            lam_tok = sum(
                p + (decode_est if decode_est is not None else m)
                for _, p, m in self._arrivals) / span
            prompt_est = self._ewma_prompt or float(
                self.default_prompt_tokens)
            base_s = self._ewma_prefill_s or 0.0
        mu_tok = None
        util = getattr(self.engine, "util", None)
        if util is not None:
            try:
                stats = util.window_stats(now=now)
                busy = stats.get("device_busy_s") or 0.0
                toks = sum((stats.get("tokens") or {}).values())
                if busy > 1e-6 and toks:
                    mu_tok = toks / busy
            except Exception:  # noqa: BLE001 - forecast over a dying
                pass           # engine must not take the scrape down
        depth = 0
        if self.engine is not None:
            try:
                depth = self.engine.queue_depth()
            except Exception:  # noqa: BLE001
                pass
        rho = (lam_tok / mu_tok) if mu_tok else 0.0
        headroom = max(0.0, mu_tok - lam_tok) if mu_tok else 0.0
        backlog_tokens = depth * prompt_est
        predicted_s = base_s + (backlog_tokens / mu_tok if mu_tok else 0.0)
        collapse = self._eval_collapse(now, depth, rho)
        return {
            "window_s": round(min(self.window_s, now - self._created_at), 3),
            "arrivals": n,
            "lambda_rps": round(lam_req, 4),
            "lambda_tok_s": round(lam_tok, 3),
            "mu_tok_s": round(mu_tok, 3) if mu_tok else None,
            "rho": round(rho, 4),
            "headroom_tok_s": round(headroom, 3),
            "queue_depth": depth,
            "backlog_tokens": round(backlog_tokens, 1),
            "base_prefill_s": round(base_s, 6),
            "predicted_ttft_ms": round(predicted_s * 1000.0, 3),
            "collapse_warning": collapse,
            "collapse_events": self.collapse_events,
        }

    def _eval_collapse(self, now: float, depth: int, rho: float) -> bool:
        """Sustained dq/dt > 0 while ρ→1: the queue is at a new high over
        the eval window AND the device has no headroom to drain it. Net
        growth, not strict monotonicity — a batch admission momentarily
        dips the depth without changing the trend, and an all-rising test
        would reset on every such dip and arm only after the symptom."""
        with self._lock:
            samples = self._depth_samples
            if not samples or now - samples[-1][0] >= 0.2:
                samples.append((now, depth))
            window = list(samples)
            rising = (len(window) == samples.maxlen
                      and window[-1][1] > window[-2][1]
                      and window[-1][1] > window[0][1])
            # depth measured dip-tolerantly over the last two looks, like
            # the rise test: one admission wave must not un-saturate it
            deep = max(w[1] for w in window[-2:]) if window else depth
            saturated = rho >= self.rho_warn or deep >= self.depth_warn
            collapse = bool(rising and saturated)
            if collapse and not self._collapse:
                self.collapse_events += 1
                if self.logger is not None:
                    try:
                        self.logger.warnf(
                            "capacity collapse warning: queue depth rising "
                            "across %d evals at rho=%.2f",
                            len(samples), rho)
                    except Exception:  # noqa: BLE001
                        pass
            self._collapse = collapse
            return collapse

    def publish(self, now: Optional[float] = None) -> None:
        """Scrape-hook re-eval: recompute the window so the gauges DECAY
        while the replica idles (λ→0 ⇒ ρ→0, headroom→μ window drains)."""
        stats = self.evaluate(now)
        self._obs.gauge("app_tpu_capacity_rho", stats["rho"])
        self._obs.gauge("app_tpu_capacity_headroom_tok_s",
                        stats["headroom_tok_s"])
        self._obs.gauge("app_tpu_capacity_predicted_ttft_ms",
                        stats["predicted_ttft_ms"])
        self._obs.gauge("app_tpu_capacity_collapse_warning",
                        1 if stats["collapse_warning"] else 0)


def register_meter_metrics(metrics) -> None:
    """Idempotent registration (the register_qos_metrics idiom)."""
    counters = [
        ("app_tpu_meter_device_seconds_total",
         "Attributed device time by tenant, QoS class and phase "
         "(token-weighted apportionment of the step ledger's device "
         "segments)"),
        ("app_tpu_meter_flops_total",
         "Attributed analytic FLOPs by tenant, QoS class and phase "
         "(2·P per token, the MFU convention)"),
        ("app_tpu_meter_page_seconds_total",
         "Attributed KV page-seconds by tenant, QoS class and phase "
         "(pages held x wall seconds between metered syncs)"),
        ("app_tpu_meter_queue_seconds_total",
         "Pre-admission queue wait by tenant and QoS class "
         "(phase=queue; first service only, replays excluded)"),
    ]
    gauges = [
        ("app_tpu_capacity_rho",
         "Utilization rho = token arrival rate / token service rate "
         "(>= 1 means the queue grows without bound)"),
        ("app_tpu_capacity_headroom_tok_s",
         "Token throughput headroom mu - lambda before saturation "
         "(what the replica can still absorb)"),
        ("app_tpu_capacity_predicted_ttft_ms",
         "Fluid-model TTFT forecast: base prefill service + queue "
         "backlog / service rate"),
        ("app_tpu_capacity_collapse_warning",
         "Queueing-collapse early warning: 1 while queue depth rises "
         "across consecutive evals with rho near 1"),
    ]
    for name, desc in counters:
        try:
            if metrics.get(name) is None:
                metrics.new_counter(name, desc)
        except Exception:  # noqa: BLE001 - re-registration is benign
            pass
    for name, desc in gauges:
        try:
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        except Exception:  # noqa: BLE001
            pass


def install_routes(app, meter, path: str = "/debug/capacity") -> None:
    """GET /debug/capacity — attribution totals + top-K tenants + the
    headroom forecast (docs/observability.md surface #13)."""

    @app.get(path)
    def capacity_debug(ctx):  # noqa: ARG001 - gofr handler signature
        return meter.snapshot()
