"""Utilization ledger: roofline telemetry for the serving engine.

The flight recorder (tpu/flightrecorder.py) answers "where did THIS request
spend its time"; this module answers "how close does the engine run to the
hardware" — the efficiency yardstick the north-star target (≥2000 tok/s,
p50 TTFT <150 ms on v5e-8) is ultimately judged against. Three surfaces:

  * **Dispatch accounting** — the engine's sync path reports every executed
    dispatch (prefill / decode / verify) with its dispatch and sync
    timestamps; the ledger unions the [dispatched, synced] intervals into a
    rolling device-busy window (``app_tpu_device_duty_cycle``) and tracks
    host/scheduler time (``app_tpu_host_overhead_seconds``) and sync-wait
    separately, so "device idle because the host is slow" is visible as a
    number, not a profiler session.
  * **MFU / MBU estimation** — analytic FLOPs and HBM bytes per dispatch
    derived from the model config (the PaLM-report convention: a forward
    pass costs 2·P FLOPs per token; decode traffic is the weight read per
    step plus the live KV read), divided by a per-platform peak table
    (env-overridable ``TPU_PEAK_FLOPS`` / ``TPU_PEAK_HBM_BW``, per device).
    Exposed as ``app_tpu_mfu`` / ``app_tpu_mbu`` gauges split by
    prefill/decode phase.
  * **Memory & engine snapshot** — a background ``MemorySampler`` polling
    ``TPUClient.memory_stats()`` into ``app_tpu_hbm_bytes{kind=in_use|limit}``
    and KV page-pool occupancy (``app_tpu_kv_pool_pages{kind=used|free}``),
    and ``GET /debug/engine`` (``app.enable_engine_snapshot(engine)``): one
    JSON snapshot of slots / buckets / page pool / utilization window /
    executor compile table — the fleet-level sibling of ``/debug/requests``.

Accounting conventions (all host-side, best-effort, O(1) per dispatch —
the MetricsHook posture):

  * FLOPs count USEFUL work only: decode flops are 2·P per ACTIVE row per
    step, so junk rows in a half-empty lock-step batch show up as lost MFU
    rather than being flattered away. Prefill counts the admitted prompt
    tokens (prefix-cache hits count their full prompt — a small MFU
    overcount bounded by the hit's shared pages).
  * The device-busy interval starts when the dispatch call RETURNS (the
    program is enqueued) and ends at the host sync, unioned under a
    watermark so pipelined dispatches are never double-counted. Chunked
    prefills account at the final chunk's sync.
  * int8 KV scale reads/writes are ignored by the byte model (<2% of
    traffic at serving page sizes); document-level estimate, not a meter.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .capacity import kv_token_bytes, params_bytes
from .obs import MetricsHook

# per-chip peak dense-matmul FLOP/s (bf16) and HBM bandwidth (bytes/s),
# matched against jax's device_kind by lowercase substring, most specific
# first. Public spec-sheet numbers; override per deployment with
# TPU_PEAK_FLOPS / TPU_PEAK_HBM_BW when the fleet knows better.
PEAK_TABLE: Tuple[Tuple[str, Tuple[float, float]], ...] = (
    ("v6e", (918e12, 1640e9)),
    ("trillium", (918e12, 1640e9)),
    ("v5p", (459e12, 2765e9)),
    ("v5 lite", (197e12, 819e9)),      # jax reports v5e as "TPU v5 lite"
    ("v5e", (197e12, 819e9)),
    ("v5litepod", (197e12, 819e9)),
    ("v4", (275e12, 1228e9)),
    ("v3", (123e12, 900e9)),
    ("v2", (46e12, 700e9)),
)
# CPU / unknown backends: a nominal placeholder so the plumbing (gauges,
# snapshot, tests) works everywhere — the absolute MFU number is only
# meaningful on a device the table (or the env override) knows.
DEFAULT_PEAKS = (1e12, 1e11)

PEAK_FLOPS_ENV = "TPU_PEAK_FLOPS"
PEAK_HBM_BW_ENV = "TPU_PEAK_HBM_BW"


def resolve_peaks(platform: Optional[str] = None,
                  device_kind: Optional[str] = None) -> Tuple[float, float, str]:
    """(peak_flops, peak_hbm_bw, source) per device. Env overrides win;
    then the device-kind table; then the nominal placeholder."""
    env_flops = os.environ.get(PEAK_FLOPS_ENV)
    env_bw = os.environ.get(PEAK_HBM_BW_ENV)
    if env_flops or env_bw:
        table = _lookup_peaks(device_kind)
        return (float(env_flops) if env_flops else table[0],
                float(env_bw) if env_bw else table[1], "env")
    if platform and platform.lower() not in ("tpu",) and not device_kind:
        return (*DEFAULT_PEAKS, "default")
    flops, bw = _lookup_peaks(device_kind)
    if (flops, bw) == DEFAULT_PEAKS:
        return flops, bw, "default"
    return flops, bw, "table"


def _lookup_peaks(device_kind: Optional[str]) -> Tuple[float, float]:
    kind = (device_kind or "").lower()
    for needle, peaks in PEAK_TABLE:
        if needle in kind:
            return peaks
    return DEFAULT_PEAKS


# -- analytic roofline model (pure functions, hand-checkable) -----------------
def prefill_flops(cfg, tokens: int) -> float:
    """Forward-pass FLOPs for `tokens` prompt tokens: 2·P·T (the PaLM MFU
    convention — matmul MACs only, attention quadratic term excluded)."""
    return 2.0 * cfg.param_count() * tokens


def decode_flops(cfg, rows: int, steps: int) -> float:
    """A decode (or verify) dispatch computing `steps` positions for each
    of `rows` active sequences: 2·P per position."""
    return 2.0 * cfg.param_count() * rows * steps


def prefill_bytes(cfg, tokens: int,
                  params_nbytes: Optional[int] = None) -> float:
    """HBM traffic of one prefill dispatch: one weight read (prefill is
    compute-bound; weights stream once per dispatch) + the KV written for
    every prompt token."""
    weights = params_nbytes if params_nbytes else params_bytes(cfg)
    return float(weights) + float(tokens) * kv_token_bytes(cfg)


def decode_bytes(cfg, rows: int, steps: int, kv_tokens: int,
                 params_nbytes: Optional[int] = None) -> float:
    """HBM traffic of one decode dispatch: per step, the whole weight tree
    is read once (shared across the batch — THE reason batching wins) plus
    the live KV context (`kv_tokens` tokens across all rows) and one KV
    write per row."""
    weights = params_nbytes if params_nbytes else params_bytes(cfg)
    per_step = (float(weights)
                + float(kv_tokens) * kv_token_bytes(cfg)
                + float(rows) * kv_token_bytes(cfg))
    return float(steps) * per_step


class UtilizationLedger:
    """Rolling per-dispatch accounting window (see module docstring).

    All ``record_*`` / ``note_host`` calls are hot-path safe: one short
    lock, O(1) amortized work, failures swallowed at the metrics sink."""

    def __init__(self, cfg=None, metrics=None, n_devices: int = 1,
                 params_nbytes: Optional[int] = None,
                 window_s: float = 60.0,
                 platform: Optional[str] = None,
                 device_kind: Optional[str] = None,
                 created_at: Optional[float] = None):
        self.cfg = cfg
        self.n_devices = max(1, int(n_devices))
        self.params_nbytes = params_nbytes
        self.window_s = float(window_s)
        self._platform = platform
        self._device_kind = device_kind
        self._peaks: Optional[Tuple[float, float, str]] = None
        self._lock = threading.Lock()
        # (synced_at, phase, flops, bytes, busy_s, sync_wait_s, tokens)
        self._entries: "collections.deque" = collections.deque()
        # (t, host_s) — scheduler/prep/demux time noted by the engine loop
        self._host: "collections.deque" = collections.deque()
        self._busy_until = 0.0          # device-busy union watermark
        # MONOTONIC clock domain: the engine stamps dispatch/sync times
        # with time.monotonic() (an NTP step must not warp the busy
        # window), so the window's own "now" must come from the same clock
        self._created_at = (created_at if created_at is not None
                            else time.monotonic())
        self._obs = MetricsHook(metrics)
        self.dispatches_total = 0
        # disaggregated serving (tpu/disagg.py): when this ledger belongs
        # to one pool of a prefill/decode split, `pool` tags a per-pool
        # duty-cycle gauge so both halves are comparable side by side
        # (the un-labelled duty cycle would otherwise collapse them)
        self.pool = ""

    # -- wiring ---------------------------------------------------------------
    def use_metrics(self, metrics) -> None:
        if metrics is not None:
            self._obs = MetricsHook(metrics)

    def peaks(self) -> Tuple[float, float, str]:
        """Per-device (peak_flops, peak_hbm_bw, source), resolved lazily so
        constructing a ledger never touches the device runtime."""
        if self._peaks is None:
            platform, kind = self._platform, self._device_kind
            if platform is None and kind is None:
                try:
                    import jax

                    device = jax.devices()[0]
                    platform = device.platform
                    kind = device.device_kind
                except Exception:  # noqa: BLE001 - no backend: placeholder
                    pass
            self._peaks = resolve_peaks(platform, kind)
        return self._peaks

    # -- recording (engine sync path) -----------------------------------------
    def record_prefill(self, tokens: int, dispatched_at: float,
                       synced_at: float, sync_wait_s: float = 0.0) -> None:
        if self.cfg is None:
            return
        self._record("prefill", prefill_flops(self.cfg, tokens),
                     prefill_bytes(self.cfg, tokens, self.params_nbytes),
                     tokens, dispatched_at, synced_at, sync_wait_s)

    def record_decode(self, rows: int, steps: int, kv_tokens: int,
                      dispatched_at: float, synced_at: float,
                      sync_wait_s: float = 0.0) -> None:
        if self.cfg is None:
            return
        self._record("decode", decode_flops(self.cfg, rows, steps),
                     decode_bytes(self.cfg, rows, steps, kv_tokens,
                                  self.params_nbytes),
                     rows * steps, dispatched_at, synced_at, sync_wait_s)

    def _record(self, phase: str, flops: float, nbytes: float, tokens: int,
                dispatched_at: float, synced_at: float,
                sync_wait_s: float) -> None:
        with self._lock:
            busy = max(0.0, synced_at - max(dispatched_at, self._busy_until))
            self._busy_until = max(self._busy_until, synced_at)
            self._entries.append((synced_at, phase, flops, nbytes, busy,
                                  max(0.0, sync_wait_s), tokens))
            self.dispatches_total += 1
            self._prune(synced_at)
        self.publish(now=synced_at)

    def note_host(self, seconds: float, now: Optional[float] = None) -> None:
        """Host/scheduler overhead: time the engine loop spent in admission,
        host prep, and dispatch enqueues (never inside a device sync)."""
        if seconds <= 0.0:
            return
        with self._lock:
            t = now if now is not None else time.monotonic()
            self._host.append((t, seconds))
            self._prune(t)

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._entries and self._entries[0][0] < cutoff:
            self._entries.popleft()
        while self._host and self._host[0][0] < cutoff:
            self._host.popleft()

    def device_slices(self) -> List[Dict[str, Any]]:
        """The window's dispatch→sync busy intervals as drawable slices,
        oldest first, for the timeline exporter's async device track
        (tpu/timeline.py). The busy-union watermark already made the
        intervals non-overlapping: each entry's busy time starts where
        the previous sync (or its own dispatch) ended."""
        with self._lock:
            entries = list(self._entries)
        return [{"start": synced - busy, "end": synced, "phase": phase,
                 "tokens": toks, "busy_s": busy, "sync_wait_s": wait}
                for synced, phase, _flops, _nbytes, busy, wait, toks
                in entries if busy > 0.0]

    # -- rolling window read-out ----------------------------------------------
    def window_stats(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = now if now is not None else time.monotonic()
        peak_flops, peak_bw, peak_source = self.peaks()
        agg_flops = {"prefill": 0.0, "decode": 0.0}
        agg_bytes = {"prefill": 0.0, "decode": 0.0}
        tokens = {"prefill": 0, "decode": 0}
        with self._lock:
            self._prune(now)
            busy = sync_wait = 0.0
            for _, phase, flops, nbytes, busy_s, wait_s, toks in self._entries:
                agg_flops[phase] += flops
                agg_bytes[phase] += nbytes
                tokens[phase] += toks
                busy += busy_s
                sync_wait += wait_s
            host = sum(h for _, h in self._host)
            dispatches = len(self._entries)
        span = max(1e-9, min(self.window_s, now - self._created_at))
        flops_cap = peak_flops * self.n_devices * span
        bytes_cap = peak_bw * self.n_devices * span
        total_flops = sum(agg_flops.values())
        total_bytes = sum(agg_bytes.values())
        return {
            "window_s": round(span, 3),
            "dispatches": dispatches,
            "device_busy_s": round(busy, 6),
            "duty_cycle": round(min(1.0, busy / span), 6),
            "host_overhead_s": round(host, 6),
            "sync_wait_s": round(sync_wait, 6),
            "tokens": dict(tokens),
            "mfu": {
                "prefill": agg_flops["prefill"] / flops_cap,
                "decode": agg_flops["decode"] / flops_cap,
                "total": total_flops / flops_cap,
            },
            "mbu": {
                "prefill": agg_bytes["prefill"] / bytes_cap,
                "decode": agg_bytes["decode"] / bytes_cap,
                "total": total_bytes / bytes_cap,
            },
            "peak_flops": peak_flops,
            "peak_hbm_bw": peak_bw,
            "peak_source": peak_source,
            "n_devices": self.n_devices,
        }

    def publish(self, now: Optional[float] = None) -> None:
        """Recompute the window and push the gauges. Called after every
        recorded dispatch and from the container's metrics-scrape hook (so
        an idle engine decays toward zero instead of freezing stale)."""
        stats = self.window_stats(now=now)
        self._obs.gauge("app_tpu_device_duty_cycle", stats["duty_cycle"])
        if self.pool:
            self._obs.gauge("app_tpu_disagg_pool_duty_cycle",
                            stats["duty_cycle"], pool=self.pool)
        self._obs.gauge("app_tpu_host_overhead_seconds",
                        stats["host_overhead_s"])
        for phase in ("prefill", "decode"):
            self._obs.gauge("app_tpu_mfu", stats["mfu"][phase], phase=phase)
            self._obs.gauge("app_tpu_mbu", stats["mbu"][phase], phase=phase)


def register_utilization_metrics(metrics) -> None:
    """Register the ledger/sampler gauges on a metrics Manager (idempotent
    — TPUClient.register_metrics also registers them on full deployments)."""
    for name, desc in (
        ("app_tpu_device_duty_cycle",
         "fraction of the rolling window the device spent executing "
         "dispatched programs"),
        ("app_tpu_host_overhead_seconds",
         "host/scheduler seconds (admission, prep, demux) in the rolling "
         "utilization window"),
        ("app_tpu_mfu",
         "model FLOPs utilization vs the platform peak, by phase"),
        ("app_tpu_mbu",
         "HBM bandwidth utilization vs the platform peak, by phase"),
        ("app_tpu_hbm_bytes",
         "HBM bytes per device (kind=in_use|limit)"),
        ("app_tpu_kv_pool_pages",
         "KV page-pool occupancy (kind=used|free)"),
        ("app_tpu_kv_tier_bytes",
         "host KV tier occupancy in bytes (kind=used|capacity)"),
        ("app_tpu_kv_tier_pages",
         "page blobs resident in the host KV tier"),
    ):
        try:
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        except Exception:  # noqa: BLE001 - already registered
            pass
    for name, desc in (
        ("app_tpu_kv_tier_spilled_total",
         "KV pages spilled from the pool to the host tier on eviction"),
        ("app_tpu_kv_tier_restored_total",
         "KV pages restored into the pool from the tiers by H2D copy"),
        ("app_tpu_kv_tier_hits_total",
         "tier lookups during the admission prefix walk that found a "
         "verified page blob"),
        ("app_tpu_kv_tier_misses_total",
         "prefix pages past the HBM hit the tiers could not supply "
         "(re-prefilled instead)"),
        ("app_tpu_kv_tier_corrupt_total",
         "tier blobs dropped on checksum/content verification failure "
         "(degraded to a miss)"),
        ("app_tpu_kv_tier_pinned_total",
         "conversation-trunk chain keys pinned in the host tier"),
    ):
        try:
            if metrics.get(name) is None:
                metrics.new_counter(name, desc)
        except Exception:  # noqa: BLE001 - already registered
            pass


class MemorySampler:
    """Background HBM / page-pool gauge refresher.

    Polls ``TPUClient.memory_stats()`` (or ``jax.devices()`` directly when
    no client was injected) every ``interval_s`` into
    ``app_tpu_hbm_bytes{device,kind}``, plus the engine's page-pool
    occupancy when it serves from a paged pool. One immediate sample runs
    at start() so the gauges exist before the first interval elapses."""

    def __init__(self, metrics, tpu=None, engine=None,
                 interval_s: float = 10.0, logger=None):
        self._obs = MetricsHook(metrics)
        self.tpu = tpu
        self.engine = engine
        self.interval_s = max(0.5, float(interval_s))
        self.logger = logger
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _device_stats(self) -> List[Dict[str, Any]]:
        if self.tpu is not None:
            return self.tpu.memory_stats()
        try:
            import jax

            out = []
            for d in jax.devices():
                try:
                    stats = d.memory_stats() or {}
                except Exception:  # noqa: BLE001 - CPU backends
                    stats = {}
                out.append({"id": d.id,
                            "bytes_in_use": stats.get("bytes_in_use", 0),
                            "bytes_limit": stats.get("bytes_limit", 0)})
            return out
        except Exception:  # noqa: BLE001
            return []

    def sample_once(self) -> None:
        for s in self._device_stats():
            dev = str(s["id"])
            self._obs.gauge("app_tpu_hbm_bytes", s["bytes_in_use"],
                            device=dev, kind="in_use")
            self._obs.gauge("app_tpu_hbm_bytes", s["bytes_limit"],
                            device=dev, kind="limit")
        allocator = getattr(self.engine, "allocator", None)
        if allocator is not None:
            self._obs.gauge("app_tpu_kv_pool_pages", allocator.used_pages,
                            kind="used")
            self._obs.gauge("app_tpu_kv_pool_pages", allocator.free_pages,
                            kind="free")
        kv_tier = getattr(self.engine, "kv_tier", None)
        if kv_tier is not None:
            tier_stats = kv_tier.stats()
            self._obs.gauge("app_tpu_kv_tier_bytes",
                            tier_stats["used_bytes"], kind="used")
            self._obs.gauge("app_tpu_kv_tier_bytes",
                            tier_stats["capacity_bytes"], kind="capacity")
            self._obs.gauge("app_tpu_kv_tier_pages", tier_stats["pages"])

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception as exc:  # noqa: BLE001 - sampling must not die
                if self.logger is not None:
                    self.logger.debugf("memory sample failed: %s", exc)
            self._stop.wait(self.interval_s)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="hbm-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- /debug/engine ------------------------------------------------------------
def engine_snapshot(engine, tpu=None) -> Dict[str, Any]:
    """One JSON snapshot of the whole engine: slots, buckets, page pool,
    utilization window, compile table, HBM. Read-only and best-effort —
    slot fields are read without the engine's state lock (a torn read of a
    transitioning slot is acceptable for an operator surface; taking the
    lock would let a stalled loop block the debug endpoint)."""
    out: Dict[str, Any] = {
        "engine": {
            "class": type(engine).__name__,
            "n_slots": engine.n_slots,
            "max_seq_len": engine.max_seq_len,
            "prefill_buckets": list(engine.prefill_buckets),
            "decode_block_size": engine.decode_block_size,
            "pipeline_depth": engine.pipeline_depth,
            "chunk_prefill_tokens": engine.chunk_prefill_tokens,
            "speculative_tokens": engine.speculative_tokens,
            "cache_len": getattr(engine, "_cache_len", None),
            "queue_depth": engine._pending.qsize(),
            "inflight_dispatches": len(engine._inflight),
            "draining": engine._draining,
            "stall_seconds": round(engine.stall_seconds, 1),
        },
    }
    slots = []
    active = 0
    for i, slot in enumerate(engine.slots):
        request = slot.request
        entry: Dict[str, Any] = {"slot": i, "active": slot.active}
        if request is not None:
            active += 1
            entry.update(request_id=request.id, length=slot.length,
                         remaining=slot.remaining,
                         generated=request.generated)
        chunking = slot.chunking
        if chunking is not None:
            entry["chunking_request_id"] = chunking.id
        if slot.pages is not None:
            entry["pages"] = len(slot.pages)
        slots.append(entry)
    out["engine"]["active_slots"] = active
    out["slots"] = slots

    allocator = getattr(engine, "allocator", None)
    if allocator is not None:
        out["page_pool"] = {
            "n_pages": allocator.n_pages,
            "page_size": allocator.page_size,
            "used": allocator.used_pages,
            "free": allocator.free_pages,
        }
        prefix = getattr(engine, "prefix", None)
        if prefix is not None:
            try:
                out["page_pool"]["prefix_cache"] = prefix.stats()
                # bounded hot-chain-key digest so fleet routers polling
                # this surface never pay O(pool) serialization
                out["page_pool"]["prefix_digest"] = prefix.digest()
            except Exception:  # noqa: BLE001
                pass
        kv_tier = getattr(engine, "kv_tier", None)
        if kv_tier is not None:
            try:
                tier = kv_tier.stats()
                tier["spilled_pages"] = getattr(engine, "_kv_spilled", 0)
                tier["restored_pages"] = getattr(engine, "_kv_restored", 0)
                out["page_pool"]["kv_tier"] = tier
            except Exception:  # noqa: BLE001
                pass

    breaker = getattr(engine, "breaker", None)
    if breaker is not None:
        out["breaker"] = breaker.snapshot()
    # crash-only recovery evidence (plain engine counters, metrics-free)
    if hasattr(engine, "resets_total"):
        out["recovery"] = {
            "resets_total": engine.resets_total,
            "replays_total": engine.replays_total,
            "replayed_tokens_total": engine.replayed_tokens_total,
            "quarantined_total": engine.quarantined_total,
            "retry_budget": getattr(engine, "retry_budget", None),
        }
    faults = getattr(engine, "faults", None)
    if faults is not None:
        out["faults"] = faults.snapshot()

    util = getattr(engine, "util", None)
    if util is not None:
        out["utilization"] = util.window_stats()
    executor = getattr(engine, "executor", None)
    if executor is not None and hasattr(executor, "compile_table"):
        out["compile"] = executor.compile_table()

    sampler = MemorySampler(None, tpu=tpu)
    hbm = sampler._device_stats()
    if hbm:
        out["hbm"] = hbm
    return out


def install_routes(app, engine, path: str = "/debug/engine") -> None:
    """Register GET /debug/engine on a gofr_tpu App (the profiler /
    flight-recorder install_routes idiom)."""

    @app.get(path)
    def debug_engine(ctx):  # noqa: ANN001
        return engine_snapshot(engine, tpu=ctx.container.tpu)
