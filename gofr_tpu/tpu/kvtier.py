"""Tiered KV page cache: host-RAM warm tier + optional Redis cold tier.

The paged engine's `PrefixCache` shares KV pages only while they stay
resident in the HBM-modeled page pool — LRU eviction hands the page id
back to the allocator and the KV content is gone, so a multi-turn chat
working set larger than the pool re-pays full prefill every turn. This
module keeps evicted page CONTENT alive in cheaper memory:

    HBM page pool  --spill on evict-->  HostKVTier (pinned numpy blobs)
                                            |  write-behind on evict
                                            v
                                        RedisKVTier (base64+crc32 blobs)

Keys are the PrefixCache's cumulative chain keys, so a page blob is
addressed by the full token history it encodes. Every tier verifies the
stored token content against the requested tokens on get — a hash
collision or a corrupt blob degrades to a miss (recompute), never to
serving another prompt's KV. That mirrors prefixcache.py's collision
posture and is what makes restore safe to gate only on a bit-equivalence
test rather than on trust in the hash.

Threading: the engine loop thread calls put()/get() during admission and
eviction; HTTP handler threads call pin() (conversation pinning) and
stats(). A single lock covers the index; blob payloads are immutable
numpy arrays once stored, so readers outside the lock are safe.

The Redis tier rides the gated `datasource/kvredis.py` driver (or any
object with its set/get/delete surface, e.g. the test fake). It is
strictly best-effort: a down Redis raises ConnectionError inside the
driver, which this module swallows and counts — serving never blocks on
the cold tier.
"""

from __future__ import annotations

import base64
import json
import queue
import threading
import time
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

try:
    # np.dtype("bfloat16") only resolves after ml_dtypes (a jax dep)
    # registers it — without this, decode_blob would degrade EVERY bf16
    # cold-tier blob to a miss
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover - jax environments ship it
    pass

BLOB_VERSION = 1


class PageBlob:
    """One page's KV content on the host: the `[L, Hkv, dh, ps]` k/v
    slices of the pool (plus int8 scale planes when the pool is q8),
    alongside the exact tokens the page encodes for content
    verification."""

    __slots__ = ("tokens", "k", "v", "k_scale", "v_scale")

    def __init__(self, tokens: Sequence[int], k: np.ndarray, v: np.ndarray,
                 k_scale: Optional[np.ndarray] = None,
                 v_scale: Optional[np.ndarray] = None):
        self.tokens: Tuple[int, ...] = tuple(int(t) for t in tokens)
        self.k = np.ascontiguousarray(k)
        self.v = np.ascontiguousarray(v)
        self.k_scale = (np.ascontiguousarray(k_scale)
                        if k_scale is not None else None)
        self.v_scale = (np.ascontiguousarray(v_scale)
                        if v_scale is not None else None)

    @property
    def nbytes(self) -> int:
        n = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes
        if self.v_scale is not None:
            n += self.v_scale.nbytes
        return n


# -- wire format for the cold tier -------------------------------------------

def _pack_array(arr: np.ndarray) -> Dict[str, Any]:
    return {"dtype": str(arr.dtype), "shape": list(arr.shape),
            "data": base64.b64encode(arr.tobytes()).decode("ascii")}


def _unpack_array(spec: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(spec["data"].encode("ascii"))
    return np.frombuffer(raw, dtype=np.dtype(spec["dtype"])).reshape(
        spec["shape"]).copy()


def encode_blob(blob: PageBlob) -> str:
    """Versioned, checksummed JSON envelope. Stored as a STRING because
    the Redis datasource runs decode_responses=True (string wire) and the
    in-repo fake stores str(value) — a str round-trips both."""
    body: Dict[str, Any] = {
        "v": BLOB_VERSION,
        "tokens": list(blob.tokens),
        "k": _pack_array(blob.k),
        "val": _pack_array(blob.v),
    }
    if blob.k_scale is not None:
        body["k_scale"] = _pack_array(blob.k_scale)
    if blob.v_scale is not None:
        body["v_scale"] = _pack_array(blob.v_scale)
    payload = blob.k.tobytes() + blob.v.tobytes()
    if blob.k_scale is not None:
        payload += blob.k_scale.tobytes()
    if blob.v_scale is not None:
        payload += blob.v_scale.tobytes()
    body["crc"] = zlib.crc32(payload) & 0xFFFFFFFF
    return json.dumps(body)


def decode_blob(raw: Any) -> Optional[PageBlob]:
    """Envelope -> PageBlob; any structural problem, version skew, or
    checksum mismatch returns None (degrade to miss, never wrong KV)."""
    try:
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8")
        body = json.loads(raw)
        if body.get("v") != BLOB_VERSION:
            return None
        k = _unpack_array(body["k"])
        v = _unpack_array(body["val"])
        k_scale = (_unpack_array(body["k_scale"])
                   if "k_scale" in body else None)
        v_scale = (_unpack_array(body["v_scale"])
                   if "v_scale" in body else None)
        payload = k.tobytes() + v.tobytes()
        if k_scale is not None:
            payload += k_scale.tobytes()
        if v_scale is not None:
            payload += v_scale.tobytes()
        if (zlib.crc32(payload) & 0xFFFFFFFF) != body.get("crc"):
            return None
        return PageBlob(body["tokens"], k, v, k_scale, v_scale)
    except Exception:  # noqa: BLE001 - corrupt blob IS the expected failure
        return None


class RedisKVTier:
    """Cold tier over the gated Redis datasource (or any set/get/delete
    twin). Write-behind by default: puts enqueue onto a bounded queue
    drained by a daemon worker, so host-tier eviction never blocks on the
    network; a full queue drops the blob (it is a CACHE — the only cost
    is a future recompute). `write_behind=False` runs puts inline for
    deterministic tests."""

    KEY_PREFIX = "gofr:kvpage:"

    def __init__(self, store: Any, write_behind: bool = True,
                 ttl_s: Optional[float] = None, queue_depth: int = 64):
        self.store = store
        self.ttl_s = ttl_s
        self.hits = 0
        self.misses = 0
        self.stored = 0
        self.corrupt = 0
        self.errors = 0
        self.dropped = 0
        self._lock = threading.Lock()
        self._q: Optional["queue.Queue"] = None
        if write_behind:
            self._q = queue.Queue(maxsize=queue_depth)
            worker = threading.Thread(target=self._drain,
                                      name="kvtier-redis-writer", daemon=True)
            worker.start()

    def _key(self, key: int) -> str:
        return f"{self.KEY_PREFIX}{key:#x}"

    def _set(self, key: int, blob: PageBlob) -> None:
        try:
            self.store.set(self._key(key), encode_blob(blob),
                           ttl_s=self.ttl_s)
            with self._lock:
                self.stored += 1
        except Exception:  # noqa: BLE001 - cold tier is best-effort
            with self._lock:
                self.errors += 1

    def _drain(self) -> None:
        while True:
            key, blob = self._q.get()
            try:
                self._set(key, blob)
            finally:
                self._q.task_done()

    def put(self, key: int, blob: PageBlob) -> None:
        if self._q is None:
            self._set(key, blob)
            return
        try:
            self._q.put_nowait((key, blob))
        except queue.Full:
            with self._lock:
                self.dropped += 1

    def get(self, key: int, tokens: Sequence[int]) -> Optional[PageBlob]:
        try:
            raw = self.store.get(self._key(key))
        except Exception:  # noqa: BLE001
            with self._lock:
                self.errors += 1
            return None
        if raw is None:
            with self._lock:
                self.misses += 1
            return None
        blob = decode_blob(raw)
        if blob is None or blob.tokens != tuple(int(t) for t in tokens):
            # corrupt or collided: purge so the next lookup is a clean miss
            with self._lock:
                self.corrupt += 1
                self.misses += 1
            try:
                self.store.delete(self._key(key))
            except Exception:  # noqa: BLE001
                pass
            return None
        with self._lock:
            self.hits += 1
        return blob

    def flush(self, timeout_s: float = 5.0) -> None:
        """Block until the write-behind queue drains (tests/shutdown)."""
        if self._q is None:
            return
        deadline = time.monotonic() + timeout_s
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.005)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "stored": self.stored, "corrupt": self.corrupt,
                    "errors": self.errors, "dropped": self.dropped}


class HostKVTier:
    """Bounded host-RAM LRU over PageBlobs, keyed by cumulative prefix
    keys. Spill target for PrefixCache eviction and restore source for
    admission; optionally backed by a RedisKVTier cold tier (write-behind
    on eviction, promote-on-hit)."""

    def __init__(self, capacity_bytes: int, page_size: int,
                 cold: Optional[RedisKVTier] = None):
        self.capacity_bytes = int(capacity_bytes)
        self.page_size = page_size
        self.cold = cold
        self._blobs: "OrderedDict[int, PageBlob]" = OrderedDict()
        self._pins: Dict[int, float] = {}          # key -> pin deadline
        self._lock = threading.Lock()
        self.used_bytes = 0
        self.hits = 0
        self.misses = 0
        self.stored = 0
        self.evicted = 0
        self.corrupt = 0
        self.rejected = 0

    # -- internals (caller holds the lock) -----------------------------------
    def _pinned(self, key: int, now: float) -> bool:
        deadline = self._pins.get(key)
        if deadline is None:
            return False
        if deadline <= now:
            del self._pins[key]
            return False
        return True

    def _evict_to_fit(self) -> None:
        """LRU-evict until under capacity, skipping unexpired pins. When
        everything left is pinned the tier runs temporarily over budget —
        pins are TTL-bounded, so the overshoot is too; starving the spill
        path instead would silently turn pinning into data loss."""
        now = time.monotonic()
        if self.used_bytes <= self.capacity_bytes:
            return
        for key in list(self._blobs):
            if self.used_bytes <= self.capacity_bytes:
                break
            if self._pinned(key, now):
                continue
            blob = self._blobs.pop(key)
            self.used_bytes -= blob.nbytes
            self.evicted += 1
            if self.cold is not None:
                self.cold.put(key, blob)

    # -- the tier protocol ---------------------------------------------------
    def put(self, key: int, blob: PageBlob) -> bool:
        """Admit a spilled page. Returns False when the blob alone exceeds
        capacity (it would evict the whole tier for one entry)."""
        if blob.nbytes > self.capacity_bytes:
            with self._lock:
                self.rejected += 1
            return False
        with self._lock:
            old = self._blobs.pop(key, None)
            if old is not None:
                self.used_bytes -= old.nbytes
            self._blobs[key] = blob
            self.used_bytes += blob.nbytes
            self.stored += 1
            self._evict_to_fit()
        return True

    def get(self, key: int, tokens: Sequence[int]) -> Optional[PageBlob]:
        """Content-verified lookup; falls through to the cold tier on miss
        and promotes a cold hit back into host RAM."""
        want = tuple(int(t) for t in tokens)
        with self._lock:
            blob = self._blobs.get(key)
            if blob is not None:
                if blob.tokens != want:
                    # collision/corruption: drop so it cannot hit again
                    self._blobs.pop(key)
                    self.used_bytes -= blob.nbytes
                    self.corrupt += 1
                    self.misses += 1
                    return None
                self._blobs.move_to_end(key)
                self.hits += 1
                return blob
            self.misses += 1
        if self.cold is None:
            return None
        cold_blob = self.cold.get(key, want)
        if cold_blob is not None:
            self.put(key, cold_blob)   # promote: next turn hits warm
        return cold_blob

    def contains(self, key: int, tokens: Sequence[int]) -> bool:
        """Non-mutating peek (no LRU touch, no counters, no cold probe)."""
        want = tuple(int(t) for t in tokens)
        with self._lock:
            blob = self._blobs.get(key)
            return blob is not None and blob.tokens == want

    def pin(self, keys: Sequence[int], ttl_s: float) -> int:
        """Protect the given chain keys from warm-tier LRU eviction for
        ttl_s seconds (conversation pinning: a resumable conversation's
        trunk must survive churn between turns). Pins are residency-
        INDEPENDENT: a trunk page still live in HBM spills here later,
        and the pin must already cover it when the blob arrives."""
        now = time.monotonic()
        deadline = now + ttl_s
        with self._lock:
            # opportunistic prune so the pin set tracks live conversations
            for stale in [k for k, d in self._pins.items() if d <= now]:
                del self._pins[stale]
            for key in keys:
                self._pins[key] = max(self._pins.get(key, 0.0), deadline)
        return len(keys)

    def clear(self) -> None:
        with self._lock:
            self._blobs.clear()
            self._pins.clear()
            self.used_bytes = 0

    def keys(self) -> List[int]:
        with self._lock:
            return list(self._blobs)

    def inventory(self, limit: int = 64) -> List[Dict[str, Any]]:
        """The newest ``limit`` resident pages as ``{key, tokens}`` rows —
        the warm-boot pre-warm feed (fleet elasticity): a booting replica
        fetches a peer's inventory and issues ``get(key, tokens)`` against
        its OWN tier, so shared-cold-tier (Redis) hits promote straight
        into host RAM before the first request arrives. Keys alone would
        not do: ``get`` content-verifies against the token window."""
        with self._lock:
            rows = [(k, b.tokens) for k, b in self._blobs.items()]
        rows = rows[-max(0, int(limit)):] if limit else []
        return [{"key": int(k), "tokens": list(t)} for k, t in rows]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            now = time.monotonic()
            out = {
                "capacity_bytes": self.capacity_bytes,
                "used_bytes": self.used_bytes,
                "pages": len(self._blobs),
                "hits": self.hits,
                "misses": self.misses,
                "stored": self.stored,
                "evicted": self.evicted,
                "corrupt": self.corrupt,
                "rejected": self.rejected,
                "pinned": sum(1 for k, d in self._pins.items() if d > now),
            }
        if self.cold is not None:
            out["redis"] = self.cold.stats()
        return out
