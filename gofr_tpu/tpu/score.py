"""Post-hoc model passes: per-token logprobs (OpenAI `logprobs`) and
sequence embeddings (`/v1/embeddings`).

Design: additive passes instead of plumbing through the serving hot path.
For this engine's decoding (greedy / temperature / top-k/p are all draws
from the position's distribution), the distribution at completion position
i conditions only on the tokens before it — so a teacher-forced forward
over prompt+completion reproduces the decode-time distributions exactly,
and one additive program family delivers chosen-token logprobs + top-K
alternatives with ZERO changes to the prefill/decode/speculative programs
or their signatures. The cost model matches how the features are used:
nothing on the default path, one bucketed forward per request that asks.

Both passes share ONE windowed-cache driver (`_window_pass`): W tokens per
dispatch against a bucket-sized running cache, so the scoring pass's
logits buffer is [1, W, V] (~64 MB at Llama-3 vocab) instead of
[1, S, V], and the embedding pass never materializes logits at all. Top-K
reduces on device; only [W, K+1] floats (or one [D] row) cross to the
host per window.

Parity: the reference returns exactly what its upstream surface promises
rather than approximations (responder envelope discipline,
/root/reference/pkg/gofr/http/responder.go:24-50); here the promise is
OpenAI's `logprobs` / `embeddings` contracts on the /v1 surface.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def _window_pass(engine, length: int, program_name: str, make_fn,
                 window_args, collect, work_length=None) -> None:
    """Shared windowed-cache driver for the post-hoc passes.

    Owns the mechanics both passes must agree on — bucket selection, fp
    cache init (the plain model forward, independent of the engine's
    serving kv_dtype), W-sized zero-padded windows, broadcast positions,
    and executor compilation with donated caches — so the passes cannot
    silently diverge. Runs independently of the serving loop (no engine
    state is touched; device execution interleaves with serving dispatches
    under JAX's own serialization), so a busy server scores/embeds without
    pausing decode.

    make_fn(cfg, W) builds the window program (signature
    (params, *extra, positions, k, v) -> (k, v, *outputs));
    window_args(w0, n, W) returns the pass-specific extra arrays for the
    window starting at w0 holding n live tokens; collect(w0, n, W, outs)
    receives the outputs past (new_k, new_v). Padded tail positions
    produce garbage the collectors slice away — causality guarantees they
    cannot contaminate earlier positions.
    """
    import math

    import jax.numpy as jnp

    from ..models.llama import init_kv_cache
    from .executor import next_bucket

    S = next_bucket(length, engine.prefill_buckets)
    # W must DIVIDE S: prefill buckets are config-controlled (the
    # llm-server parses arbitrary ints), and a bucket like 192 would give
    # the final 128-wide window positions past the S-length cache —
    # "working" only by JAX's out-of-bounds scatter-drop while attention
    # reads garbage. gcd(S, 128) always divides S; power-of-two buckets
    # keep the full W=128 window (ADVICE r5).
    W = math.gcd(S, 128)
    k, v = init_kv_cache(engine.cfg, 1, S)
    fn = make_fn(engine.cfg, W)
    # work_length < length lets a pass skip trailing positions it never
    # reads (scoring: position L-1 has no target, so an L ≡ 1 (mod W)
    # sequence must not dispatch a whole discarded window for it)
    for w0 in range(0, work_length or length, W):
        n = min(W, length - w0)
        positions = jnp.broadcast_to(
            jnp.arange(w0, w0 + W, dtype=jnp.int32), (1, W))
        args = (engine.params, *window_args(w0, n, W), positions, k, v)
        program = engine.executor.compile(
            f"{program_name}-{S}x{W}", fn, args,
            donate_argnums=(len(args) - 2, len(args) - 1))
        k, v, *outs = program(*args)
        collect(w0, n, W, outs)


def make_score_fn(cfg, W: int, K: int):
    """Window program: forward W tokens against the running cache, emit
    (new_k, new_v, chosen_lp [W], top_ids [W, K], top_lps [W, K]).

    `targets[j]` is the NEXT token after window position j (what the model
    was asked to predict there)."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import llama_forward

    def fn(params, toks, targets, positions, k, v):
        logits, k, v = llama_forward(params, cfg, toks, positions, k, v)
        lsm = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
        top_lps, top_ids = jax.lax.top_k(lsm, K)
        chosen = jnp.take_along_axis(lsm, targets[0][:, None], axis=1)[:, 0]
        return k, v, chosen, top_ids, top_lps

    return fn


def make_embed_fn(cfg, W: int):
    """Window program for embeddings: forward W tokens against the running
    cache, emit (new_k, new_v, hidden [W, D]) — the final-norm hidden
    states (llama_forward_hidden); the host takes the last live position's
    row. No vocab projection at all: the [1, W, V] logits buffer never
    exists on this pass."""
    from ..models.llama import llama_forward_hidden

    def fn(params, toks, positions, k, v):
        hidden, k, v = llama_forward_hidden(params, cfg, toks, positions,
                                            k, v)
        return k, v, hidden[0]

    return fn


# every scoring program computes the MAXIMUM top-K and the host slices to
# the requested `top`: the extra lanes cost nothing next to the forward,
# and it keeps the program family keyed by bucket alone — so one warmup
# pass per bucket covers every client top value (no per-top cache misses)
_SCORE_K = 20


def score_tokens(engine, prompt_tokens: Sequence[int],
                 completion_tokens: Sequence[int], top: int = 5,
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-token logprobs for `completion_tokens` given `prompt_tokens`.

    Returns (chosen_lp [C], top_ids [C, top], top_lps [C, top]) as numpy.
    Compiles one program per (cache bucket, window) pair through the
    engine's executor — bounded like every other program family.
    """
    import jax.numpy as jnp

    if not completion_tokens:
        raise ValueError("completion_tokens must be non-empty")
    if not 1 <= top <= _SCORE_K:
        raise ValueError(f"top must be in [1, {_SCORE_K}], got {top}")
    seq = list(prompt_tokens) + list(completion_tokens)
    P, L = len(prompt_tokens), len(seq)
    if P < 1:
        raise ValueError("prompt_tokens must be non-empty")
    if L > engine.prefill_buckets[-1]:
        raise ValueError(f"prompt+completion of {L} tokens exceeds the "
                         f"largest scoring bucket "
                         f"({engine.prefill_buckets[-1]})")

    chosen_parts: List[np.ndarray] = []
    ids_parts: List[np.ndarray] = []
    lps_parts: List[np.ndarray] = []

    def window_args(w0, n, W):
        toks = np.zeros((1, W), dtype=np.int32)
        targets = np.zeros((1, W), dtype=np.int32)
        toks[0, :n] = seq[w0:w0 + n]
        m = min(W, L - 1 - w0)  # positions with a real target
        targets[0, :m] = seq[w0 + 1:w0 + 1 + m]
        return jnp.asarray(toks), jnp.asarray(targets)

    def collect(w0, n, W, outs):
        m = min(W, L - 1 - w0)
        if m <= 0:
            return
        chosen, top_ids, top_lps = outs
        chosen_parts.append(np.asarray(chosen)[:m])
        ids_parts.append(np.asarray(top_ids)[:m])
        lps_parts.append(np.asarray(top_lps)[:m])

    _window_pass(engine, L, "score",
                 lambda cfg, W: make_score_fn(cfg, W, _SCORE_K),
                 window_args, collect, work_length=L - 1)

    chosen = np.concatenate(chosen_parts)[P - 1:L - 1]
    ids = np.concatenate(ids_parts)[P - 1:L - 1, :top]
    lps = np.concatenate(lps_parts)[P - 1:L - 1, :top]
    return chosen, ids, lps


def embed_tokens(engine, tokens: Sequence[int],
                 normalize: bool = True) -> np.ndarray:
    """Sequence embedding: the final-norm hidden state at the LAST
    position (the causal summary of the whole sequence — the pooling
    E5-Mistral-style decoder embedders use), optionally L2-normalized
    (the OpenAI /v1/embeddings convention: unit-length vectors). Returns
    float32 [D]."""
    import jax.numpy as jnp

    if not tokens:
        raise ValueError("tokens must be non-empty")
    L = len(tokens)
    if L > engine.prefill_buckets[-1]:
        raise ValueError(f"input of {L} tokens exceeds the largest "
                         f"embedding bucket ({engine.prefill_buckets[-1]})")
    out = {}

    def window_args(w0, n, W):
        toks = np.zeros((1, W), dtype=np.int32)
        toks[0, :n] = tokens[w0:w0 + n]
        return (jnp.asarray(toks),)

    def collect(w0, n, W, outs):
        if w0 + W >= L:  # the window holding position L-1
            out["last"] = np.asarray(outs[0][L - 1 - w0], dtype=np.float32)

    _window_pass(engine, L, "embed", make_embed_fn, window_args, collect)
    last = out["last"]
    if normalize:
        norm = float(np.linalg.norm(last))
        if norm > 0.0:
            last = last / norm
    return last


def warmup_post_hoc(engine, embeddings: bool = True) -> int:
    """Pre-compile the scoring (and optionally embedding) program families
    — one window program per cache bucket — so the first client logprobs/
    embeddings request never pays a compile under its REQUEST_TIMEOUT
    (docs/serving.md's warm-at-boot recipe, as an API). Covers EVERY
    client `top` value: the scoring program always computes _SCORE_K lanes
    and the host slices (see _SCORE_K). Returns the number of passes run.
    Cost: one bucket-length forward per bucket per family, once per boot,
    amortized across boots by PROGRAM_CACHE_DIR."""
    ran = 0
    for S in engine.prefill_buckets:
        score_tokens(engine, [1] * max(1, S - 1), [1])
        ran += 1
        if embeddings:
            embed_tokens(engine, [1] * S)
            ran += 1
    return ran
