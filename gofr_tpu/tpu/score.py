"""Teacher-forced per-token logprobs: the OpenAI `logprobs` feature.

Design: a POST-HOC scoring pass instead of logprob plumbing through the
serving hot path. For this engine's decoding (greedy / temperature /
top-k/p are all draws from the position's distribution), the distribution
at completion position i conditions only on the tokens before it — so a
teacher-forced forward over prompt+completion reproduces the decode-time
distributions exactly, and one additive program family delivers
chosen-token logprobs + top-K alternatives with ZERO changes to the
prefill/decode/speculative programs or their signatures. The cost model
matches how the feature is used: nothing on the default path, one
bucketed forward per request that asks.

The pass runs in cache-bucket windows (W tokens per dispatch) so the
logits buffer is [1, W, V] (~64 MB at Llama-3 vocab) instead of
[1, S, V]; the top-K reduction happens on device and only [W, K+1] floats
cross to the host per window.

Parity: the reference returns exactly what its upstream surface promises
rather than approximations (responder envelope discipline,
/root/reference/pkg/gofr/http/responder.go:24-50); here the promise is
OpenAI's `logprobs` contract on /v1 completions + chat.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def make_score_fn(cfg, W: int, K: int):
    """Window program: forward W tokens against the running cache, emit
    (new_k, new_v, chosen_lp [W], top_ids [W, K], top_lps [W, K]).

    `targets[j]` is the NEXT token after window position j (what the model
    was asked to predict there); padded tail positions produce garbage
    that the host slices away — causality guarantees they cannot
    contaminate earlier positions."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import llama_forward

    def fn(params, toks, targets, positions, k, v):
        logits, k, v = llama_forward(params, cfg, toks, positions, k, v)
        lsm = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
        top_lps, top_ids = jax.lax.top_k(lsm, K)
        chosen = jnp.take_along_axis(lsm, targets[0][:, None], axis=1)[:, 0]
        return k, v, chosen, top_ids, top_lps

    return fn


def score_tokens(engine, prompt_tokens: Sequence[int],
                 completion_tokens: Sequence[int], top: int = 5,
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-token logprobs for `completion_tokens` given `prompt_tokens`.

    Returns (chosen_lp [C], top_ids [C, top], top_lps [C, top]) as numpy.
    Compiles one program per (cache bucket, window, top) triple through the
    engine's executor — bounded like every other program family. Runs
    independently of the serving loop (no engine state is touched; device
    execution interleaves with serving dispatches under JAX's own
    serialization), so a busy server can score without pausing decode.
    """
    import jax.numpy as jnp

    from ..models.llama import init_kv_cache
    from .executor import next_bucket

    if not completion_tokens:
        raise ValueError("completion_tokens must be non-empty")
    if not 1 <= top <= 20:
        raise ValueError(f"top must be in [1, 20], got {top}")
    seq = list(prompt_tokens) + list(completion_tokens)
    P, L = len(prompt_tokens), len(seq)
    if P < 1:
        raise ValueError("prompt_tokens must be non-empty")
    buckets = engine.prefill_buckets
    if L > buckets[-1]:
        raise ValueError(f"prompt+completion of {L} tokens exceeds the "
                         f"largest scoring bucket ({buckets[-1]})")
    S = next_bucket(L, buckets)
    W = min(128, S)
    cfg = engine.cfg
    # fp cache regardless of the engine's serving kv_dtype: this is the
    # plain model forward, not the quantized serving cache
    k, v = init_kv_cache(cfg, 1, S)

    chosen_parts: List[np.ndarray] = []
    ids_parts: List[np.ndarray] = []
    lps_parts: List[np.ndarray] = []
    fn = make_score_fn(cfg, W, top)
    # windows cover positions [0, L-1); position j predicts seq[j+1], so
    # the last position that matters is L-2
    for w0 in range(0, L - 1, W):
        toks = np.zeros((1, W), dtype=np.int32)
        targets = np.zeros((1, W), dtype=np.int32)
        n = min(W, L - w0)          # tokens fed this window
        toks[0, :n] = seq[w0:w0 + n]
        m = min(W, L - 1 - w0)      # positions with a real target
        targets[0, :m] = seq[w0 + 1:w0 + 1 + m]
        positions = jnp.broadcast_to(
            jnp.arange(w0, w0 + W, dtype=jnp.int32), (1, W))
        args = (engine.params, jnp.asarray(toks), jnp.asarray(targets),
                positions, k, v)
        program = engine.executor.compile(
            f"score-{S}x{W}k{top}", fn, args, donate_argnums=(4, 5))
        k, v, chosen, top_ids, top_lps = program(*args)
        chosen_parts.append(np.asarray(chosen)[:m])
        ids_parts.append(np.asarray(top_ids)[:m])
        lps_parts.append(np.asarray(top_lps)[:m])

    chosen = np.concatenate(chosen_parts)[P - 1:L - 1]
    ids = np.concatenate(ids_parts)[P - 1:L - 1]
    lps = np.concatenate(lps_parts)[P - 1:L - 1]
    return chosen, ids, lps
