"""Incident autopsy plane: SLO burn-rate alerting + evidence bundles.

PRs 1-4 built deep *recording* observability — the flight recorder
(tpu/flightrecorder.py) explains one request, the utilization ledger
(tpu/utilization.py) scores the engine against the roofline, the step
ledger (tpu/stepledger.py) explains one loop iteration — but none of it
*reacts*: the SLO goodput gauges carry no error-budget semantics an
operator can page on, and when the straggler sentinel, the reset-storm
breaker, or a poison quarantine fires at 3 a.m., the evidence (step
ring, engine snapshot, slowest requests) has rolled out of its bounded
rings by the time a human curls ``/debug/*``. This module closes the
loop with the standard SRE pair:

  * **SLOBurnEngine** — rolling error-budget accounting over the
    existing TTFT/TPOT targets plus an availability SLO (errored or
    shed vs. completed), computed over PAIRED fast/slow windows
    (default 5 m / 1 h). The burn rate is ``observed error rate /
    error budget`` where the budget is ``1 - objective`` (objective
    0.99 and a 2 % bad fraction burn at 2x). Alerting follows the
    multi-window multi-burn-rate rule (Google SRE workbook ch. 5): a
    state is ``page`` only when BOTH windows burn past the page
    threshold — the fast window gives reaction time, the slow window
    keeps one bad minute (or one straggler step) from paging — and
    recovery is automatic as the fast window drains. Published as
    ``app_tpu_slo_burn_rate{slo,window}`` and
    ``app_tpu_slo_alert_state{slo}`` (0 ok / 1 warn / 2 page), served
    at ``GET /debug/slo``.
  * **IncidentManager** — subscribes to anomaly triggers (burn-rate
    page transitions, straggler-sentinel streaks, breaker open, poison
    quarantine) and captures a rate-limited **evidence bundle**: frozen
    JSON snapshots of the step ring, the ``/debug/engine`` payload, the
    K slowest in-flight/recent requests from the flight recorder,
    recent recorder engine events, a config fingerprint, and (when the
    profiler is idle) a triggered xprof trace dir. Bundles are written
    under ``INCIDENT_DIR``, indexed in a bounded ring, served at
    ``GET /debug/incidents[/{id}]``, counted in
    ``app_tpu_incidents_total{trigger}`` (suppressed triggers in
    ``app_tpu_incidents_suppressed_total{trigger}``) and surfaced as
    ``incident`` flight-recorder events.

Hot-path contract: every engine hook is one None-guarded attribute
check (``if self.incidents is not None: ...``), ``trigger()`` does O(1)
bookkeeping under one short lock and hands the actual capture to a
daemon thread — the engine loop never snapshots, serializes, or touches
the filesystem. A busy profiler is *skipped*, never awaited.

Wire-up (App.enable_incident_autopsy, both example servers):

    GET /debug/slo              -> budgets, burn rates, alert states
    GET /debug/incidents        -> bundle index + trigger/suppression
                                   counters
    GET /debug/incidents/{id}   -> one frozen evidence bundle
"""

from __future__ import annotations

import collections
import hashlib
import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .obs import MetricsHook

SLO_NAMES = ("ttft", "tpot", "availability")

# alert states (gauge values); the standard both-windows rule decides
STATE_OK, STATE_WARN, STATE_PAGE = 0, 1, 2
STATE_LABELS = {STATE_OK: "ok", STATE_WARN: "warn", STATE_PAGE: "page"}

# paired windows + thresholds: the SRE-workbook 5m/1h "fast burn" pair;
# 14.4x burn spends a 30-day budget in ~2 days, 6x in ~5 days
DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0
DEFAULT_PAGE_BURN = 14.4
DEFAULT_WARN_BURN = 6.0

DEFAULT_OBJECTIVES = {"ttft": 0.99, "tpot": 0.99, "availability": 0.999}

# per-window event cap: at the north-star ~50 req/s a 1 h window holds
# 180k completions; beyond the cap the oldest events age out early and
# the window simply covers a shorter span — accounting degrades, never
# grows without bound
_WINDOW_MAXLEN = 65536


class _Window:
    """One rolling (t, bad) event window with O(1) running totals."""

    __slots__ = ("window_s", "events", "n", "bad", "peak_burn")

    def __init__(self, window_s: float):
        self.window_s = float(window_s)
        self.events: "collections.deque" = collections.deque(
            maxlen=_WINDOW_MAXLEN)
        self.n = 0
        self.bad = 0
        self.peak_burn = 0.0

    def add(self, t: float, bad: bool) -> None:
        if len(self.events) == self.events.maxlen:
            # maxlen eviction drops the OLDEST event: keep totals honest
            t0, b0 = self.events[0]
            self.n -= 1
            self.bad -= b0
        self.events.append((t, 1 if bad else 0))
        self.n += 1
        self.bad += 1 if bad else 0

    def prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self.events and self.events[0][0] < cutoff:
            _, b = self.events.popleft()
            self.n -= 1
            self.bad -= b

    def error_rate(self) -> Optional[float]:
        if self.n <= 0:
            return None
        return self.bad / self.n

    def burn(self, budget: float, min_events: int) -> Optional[float]:
        """Burn rate = error rate / budget; None until the window holds
        `min_events` observations (a near-empty window must not page)."""
        if self.n < min_events:
            return None
        rate = self.error_rate()
        if rate is None:
            return None
        value = rate / max(budget, 1e-9)
        if value > self.peak_burn:
            self.peak_burn = value
        return value


class _SLOTrack:
    __slots__ = ("name", "objective", "budget", "fast", "slow", "state")

    def __init__(self, name: str, objective: float,
                 fast_window_s: float, slow_window_s: float):
        self.name = name
        self.objective = float(objective)
        self.budget = max(1e-9, 1.0 - self.objective)
        self.fast = _Window(fast_window_s)
        self.slow = _Window(slow_window_s)
        self.state = STATE_OK


class SLOBurnEngine:
    """Error-budget burn accounting over paired windows (module doc).

    Fed by the flight recorder (``use_burn_engine``): each completed
    request contributes one event per SLO it can score (ttft/tpot need
    the respective measurement; availability scores every completion,
    bad when it errored), and every stall/breaker shed contributes an
    availability failure — the requests the SLO *lost* without serving.
    All public methods take one short lock; ``on_page`` fires outside
    it (the IncidentManager takes its own lock)."""

    def __init__(self, slo_ttft_s: float = 0.150, slo_tpot_s: float = 0.050,
                 objectives: Optional[Dict[str, float]] = None,
                 fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 page_burn: float = DEFAULT_PAGE_BURN,
                 warn_burn: float = DEFAULT_WARN_BURN,
                 min_events: int = 12, metrics=None, logger=None,
                 clock=time.monotonic,
                 on_page: Optional[Callable[..., Any]] = None):
        self.slo_ttft_s = float(slo_ttft_s)
        self.slo_tpot_s = float(slo_tpot_s)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.page_burn = float(page_burn)
        self.warn_burn = float(warn_burn)
        self.min_events = max(1, int(min_events))
        self._clock = clock
        self._obs = MetricsHook(metrics, logger=logger)
        self.logger = logger
        self.on_page = on_page
        objectives = dict(DEFAULT_OBJECTIVES, **(objectives or {}))
        self._lock = threading.Lock()
        self._tracks = {
            name: _SLOTrack(name, objectives[name],
                            self.fast_window_s, self.slow_window_s)
            for name in SLO_NAMES}
        # recent alert transitions, for /debug/slo (the paging history an
        # operator reads back after the fact)
        self._transitions: "collections.deque" = collections.deque(maxlen=32)

    def use_metrics(self, metrics) -> None:
        if metrics is not None:
            self._obs = MetricsHook(metrics, logger=self.logger)

    # -- event intake (flight-recorder thread, best-effort) -------------------
    def observe_request(self, ttft_s: Optional[float],
                        tpot_s: Optional[float], error: bool = False) -> None:
        """One completed request: scores ttft/tpot when measured, and
        availability always (bad on an errored outcome)."""
        try:
            events = [("availability", bool(error))]
            if ttft_s is not None:
                events.append(("ttft", ttft_s > self.slo_ttft_s))
            if tpot_s is not None:
                events.append(("tpot", tpot_s > self.slo_tpot_s))
            self._record(events)
        except Exception:  # noqa: BLE001 - accounting is best-effort
            pass

    def observe_shed(self) -> None:
        """A request the server refused (stall/breaker shed): budget
        spent without serving — an availability failure."""
        try:
            self._record([("availability", True)])
        except Exception:  # noqa: BLE001
            pass

    def _record(self, events: List[tuple]) -> None:
        now = self._clock()
        paged: List[tuple] = []
        with self._lock:
            for name, bad in events:
                track = self._tracks[name]
                track.fast.add(now, bad)
                track.slow.add(now, bad)
            paged = self._recompute_locked(now)
        self._fire(paged)

    # -- state machine --------------------------------------------------------
    def _burns_locked(self, track: _SLOTrack, now: float):
        track.fast.prune(now)
        track.slow.prune(now)
        return (track.fast.burn(track.budget, self.min_events),
                track.slow.burn(track.budget, self.min_events))

    def _recompute_locked(self, now: float) -> List[tuple]:
        """Re-evaluate every track; publish gauges; return page
        transitions to fire outside the lock."""
        paged = []
        for track in self._tracks.values():
            fast, slow = self._burns_locked(track, now)

            def both_over(threshold: float) -> bool:
                return (fast is not None and slow is not None
                        and fast >= threshold and slow >= threshold)

            state = STATE_OK
            if both_over(self.page_burn):
                state = STATE_PAGE
            elif both_over(self.warn_burn):
                state = STATE_WARN
            if state != track.state:
                info = {"slo": track.name,
                        "from": STATE_LABELS[track.state],
                        "to": STATE_LABELS[state],
                        "burn_fast": round(fast, 3) if fast is not None
                        else None,
                        "burn_slow": round(slow, 3) if slow is not None
                        else None,
                        # lint: clock-ok operator-facing transition timestamp (burn math itself uses the monotonic window clock)
                        "t": time.time()}
                self._transitions.append(info)
                if state == STATE_PAGE:
                    paged.append((track.name, info))
                track.state = state
            self._publish_track(track, fast, slow)
        return paged

    def _publish_track(self, track: _SLOTrack, fast, slow) -> None:
        if fast is not None:
            self._obs.gauge("app_tpu_slo_burn_rate", round(fast, 4),
                            slo=track.name, window="fast")
        if slow is not None:
            self._obs.gauge("app_tpu_slo_burn_rate", round(slow, 4),
                            slo=track.name, window="slow")
        self._obs.gauge("app_tpu_slo_alert_state", track.state,
                        slo=track.name)

    def _fire(self, paged: List[tuple]) -> None:
        for name, info in paged:
            if self.logger is not None:
                try:
                    self.logger.errorf(
                        "SLO %s burning: fast %.1fx / slow %.1fx over "
                        "budget — PAGE", name, info.get("burn_fast") or 0.0,
                        info.get("burn_slow") or 0.0)
                except Exception:  # noqa: BLE001
                    pass
            if self.on_page is not None:
                try:
                    self.on_page(slo=name, **{k: v for k, v in info.items()
                                              if k != "slo"})
                except Exception:  # noqa: BLE001 - alerting is best-effort
                    pass

    # -- operator surface -----------------------------------------------------
    def publish(self) -> None:
        """Scrape hook: re-evaluate so burn DECAYS while the server is
        idle (no completions means no _record calls to age the windows)."""
        with self._lock:
            paged = self._recompute_locked(self._clock())
        self._fire(paged)

    def states(self) -> Dict[str, str]:
        """Current alert state per SLO track ("ok"/"warn"/"page"), fresh:
        re-evaluates first so burn decays toward ok even when nothing
        completes and nothing scrapes. This is the probe the QoS shed
        ladder (tpu/qos.py) actuates on — the point where the burn
        engine stops being a read-only pager."""
        with self._lock:
            paged = self._recompute_locked(self._clock())
            out = {name: STATE_LABELS[track.state]
                   for name, track in self._tracks.items()}
        self._fire(paged)
        return out

    def peaks(self) -> Dict[str, Dict[str, float]]:
        """Max burn rate observed per SLO/window (soak artifacts)."""
        with self._lock:
            return {name: {"fast": round(t.fast.peak_burn, 3),
                           "slow": round(t.slow.peak_burn, 3)}
                    for name, t in self._tracks.items()}

    def snapshot(self) -> Dict[str, Any]:
        """The /debug/slo payload."""
        now = self._clock()
        with self._lock:
            slos: Dict[str, Any] = {}
            for name, track in self._tracks.items():
                fast, slow = self._burns_locked(track, now)
                slos[name] = {
                    "objective": track.objective,
                    "error_budget": round(track.budget, 6),
                    "state": STATE_LABELS[track.state],
                    "windows": {
                        "fast": {
                            "window_s": track.fast.window_s,
                            "events": track.fast.n,
                            "bad": track.fast.bad,
                            "error_rate": track.fast.error_rate(),
                            "burn_rate": (round(fast, 3)
                                          if fast is not None else None),
                            "peak_burn": round(track.fast.peak_burn, 3),
                        },
                        "slow": {
                            "window_s": track.slow.window_s,
                            "events": track.slow.n,
                            "bad": track.slow.bad,
                            "error_rate": track.slow.error_rate(),
                            "burn_rate": (round(slow, 3)
                                          if slow is not None else None),
                            "peak_burn": round(track.slow.peak_burn, 3),
                        },
                    },
                }
            return {
                "targets": {"ttft_s": self.slo_ttft_s,
                            "tpot_s": self.slo_tpot_s},
                "thresholds": {"page_burn": self.page_burn,
                               "warn_burn": self.warn_burn,
                               "min_events": self.min_events},
                "slos": slos,
                "transitions": list(self._transitions),
            }


class IncidentManager:
    """Anomaly-triggered evidence bundles (module doc).

    ``trigger()`` is the hot-path entry: one short lock for the
    rate-limit decision (cooldown + max-per-hour), then a daemon thread
    does the capture — snapshotting the step ring / engine / recorder,
    fingerprinting the config, optionally kicking an async profiler
    capture, and writing ``INCIDENT_DIR/incident-<id>.json``. Bundles
    live in a bounded ring for ``GET /debug/incidents``; files persist
    past eviction for after-the-fact forensics."""

    def __init__(self, engine=None, recorder=None, dir: str = "./incidents",
                 capacity: int = 32, cooldown_s: float = 300.0,
                 max_per_hour: int = 6, slowest_k: int = 5,
                 profile_seconds: float = 0.0,
                 profile_dir: Optional[str] = None,
                 straggler_streak: int = 3, straggler_window: int = 32,
                 fingerprint: Optional[Dict[str, Any]] = None,
                 metrics=None, logger=None, clock=time.monotonic):
        self.engine = engine
        self.recorder = recorder
        self.dir = dir
        self.capacity = max(1, int(capacity))
        self.cooldown_s = float(cooldown_s)
        self.max_per_hour = max(1, int(max_per_hour))
        self.slowest_k = max(1, int(slowest_k))
        self.profile_seconds = float(profile_seconds)
        # None -> autopsy captures land beside the bundles (dir/profiles);
        # App.enable_incident_autopsy overrides with PROFILE_DIR when set
        self.profile_dir = profile_dir or os.path.join(dir, "profiles")
        self.straggler_streak = max(1, int(straggler_streak))
        self.straggler_window = max(self.straggler_streak,
                                    int(straggler_window))
        self._fingerprint_extra = dict(fingerprint or {})
        self._obs = MetricsHook(metrics, logger=logger)
        self.logger = logger
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.capacity)
        self._capture_times: "collections.deque" = collections.deque()
        self._last_capture_at: Optional[float] = None
        self.captured_total = 0
        self.suppressed: Dict[str, int] = {}
        self.triggers: Dict[str, int] = {}
        # flagged-step seq numbers; `streak` of them inside a span of
        # `straggler_window` steps escalates to a trigger
        self._straggler_seqs: "collections.deque" = collections.deque(
            maxlen=self.straggler_streak)
        self._threads: List[threading.Thread] = []

    # -- trigger intake (engine loop thread: O(1), never blocks) --------------
    def trigger(self, kind: str, **ctx) -> Optional[int]:
        """Record an anomaly; returns the incident id when a capture was
        admitted, None when rate-limited. The capture itself runs on a
        daemon thread — this call only takes the bookkeeping lock."""
        now = self._clock()
        with self._lock:
            self.triggers[kind] = self.triggers.get(kind, 0) + 1
            while (self._capture_times
                   and now - self._capture_times[0] > 3600.0):
                self._capture_times.popleft()
            limited = (
                (self._last_capture_at is not None
                 and now - self._last_capture_at < self.cooldown_s)
                or len(self._capture_times) >= self.max_per_hour)
            if limited:
                self.suppressed[kind] = self.suppressed.get(kind, 0) + 1
            else:
                incident_id = next(self._seq)
                self._last_capture_at = now
                self._capture_times.append(now)
        if limited:
            self._obs.counter("app_tpu_incidents_suppressed_total",
                              trigger=kind)
            return None
        self._obs.counter("app_tpu_incidents_total", trigger=kind)
        thread = threading.Thread(
            target=self._capture, args=(incident_id, kind, ctx),
            name=f"incident-{incident_id}", daemon=True)
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            self._threads.append(thread)
        thread.start()
        return incident_id

    def note_straggler(self, step: int, **ctx) -> None:
        """Straggler-sentinel feed: escalate to a trigger only when
        `straggler_streak` flagged steps land within `straggler_window`
        steps of each other — one slow iteration is the sentinel's
        (already-counted) business, a STREAK is an incident."""
        try:
            with self._lock:
                self._straggler_seqs.append(int(step))
                full = len(self._straggler_seqs) == self.straggler_streak
                spread = (self._straggler_seqs[-1] - self._straggler_seqs[0]
                          if full else None)
                streak = full and spread < self.straggler_window
                if streak:
                    self._straggler_seqs.clear()
            if streak:
                self.trigger("straggler_streak",
                             flagged_steps=self.straggler_streak,
                             within_steps=self.straggler_window, **ctx)
        except Exception:  # noqa: BLE001 - never disturb the loop
            pass

    def on_slo_page(self, slo: str, **info) -> None:
        """SLOBurnEngine.on_page adapter."""
        self.trigger("slo_page", slo=slo, **info)

    # -- capture (daemon thread) ----------------------------------------------
    def config_fingerprint(self) -> Dict[str, Any]:
        facts: Dict[str, Any] = dict(self._fingerprint_extra)
        engine = self.engine
        if engine is not None:
            try:
                facts.update({
                    "engine": type(engine).__name__,
                    "n_slots": getattr(engine, "n_slots", None),
                    "max_seq_len": getattr(engine, "max_seq_len", None),
                    "prefill_buckets": list(
                        getattr(engine, "prefill_buckets", ()) or ()),
                    "decode_block_size": getattr(engine, "decode_block_size",
                                                 None),
                    "speculative_tokens": getattr(engine,
                                                  "speculative_tokens", None),
                    "chunk_prefill_tokens": getattr(
                        engine, "chunk_prefill_tokens", None),
                    "retry_budget": getattr(engine, "retry_budget", None),
                })
                cfg = getattr(engine, "cfg", None)
                if cfg is not None:
                    import dataclasses

                    facts["model"] = {
                        k: v for k, v in dataclasses.asdict(cfg).items()
                        if isinstance(v, (int, float, str, bool, type(None)))}
            except Exception:  # noqa: BLE001
                pass
        digest = hashlib.sha256(
            json.dumps(facts, sort_keys=True, default=str).encode()
        ).hexdigest()[:16]
        return {"sha256_16": digest, "facts": facts}

    def _capture(self, incident_id: int, kind: str,
                 ctx: Dict[str, Any]) -> None:
        bundle: Dict[str, Any] = {
            "id": incident_id,
            "trigger": kind,
            "context": ctx,
            "captured_at": time.time(),  # lint: clock-ok incident bundles are correlated with external logs by wall time
        }
        engine = self.engine
        recorder = self.recorder or getattr(engine, "recorder", None)
        try:
            steps = getattr(engine, "steps", None)
            if steps is not None:
                bundle["steps"] = steps.snapshot(recent=32)
        except Exception as exc:  # noqa: BLE001 - partial bundles > no bundle
            bundle["steps_error"] = str(exc)
        try:
            if engine is not None:
                from .utilization import engine_snapshot

                bundle["engine"] = engine_snapshot(engine)
        except Exception as exc:  # noqa: BLE001
            bundle["engine_error"] = str(exc)
        try:
            if recorder is not None:
                snap = recorder.snapshot()
                bundle["slo_goodput"] = snap.get("slo")
                bundle["engine_events"] = snap.get("engine_events", [])
                slowest = self._slowest(snap)
                bundle["slowest_requests"] = slowest
                if slowest:
                    # the deep link: the single request most likely to
                    # explain the anomaly, resolvable at
                    # /debug/requests/{id} while it is still in the ring
                    bundle["slowest_request_id"] = slowest[0].get("id")
        except Exception as exc:  # noqa: BLE001
            bundle["recorder_error"] = str(exc)
        try:
            # what WAS the engine loop doing: the host sampling
            # profiler's top loop-thread stacks (tpu/hostprof.py), read
            # at capture time so enable order doesn't matter
            prof = getattr(engine, "hostprof", None)
            if prof is not None:
                bundle["loop_stacks"] = prof.top_loop_stacks()
        except Exception as exc:  # noqa: BLE001
            bundle["hostprof_error"] = str(exc)
        bundle["config_fingerprint"] = self.config_fingerprint()
        bundle["profile"] = self._maybe_profile(incident_id)
        path = None
        try:
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(self.dir, f"incident-{incident_id}.json")
            with open(path, "w", encoding="utf-8") as fp:
                json.dump(bundle, fp, indent=1, default=str)
            bundle["path"] = path
        except Exception as exc:  # noqa: BLE001 - keep the in-memory bundle
            bundle["write_error"] = str(exc)
        with self._lock:
            self._ring.append(bundle)
            self.captured_total += 1
        if recorder is not None:
            try:
                recorder.record_engine_event("incident", id=incident_id,
                                             trigger=kind, path=path)
            except Exception:  # noqa: BLE001
                pass
        if self.logger is not None:
            try:
                self.logger.errorf(
                    "incident %d captured (trigger=%s): %s", incident_id,
                    kind, path or "in-memory only")
            except Exception:  # noqa: BLE001
                pass

    def _slowest(self, snap: Dict[str, Any]) -> List[Dict[str, Any]]:
        """K slowest requests: completed ones by TTFT (the blown-budget
        evidence), then the oldest in-flight ones (the still-stuck
        evidence), each tagged with where it was found."""
        done = sorted(
            (r for r in snap.get("recent", []) if "ttft_s" in r),
            key=lambda r: -r["ttft_s"])
        live = snap.get("in_flight", [])  # already oldest-first
        out = []
        for rec in itertools.chain(live, done):
            entry = dict(rec)
            entry["where"] = "in_flight" if rec in live else "recent"
            out.append(entry)
            if len(out) >= self.slowest_k:
                break
        # oldest in-flight first, then slowest completions — the head of
        # the list is the best single suspect either way
        return out

    def _maybe_profile(self, incident_id: int) -> Dict[str, Any]:
        """Kick an async device-trace capture when enabled AND the
        profiler is idle. Busy (a manual capture, an earlier incident) is
        SKIPPED — an incident capture must never wait on the device."""
        if self.profile_seconds <= 0:
            return {"skipped": "disabled"}
        try:
            from . import profiler

            trace_dir, seconds = profiler.start_capture(
                self.profile_seconds, self.profile_dir,
                trigger="incident")
            return {"trace_dir": trace_dir, "seconds": seconds,
                    "status": "capturing"}
        except RuntimeError:
            return {"skipped": "busy"}
        except Exception as exc:  # noqa: BLE001
            return {"skipped": f"error: {exc}"}

    # -- operator surface -----------------------------------------------------
    def wait_idle(self, timeout_s: float = 10.0) -> bool:
        """Block until outstanding captures finish (tests, soak drains)."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            threads = list(self._threads)
        for thread in threads:
            thread.join(timeout=max(0.0, deadline - time.monotonic()))
        return all(not t.is_alive() for t in threads)

    def index(self) -> Dict[str, Any]:
        """The /debug/incidents payload: newest-first bundle metadata."""
        with self._lock:
            ring = list(self._ring)
            out = {
                "captured_total": self.captured_total,
                "capacity": self.capacity,
                "dir": self.dir,
                "rate_limit": {"cooldown_s": self.cooldown_s,
                               "max_per_hour": self.max_per_hour},
                "triggers": dict(self.triggers),
                "suppressed": dict(self.suppressed),
            }
        out["incidents"] = [
            {"id": b["id"], "trigger": b["trigger"],
             "captured_at": b["captured_at"],
             "slowest_request_id": b.get("slowest_request_id"),
             "path": b.get("path"),
             "profile": (b.get("profile") or {}).get("trace_dir")
             or (b.get("profile") or {}).get("skipped")}
            for b in reversed(ring)]
        return out

    def lookup(self, incident_id: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            for bundle in self._ring:
                if bundle["id"] == incident_id:
                    return bundle
        return None

    def export_trace(self, incident_id: int) -> Optional[Dict[str, Any]]:
        """One bundle's ``slowest_requests`` as a replayable loadgen
        trace document (header fields + ``events``) — the traffic shape
        that blew the SLO, ready for ``tools/loadgen.py replay``. None
        when the id is not in the ring."""
        bundle = self.lookup(incident_id)
        if bundle is None:
            return None
        # local import: loadgen is the traffic plane; the autopsy plane
        # must not hard-depend on it at module import
        from ..loadgen.trace import TRACE_VERSION, events_from_incident

        events = events_from_incident(bundle)
        return {"trace_version": TRACE_VERSION,
                "source": f"incident:{incident_id}",
                "trigger": bundle.get("trigger"),
                "captured_at": bundle.get("captured_at"),
                "events": events}


def register_incident_metrics(metrics) -> None:
    """Register the autopsy-plane instruments on a metrics Manager
    (idempotent — TPUClient.register_metrics also registers them)."""
    for name, desc in (
        ("app_tpu_incidents_total",
         "incident evidence bundles captured, by trigger"),
        ("app_tpu_incidents_suppressed_total",
         "incident triggers suppressed by the capture rate limit "
         "(cooldown / max-per-hour), by trigger"),
    ):
        try:
            if metrics.get(name) is None:
                metrics.new_counter(name, desc)
        except Exception:  # noqa: BLE001 - already registered
            pass
    for name, desc in (
        ("app_tpu_slo_burn_rate",
         "SLO error-budget burn rate (error rate / budget) by slo and "
         "window (fast/slow)"),
        ("app_tpu_slo_alert_state",
         "SLO alert state by slo: 0 ok, 1 warn, 2 page (both-windows "
         "burn rule)"),
    ):
        try:
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        except Exception:  # noqa: BLE001
            pass


def install_routes(app, burn: SLOBurnEngine, incidents: IncidentManager,
                   slo_path: str = "/debug/slo",
                   incidents_path: str = "/debug/incidents") -> None:
    """Register the autopsy-plane endpoints on a gofr_tpu App (the
    flight-recorder install_routes idiom)."""
    from ..http.errors import HTTPError

    @app.get(slo_path)
    def debug_slo(ctx):  # noqa: ANN001
        return burn.snapshot()

    @app.get(incidents_path)
    def debug_incidents(ctx):  # noqa: ANN001
        return incidents.index()

    @app.get(incidents_path + "/{id}")
    def debug_incident_detail(ctx):  # noqa: ANN001
        raw = ctx.request.path_param("id")
        try:
            incident_id = int(raw)
        except (TypeError, ValueError) as exc:
            raise HTTPError(f"invalid incident id {raw!r}",
                            status_code=400) from exc
        bundle = incidents.lookup(incident_id)
        if bundle is None:
            raise HTTPError(
                f"incident {incident_id} not in the ring (the last "
                f"{incidents.capacity} bundles; older files persist "
                f"under {incidents.dir})", status_code=404)
        return bundle

    @app.get(incidents_path + "/{id}/trace")
    def debug_incident_trace(ctx):  # noqa: ANN001
        raw = ctx.request.path_param("id")
        try:
            incident_id = int(raw)
        except (TypeError, ValueError) as exc:
            raise HTTPError(f"invalid incident id {raw!r}",
                            status_code=400) from exc
        trace = incidents.export_trace(incident_id)
        if trace is None:
            raise HTTPError(f"incident {incident_id} not in the ring",
                            status_code=404)
        return trace
