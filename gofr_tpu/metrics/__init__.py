"""Name-keyed metrics registry with Prometheus text exposition.

Parity: reference pkg/gofr/metrics/register.go:15-25 (8-method Manager:
new_counter/new_updown_counter/new_histogram/new_gauge + typed record calls),
metrics/store.go:16-26 (name-keyed store, duplicate/missing-name errors in
metrics/errors.go), metrics/exporters/exporter.go (Prometheus exposition).

TPU-era additions (SURVEY.md §5): tokens/sec, TTFT/TPOT histograms, batch-size
gauge, HBM bytes, queue depth, compile-cache hits are registered by the
container/TPU client on top of this Manager.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted(labels.items()))


class MetricError(Exception):
    pass


class DuplicateMetric(MetricError):
    def __init__(self, name: str):
        super().__init__(f"metric {name} already registered")


class MetricNotFound(MetricError):
    def __init__(self, name: str):
        super().__init__(f"metric {name} not registered")


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, desc: str):
        self.name = name
        self.desc = desc
        self.series: Dict[LabelKey, object] = {}
        self.lock = threading.Lock()

    def expose(self) -> List[str]:  # pragma: no cover - interface
        raise NotImplementedError

    def _header(self) -> List[str]:
        return [f"# HELP {self.name} {self.desc}", f"# TYPE {self.name} {self.kind}"]


def _escape_label(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


class Counter(_Instrument):
    kind = "counter"

    def add(self, value: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self.lock:
            self.series[key] = float(self.series.get(key, 0.0)) + value  # type: ignore[arg-type]

    def expose(self) -> List[str]:
        # snapshot under the lock: a hot-loop add() inserting a NEW label
        # key during a scrape would otherwise mutate the dict mid-iteration
        # and 500 the /metrics endpoint
        with self.lock:
            series = list(self.series.items())
        out = self._header()
        for key, val in sorted(series):
            out.append(f"{self.name}{_fmt_labels(key)} {val}")
        return out


class UpDownCounter(Counter):
    kind = "gauge"  # prometheus has no updown counter; exposed as gauge


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        with self.lock:
            self.series[_label_key(labels)] = float(value)

    def expose(self) -> List[str]:
        with self.lock:   # see Counter.expose
            series = list(self.series.items())
        out = self._header()
        for key, val in sorted(series):
            out.append(f"{self.name}{_fmt_labels(key)} {val}")
        return out


DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30)


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name: str, desc: str, buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, desc)
        self.buckets = sorted(buckets)

    def record(self, value: float, **labels: str) -> None:
        self.record_n(value, 1, **labels)

    def record_n(self, value: float, n: int, **labels: str) -> None:
        """Record `n` identical observations in one lock acquisition.

        The serving hot loop emits one TPOT sample per generated token; at
        thousands of tokens/sec the per-call dict lookup + lock dominates —
        a decode block's tokens all share one measured step time, so they
        batch losslessly."""
        if n <= 0:
            return
        key = _label_key(labels)
        with self.lock:
            entry = self.series.get(key)
            if entry is None:
                entry = {"counts": [0] * (len(self.buckets) + 1), "sum": 0.0, "count": 0}
                self.series[key] = entry
            idx = bisect.bisect_left(self.buckets, value)
            entry["counts"][idx] += n  # type: ignore[index]
            entry["sum"] += value * n  # type: ignore[operator]
            entry["count"] += n  # type: ignore[operator]

    def percentile(self, q: float, **labels: str) -> float:
        """Approximate percentile from bucket MIDPOINTS (for tests/health,
        not SLO math): the percentile falls in bucket i, and the estimate
        is the midpoint of that bucket's (lower, upper] range — lower is 0
        for the first bucket. Observations past the last bound clamp to the
        last bound (the overflow bucket has no upper edge to average)."""
        key = _label_key(labels)
        with self.lock:
            entry = self.series.get(key)
            if not entry:
                return math.nan
            target = q * entry["count"]  # type: ignore[index]
            counts = list(entry["counts"])  # type: ignore[index]
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                if i >= len(self.buckets):
                    return self.buckets[-1]
                lower = self.buckets[i - 1] if i > 0 else 0.0
                return (lower + self.buckets[i]) / 2.0
        return self.buckets[-1]

    def expose(self) -> List[str]:
        with self.lock:   # see Counter.expose — counts lists mutate in
            # place under record_n, so each entry is deep-copied here
            series = [(key, {"counts": list(entry["counts"]),  # type: ignore[index]
                             "sum": entry["sum"],              # type: ignore[index]
                             "count": entry["count"]})         # type: ignore[index]
                      for key, entry in self.series.items()]
        out = self._header()
        for key, entry in sorted(series):
            cum = 0
            for i, bound in enumerate(self.buckets):
                cum += entry["counts"][i]  # type: ignore[index]
                lk = dict(key)
                lk["le"] = repr(bound) if isinstance(bound, float) else str(bound)
                out.append(f"{self.name}_bucket{_fmt_labels(_label_key(lk))} {cum}")
            cum += entry["counts"][-1]  # type: ignore[index]
            lk = dict(key)
            lk["le"] = "+Inf"
            out.append(f"{self.name}_bucket{_fmt_labels(_label_key(lk))} {cum}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} {entry['sum']}")  # type: ignore[index]
            out.append(f"{self.name}_count{_fmt_labels(key)} {entry['count']}")  # type: ignore[index]
        return out


class Manager:
    """The 8-method metrics manager handed to user handlers via ctx.metrics()."""

    def __init__(self, logger=None):
        self._store: Dict[str, _Instrument] = {}
        self._lock = threading.Lock()
        self._logger = logger

    def _register(self, inst: _Instrument) -> None:
        with self._lock:
            if inst.name in self._store:
                err = DuplicateMetric(inst.name)
                if self._logger is not None:
                    self._logger.error(str(err))
                    return
                raise err
            self._store[inst.name] = inst

    def _get(self, name: str, kind: type) -> _Instrument:
        inst = self._store.get(name)
        if inst is None or not isinstance(inst, kind):
            err = MetricNotFound(name)
            if self._logger is not None:
                self._logger.error(str(err))
                return kind(name, "unregistered")  # inert throwaway
            raise err
        return inst

    # -- registration --------------------------------------------------------
    def new_counter(self, name: str, desc: str) -> None:
        self._register(Counter(name, desc))

    def new_updown_counter(self, name: str, desc: str) -> None:
        self._register(UpDownCounter(name, desc))

    def new_gauge(self, name: str, desc: str) -> None:
        self._register(Gauge(name, desc))

    def new_histogram(self, name: str, desc: str, buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        self._register(Histogram(name, desc, buckets))

    # -- recording -----------------------------------------------------------
    def increment_counter(self, name: str, value: float = 1.0, **labels: str) -> None:
        self._get(name, Counter).add(value, **labels)  # type: ignore[attr-defined]

    def delta_updown_counter(self, name: str, value: float, **labels: str) -> None:
        self._get(name, UpDownCounter).add(value, **labels)  # type: ignore[attr-defined]

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        self._get(name, Gauge).set(value, **labels)  # type: ignore[attr-defined]

    def record_histogram(self, name: str, value: float, **labels: str) -> None:
        self._get(name, Histogram).record(value, **labels)  # type: ignore[attr-defined]

    def record_histogram_n(self, name: str, value: float, n: int,
                           **labels: str) -> None:
        self._get(name, Histogram).record_n(value, n, **labels)  # type: ignore[attr-defined]

    # -- introspection -------------------------------------------------------
    def get(self, name: str) -> Optional[_Instrument]:
        return self._store.get(name)

    def expose(self) -> str:
        """Render the whole registry in Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            instruments = list(self._store.values())
        for inst in sorted(instruments, key=lambda i: i.name):
            lines.extend(inst.expose())
        return "\n".join(lines) + "\n"


def new_metrics_manager(logger=None) -> Manager:
    return Manager(logger=logger)
