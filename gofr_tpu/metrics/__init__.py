"""Name-keyed metrics registry with Prometheus text exposition.

Parity: reference pkg/gofr/metrics/register.go:15-25 (8-method Manager:
new_counter/new_updown_counter/new_histogram/new_gauge + typed record calls),
metrics/store.go:16-26 (name-keyed store, duplicate/missing-name errors in
metrics/errors.go), metrics/exporters/exporter.go (Prometheus exposition).

TPU-era additions (SURVEY.md §5): tokens/sec, TTFT/TPOT histograms, batch-size
gauge, HBM bytes, queue depth, compile-cache hits are registered by the
container/TPU client on top of this Manager.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def format_bucket_bound(bound) -> str:
    """Canonical `le` label rendering, pinned by test_metrics:

      * +inf -> "+Inf"
      * integral values -> one decimal place ("1.0", not "1"), so an int
        bucket bound and its float twin can never emit two different
        series for the same bound
      * everything else -> shortest positional decimal, never exponent
        notation (repr's "1e-05" is expanded to "0.00001" — PromQL treats
        `le` as an opaque string, so "1e-05" and "0.00001" would be
        DIFFERENT series across clients that render differently)
    """
    v = float(bound)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return f"{int(v)}.0"
    s = repr(v)
    if "e" in s or "E" in s:
        from decimal import Decimal

        s = format(Decimal(s), "f")
    return s


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted(labels.items()))


class MetricError(Exception):
    pass


class DuplicateMetric(MetricError):
    def __init__(self, name: str):
        super().__init__(f"metric {name} already registered")


class MetricNotFound(MetricError):
    def __init__(self, name: str):
        super().__init__(f"metric {name} not registered")


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, desc: str):
        self.name = name
        self.desc = desc
        self.series: Dict[LabelKey, object] = {}
        self.lock = threading.Lock()

    def expose(self, openmetrics: bool = False) -> List[str]:  # pragma: no cover - interface
        raise NotImplementedError

    def _header(self) -> List[str]:
        return [f"# HELP {self.name} {self.desc}", f"# TYPE {self.name} {self.kind}"]


def _escape_label(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


class Counter(_Instrument):
    kind = "counter"

    def add(self, value: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self.lock:
            self.series[key] = float(self.series.get(key, 0.0)) + value  # type: ignore[arg-type]

    def expose(self, openmetrics: bool = False) -> List[str]:
        # snapshot under the lock: a hot-loop add() inserting a NEW label
        # key during a scrape would otherwise mutate the dict mid-iteration
        # and 500 the /metrics endpoint
        with self.lock:
            series = list(self.series.items())
        out = self._header()
        for key, val in sorted(series):
            out.append(f"{self.name}{_fmt_labels(key)} {val}")
        return out


class UpDownCounter(Counter):
    kind = "gauge"  # prometheus has no updown counter; exposed as gauge


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        with self.lock:
            self.series[_label_key(labels)] = float(value)

    def expose(self, openmetrics: bool = False) -> List[str]:
        with self.lock:   # see Counter.expose
            series = list(self.series.items())
        out = self._header()
        for key, val in sorted(series):
            out.append(f"{self.name}{_fmt_labels(key)} {val}")
        return out


DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30)


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name: str, desc: str, buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, desc)
        self.buckets = sorted(buckets)

    def record(self, value: float,
               exemplar: Optional[Dict[str, Any]] = None,
               **labels: str) -> None:
        self.record_n(value, 1, exemplar=exemplar, **labels)

    def record_n(self, value: float, n: int,
                 exemplar: Optional[Dict[str, Any]] = None,
                 **labels: str) -> None:
        """Record `n` identical observations in one lock acquisition.

        The serving hot loop emits one TPOT sample per generated token; at
        thousands of tokens/sec the per-call dict lookup + lock dominates —
        a decode block's tokens all share one measured step time, so they
        batch losslessly.

        `exemplar` (optional, e.g. {"trace_id": ..., "request_id": ...})
        attaches a correlation handle to the bucket this value lands in,
        last-write-wins per bucket — the Dapper-style metrics→trace link.
        Stored exemplars surface ONLY in OpenMetrics exposition (scrapes
        negotiating `application/openmetrics-text`); classic Prometheus
        text output is byte-identical with or without them."""
        if n <= 0:
            return
        key = _label_key(labels)
        with self.lock:
            entry = self.series.get(key)
            if entry is None:
                entry = {"counts": [0] * (len(self.buckets) + 1), "sum": 0.0, "count": 0}
                self.series[key] = entry
            idx = bisect.bisect_left(self.buckets, value)
            entry["counts"][idx] += n  # type: ignore[index]
            entry["sum"] += value * n  # type: ignore[operator]
            entry["count"] += n  # type: ignore[operator]
            if exemplar:
                # per-bucket last-write-wins: one (labels, value, timestamp)
                # triple per bucket keeps memory O(buckets), and "most
                # recent offender" is exactly what a deep link should open
                entry.setdefault("exemplars", {})[idx] = (  # type: ignore[union-attr]
                    _label_key({k: str(v) for k, v in exemplar.items()}),
                    float(value), time.time())

    def percentile(self, q: float, **labels: str) -> float:
        """Approximate percentile from bucket MIDPOINTS (for tests/health,
        not SLO math): the percentile falls in bucket i, and the estimate
        is the midpoint of that bucket's (lower, upper] range — lower is 0
        for the first bucket. Observations past the last bound clamp to the
        last bound (the overflow bucket has no upper edge to average)."""
        key = _label_key(labels)
        with self.lock:
            entry = self.series.get(key)
            if not entry:
                return math.nan
            target = q * entry["count"]  # type: ignore[index]
            counts = list(entry["counts"])  # type: ignore[index]
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                if i >= len(self.buckets):
                    return self.buckets[-1]
                lower = self.buckets[i - 1] if i > 0 else 0.0
                return (lower + self.buckets[i]) / 2.0
        return self.buckets[-1]

    @staticmethod
    def _fmt_exemplar(ex: Tuple) -> str:
        """OpenMetrics exemplar suffix: ` # {labels} value timestamp`."""
        labels, value, ts = ex
        inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)
        return f" # {{{inner}}} {value} {round(ts, 3)}"

    def expose(self, openmetrics: bool = False) -> List[str]:
        with self.lock:   # see Counter.expose — counts lists mutate in
            # place under record_n, so each entry is deep-copied here
            series = [(key, {"counts": list(entry["counts"]),  # type: ignore[index]
                             "sum": entry["sum"],              # type: ignore[index]
                             "count": entry["count"],          # type: ignore[index]
                             "exemplars": dict(entry.get("exemplars") or ())})  # type: ignore[union-attr]
                      for key, entry in self.series.items()]
        out = self._header()
        for key, entry in sorted(series):
            exemplars = entry["exemplars"] if openmetrics else {}
            cum = 0
            for i, bound in enumerate(self.buckets):
                cum += entry["counts"][i]  # type: ignore[index]
                lk = dict(key)
                lk["le"] = format_bucket_bound(bound)
                tail = (self._fmt_exemplar(exemplars[i])
                        if i in exemplars else "")
                out.append(f"{self.name}_bucket"
                           f"{_fmt_labels(_label_key(lk))} {cum}{tail}")
            cum += entry["counts"][-1]  # type: ignore[index]
            lk = dict(key)
            lk["le"] = "+Inf"
            overflow = len(self.buckets)
            tail = (self._fmt_exemplar(exemplars[overflow])
                    if overflow in exemplars else "")
            out.append(f"{self.name}_bucket"
                       f"{_fmt_labels(_label_key(lk))} {cum}{tail}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} {entry['sum']}")  # type: ignore[index]
            out.append(f"{self.name}_count{_fmt_labels(key)} {entry['count']}")  # type: ignore[index]
        return out


class Manager:
    """The 8-method metrics manager handed to user handlers via ctx.metrics()."""

    def __init__(self, logger=None):
        self._store: Dict[str, _Instrument] = {}
        self._lock = threading.Lock()
        self._logger = logger

    def _register(self, inst: _Instrument) -> None:
        with self._lock:
            if inst.name in self._store:
                err = DuplicateMetric(inst.name)
                if self._logger is not None:
                    self._logger.error(str(err))
                    return
                raise err
            self._store[inst.name] = inst

    def _get(self, name: str, kind: type) -> _Instrument:
        inst = self._store.get(name)
        if inst is None or not isinstance(inst, kind):
            err = MetricNotFound(name)
            if self._logger is not None:
                self._logger.error(str(err))
                return kind(name, "unregistered")  # inert throwaway
            raise err
        return inst

    # -- registration --------------------------------------------------------
    def new_counter(self, name: str, desc: str) -> None:
        self._register(Counter(name, desc))

    def new_updown_counter(self, name: str, desc: str) -> None:
        self._register(UpDownCounter(name, desc))

    def new_gauge(self, name: str, desc: str) -> None:
        self._register(Gauge(name, desc))

    def new_histogram(self, name: str, desc: str, buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        self._register(Histogram(name, desc, buckets))

    # -- recording -----------------------------------------------------------
    def increment_counter(self, name: str, value: float = 1.0, **labels: str) -> None:
        self._get(name, Counter).add(value, **labels)  # type: ignore[attr-defined]

    def delta_updown_counter(self, name: str, value: float, **labels: str) -> None:
        self._get(name, UpDownCounter).add(value, **labels)  # type: ignore[attr-defined]

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        self._get(name, Gauge).set(value, **labels)  # type: ignore[attr-defined]

    def record_histogram(self, name: str, value: float,
                         exemplar: Optional[Dict[str, Any]] = None,
                         **labels: str) -> None:
        self._get(name, Histogram).record(value, exemplar=exemplar, **labels)  # type: ignore[attr-defined]

    def record_histogram_n(self, name: str, value: float, n: int,
                           exemplar: Optional[Dict[str, Any]] = None,
                           **labels: str) -> None:
        self._get(name, Histogram).record_n(value, n, exemplar=exemplar, **labels)  # type: ignore[attr-defined]

    # -- introspection -------------------------------------------------------
    def get(self, name: str) -> Optional[_Instrument]:
        return self._store.get(name)

    def expose(self, openmetrics: bool = False) -> str:
        """Render the whole registry in Prometheus text exposition format.

        openmetrics=True renders the OpenMetrics dialect instead: the same
        lines plus per-bucket histogram exemplars and the terminating
        `# EOF` marker — what a scrape negotiating
        `Accept: application/openmetrics-text` gets. Classic output never
        carries exemplars (Prometheus' text parser rejects them)."""
        lines: List[str] = []
        with self._lock:
            instruments = list(self._store.values())
        for inst in sorted(instruments, key=lambda i: i.name):
            lines.extend(inst.expose(openmetrics=openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


def new_metrics_manager(logger=None) -> Manager:
    return Manager(logger=logger)
