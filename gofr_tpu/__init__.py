"""gofr_tpu: a TPU-native microservice + model-serving framework.

Built from scratch with the capability surface of the reference framework
surveyed in SURVEY.md (App facade, DI container, HTTP/gRPC/metrics servers,
middleware, datasources, pub/sub, cron, migrations, circuit breaker, CRUD,
swagger) plus a first-class TPU serving runtime: JAX/XLA executors with an
AOT compile cache, dynamic and continuous batching schedulers, device-resident
KV cache, and mesh parallelism (dp/tp/sp/pp) for multi-chip serving.
"""

from .app import App, new_app
from .cmd import new_cmd
from .config import Config, EnvFile, MockConfig
from .container import Container, new_mock_container
from .context import Context
from .http.errors import (EntityAlreadyExists, EntityNotFound, HTTPError,
                          InvalidParam, MissingParam)
from .http.responder import File, Raw, Redirect, Response, Stream
from .version import FRAMEWORK

__version__ = FRAMEWORK
__all__ = [
    "App", "new_app", "new_cmd", "Config", "EnvFile", "MockConfig",
    "Container", "new_mock_container", "Context", "EntityAlreadyExists",
    "EntityNotFound", "HTTPError", "InvalidParam", "MissingParam",
    "File", "Raw", "Redirect", "Response", "Stream", "FRAMEWORK",
]
