"""Per-request Context: request + container + span, handed to every handler.

Parity: reference pkg/gofr/context.go:12-71 — Context embeds the stdlib
context (here: plain attributes + deadline), the transport Request, and the
*Container; `Trace(name)` opens a child span (:45-51); `Bind` delegates to the
request (:53-55). Handlers access datasources as ctx.sql / ctx.kv / ctx.tpu
and the logger methods directly (ctx.info/debug/error...), mirroring how the
reference embeds Logger in Container.
"""

from __future__ import annotations

import time
from typing import Any, Optional


class Context:
    def __init__(self, request: Any, container: Any, responder: Any = None,
                 deadline: Optional[float] = None):
        self.request = request
        self.container = container
        self.responder = responder
        self.deadline = deadline
        self.span = getattr(request, "span", None)

    # -- request passthrough --------------------------------------------------
    def param(self, key: str) -> str:
        return self.request.param(key)

    def params(self, key: str):
        return self.request.params(key)

    def path_param(self, key: str) -> str:
        return self.request.path_param(key)

    def bind(self, target: Any = None) -> Any:
        return self.request.bind(target)

    def header(self, key: str) -> str:
        getter = getattr(self.request, "header", None)
        return getter(key) if getter else ""

    def host_name(self) -> str:
        return self.request.host_name()

    # -- deadline (stdlib-context analog) -------------------------------------
    def done(self) -> bool:
        return self.deadline is not None and time.time() >= self.deadline

    def remaining(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.time())

    # -- tracing (context.go:45-51) -------------------------------------------
    def trace(self, name: str):
        tracer = self.container.tracer
        if tracer is None:
            from .tracing import Tracer
            tracer = Tracer()
        span = tracer.start_span(name, parent=self.span)
        return span

    # -- container passthrough ------------------------------------------------
    @property
    def sql(self):
        return self.container.sql

    @property
    def kv(self):
        return self.container.kv

    @property
    def tpu(self):
        return self.container.tpu

    @property
    def pubsub(self):
        return self.container.pubsub

    @property
    def config(self):
        return self.container.config

    @property
    def logger(self):
        return self.container.logger

    def metrics(self):
        return self.container.metrics()

    def get_http_service(self, name: str):
        return self.container.get_http_service(name)

    def publish(self, topic: str, message: Any) -> None:
        import json

        pub = self.container.get_publisher()
        if pub is None:
            raise RuntimeError("no pub/sub backend configured (set PUBSUB_BACKEND)")
        if isinstance(message, (dict, list)):
            message = json.dumps(message).encode()
        elif isinstance(message, str):
            message = message.encode()
        pub.publish(topic, message)

    # -- logger passthrough ---------------------------------------------------
    def __getattr__(self, name: str):
        logger = self.__dict__.get("container").logger
        if hasattr(logger, name):
            return getattr(logger, name)
        raise AttributeError(name)
