"""Config layer: env-file loader with APP_ENV overlays.

Parity: reference pkg/gofr/config/config.go:3-6 (Config{Get, GetOrDefault}) and
pkg/gofr/config/godotenv.go:25-69 (.env + .local.env / .{APP_ENV}.env overlay).
Process environment variables always take precedence over file values.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


class Config:
    """Minimal read interface every component depends on."""

    def get(self, key: str) -> Optional[str]:  # pragma: no cover - interface
        raise NotImplementedError

    def get_or_default(self, key: str, default: str) -> str:
        val = self.get(key)
        return val if val not in (None, "") else default

    # convenience typed getters (the reference parses ints ad-hoc at call sites)
    def get_int(self, key: str, default: int) -> int:
        val = self.get(key)
        if val in (None, ""):
            return default
        try:
            return int(val)
        except ValueError:
            return default

    def get_float(self, key: str, default: float) -> float:
        val = self.get(key)
        if val in (None, ""):
            return default
        try:
            return float(val)
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        val = self.get(key)
        if val in (None, ""):
            return default
        return val.strip().lower() in ("1", "true", "yes", "on")


def _parse_env_file(path: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as fp:
            for line in fp:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if "=" not in line:
                    continue
                key, _, val = line.partition("=")
                key = key.strip()
                val = val.strip()
                if len(val) >= 2 and val[0] == val[-1] and val[0] in ("'", '"'):
                    val = val[1:-1]
                if key:
                    out[key] = val
    except OSError:
        pass
    return out


class EnvFile(Config):
    """Loads `<dir>/.env`, then overlays `.local.env` or `.{APP_ENV}.env`.

    Overlay precedence mirrors the reference loader (godotenv.go:32-69):
    if APP_ENV is set, `.{APP_ENV}.env` overrides; otherwise `.local.env`
    overrides when present. Real process env vars override everything.
    """

    def __init__(self, config_dir: str = "./configs", environ: Optional[Dict[str, str]] = None):
        self._environ = environ if environ is not None else os.environ  # type: ignore[assignment]
        self._values: Dict[str, str] = {}
        base = _parse_env_file(os.path.join(config_dir, ".env"))
        self._values.update(base)
        app_env = self._environ.get("APP_ENV", "") or base.get("APP_ENV", "")
        if app_env:
            overlay = _parse_env_file(os.path.join(config_dir, f".{app_env}.env"))
        else:
            overlay = _parse_env_file(os.path.join(config_dir, ".local.env"))
        self._values.update(overlay)

    def get(self, key: str) -> Optional[str]:
        if key in self._environ:
            return self._environ[key]
        return self._values.get(key)


class MockConfig(Config):
    """Map-backed Config for tests. Parity: config/mock_config.go:7-24."""

    def __init__(self, values: Optional[Dict[str, str]] = None):
        self.values = dict(values or {})

    def get(self, key: str) -> Optional[str]:
        return self.values.get(key)


def new_env_file(config_dir: str = "./configs") -> EnvFile:
    return EnvFile(config_dir)
