"""App facade: one object that boots HTTP/metrics/gRPC servers, subscribers,
cron jobs, and (TPU-era) the model-serving engine.

Parity: reference pkg/gofr/gofr.go — New/NewCMD (:63-112), route verbs
(:210-241), Subscribe (:360-368), AddHTTPService (:197-207), Migrate
(:257-262), AddCronJob (:390-400), AddRESTHandlers (:370-383), Enable*Auth
(:324-358), UseMiddleware (:386-388), Run (:115-178); default ports 8000 /
9000 / 2121 (default.go:3-7); handler timeout + health/alive/catch-all
(handler.go:18-102); metrics server (metricsServer.go:20-34).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from .config import Config, EnvFile
from .container import Container
from .context import Context
from .http import middleware as mw
from .http.errors import HTTPError, RequestTimeout, ServiceUnavailable
from .http.request import Request
from .http.responder import File, Responder, Response, Stream
from .http.router import Router
from .http.server import HTTPServer
from .subscriber import SubscriptionManager

DEFAULT_HTTP_PORT = 8000     # default.go:3-7
DEFAULT_GRPC_PORT = 9000
DEFAULT_METRICS_PORT = 2121
DEFAULT_REQUEST_TIMEOUT_S = 5.0  # handler.go:18

Handler = Callable[[Context], Any]

_FAVICON = bytes.fromhex(  # 1x1 transparent gif, stands in for static/favicon.ico
    "47494638396101000100800000000000ffffff21f90401000001002c00000000010001000002024c01003b")


def _stream_with_slot(stream: Stream, release: Callable[[], None]) -> Stream:
    """Tie a concurrency slot to a streaming response's lifetime: released
    (once) when the body finishes or the connection closes, chaining any
    user on_close."""
    prev = stream.on_close
    released = threading.Event()

    def close() -> None:
        try:
            if prev is not None:
                prev()
        finally:
            if not released.is_set():
                released.set()
                release()

    stream.on_close = close
    return stream


class App:
    def __init__(self, config_dir: Optional[str] = None, config: Optional[Config] = None,
                 container: Optional[Container] = None):
        if container is not None:
            self.container = container
            self.config = container.config
        else:
            if config is None:
                config_dir = config_dir or os.environ.get("GOFR_CONFIGS_DIR", "./configs")
                config = EnvFile(config_dir)
            self.config = config
            self.container = Container.create(config)

        from . import native

        native.available()  # build/load the C++ runtime helpers at boot so
        # no request-path call ever pays the compile

        self.logger = self.container.logger
        self.router = Router()
        self.request_timeout_s = self.config.get_float("REQUEST_TIMEOUT", DEFAULT_REQUEST_TIMEOUT_S)
        # cap on concurrently RUNNING handlers (incl. 408-abandoned ones
        # still executing and live streaming responses): the backpressure
        # the per-request-thread model otherwise lacks (VERDICT r2 weak #7).
        # <= 0 disables the cap, matching the REQUEST_TIMEOUT convention
        self.max_concurrent_requests = self.config.get_int(
            "MAX_CONCURRENT_REQUESTS", 256)
        self._handler_slots = (
            threading.BoundedSemaphore(self.max_concurrent_requests)
            if self.max_concurrent_requests > 0 else None)
        self.http_port = self.config.get_int("HTTP_PORT", DEFAULT_HTTP_PORT)
        self.grpc_port = self.config.get_int("GRPC_PORT", DEFAULT_GRPC_PORT)
        self.metrics_port = self.config.get_int("METRICS_PORT", DEFAULT_METRICS_PORT)

        self._http_server: Optional[HTTPServer] = None
        self._metrics_server: Optional[HTTPServer] = None
        self._grpc_server = None
        self._grpc_services: list = []
        self._subscriptions = SubscriptionManager(self.container)
        self._cron = None
        self._user_middleware: list = []
        self._static_dirs: Dict[str, str] = {}
        self._openapi_path = "./static/openapi.json"
        self._started = False
        self._shutdown_hooks: list = []

        # default chain: Tracer -> Logging -> CORS -> Metrics (http/router.go:21-33)
        self.router.use_middleware(
            mw.tracer_middleware(self.container.tracer),
            mw.logging_middleware(self.logger),
            mw.cors_middleware(),
            mw.metrics_middleware(self.container.metrics_manager),
        )

    # -- route registration ---------------------------------------------------
    def add_route(self, method: str, pattern: str, handler: Optional[Handler] = None):
        if handler is None:  # decorator form: @app.get("/path")
            def decorator(fn: Handler) -> Handler:
                self.add_route(method, pattern, fn)
                return fn
            return decorator
        self.router.add(method, pattern, self._wire(handler))
        return handler

    def get(self, pattern: str, handler: Optional[Handler] = None):
        return self.add_route("GET", pattern, handler)

    def post(self, pattern: str, handler: Optional[Handler] = None):
        return self.add_route("POST", pattern, handler)

    def put(self, pattern: str, handler: Optional[Handler] = None):
        return self.add_route("PUT", pattern, handler)

    def patch(self, pattern: str, handler: Optional[Handler] = None):
        return self.add_route("PATCH", pattern, handler)

    def delete(self, pattern: str, handler: Optional[Handler] = None):
        return self.add_route("DELETE", pattern, handler)

    # -- handler adapter (handler.go:41-76) -----------------------------------
    def _wire(self, handler: Handler):
        def wire_handler(request: Request) -> Response:
            responder = Responder(request.method)
            # backpressure: a 408-abandoned handler keeps running (the
            # reference's goroutine model, handler.go:58-75) but still holds
            # its slot until it actually finishes — a stalled dependency
            # turns into fast 503s instead of unbounded thread growth.
            # /.well-known/* (liveness, health, swagger) bypasses the cap:
            # "is the process up" must keep answering precisely when the
            # app is shedding everything else
            shed = (self._handler_slots is not None
                    and not request.path.startswith("/.well-known/"))
            if shed and not self._handler_slots.acquire(timeout=0.5):
                return responder.respond(
                    None, ServiceUnavailable("server overloaded; try again later"))
            deadline = time.time() + self.request_timeout_s if self.request_timeout_s > 0 else None
            ctx = Context(request=request, container=self.container,
                          responder=responder, deadline=deadline)
            result: Dict[str, Any] = {}
            done = threading.Event()
            state_lock = threading.Lock()  # transfer-vs-abandon decision

            def release_slot() -> None:
                if shed:
                    self._handler_slots.release()

            def run() -> None:
                transferred = False
                try:
                    data = handler(ctx)
                    with state_lock:
                        if (shed and isinstance(data, Stream)
                                and not result.get("abandoned")):
                            # a streaming body is generated AFTER the handler
                            # returns, for the connection's whole lifetime —
                            # the slot must follow the stream, not the thread
                            data = _stream_with_slot(data, release_slot)
                            transferred = True
                        result["data"] = data
                except BaseException as exc:  # noqa: BLE001 - surfaced via responder
                    result["err"] = exc
                finally:
                    done.set()
                    if not transferred:
                        release_slot()

            # the reference runs the user handler in its own goroutine and
            # responds 408 if the deadline passes first, leaving the handler
            # running (handler.go:58-75); same model with a thread here
            t = threading.Thread(target=run, name="handler", daemon=True)
            try:
                t.start()
            except RuntimeError:  # can't start new thread: release the slot
                release_slot()
                raise
            done.wait(timeout=None if deadline is None else self.request_timeout_s)
            if not done.is_set():
                with state_lock:
                    if not done.is_set():  # a just-finished run keeps its result
                        result["abandoned"] = True
                        return responder.respond(None, RequestTimeout())
            err = result.get("err")
            if err is not None and not isinstance(err, Exception):
                raise err  # SystemExit/KeyboardInterrupt propagate
            return responder.respond(result.get("data"), err)

        return wire_handler

    # -- middleware & auth ----------------------------------------------------
    def use_middleware(self, *mws) -> None:
        self.router.use_middleware(*mws)

    def enable_basic_auth(self, *creds: str, users: Optional[Dict[str, str]] = None,
                          validate_func=None) -> None:
        userdict = dict(users or {})
        for i in range(0, len(creds) - 1, 2):
            userdict[creds[i]] = creds[i + 1]
        self.router.use_middleware(mw.basic_auth_middleware(userdict, validate_func))

    def enable_api_key_auth(self, *keys: str, validate_func=None) -> None:
        self.router.use_middleware(mw.api_key_auth_middleware(keys, validate_func))

    def enable_oauth(self, secret: str) -> None:
        self.router.use_middleware(mw.oauth_middleware(secret))

    def enable_oauth_jwks(self, jwks_url: str,
                          refresh_interval_s: float = 300.0,
                          keyset=None) -> None:
        """RS256 bearer-JWT auth against a background-refreshed JWKS endpoint
        (reference oauth.go:53-140). Gated on the `cryptography` package:
        misconfiguration logs and skips rather than failing boot, matching
        the reference's nil-datasource posture."""
        try:
            keyset = keyset or mw.JWKSKeySet(
                jwks_url, refresh_interval_s=refresh_interval_s,
                logger=self.logger)
        except RuntimeError as exc:
            self.logger.errorf("OAuth JWKS disabled: %s", exc)
            return
        self.router.use_middleware(mw.oauth_jwks_middleware(keyset))

    def enable_profiler(self, path: str = "/debug/profile") -> None:
        """Expose on-demand xprof device-trace capture (tpu/profiler.py).

        Config: PROFILE_DIR (capture root for POSTs without "dir" and
        incident-autopsy captures, default ./profiles); status() reports
        trace paths relative to it, so "where did my trace go" doesn't
        depend on the server's cwd."""
        from .tpu.profiler import configure, install_routes

        configure(self.config.get_or_default("PROFILE_DIR", "./profiles"))
        install_routes(self, path)

    def enable_timeline(self, engine, path: str = "/debug/timeline"):
        """Expose the Perfetto trace-event export (tpu/timeline.py):
        GET /debug/timeline[?steps=N] renders the step ledger, flight
        recorder, utilization ledger, and live compile events as one
        chrome://tracing / ui.perfetto.dev-loadable JSON payload — real
        threads as named tracks, device busy slices on an async track,
        per-request flow arrows from enqueued to finished. A DISAGG
        both engine contributes its prefill half under its own thread
        block, so the hand-off is visible in one load.

        Config: TIMELINE_STEPS (default step window, 128). Returns the
        TimelineExporter (also attached as engine.timeline for the
        fleet stitcher and soak gates)."""
        from .tpu.timeline import (TimelineExporter, install_routes,
                                   register_timeline_metrics)

        metrics = self.container.metrics_manager
        if metrics is not None:
            register_timeline_metrics(metrics)
        exporter = TimelineExporter(
            engine, process_name=self.container.app_name,
            max_steps=self.config.get_int("TIMELINE_STEPS", 128),
            metrics=metrics)
        engine.timeline = exporter
        install_routes(self, exporter, path)
        return exporter

    def enable_hostprof(self, engine=None, path: str = "/debug/hostprof"):
        """Start the always-on host sampling profiler (tpu/hostprof.py)
        and expose GET /debug/hostprof: bounded collapsed-stack
        aggregation over sys._current_frames(), classified per thread
        (engine loop / finisher / http / other), with the sampler's
        measured self-overhead in its own payload. Stopped via
        on_shutdown, like the memory sampler.

        Config: HOSTPROF_HZ (sampling rate, default 50; <= 0 disables
        and returns None), HOSTPROF_MAX_STACKS (distinct stacks kept per
        class, 256), HOSTPROF_TOP_K (stacks shown per class, 5). Returns
        the HostProfiler (also attached as engine.hostprof so incident
        bundles can embed the loop's top stacks)."""
        from .tpu.hostprof import (HostProfiler, install_routes,
                                   register_hostprof_metrics)

        hz = self.config.get_float("HOSTPROF_HZ", 50.0)
        if hz <= 0:
            return None
        metrics = self.container.metrics_manager
        if metrics is not None:
            register_hostprof_metrics(metrics)
        prof = HostProfiler(
            hz=hz,
            max_stacks=self.config.get_int("HOSTPROF_MAX_STACKS", 256),
            top_k=self.config.get_int("HOSTPROF_TOP_K", 5),
            metrics=metrics, logger=self.logger)
        prof.start()
        self.on_shutdown(lambda: prof.stop())
        if engine is not None:
            engine.hostprof = prof
        install_routes(self, prof, path)
        return prof

    def enable_flight_recorder(self, engine, path: str = "/debug/requests"):
        """Attach a per-request flight recorder to `engine` and expose its
        operator endpoints (tpu/flightrecorder.py): GET /debug/requests
        (in-flight + recent completions with phase timings + SLO goodput)
        and GET /debug/requests/{id} (one request's full timeline). Also
        registers the app_tpu_slo_*_goodput gauges on the metrics Manager.

        Config: FLIGHT_RECORDER_CAPACITY (completed-request ring size,
        default 256), FLIGHT_RECORDER_MAX_EVENTS (per-request event cap,
        default 512), SLO_TTFT_TARGET_S / SLO_TPOT_TARGET_S (goodput
        targets, defaults 0.15 / 0.05). An engine built with its own
        flight_recorder= keeps it; this call then only wires the app's
        metrics/tracer sinks and the routes. Returns the recorder."""
        from .tpu.flightrecorder import (FlightRecorder, install_routes,
                                         register_slo_gauges)

        recorder = getattr(engine, "recorder", None)
        if recorder is None:
            recorder = FlightRecorder(
                capacity=self.config.get_int("FLIGHT_RECORDER_CAPACITY", 256),
                max_events=self.config.get_int(
                    "FLIGHT_RECORDER_MAX_EVENTS", 512),
                slo_ttft_s=self.config.get_float("SLO_TTFT_TARGET_S", 0.150),
                slo_tpot_s=self.config.get_float("SLO_TPOT_TARGET_S", 0.050),
                metrics=self.container.metrics_manager,
                tracer=self.container.tracer)
            engine.recorder = recorder
        else:
            recorder.use_metrics(self.container.metrics_manager)
            recorder.use_tracer(self.container.tracer)
        # DISAGG_MODE=both: the prefill pool gets its own recorder so the
        # prefill half of every hand-off is visible to journey assembly
        # (tpu/journey.py) and emits engine spans on the shared trace.
        # metrics stays None — the client-facing goodput gauges belong to
        # the serving (decode) engine's recorder alone
        disagg = getattr(engine, "disagg_router", None)
        prefill = (getattr(disagg, "prefill_engine", None)
                   if disagg is not None else None)
        if prefill is not None and getattr(prefill, "recorder", None) is None:
            prefill.recorder = FlightRecorder(
                capacity=self.config.get_int("FLIGHT_RECORDER_CAPACITY", 256),
                max_events=self.config.get_int(
                    "FLIGHT_RECORDER_MAX_EVENTS", 512),
                tracer=self.container.tracer)
        if self.container.metrics_manager is not None:
            register_slo_gauges(self.container.metrics_manager)
        install_routes(self, recorder, path)
        return recorder

    def enable_journey(self, engine, path: str = "/debug/journey"):
        """Expose the replica-local journey surface (tpu/journey.py):
        GET /debug/journey (recent index) and GET /debug/journey/{id}
        (one causally-ordered hop waterfall, id = engine request id or
        32-hex trace id) — the same endpoint shape the fleet router
        serves, assembled here from this replica's flight recorder(s)
        (both halves of a DISAGG both pair). Requires a flight recorder
        (enable_flight_recorder); returns None without one."""
        if getattr(engine, "recorder", None) is None:
            return None
        from .tpu.journey import install_routes as install_journey_routes

        install_journey_routes(self, engine, path)
        return engine.recorder

    def enable_fault_injection(self, engine, path: str = "/debug/faults"):
        """Arm the chaos plane (tpu/faults.py) on an engine and expose the
        POST/GET /debug/faults drill endpoints — HARD-gated on
        FAULT_INJECTION=true in config. When disabled (the default) this
        returns None, registers NO route (the endpoint 404s), and the
        engine/executor/device keep their zero-overhead ``faults=None``
        fast path.

        Config: FAULT_INJECTION (master switch), FAULT_INJECTION_PLAN
        (inline JSON fault schedule or ``@/path/to/plan.json``),
        FAULT_INJECTION_SEED (deterministic trigger RNG). Returns the
        FaultPlane when enabled."""
        from .tpu.faults import install_routes, plane_from_config

        plane = plane_from_config(self.config, logger=self.logger)
        if plane is None:
            return None
        engine.faults = plane
        executor = getattr(engine, "executor", None)
        if executor is not None:
            executor.faults = plane
        if self.container.tpu is not None:
            self.container.tpu.faults = plane
        install_routes(self, plane, path)
        self.logger.warnf(
            "FAULT INJECTION ENABLED: chaos plane armed on the engine, "
            "executor, and device; POST %s drives drills", path)
        return plane

    def enable_engine_snapshot(self, engine, path: str = "/debug/engine"):
        """Expose the engine's fleet-level operator surface
        (tpu/utilization.py): GET /debug/engine — one JSON snapshot of
        slots / buckets / page pool / utilization window / compile table —
        plus the utilization gauges (app_tpu_mfu / app_tpu_mbu /
        app_tpu_device_duty_cycle / app_tpu_host_overhead_seconds) and a
        background HBM / page-pool sampler.

        Config: ENGINE_HBM_SAMPLE_S (sampler cadence, default 10 s; <= 0
        disables the background thread — the gauges still refresh at every
        metrics scrape). TPU_PEAK_FLOPS / TPU_PEAK_HBM_BW override the
        per-device peak table the MFU/MBU math divides by. Returns the
        engine's UtilizationLedger (or None for engines without one)."""
        from .tpu.utilization import (MemorySampler,
                                      install_routes as install_engine_routes,
                                      register_utilization_metrics)

        metrics = self.container.metrics_manager
        if metrics is not None:
            register_utilization_metrics(metrics)
        util = getattr(engine, "util", None)
        if util is not None:
            util.use_metrics(metrics)
            # scrape-time republish: an idle engine's duty cycle must decay
            # to zero, not freeze at the last dispatch's value
            self.container.add_scrape_hook("engine_util", util.publish)
        install_engine_routes(self, engine, path)
        interval = self.config.get_float("ENGINE_HBM_SAMPLE_S", 10.0)
        if interval > 0:
            sampler = MemorySampler(metrics, tpu=self.container.tpu,
                                    engine=engine, interval_s=interval,
                                    logger=self.logger)
            sampler.start()
            self.on_shutdown(sampler.stop)
        return util

    def enable_step_ledger(self, engine, path: str = "/debug/steps"):
        """Expose the engine's step anatomy ledger (tpu/stepledger.py):
        GET /debug/steps — recent per-iteration segment attributions,
        per-phase/segment summary, straggler sentinel baselines and the
        recent straggler list — plus the app_tpu_step_seconds{phase,
        segment} histograms (exemplar-carrying) and
        app_tpu_step_stragglers_total{cause}.

        Config: STEP_LEDGER_CAPACITY (ring size, default 512),
        STEP_STRAGGLER_K (a step slower than k × the rolling per-phase
        baseline is flagged, default 3.0), STEP_BASELINE_ALPHA (EWMA
        smoothing, default 0.1), STEP_BASELINE_MIN_SAMPLES (observations
        before the sentinel arms, default 16). Returns the ledger (None
        for engines without one)."""
        from .tpu.stepledger import install_routes, register_step_metrics

        ledger = getattr(engine, "steps", None)
        if ledger is None:
            return None
        metrics = self.container.metrics_manager
        if metrics is not None:
            register_step_metrics(metrics)
            ledger.use_metrics(metrics)
        ledger.configure(
            capacity=self.config.get_int("STEP_LEDGER_CAPACITY", 512),
            straggler_k=self.config.get_float("STEP_STRAGGLER_K", 3.0),
            baseline_alpha=self.config.get_float("STEP_BASELINE_ALPHA", 0.1),
            min_samples=self.config.get_int("STEP_BASELINE_MIN_SAMPLES", 16))
        install_routes(self, ledger, path)
        return ledger

    def enable_incident_autopsy(self, engine, slo_path: str = "/debug/slo",
                                incidents_path: str = "/debug/incidents"):
        """Wire the incident autopsy plane (tpu/incidents.py) onto an
        engine: the SLO burn-rate engine (error-budget accounting over
        paired fast/slow windows, fed by the flight recorder, published
        as app_tpu_slo_burn_rate{slo,window} / app_tpu_slo_alert_state
        {slo} and served at GET /debug/slo) plus the IncidentManager
        (anomaly-triggered, rate-limited evidence bundles at
        GET /debug/incidents[/{id}], triggered by burn-rate pages,
        straggler streaks, breaker opens, and poison quarantines).

        Config: SLO_BURN_FAST_WINDOW_S / SLO_BURN_SLOW_WINDOW_S (paired
        windows, defaults 300/3600), SLO_BURN_PAGE / SLO_BURN_WARN
        (both-windows burn thresholds, 14.4/6.0),
        SLO_BURN_OBJECTIVE_{TTFT,TPOT,AVAILABILITY} (objectives,
        0.99/0.99/0.999), SLO_BURN_MIN_EVENTS (window arm floor, 12);
        INCIDENT_DIR (bundle directory, ./incidents), INCIDENT_RING
        (in-memory bundle ring, 32), INCIDENT_COOLDOWN_S /
        INCIDENT_MAX_PER_HOUR (capture rate limit, 300/6),
        INCIDENT_SLOWEST_K (requests embedded per bundle, 5),
        INCIDENT_PROFILE_S (attach an async xprof capture per bundle;
        0 = off; a busy profiler is skipped, never awaited),
        INCIDENT_STRAGGLER_STREAK / INCIDENT_STRAGGLER_WINDOW (flagged
        steps within a step span that escalate, 3/32). Returns
        (burn_engine, incident_manager)."""
        from .tpu.incidents import (IncidentManager, SLOBurnEngine,
                                    install_routes,
                                    register_incident_metrics)

        cfg = self.config
        metrics = self.container.metrics_manager
        if metrics is not None:
            register_incident_metrics(metrics)
        recorder = getattr(engine, "recorder", None)
        burn = SLOBurnEngine(
            slo_ttft_s=cfg.get_float("SLO_TTFT_TARGET_S", 0.150),
            slo_tpot_s=cfg.get_float("SLO_TPOT_TARGET_S", 0.050),
            objectives={
                "ttft": cfg.get_float("SLO_BURN_OBJECTIVE_TTFT", 0.99),
                "tpot": cfg.get_float("SLO_BURN_OBJECTIVE_TPOT", 0.99),
                "availability": cfg.get_float(
                    "SLO_BURN_OBJECTIVE_AVAILABILITY", 0.999)},
            fast_window_s=cfg.get_float("SLO_BURN_FAST_WINDOW_S", 300.0),
            slow_window_s=cfg.get_float("SLO_BURN_SLOW_WINDOW_S", 3600.0),
            page_burn=cfg.get_float("SLO_BURN_PAGE", 14.4),
            warn_burn=cfg.get_float("SLO_BURN_WARN", 6.0),
            min_events=cfg.get_int("SLO_BURN_MIN_EVENTS", 12),
            metrics=metrics, logger=self.logger)
        incidents = IncidentManager(
            engine=engine, recorder=recorder,
            dir=cfg.get_or_default("INCIDENT_DIR", "./incidents"),
            capacity=cfg.get_int("INCIDENT_RING", 32),
            cooldown_s=cfg.get_float("INCIDENT_COOLDOWN_S", 300.0),
            max_per_hour=cfg.get_int("INCIDENT_MAX_PER_HOUR", 6),
            slowest_k=cfg.get_int("INCIDENT_SLOWEST_K", 5),
            profile_seconds=cfg.get_float("INCIDENT_PROFILE_S", 0.0),
            # autopsy captures land under the profiler's configured root
            # (PROFILE_DIR) when set, else beside the bundles
            profile_dir=(cfg.get("PROFILE_DIR")
                         or os.path.join(
                             cfg.get_or_default("INCIDENT_DIR",
                                                "./incidents"),
                             "profiles")),
            straggler_streak=cfg.get_int("INCIDENT_STRAGGLER_STREAK", 3),
            straggler_window=cfg.get_int("INCIDENT_STRAGGLER_WINDOW", 32),
            fingerprint={"app": self.container.app_name,
                         "version": self.container.app_version},
            metrics=metrics, logger=self.logger)
        burn.on_page = incidents.on_slo_page
        if recorder is not None:
            recorder.use_burn_engine(burn)
        engine.incidents = incidents
        # scrape-time re-evaluation: burn must DECAY while the server is
        # idle (no completions would otherwise freeze a paging state)
        self.container.add_scrape_hook("slo_burn", burn.publish)
        install_routes(self, burn, incidents, slo_path, incidents_path)
        return burn, incidents

    def enable_qos(self, engine, burn=None, path: str = "/debug/qos"):
        """Wire the QoS serving plane (tpu/qos.py) onto an engine:
        tenant classes mapped onto priority bands, per-class deadline
        budgets and slot/page quotas, and the burn-actuated shed ladder
        (park batch -> preempt batch with replay -> 503 standard) that
        finally makes the SLOBurnEngine ACT. When the app's pub/sub
        broker is configured (PUBSUB_BACKEND) a batch lane consumes
        offline jobs into the engine's batch band, with a cron drain
        kick, so duty-cycle stays high between interactive bursts.

        burn defaults to the engine recorder's burn engine (set by
        enable_incident_autopsy — call that FIRST); without one the
        ladder never escalates but classes/quotas/deadlines still apply.

        Config: QOS_INTERACTIVE_RESERVED_SLOTS (slots the ladder keeps
        free of non-interactive admissions, 1), QOS_BATCH_PAGE_FRACTION
        (KV-page share batch may hold, 0.5), QOS_DEADLINE_{INTERACTIVE,
        STANDARD,BATCH}_S (queue deadline budgets, 0 = off),
        QOS_SHED_TRACKS (burn tracks the ladder watches, "ttft,tpot"),
        QOS_ESCALATE_HOLD_S / QOS_RECOVER_HOLD_S (ladder dwells, 5/10),
        QOS_EVAL_S (ladder eval cadence, 1.0), QOS_SHED_RETRY_AFTER_S
        (Retry-After on ladder 503s, 2.0); QOS_LANE (batch lane on/off,
        true), QOS_BATCH_TOPIC / QOS_BATCH_RESULT_TOPIC
        (qos.batch.jobs / qos.batch.results), QOS_LANE_MAX_INFLIGHT (4),
        QOS_LANE_CRON (drain-kick cron spec, every minute). Returns the
        QoSController."""
        from .tpu.qos import (BatchLane, QoSController, install_routes,
                              register_qos_metrics)

        cfg = self.config
        metrics = self.container.metrics_manager
        if metrics is not None:
            register_qos_metrics(metrics)
        tracks = [t.strip() for t in cfg.get_or_default(
            "QOS_SHED_TRACKS", "ttft,tpot").split(",") if t.strip()]
        controller = QoSController(
            interactive_reserved_slots=cfg.get_int(
                "QOS_INTERACTIVE_RESERVED_SLOTS", 1),
            batch_page_fraction=cfg.get_float("QOS_BATCH_PAGE_FRACTION",
                                              0.5),
            deadlines={
                "interactive": cfg.get_float("QOS_DEADLINE_INTERACTIVE_S",
                                             0.0),
                "standard": cfg.get_float("QOS_DEADLINE_STANDARD_S", 0.0),
                "batch": cfg.get_float("QOS_DEADLINE_BATCH_S", 0.0)},
            shed_tracks=tuple(tracks),
            escalate_hold_s=cfg.get_float("QOS_ESCALATE_HOLD_S", 5.0),
            recover_hold_s=cfg.get_float("QOS_RECOVER_HOLD_S", 10.0),
            retry_after_s=cfg.get_float("QOS_SHED_RETRY_AFTER_S", 2.0),
            metrics=metrics, logger=self.logger,
            recorder=getattr(engine, "recorder", None))
        if burn is None:
            burn = getattr(getattr(engine, "recorder", None), "burn", None)
        controller.use_burn_engine(burn)
        controller.engine = engine
        engine.qos = controller
        controller.start_eval_loop(cfg.get_float("QOS_EVAL_S", 1.0))
        self.on_shutdown(lambda: controller.stop())
        # scrape-time re-evaluation, same contract as the burn engine:
        # the ladder must RECOVER while the server is idle
        self.container.add_scrape_hook("qos", controller.publish)
        broker = getattr(self.container, "pubsub", None)
        if cfg.get_bool("QOS_LANE", True) and broker is not None:
            lane = BatchLane(
                engine, broker,
                topic=cfg.get_or_default("QOS_BATCH_TOPIC",
                                         "qos.batch.jobs"),
                result_topic=cfg.get_or_default("QOS_BATCH_RESULT_TOPIC",
                                                "qos.batch.results"),
                tokenizer=getattr(engine, "tokenizer", None),
                max_inflight=cfg.get_int("QOS_LANE_MAX_INFLIGHT", 4),
                metrics=metrics, logger=self.logger,
                controller=controller)
            controller.lane = lane
            lane.start()
            self.on_shutdown(lambda: lane.stop())
            self.add_cron_job(
                cfg.get_or_default("QOS_LANE_CRON", "* * * * *"),
                "qos-batch-lane-drain", lane.cron_drain)
        install_routes(self, controller, path)
        return controller

    def enable_capacity(self, engine, path: str = "/debug/capacity"):
        """Wire the capacity observatory (tpu/meter.py) onto an engine:
        the TPUMeter attribution ledger (per-tenant / per-class /
        per-phase device-seconds, analytic FLOPs, KV page-seconds and
        queue wait, published as the app_tpu_meter_*_total counters) and
        the HeadroomForecaster (admission-door λ, utilization-ledger μ,
        ρ, headroom and the fluid TTFT forecast, published as the
        app_tpu_capacity_* gauges with scrape-hook re-eval so they decay
        when idle), served together at GET /debug/capacity. The fleet
        twin — the router's /debug/fleet/capacity rollup with
        replicas_needed — lives in gofr_tpu/fleet/capacity.py.

        Config: METER_PAGE_TOKENS (KV page granularity for dense
        engines; paged engines inherit the allocator's page size),
        METER_WINDOW_S (bounded-window spend horizon, 300),
        METER_REQUESTS (finished per-request rows retained, 512),
        METER_TOP_K (tenants in the /debug/capacity table, 10);
        CAPACITY_WINDOW_S (λ window, 60), CAPACITY_RHO_WARN (collapse
        arm threshold, 0.85), CAPACITY_COLLAPSE_EVALS (consecutive
        rising-queue evals before the warning fires, 3). Returns the
        TPUMeter (forecaster rides on meter.forecaster)."""
        from .tpu.meter import (HeadroomForecaster, TPUMeter,
                                install_routes, register_meter_metrics)

        cfg = self.config
        metrics = self.container.metrics_manager
        if metrics is not None:
            register_meter_metrics(metrics)
        # paged engines bill at the allocator's real page size; dense
        # engines at a fixed accounting granularity
        page_tokens = getattr(getattr(engine, "allocator", None),
                              "page_size", None) \
            or cfg.get_int("METER_PAGE_TOKENS", 16)
        meter = TPUMeter(
            cfg=getattr(engine, "cfg", None),
            page_tokens=page_tokens,
            window_s=cfg.get_float("METER_WINDOW_S", 300.0),
            done_capacity=cfg.get_int("METER_REQUESTS", 512),
            top_k=cfg.get_int("METER_TOP_K", 10),
            metrics=metrics, logger=self.logger)
        meter.forecaster = HeadroomForecaster(
            engine=engine,
            window_s=cfg.get_float("CAPACITY_WINDOW_S", 60.0),
            rho_warn=cfg.get_float("CAPACITY_RHO_WARN", 0.85),
            collapse_evals=cfg.get_int("CAPACITY_COLLAPSE_EVALS", 3),
            metrics=metrics, logger=self.logger)
        engine.meter = meter
        # gauge re-eval at scrape, the utilization/burn idiom: an idle
        # replica's λ window drains so rho/headroom decay to zero
        self.container.add_scrape_hook("capacity",
                                       meter.forecaster.publish)
        install_routes(self, meter, path)
        return meter

    def enable_drain_migration(self, engine):
        """Wire the elastic replica surface (tpu/migrate.py) onto an
        engine: the warming/serving/draining Lifecycle (advertised by the
        server's /stats for fleet routers to gate on), the
        MigrationCoordinator behind POST /debug/drain (drain-with-
        migration: live sessions export as KV hand-off envelopes and
        continue on a peer, replay-ladder fallback on any failure), the
        peer-side POST /migrate landing endpoint, and the
        GET /debug/kvtier inventory that warm-booting peers pre-warm
        from.  Gated on DRAIN_MIGRATE (default true); the lifecycle is
        attached either way so /stats always has a truthful state.

        Config: DRAIN_MIGRATE (surface on/off), DRAIN_SHIP_TIMEOUT_S
        (per-session ship/relay budget, 60).  Returns the
        MigrationCoordinator (None when gated off)."""
        from .tpu.migrate import (Lifecycle, MigrationCoordinator,
                                  install_migration_routes,
                                  register_migration_metrics)

        lifecycle = getattr(engine, "lifecycle", None)
        if lifecycle is None:
            lifecycle = Lifecycle("serving")
            engine.lifecycle = lifecycle
        if not self.config.get_bool("DRAIN_MIGRATE", True):
            return None
        metrics = self.container.metrics_manager
        if metrics is not None:
            register_migration_metrics(metrics)
        coordinator = MigrationCoordinator(
            engine, lifecycle, metrics=metrics, logger=self.logger,
            ship_timeout_s=self.config.get_float("DRAIN_SHIP_TIMEOUT_S",
                                                 60.0))
        self.drain_coordinator = coordinator
        install_migration_routes(self, engine, coordinator)
        return coordinator

    # -- cross-cutting registrations ------------------------------------------
    def add_http_service(self, name: str, address: str, *options) -> None:
        from .service import new_http_service

        self.container.services[name] = new_http_service(
            address, self.logger, self.container.metrics_manager, *options)

    def subscribe(self, topic: str, handler: Optional[Handler] = None):
        if handler is None:
            def decorator(fn: Handler) -> Handler:
                self.subscribe(topic, fn)
                return fn
            return decorator
        if self.container.get_subscriber() is None:
            self.logger.error("pub/sub not configured; set PUBSUB_BACKEND (gofr.go:360-368 parity)")
            return handler
        self._subscriptions.register(topic, handler)
        return handler

    def migrate(self, migrations: Dict[int, Any]) -> None:
        from .migration import run as run_migrations

        try:
            run_migrations(migrations, self.container)
        except Exception as exc:  # noqa: BLE001 - migrate panics are recovered (gofr.go:259)
            self.logger.errorf("migration failed: %s", exc)

    def add_cron_job(self, spec: str, name: str, fn: Handler) -> None:
        if self._cron is None:
            from .cron import Crontab

            self._cron = Crontab(self.container)
        self._cron.add_job(spec, name, fn)

    def add_rest_handlers(self, entity_cls: type, table: Optional[str] = None) -> None:
        from .crud import register_crud_handlers

        register_crud_handlers(self, entity_cls, table)

    def register_grpc_service(self, service) -> None:
        self._grpc_services.append(service)

    def add_tpu(self, tpu_client) -> None:
        """Inject a TPU device client (the Mongo provider pattern, externalDB.go:5-12)."""
        tpu_client.use_logger(self.logger)
        tpu_client.use_metrics(self.container.metrics_manager)
        tpu_client.connect()
        self.container.tpu = tpu_client

    def add_document_store(self, store) -> None:
        """Inject a document store (the Mongo provider pattern: New(config)
        then UseLogger/UseMetrics/Connect, externalDB.go:5-12,
        datasource/mongo.go:142-155)."""
        store.use_logger(self.logger)
        store.use_metrics(self.container.metrics_manager)
        store.connect()
        self.container.docstore = store

    def add_static_files(self, route_prefix: str, directory: str) -> None:
        self._static_dirs[route_prefix.rstrip("/")] = directory

    # -- well-known routes (handler.go:78-102, swagger.go) --------------------
    def _register_framework_routes(self) -> None:
        def health_handler(ctx: Context):
            return ctx.container.health()

        def alive_handler(ctx: Context):
            return {"status": "UP"}

        self.router.add("GET", "/.well-known/health", self._wire(health_handler))
        self.router.add("GET", "/.well-known/alive", self._wire(alive_handler))
        self.router.add("GET", "/favicon.ico", lambda req: Response(
            status=200, headers={"Content-Type": "image/gif"}, body=_FAVICON))

        if os.path.isfile(self._openapi_path):
            from .swagger import openapi_handler, swagger_ui_handler

            self.router.add("GET", "/.well-known/openapi.json",
                            self._wire(openapi_handler(self._openapi_path)))
            self.router.add("GET", "/.well-known/swagger",
                            self._wire(swagger_ui_handler()))

        for prefix, directory in self._static_dirs.items():
            self.router.add("GET", prefix + "/{filename}", self._static_handler(directory))

    def _static_handler(self, directory: str):
        def handle(request: Request) -> Response:
            import mimetypes

            name = os.path.basename(request.path_params.get("filename", ""))
            path = os.path.join(directory, name)
            if not os.path.isfile(path):
                return Response(status=404, body=b'{"error":{"message":"not found"}}',
                                headers={"Content-Type": "application/json"})
            ctype = mimetypes.guess_type(path)[0] or "application/octet-stream"
            with open(path, "rb") as fp:
                return Response(status=200, headers={"Content-Type": ctype}, body=fp.read())

        return handle

    def _metrics_router(self) -> Router:
        router = Router()

        def metrics_handler(request: Request) -> Response:
            self.container.refresh_runtime_metrics()
            # content negotiation: a scrape that accepts the OpenMetrics
            # dialect gets exemplars (metrics→trace→request deep links);
            # classic Prometheus text stays byte-identical without them
            openmetrics = ("application/openmetrics-text"
                           in request.header("accept"))
            ctype = ("application/openmetrics-text; version=1.0.0; "
                     "charset=utf-8" if openmetrics
                     else "text/plain; version=0.0.4")
            return Response(
                status=200, headers={"Content-Type": ctype},
                body=self.container.metrics_manager.expose(
                    openmetrics=openmetrics).encode())

        def health_handler(request: Request) -> Response:
            return Response(status=200, headers={"Content-Type": "application/json"},
                            body=json.dumps(self.container.health()).encode())

        router.add("GET", "/metrics", metrics_handler)
        router.add("GET", "/.well-known/health", health_handler)
        router.add("GET", "/.well-known/alive", lambda r: Response(
            status=200, headers={"Content-Type": "application/json"}, body=b'{"status":"UP"}'))
        return router

    # -- lifecycle (gofr.go:115-178) ------------------------------------------
    def start(self) -> None:
        """Start all servers without blocking (tests + embedding)."""
        if self._started:
            return
        self._started = True
        self._register_framework_routes()

        self._metrics_server = HTTPServer(self._metrics_router(), self.metrics_port, self.logger)
        try:
            self._metrics_server.start()
            self.metrics_port = self._metrics_server.port
        except OSError as exc:
            self.logger.errorf("metrics server failed to start: %s", exc)
            self._metrics_server = None

        self._http_server = HTTPServer(self.router, self.http_port, self.logger)
        self._http_server.start()
        self.http_port = self._http_server.port

        if self._grpc_services:
            from .grpcx import GRPCServer

            self._grpc_server = GRPCServer(self.container, self.grpc_port, self.logger)
            for svc in self._grpc_services:
                self._grpc_server.register(svc)
            self._grpc_server.start()
            self.grpc_port = self._grpc_server.port

        self._subscriptions.start()
        if self._cron is not None:
            self._cron.start()
        self.logger.infof("app %s started: http=:%d metrics=:%d",
                          self.container.app_name, self.http_port, self.metrics_port)

    def run(self) -> None:
        """Start everything and block (the reference's wg.Wait, gofr.go:177)."""
        self.start()
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            self.shutdown()

    def on_shutdown(self, fn) -> None:
        """Register a hook run FIRST (LIFO) during shutdown — before any
        server stops. The place for graceful drains: an llm-server registers
        `lambda: engine.drain()` so active generations finish before the
        transport goes away."""
        self._shutdown_hooks.append(fn)

    def shutdown(self) -> None:
        for hook in reversed(self._shutdown_hooks):
            try:
                hook()
            except Exception as exc:  # noqa: BLE001 - shutdown must proceed
                self.logger.errorf("shutdown hook failed: %s", exc)
        self._subscriptions.stop()
        if self._cron is not None:
            self._cron.stop()
        for server in (self._http_server, self._metrics_server):
            if server is not None:
                server.shutdown()
        if self._grpc_server is not None:
            self._grpc_server.stop()
        if self.container.tpu is not None and hasattr(self.container.tpu, "stop"):
            self.container.tpu.stop()
        self.container.close()
        self._started = False


def new_app(config_dir: Optional[str] = None, **kwargs) -> App:
    return App(config_dir=config_dir, **kwargs)
