"""Capacity observatory, fleet half: merge every replica's
``/debug/capacity`` into one rollup with an explicit scaling
recommendation.

Each replica's TPUMeter + HeadroomForecaster (tpu/meter.py) answer "who
is consuming THIS device" and "how much load until THIS replica falls
over"; the fleet tier owns the questions operators actually page on:
what is the FLEET's utilization, which tenants dominate fleet-wide
spend, and — the number ROADMAP item 2's autoscaler will actuate on —
how many replicas does the offered load need?

``FleetCapacity.rollup()`` polls every registered replica's
``/debug/capacity`` over the same short-timeout probe clients the
registry's health loop uses (breaker-bypassing — an ejected replica
still reports its meter), degrades per replica to an ``error`` row, and
merges:

  * fleet λ (token arrival rate) = Σ replica λ; fleet μ (token service
    capacity) = Σ replica μ; fleet ρ = λ/μ; headroom = max(0, μ−λ)
  * per-tenant fleet-wide spend: device-seconds / FLOPs / page-seconds /
    queue-seconds summed across replicas per tenant
  * ``replicas_needed`` = ceil(fleet λ / (target ρ × mean per-replica
    μ)), clamped to ≥ 1 — the autoscaler hand-off contract documented in
    docs/capacity.md (target ρ from CAPACITY_TARGET_RHO, default 0.75,
    so the fleet is sized to run BELOW the queueing knee, not at it)

Served at ``GET /debug/fleet/capacity``; the headline numbers are also
published as the ``app_tpu_fleet_capacity_rho`` /
``app_tpu_fleet_replicas_needed`` gauges so the autoscaler (and a
Grafana board) can consume them without parsing the debug payload.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

DEFAULT_TARGET_RHO = 0.75


class FleetCapacity:
    """Router-owned fleet capacity rollup (module docstring)."""

    def __init__(self, registry=None, target_rho: float = DEFAULT_TARGET_RHO,
                 metrics=None, logger=None, replica_capacity_fn=None) -> None:
        self.registry = registry
        self.target_rho = min(0.99, max(0.05, float(target_rho)))
        self.metrics = metrics
        self.logger = logger
        # test seam: injectable "what do the replicas say" probe, the
        # FleetSLO idiom — the default asks the registry probe clients
        self._replica_capacity_fn = replica_capacity_fn

    @classmethod
    def from_config(cls, config, registry=None, metrics=None, logger=None):
        """Build from CAPACITY_* keys (docs/configs.md)."""
        return cls(registry=registry,
                   target_rho=config.get_float("CAPACITY_TARGET_RHO",
                                               DEFAULT_TARGET_RHO),
                   metrics=metrics, logger=logger)

    def _replica_capacities(self) -> Dict[str, Any]:
        """{replica: /debug/capacity payload (or {"error": ...})}."""
        if self._replica_capacity_fn is not None:
            return self._replica_capacity_fn()
        out: Dict[str, Any] = {}
        if self.registry is None:
            return out
        for replica in self.registry.replicas:
            try:
                resp = replica.probe.get(None, "/debug/capacity")
                body = resp.json() or {}
                out[replica.name] = body.get("data") or body
            except Exception as exc:  # noqa: BLE001 - degrade per replica
                out[replica.name] = {"error": str(exc)}
        return out

    def rollup(self) -> Dict[str, Any]:
        """The GET /debug/fleet/capacity payload."""
        snapshots = self._replica_capacities()
        replicas: Dict[str, Any] = {}
        tenants: Dict[str, Dict[str, float]] = {}
        lam_tok = 0.0
        mu_values: List[float] = []
        predicted: List[float] = []
        collapse: List[str] = []
        reporting = 0
        for name, snap in snapshots.items():
            if "error" in snap:
                replicas[name] = {"error": snap["error"]}
                continue
            forecast = snap.get("forecast") or {}
            row = {k: forecast.get(k) for k in (
                "lambda_rps", "lambda_tok_s", "mu_tok_s", "rho",
                "headroom_tok_s", "predicted_ttft_ms", "queue_depth",
                "collapse_warning")}
            row["device_s"] = (snap.get("totals") or {}).get("device_s")
            replicas[name] = row
            reporting += 1
            lam_tok += forecast.get("lambda_tok_s") or 0.0
            mu = forecast.get("mu_tok_s")
            if isinstance(mu, (int, float)) and mu > 0:
                mu_values.append(float(mu))
            ttft = forecast.get("predicted_ttft_ms")
            if isinstance(ttft, (int, float)):
                predicted.append(float(ttft))
            if forecast.get("collapse_warning"):
                collapse.append(name)
            for trow in snap.get("tenants") or []:
                tname = trow.get("tenant") or "-"
                agg = tenants.setdefault(tname, {
                    "device_s": 0.0, "flops": 0.0, "page_s": 0.0,
                    "queue_s": 0.0, "requests": 0})
                for field in agg:
                    value = trow.get(field)
                    if isinstance(value, (int, float)):
                        agg[field] = round(agg[field] + value, 6)
        mu_fleet = sum(mu_values)
        mu_per_replica = (mu_fleet / len(mu_values)) if mu_values else None
        rho = (lam_tok / mu_fleet) if mu_fleet else 0.0
        headroom = max(0.0, mu_fleet - lam_tok) if mu_fleet else 0.0
        # the autoscaler hand-off: replicas sized so the fleet runs at
        # target_rho under the CURRENT offered load. With no μ evidence
        # yet (cold fleet) the honest recommendation is "what you have".
        if mu_per_replica:
            replicas_needed = max(1, math.ceil(
                lam_tok / (self.target_rho * mu_per_replica)))
        else:
            replicas_needed = max(1, reporting or len(snapshots))
        top = sorted(tenants.items(), key=lambda kv: kv[1]["device_s"],
                     reverse=True)
        out = {
            "fleet": {
                "lambda_tok_s": round(lam_tok, 3),
                "mu_tok_s": round(mu_fleet, 3) if mu_fleet else None,
                "mu_per_replica_tok_s": (round(mu_per_replica, 3)
                                         if mu_per_replica else None),
                "rho": round(rho, 4),
                "headroom_tok_s": round(headroom, 3),
                "predicted_ttft_ms_max": (round(max(predicted), 3)
                                          if predicted else None),
                "target_rho": self.target_rho,
                "replicas_needed": replicas_needed,
                "replicas_reporting": reporting,
                "replicas_total": len(snapshots),
                "collapse_warnings": collapse,
            },
            "tenants": [{"tenant": name, **row} for name, row in top],
            "replicas": replicas,
        }
        self._publish(rho, replicas_needed, headroom)
        return out

    def _publish(self, rho: float, replicas_needed: int,
                 headroom: float) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.set_gauge("app_tpu_fleet_capacity_rho",
                                   round(rho, 4))
            self.metrics.set_gauge("app_tpu_fleet_capacity_headroom_tok_s",
                                   round(headroom, 3))
            self.metrics.set_gauge("app_tpu_fleet_replicas_needed",
                                   replicas_needed)
        except Exception:  # noqa: BLE001 - publishing is best-effort
            pass

    def publish(self) -> None:
        """Scrape-hook re-eval (the fleet burn idiom): recompute the
        rollup at scrape time so the gauges track probe reality and
        decay with the replicas' own idle decay."""
        try:
            self.rollup()
        except Exception:  # noqa: BLE001 - a scrape must never fail
            pass


def register_fleet_capacity_metrics(metrics) -> None:
    """Idempotent registration (the register_fleet_metrics idiom)."""
    for name, desc in (
        ("app_tpu_fleet_capacity_rho",
         "Fleet utilization: total token arrival rate over total token "
         "service capacity across reporting replicas"),
        ("app_tpu_fleet_capacity_headroom_tok_s",
         "Fleet token throughput headroom before saturation"),
        ("app_tpu_fleet_replicas_needed",
         "Replicas needed to serve the current offered load at the "
         "target utilization (the autoscaler hand-off number)"),
    ):
        try:
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        except Exception:  # noqa: BLE001 - re-registration is benign
            pass


def install_routes(app, router, path: str = "/debug/fleet/capacity") -> None:
    """GET /debug/fleet/capacity — the fleet capacity rollup."""

    @app.get(path)
    def fleet_capacity(ctx):  # noqa: ARG001 - gofr handler signature
        return router.capacity.rollup()
