"""FleetRouter: the forwarding core and its HTTP surface.

Retry discipline (the part that must never be wrong): a request is
retryable only while it is UNSTARTED — the upstream connection failed
(CircuitOpenError / transport error before headers) or the replica shed
it with 503 + Retry-After.  The moment an upstream response with a good
status arrives, the request is COMMITTED to that replica: bytes flow
through byte-for-byte, and an upstream death mid-stream surfaces to the
client as an SSE error event, never as a silent re-send (the prompt may
have sampled tokens already; replaying it elsewhere would double-bill
and double-generate).

Non-2xx, non-503 upstream answers (validation errors and the like) pass
through verbatim — the replica already produced the right envelope and
retrying a 400 elsewhere would just fail again.
"""

import json
import time

from ..http.errors import InvalidParam, MissingParam, ServiceUnavailable
from ..http.responder import Response, Stream
from ..tpu.qos import normalize_class
from ..service import CircuitOpenError
from .affinity import (AffinityMap, DEFAULT_BLOCK, DEFAULT_MAX_BLOCKS,
                       affinity_keys)
from .policy import DEFAULT_SPILL_DEPTH, make_policy
from .registry import FleetRegistry

DEFAULT_RETRY_BUDGET = 2
_DEFAULT_SHED_RETRY_AFTER_S = 1.0


class FleetRouter:
    """Routes /generate across the registry's replicas."""

    def __init__(self, registry, policy, affinity_map=None, metrics=None,
                 logger=None, retry_budget=DEFAULT_RETRY_BUDGET,
                 affinity_block=DEFAULT_BLOCK,
                 affinity_max_blocks=DEFAULT_MAX_BLOCKS):
        self.registry = registry
        self.policy = policy
        self.affinity_map = (affinity_map if affinity_map is not None
                             else registry.affinity_map)
        self.metrics = metrics
        self.logger = logger
        self.retry_budget = max(0, retry_budget)
        self.affinity_block = affinity_block
        self.affinity_max_blocks = affinity_max_blocks
        # plain counters so /debug/fleet works even without a metrics manager
        self.routes = {}
        self.retries = {}
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.stream_breaks = 0
        self.no_replica = 0
        # lifecycle exclusions observed per forward: replicas the elastic
        # plane held out of the candidate set (warming boot, draining
        # exit).  Deliberately NOT routed through _count_route — a
        # held-out replica is not an affinity miss, and it never feeds
        # the breaker
        self.route_skips = {}
        # per-QoS-class accounting ("unclassified" for legacy traffic):
        # routed = committed to a replica, shed = 503 replies consumed
        # by the retry loop — the fleet-level view of replica shedding
        self.class_routes = {}
        self.class_sheds = {}
        # observability plane, attached by the router app (None-guarded
        # on every touch so the forwarding path never depends on it):
        # journeys = fleet/journey.py recorder, slo = fleet/slo.py
        # rollup, capacity = fleet/capacity.py rollup, capture =
        # loadgen/capture.py arrival-trace ring
        self.journeys = None
        self.slo = None
        self.capacity = None
        self.capture = None

    @classmethod
    def from_config(cls, config, logger=None, metrics=None):
        """Build registry + policy + router from FLEET_* config keys."""
        affinity_map = AffinityMap()
        registry = FleetRegistry.from_config(config, logger=logger,
                                             metrics=metrics,
                                             affinity_map=affinity_map)
        policy = make_policy(
            config.get_or_default("FLEET_POLICY", "affinity"),
            spill_depth=config.get_int("FLEET_SPILL_DEPTH",
                                       DEFAULT_SPILL_DEPTH))
        return cls(
            registry, policy, affinity_map=affinity_map, metrics=metrics,
            logger=logger,
            retry_budget=config.get_int("FLEET_RETRY_BUDGET",
                                        DEFAULT_RETRY_BUDGET),
            affinity_block=config.get_int("FLEET_AFFINITY_BLOCK",
                                          DEFAULT_BLOCK),
            affinity_max_blocks=config.get_int("FLEET_AFFINITY_MAX_BLOCKS",
                                               DEFAULT_MAX_BLOCKS))

    def start(self):
        self.registry.start()

    def stop(self):
        self.registry.stop()

    # -- health (feeds the router app's own /.well-known/health) -------------
    def health_check(self):
        from ..datasource import (Health, STATUS_DEGRADED, STATUS_DOWN,
                                  STATUS_UP)

        up = len(self.registry.candidates())
        total = len(self.registry.replicas)
        details = {"replicas_available": up, "replicas_total": total}
        if up == 0:
            return Health(status=STATUS_DOWN, details=details)
        if up < total:
            return Health(status=STATUS_DEGRADED, details=details)
        return Health(status=STATUS_UP, details=details)

    # -- counters -------------------------------------------------------------
    def _count_route(self, reason):
        self.routes[reason] = self.routes.get(reason, 0) + 1
        if self.policy.name == "affinity":
            if reason == "affinity":
                self.affinity_hits += 1
            else:
                self.affinity_misses += 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_tpu_fleet_route_total",
                                           policy=self.policy.name,
                                           reason=reason)
            if self.policy.name == "affinity":
                if reason == "affinity":
                    self.metrics.increment_counter(
                        "app_tpu_fleet_affinity_hits_total")
                else:
                    self.metrics.increment_counter(
                        "app_tpu_fleet_affinity_misses_total")

    def _count_route_skips(self):
        """Once per forward: record replicas excluded by lifecycle, under
        the same route_total metric so dashboards see WHY the candidate
        set shrank (reason=warming|draining)."""
        for replica in list(self.registry.replicas):
            lifecycle = replica.effective_lifecycle
            if lifecycle == "serving":
                continue
            self.route_skips[lifecycle] = self.route_skips.get(lifecycle, 0) + 1
            if self.metrics is not None:
                self.metrics.increment_counter("app_tpu_fleet_route_total",
                                               policy=self.policy.name,
                                               reason=lifecycle)

    def _count_retry(self, reason):
        self.retries[reason] = self.retries.get(reason, 0) + 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_tpu_fleet_retries_total",
                                           reason=reason)

    def _count_class(self, table, metric, qos_class):
        cls = qos_class or "unclassified"
        table[cls] = table.get(cls, 0) + 1
        if self.metrics is not None:
            self.metrics.increment_counter(metric, **{"class": cls})

    def _count_stream_break(self, replica):
        self.stream_breaks += 1
        replica.stream_breaks += 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_tpu_fleet_stream_breaks_total",
                                           replica=replica.name)

    # -- forwarding -----------------------------------------------------------
    def forward(self, ctx, body, qos_class=None):
        """Route one /generate body; returns a Stream (SSE pass-through)
        or a Response (buffered pass-through), or raises
        ServiceUnavailable when every attempt found no usable replica.
        qos_class (already normalized by the route handler) is counted
        per class so fleet shedding/spillover is QoS-attributable; the
        class itself rides inside `body`, which is forwarded verbatim."""
        prompt = body.get("prompt", "")
        keys = affinity_keys(prompt, self.affinity_block,
                             self.affinity_max_blocks)
        if self.capture is not None:
            self.capture.note(prompt, qos_class=qos_class,
                              tenant=body.get("tenant"),
                              max_new=body.get("max_tokens"))
        journeys = self.journeys
        journey = None
        if journeys is not None:
            span = getattr(ctx, "span", None)
            journey = journeys.begin(
                trace_id=getattr(span, "trace_id", None),
                qos_class=qos_class, tenant=body.get("tenant"),
                prompt_chars=len(prompt))
        tried = set()
        attempts = 1 + self.retry_budget
        shortest_shed = None
        self._count_route_skips()
        for attempt in range(attempts):
            candidates = self.registry.candidates(exclude=tried)
            if not candidates:
                break
            replica, reason = self.policy.choose(candidates, keys,
                                                 self.affinity_map)
            self._count_route(reason)
            if journeys is not None:
                journeys.attempt(journey, replica.name, reason)
            replica.begin()
            try:
                resp = replica.client.request(ctx, "POST", "/generate",
                                              body=body, stream=True)
            except Exception as exc:  # noqa: BLE001 - unstarted: safe to retry
                replica.end()
                tried.add(replica.name)
                kind = ("breaker_open" if isinstance(exc, CircuitOpenError)
                        else "connect_error")
                if replica.effective_lifecycle == "draining":
                    # the replica went draining between candidate
                    # selection and connect — still UNSTARTED, still
                    # retryable, but labeled so drains don't read as
                    # transport faults
                    kind = "draining"
                self._count_retry(kind)
                if journeys is not None:
                    journeys.attempt_outcome(journey, kind)
                if self.logger is not None:
                    self.logger.warnf("fleet: %s to %s (attempt %d): %s",
                                      kind, replica.name, attempt + 1, exc)
                continue
            if resp.status_code == 503:
                if replica.effective_lifecycle == "draining":
                    # mid-drain refusal: the replica is LEAVING, not
                    # overloaded — retry elsewhere without charging the
                    # shed window (note_shed would outlive the replica)
                    resp.close()
                    replica.end()
                    tried.add(replica.name)
                    self._count_retry("draining")
                    if journeys is not None:
                        journeys.attempt_outcome(journey, "draining")
                    continue
                retry_after = _parse_retry_after(resp.header("Retry-After"))
                replica.note_shed(retry_after)
                shortest_shed = (retry_after if shortest_shed is None
                                 else min(shortest_shed, retry_after))
                resp.close()
                replica.end()
                tried.add(replica.name)
                self._count_retry("shed")
                if journeys is not None:
                    journeys.attempt_outcome(journey, "shed")
                self._count_class(self.class_sheds,
                                  "app_tpu_fleet_class_sheds_total",
                                  qos_class)
                continue
            # committed to this replica from here on — no more retries
            self._count_class(self.class_routes,
                              "app_tpu_fleet_class_routes_total", qos_class)
            if journeys is not None:
                journeys.committed(journey, replica.name, resp.status_code)
            if resp.status_code >= 400:
                content = resp.read()
                replica.end()
                if journeys is not None:
                    journeys.finish(journey, "upstream_error",
                                    error=f"upstream {resp.status_code}")
                return Response(
                    status=resp.status_code,
                    headers={"Content-Type": resp.header("Content-Type")
                             or "application/json"},
                    body=content)
            self.affinity_map.learn(keys, replica.name)
            content_type = (resp.header("Content-Type") or "").lower()
            if ("text/event-stream" in content_type
                    or resp.header("Transfer-Encoding") == "chunked"):
                return self._passthrough_stream(resp, replica,
                                                content_type
                                                or "text/event-stream",
                                                journey)
            content = resp.read()
            replica.end()
            if journeys is not None:
                journeys.finish(journey, "ok")
            return Response(
                status=resp.status_code,
                headers={"Content-Type": content_type or "application/json"},
                body=content)
        self.no_replica += 1
        if journeys is not None:
            journeys.finish(journey, "no_replica")
        retry_after = shortest_shed or self.registry.probe_s or 1.0
        raise ServiceUnavailable(
            f"no replica available after {attempts} attempt(s) "
            f"({len(self.registry.replicas)} configured, "
            f"{len(self.registry.candidates())} healthy)",
            retry_after_s=retry_after)

    def _passthrough_stream(self, resp, replica, content_type, journey=None):
        """Byte-for-byte pass-through tied to the client connection: the
        Stream's on_close closes the upstream socket (propagating client
        disconnect as upstream cancel) and releases in-flight. The
        journey record observes the stream from here: first chunk stamps
        TTFB, an upstream death goes terminal as stream_break, on_close
        finishes the journey ok (a no-op when it already broke)."""
        router = self
        journeys = self.journeys

        def chunks():
            first = True
            try:
                for chunk in resp.iter_chunks():
                    if chunk:
                        if journeys is not None:
                            if first:
                                journeys.first_chunk(journey)
                                first = False
                            journeys.chunk(journey)
                        yield chunk
            except Exception as exc:  # noqa: BLE001 - upstream died mid-stream
                router._count_stream_break(replica)
                if journeys is not None:
                    journeys.finish(journey, "stream_break", error=str(exc))
                if router.logger is not None:
                    router.logger.errorf("fleet: stream from %s broke: %s",
                                         replica.name, exc)
                event = {"error": {"message":
                                   f"upstream replica {replica.name} lost "
                                   "mid-stream", "recoverable": False}}
                yield f"data: {json.dumps(event)}\n\n".encode()

        def on_close():
            resp.close()
            replica.end()
            if journeys is not None:
                journeys.finish(journey, "ok")

        return Stream(chunks(), content_type=content_type, sse=False,
                      on_close=on_close)

    # -- debug surface --------------------------------------------------------
    def snapshot(self):
        total_routes = sum(self.routes.values())
        hits = self.affinity_hits
        misses = self.affinity_misses
        hit_rate = hits / (hits + misses) if (hits + misses) else None
        snap = self.registry.snapshot()
        for row in snap["replicas"]:
            row["affinity_entries"] = self.affinity_map.entries_for(row["name"])
        if self.journeys is not None:
            snap["journeys"] = {"finished_total": self.journeys.finished_total,
                                "capacity": self.journeys.capacity}
        return {
            "policy": self.policy.name,
            "retry_budget": self.retry_budget,
            "routes": dict(self.routes),
            "routes_total": total_routes,
            "route_skips": dict(self.route_skips),
            "retries": dict(self.retries),
            "classes": {"routes": dict(self.class_routes),
                        "sheds": dict(self.class_sheds)},
            "no_replica": self.no_replica,
            "stream_breaks": self.stream_breaks,
            "affinity": {
                "block": self.affinity_block,
                "max_blocks": self.affinity_max_blocks,
                "map_size": len(self.affinity_map),
                "hits": hits,
                "misses": misses,
                "hit_rate": round(hit_rate, 4) if hit_rate is not None else None,
            },
            **snap,
        }


def _parse_retry_after(value):
    try:
        parsed = float(value)
        return parsed if parsed > 0 else _DEFAULT_SHED_RETRY_AFTER_S
    except (TypeError, ValueError):
        return _DEFAULT_SHED_RETRY_AFTER_S


def install_routes(app, router):
    """Register the serving surface on a gofr_tpu App: POST /generate
    (the transparent front door) plus GET /debug/fleet."""

    @app.post("/generate")
    def generate(ctx):
        body = ctx.bind()
        if not isinstance(body, dict):
            raise InvalidParam(["body"])
        prompt = body.get("prompt")
        if prompt is None:
            raise MissingParam(["prompt"])
        if not isinstance(prompt, str) or not prompt:
            raise InvalidParam(["prompt"])
        # QoS class from header or body, validated AT THE FRONT DOOR
        # (typed 400 for unknown strings — tpu/qos.py contract) and
        # injected into the forwarded body so every replica sees the
        # same classification the router counted
        qos_class = normalize_class(
            ctx.request.header("X-QoS-Class") or body.get("class") or None)
        if qos_class is not None:
            body["class"] = qos_class
        tenant = ctx.request.header("X-Tenant") or body.get("tenant")
        if tenant:
            body["tenant"] = str(tenant)
        return router.forward(ctx, body, qos_class=qos_class)

    from .debug import install_routes as install_debug_routes
    install_debug_routes(app, router)
    return app
