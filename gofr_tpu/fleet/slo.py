"""Fleet SLO rollup: router-observed burn + per-replica merge.

Per-replica SLOBurnEngines (tpu/incidents.py) each miss the failures
the fleet tier absorbs or creates: a request retried onto a healthy
replica never errors anywhere, a shed consumed by the retry loop is
invisible to the replica that refused it, and a stream break is an
upstream death the REPLICA often records as a plain cancel. This module
closes that gap with two halves:

  * **FleetBurnEngine** — the same paired-window burn machine, fed by
    router-observed journey outcomes (fleet/journey.py): a terminal
    journey scores availability (bad on stream_break/upstream_error),
    its TTFB scores the fleet "ttft" track, and its stream cadence
    (chunks over stream seconds) scores "tpot"; retry exhaustion
    (no_replica) burns availability as a shed. Published as
    ``app_tpu_fleet_slo_burn_rate{slo,window}`` /
    ``app_tpu_fleet_slo_alert_state{slo}`` — the fleet twins of the
    per-replica gauges, renamed so one Grafana board can hold both.
  * **FleetSLO.rollup()** — merges every replica's ``/debug/slo``
    snapshot (over the registry probe clients) with the fleet burn view
    into the ``GET /debug/fleet/slo`` payload, including per-QoS-class
    fleet goodput windows.

The incident hook: when the fleet availability burn pages while NO
replica's own burn engine is paging, the failure lives in the routing
tier (or is being laundered by retries) — exactly the incident a
per-replica pager can never raise. FleetSLO triggers
``fleet_burn_hidden`` on its (router-owned) IncidentManager then.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional

from ..tpu.incidents import SLOBurnEngine

DEFAULT_TTFB_TARGET_S = 0.5
DEFAULT_TPOT_TARGET_S = 0.1
DEFAULT_GOODPUT_WINDOW = 256


class FleetBurnEngine(SLOBurnEngine):
    """SLOBurnEngine publishing under the fleet metric names."""

    def _publish_track(self, track, fast, slow) -> None:
        if fast is not None:
            self._obs.gauge("app_tpu_fleet_slo_burn_rate", round(fast, 4),
                            slo=track.name, window="fast")
        if slow is not None:
            self._obs.gauge("app_tpu_fleet_slo_burn_rate", round(slow, 4),
                            slo=track.name, window="slow")
        self._obs.gauge("app_tpu_fleet_slo_alert_state", track.state,
                        slo=track.name)


class FleetSLO:
    """Router-owned fleet burn + per-class goodput + replica rollup."""

    # journey outcomes that spend availability budget as ERRORS (the
    # client got a bad answer) vs as SHEDS (the client got no answer)
    _ERROR_OUTCOMES = ("stream_break", "upstream_error")
    _SHED_OUTCOMES = ("no_replica",)

    def __init__(self, burn: FleetBurnEngine, registry=None,
                 incidents=None, metrics=None, logger=None,
                 goodput_window: int = DEFAULT_GOODPUT_WINDOW,
                 replica_states_fn=None) -> None:
        self.burn = burn
        self.registry = registry
        self.incidents = incidents
        self.metrics = metrics
        self.logger = logger
        self._lock = threading.Lock()
        # per-QoS-class rolling (ok?) windows -> fleet goodput per class
        self._class_windows: Dict[str, "collections.deque"] = {}
        self._goodput_window = max(1, int(goodput_window))
        # test seam: injectable "what do the replicas say" probe; the
        # default asks the registry over the probe clients
        self._replica_states_fn = replica_states_fn
        self.hidden_pages = 0
        burn.on_page = self._on_page

    @classmethod
    def from_config(cls, config, registry=None, incidents=None,
                    metrics=None, logger=None, clock=None):
        """Build from FLEET_SLO_* keys (docs/configs.md)."""
        kw: Dict[str, Any] = {}
        if clock is not None:
            kw["clock"] = clock
        burn = FleetBurnEngine(
            slo_ttft_s=config.get_float("FLEET_SLO_TTFB_TARGET_S",
                                        DEFAULT_TTFB_TARGET_S),
            slo_tpot_s=config.get_float("FLEET_SLO_TPOT_TARGET_S",
                                        DEFAULT_TPOT_TARGET_S),
            objectives={"availability": config.get_float(
                "FLEET_SLO_OBJECTIVE_AVAILABILITY", 0.999)},
            fast_window_s=config.get_float("FLEET_SLO_FAST_WINDOW_S", 300.0),
            slow_window_s=config.get_float("FLEET_SLO_SLOW_WINDOW_S", 3600.0),
            page_burn=config.get_float("FLEET_SLO_PAGE_BURN", 14.4),
            warn_burn=config.get_float("FLEET_SLO_WARN_BURN", 6.0),
            min_events=config.get_int("FLEET_SLO_MIN_EVENTS", 12),
            metrics=metrics, logger=logger, **kw)
        return cls(burn, registry=registry, incidents=incidents,
                   metrics=metrics, logger=logger,
                   goodput_window=config.get_int(
                       "FLEET_SLO_GOODPUT_WINDOW", DEFAULT_GOODPUT_WINDOW))

    # -- journey intake (fleet/journey.py finish hook) ------------------------
    def observe_journey(self, rec) -> None:
        """One terminal journey -> burn events + class goodput."""
        try:
            outcome = rec.outcome or "ok"
            if outcome in self._SHED_OUTCOMES:
                self.burn.observe_shed()
                ok = False
            else:
                error = outcome in self._ERROR_OUTCOMES
                ttfb = rec.ttfb_s()
                tpot = None
                stream_s = rec.stream_s()
                if stream_s is not None and rec.chunks > 1:
                    tpot = stream_s / (rec.chunks - 1)
                self.burn.observe_request(ttfb, tpot, error=error)
                ok = not error
            cls = rec.qos_class or "unclassified"
            with self._lock:
                window = self._class_windows.get(cls)
                if window is None:
                    window = collections.deque(maxlen=self._goodput_window)
                    self._class_windows[cls] = window
                window.append(1 if ok else 0)
                goodput = sum(window) / len(window)
            if self.metrics is not None:
                self.metrics.set_gauge("app_tpu_fleet_slo_goodput",
                                       round(goodput, 4), **{"class": cls})
        except Exception:  # noqa: BLE001 - accounting is best-effort
            pass

    # -- the hidden-burn incident ---------------------------------------------
    def _replica_slo_states(self) -> Dict[str, Any]:
        """{replica: {slo: state}} (or {"error": ...}) via /debug/slo."""
        if self._replica_states_fn is not None:
            return self._replica_states_fn()
        out: Dict[str, Any] = {}
        if self.registry is None:
            return out
        for replica in self.registry.replicas:
            try:
                resp = replica.probe.get(None, "/debug/slo")
                body = resp.json() or {}
                data = body.get("data") or body
                out[replica.name] = {
                    name: slo.get("state")
                    for name, slo in (data.get("slos") or {}).items()}
            except Exception as exc:  # noqa: BLE001 - unreachable replica
                out[replica.name] = {"error": str(exc)}
        return out

    def _on_page(self, slo: str, **info) -> None:
        """Fleet burn paged: if no replica pages too, the failure is
        fleet-tier-only — the incident per-replica pagers cannot raise."""
        try:
            states = self._replica_slo_states()
            replica_paging = [
                name for name, slos in states.items()
                if any(state == "page" for state in slos.values()
                       if isinstance(state, str))]
            if replica_paging:
                return  # a replica is already paging; not hidden
            self.hidden_pages += 1
            if self.logger is not None:
                self.logger.errorf(
                    "fleet SLO %s pages while every replica is quiet — "
                    "the burn lives in the routing tier", slo)
            if self.incidents is not None:
                self.incidents.trigger("fleet_burn_hidden", slo=slo,
                                       replica_states=states, **info)
        except Exception:  # noqa: BLE001 - alerting is best-effort
            pass

    # -- operator surface -----------------------------------------------------
    def class_goodput(self) -> Dict[str, Any]:
        with self._lock:
            return {cls: {"window": len(window),
                          "goodput": round(sum(window) / len(window), 4)}
                    for cls, window in self._class_windows.items() if window}

    def rollup(self) -> Dict[str, Any]:
        """The GET /debug/fleet/slo payload: fleet burn + class goodput
        + every replica's own /debug/slo snapshot, merged."""
        replicas: Dict[str, Any] = {}
        paging: List[str] = []
        if self.registry is not None:
            for replica in self.registry.replicas:
                try:
                    resp = replica.probe.get(None, "/debug/slo")
                    body = resp.json() or {}
                    data = body.get("data") or body
                    slos = data.get("slos") or {}
                    row = {
                        name: {"state": slo.get("state"),
                               "burn_fast": ((slo.get("windows") or {})
                                             .get("fast") or {})
                               .get("burn_rate"),
                               "burn_slow": ((slo.get("windows") or {})
                                             .get("slow") or {})
                               .get("burn_rate")}
                        for name, slo in slos.items()}
                    replicas[replica.name] = row
                    if any(col.get("state") == "page"
                           for col in row.values()):
                        paging.append(replica.name)
                except Exception as exc:  # noqa: BLE001 - degrade per replica
                    replicas[replica.name] = {"error": str(exc)}
        fleet = self.burn.snapshot()
        return {
            "fleet": fleet,
            "fleet_states": {name: slo.get("state")
                             for name, slo in fleet["slos"].items()},
            "classes": self.class_goodput(),
            "replicas": replicas,
            "replicas_paging": paging,
            "hidden_pages": self.hidden_pages,
        }


def register_fleet_slo_metrics(metrics) -> None:
    """Idempotent registration (the register_fleet_metrics idiom)."""
    for name, desc in (
        ("app_tpu_fleet_slo_burn_rate",
         "Fleet error-budget burn rate from router-observed outcomes, "
         "by slo and window (fast/slow)"),
        ("app_tpu_fleet_slo_alert_state",
         "Fleet SLO alert state: 0 ok, 1 warn, 2 page (both-windows "
         "burn rule over router-observed outcomes)"),
        ("app_tpu_fleet_slo_goodput",
         "Fleet goodput fraction over recent journeys, by QoS class"),
    ):
        try:
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        except Exception:  # noqa: BLE001 - re-registration is benign
            pass


def install_routes(app, router, path: str = "/debug/fleet/slo") -> None:
    """GET /debug/fleet/slo — the fleet burn/goodput rollup."""

    @app.get(path)
    def fleet_slo(ctx):  # noqa: ANN001, ARG001
        return router.slo.rollup()
