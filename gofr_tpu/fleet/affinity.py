"""Prefix-affinity primitives shared by the router and the replicas.

The router sees prompt TEXT; a replica's prefix cache keys on token
pages.  Bridging them exactly would force the router to tokenize with
every replica's tokenizer, so affinity uses a cheaper shared currency:
stable hashes of the prompt's leading character blocks (`affinity_keys`).
Both sides compute the same keys from the same text, which is all
affinity needs — two requests that share a leading text block would also
share leading token pages.

Three pieces:

  - ``affinity_keys(prompt, block)``: cumulative blake2b hashes of
    ``prompt[:block]``, ``prompt[:2*block]``, ... — shortest to longest.
    Deterministic across processes and restarts (unlike ``hash()``).
  - ``AffinityRecorder``: replica-side bounded LRU of keys it has
    served, advertised via ``/stats`` as a tiny digest (plus a boot
    ``generation`` id so routers can tell a restarted — cold — replica
    from a warm one).
  - ``AffinityMap``: router-side key -> replica-name map, learned from
    completed responses and re-warmed by merging advertised digests
    (so a restarted ROUTER recovers affinity without cold-starting
    every session).
"""

import hashlib
import threading
import uuid
from collections import OrderedDict

DEFAULT_BLOCK = 256
DEFAULT_MAX_BLOCKS = 4


def affinity_keys(prompt, block=DEFAULT_BLOCK, max_blocks=DEFAULT_MAX_BLOCKS):
    """Stable hashes of the prompt's cumulative leading char blocks.

    Returns shortest-prefix first; matching should walk the list in
    reverse (longest prefix wins).  Empty prompt -> no keys.
    """
    if not prompt or block <= 0:
        return []
    keys = []
    for i in range(1, max_blocks + 1):
        end = i * block
        piece = prompt[:end].encode("utf-8", "replace")
        keys.append(hashlib.blake2b(piece, digest_size=8).hexdigest())
        if end >= len(prompt):
            break
    return keys


class AffinityRecorder:
    """Replica-side bounded LRU of affinity keys this process served.

    ``digest()`` is the cheap payload `/stats` advertises to routers:
    a bounded list of the hottest keys plus a per-boot ``generation``
    id.  A replica restart changes the generation, telling routers the
    KV behind those keys is gone.
    """

    def __init__(self, block=DEFAULT_BLOCK, max_blocks=DEFAULT_MAX_BLOCKS,
                 capacity=512):
        self.block = block
        self.max_blocks = max_blocks
        self.capacity = capacity
        self.generation = uuid.uuid4().hex[:12]
        self._keys = OrderedDict()
        self._lock = threading.Lock()

    def record(self, prompt):
        keys = affinity_keys(prompt, self.block, self.max_blocks)
        if not keys:
            return
        with self._lock:
            for key in keys:
                self._keys[key] = self._keys.get(key, 0) + 1
                self._keys.move_to_end(key)
            while len(self._keys) > self.capacity:
                self._keys.popitem(last=False)

    def digest(self, k=32):
        """Bounded, O(k) snapshot: the k most-recently-served keys."""
        with self._lock:
            hot = list(self._keys)[-k:]
        return {
            "block": self.block,
            "generation": self.generation,
            "keys": hot,
        }


class AffinityMap:
    """Router-side key -> replica-name map with LRU eviction.

    ``learn`` is called on every completed response; ``merge_digest``
    folds in a replica's advertised digest on probe so a freshly
    restarted router warms up without misrouting the first turn of
    every live session.  Learned entries always win over merged ones —
    the router watched the response land, the digest is just a hint.
    """

    def __init__(self, capacity=4096):
        self.capacity = capacity
        self._entries = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def learn(self, keys, replica):
        with self._lock:
            for key in keys:
                self._entries[key] = replica
                self._entries.move_to_end(key)
            self._evict()

    def merge_digest(self, replica, keys):
        """Fold a replica's advertised keys in WITHOUT overriding
        entries the router learned first-hand."""
        with self._lock:
            for key in keys:
                if key not in self._entries:
                    self._entries[key] = replica
            self._evict()

    def lookup(self, keys):
        """Longest-prefix match; returns (replica_name, key) or
        (None, None).  Refreshes the matched entry's recency."""
        with self._lock:
            for key in reversed(keys):
                name = self._entries.get(key)
                if name is not None:
                    self._entries.move_to_end(key)
                    return name, key
        return None, None

    def forget(self, replica):
        """Drop every entry pointing at `replica` (restart detected via
        generation change, or replica removed from the fleet)."""
        with self._lock:
            stale = [k for k, v in self._entries.items() if v == replica]
            for key in stale:
                del self._entries[key]
        return len(stale)

    def entries_for(self, replica):
        with self._lock:
            return sum(1 for v in self._entries.values() if v == replica)

    def _evict(self):
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
