"""Replica registry: per-backend clients, health, load, and probing.

Each replica carries TWO outbound clients built from `gofr_tpu.service`:

  - ``client``: the serving path, wrapped in a CircuitBreaker so repeated
    transport failures eject the replica (CircuitOpenError routes around
    it) and the breaker's own prober closes the circuit when the replica
    answers health again;
  - ``probe``: a short-timeout plain HTTPService the registry's probe
    loop uses.  It deliberately BYPASSES the breaker — you cannot learn
    a replica recovered through a client that refuses to talk to it.

The probe loop hits the replica's existing surfaces every FLEET_PROBE_S:
`/.well-known/health` (the PR 3 aggregate: DOWN while the reset-storm
breaker holds the engine) for state, and `/stats` for queue depth,
duty cycle, and the affinity digest (merged into the router's
AffinityMap; a changed `generation` means the replica restarted, so its
learned affinity entries are dropped before merging the cold digest).

Shedding is separate from breaking: a 503 + Retry-After from a live
replica marks ``shed_until`` (honoured by ``available``) without
touching the breaker's failure count — a shedding replica is overloaded,
not dead.
"""

import threading
import time

from ..datasource import STATUS_DEGRADED, STATUS_DOWN, STATUS_UP
from ..service import CircuitBreaker, HTTPService
from .affinity import AffinityMap

DEFAULT_PROBE_S = 2.0
DEFAULT_TIMEOUT_S = 30.0
DEFAULT_BREAKER_THRESHOLD = 3
DEFAULT_BREAKER_INTERVAL_S = 2.0

_STATE_GAUGE = {STATUS_UP: 2, STATUS_DEGRADED: 1, STATUS_DOWN: 0}


class Replica:
    """One backend: breaker-wrapped client + last-probed load/health."""

    def __init__(self, name, address, logger=None, metrics=None,
                 timeout_s=DEFAULT_TIMEOUT_S,
                 breaker_threshold=DEFAULT_BREAKER_THRESHOLD,
                 breaker_interval_s=DEFAULT_BREAKER_INTERVAL_S):
        self.name = name
        self.address = address.rstrip("/")
        svc = HTTPService(self.address, logger, metrics, timeout_s=timeout_s)
        svc.health_endpoint = ".well-known/health"
        self.client = CircuitBreaker(svc, breaker_threshold, breaker_interval_s)
        self.probe = HTTPService(self.address, logger, None,
                                 timeout_s=min(5.0, timeout_s))
        # last probe observations
        self.state = "UNKNOWN"
        self.state_detail = ""
        self.queue_depth = 0
        self.active_slots = 0
        self.duty_cycle = 0.0
        self.generation = None
        self.last_probe_at = 0.0
        self.probe_error = None
        # router-side serving state
        self.shed_until = 0.0  # monotonic deadline from 503 Retry-After
        self.stream_breaks = 0
        self._inflight = 0
        self._lock = threading.Lock()
        # elastic lifecycle (fleet/elastic.py): what the replica itself
        # advertises via /stats fleet.lifecycle, plus a router-side
        # override pinned while THIS router launches (warming) or drains
        # (draining) it.  The override outranks the advertisement — a
        # freshly launched replica must not take traffic on the strength
        # of a probe that raced its boot, and a drain the router ordered
        # holds even if the replica's advertisement lags a probe cycle.
        self.lifecycle = "serving"
        self.lifecycle_override = None
        self.scaleout_wanted = False

    # -- in-flight accounting -------------------------------------------------
    def begin(self):
        with self._lock:
            self._inflight += 1

    def end(self):
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    @property
    def inflight(self):
        with self._lock:
            return self._inflight

    def load(self):
        """Routing load: last-probed queue depth plus what THIS router
        has in flight (covers requests newer than the last probe)."""
        return max(0, self.queue_depth) + self.inflight

    # -- availability ---------------------------------------------------------
    def note_shed(self, retry_after_s):
        self.shed_until = max(self.shed_until,
                              time.monotonic() + max(0.1, retry_after_s))

    def shedding(self, now=None):
        return (now if now is not None else time.monotonic()) < self.shed_until

    @property
    def breaker_open(self):
        return self.client.open

    @property
    def effective_lifecycle(self):
        return self.lifecycle_override or self.lifecycle

    def available(self, now=None):
        return (self.state != STATUS_DOWN and not self.breaker_open
                and not self.shedding(now)
                and self.effective_lifecycle == "serving")

    def snapshot(self):
        return {
            "name": self.name,
            "address": self.address,
            "state": self.state,
            "state_detail": self.state_detail,
            "available": self.available(),
            "breaker_open": self.breaker_open,
            "breaker_failures": self.client.failure_count,
            "shedding": self.shedding(),
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "duty_cycle": self.duty_cycle,
            "inflight": self.inflight,
            "load": self.load(),
            "stream_breaks": self.stream_breaks,
            "lifecycle": self.effective_lifecycle,
            "scaleout_wanted": self.scaleout_wanted,
            "generation": self.generation,
            "probe_age_s": (round(time.monotonic() - self.last_probe_at, 3)
                            if self.last_probe_at else None),
            "probe_error": self.probe_error,
        }


class FleetRegistry:
    """Holds the replica set, runs the probe loop, publishes gauges."""

    def __init__(self, replicas, affinity_map=None, probe_s=DEFAULT_PROBE_S,
                 metrics=None, logger=None):
        self.replicas = list(replicas)
        self.affinity_map = affinity_map if affinity_map is not None else AffinityMap()
        self.probe_s = probe_s
        self.metrics = metrics
        self.logger = logger
        self._stop = threading.Event()
        self._thread = None
        # construction defaults for replicas added at runtime
        # (FleetAutoscaler scale-up); from_config overrides with its
        # FLEET_* values so launched replicas match the seeded ones
        self.replica_timeout_s = DEFAULT_TIMEOUT_S
        self.breaker_threshold = DEFAULT_BREAKER_THRESHOLD
        self.breaker_interval_s = DEFAULT_BREAKER_INTERVAL_S
        self._members_lock = threading.Lock()

    @classmethod
    def from_config(cls, config, logger=None, metrics=None, affinity_map=None):
        """Parse FLEET_REPLICAS: comma-separated `name=url` pairs, or bare
        URLs auto-named r0, r1, ..."""
        raw = config.get_or_default("FLEET_REPLICAS", "")
        entries = [e.strip() for e in raw.split(",") if e.strip()]
        if not entries:
            raise ValueError(
                "FLEET_REPLICAS is required (comma-separated name=url or url)")
        timeout_s = config.get_float("FLEET_TIMEOUT_S", DEFAULT_TIMEOUT_S)
        threshold = config.get_int("FLEET_BREAKER_THRESHOLD",
                                   DEFAULT_BREAKER_THRESHOLD)
        interval_s = config.get_float("FLEET_BREAKER_INTERVAL_S",
                                      DEFAULT_BREAKER_INTERVAL_S)
        replicas = []
        for i, entry in enumerate(entries):
            if "=" in entry and not entry.split("=", 1)[0].startswith("http"):
                name, address = entry.split("=", 1)
            else:
                name, address = f"r{i}", entry
            replicas.append(Replica(name.strip(), address.strip(),
                                    logger=logger, metrics=metrics,
                                    timeout_s=timeout_s,
                                    breaker_threshold=threshold,
                                    breaker_interval_s=interval_s))
        probe_s = config.get_float("FLEET_PROBE_S", DEFAULT_PROBE_S)
        registry = cls(replicas, affinity_map=affinity_map, probe_s=probe_s,
                       metrics=metrics, logger=logger)
        registry.replica_timeout_s = timeout_s
        registry.breaker_threshold = threshold
        registry.breaker_interval_s = interval_s
        return registry

    def replica(self, name):
        for r in self.replicas:
            if r.name == name:
                return r
        return None

    def candidates(self, exclude=()):
        now = time.monotonic()
        return [r for r in self.replicas
                if r.available(now) and r.name not in exclude]

    # -- elastic membership ---------------------------------------------------
    def add_replica(self, name, address, lifecycle_override="warming"):
        """Register a freshly launched replica (autoscaler scale-up).
        It joins under a ``warming`` override — a brand-new Replica's
        UNKNOWN state would otherwise pass ``available()`` before the
        first probe, routing traffic at a cold, still-compiling engine.
        The override clears when the replica's own advertisement says
        serving.  Idempotent on name."""
        existing = self.replica(name)
        if existing is not None:
            return existing
        replica = Replica(name, address, logger=self.logger,
                          metrics=self.metrics,
                          timeout_s=self.replica_timeout_s,
                          breaker_threshold=self.breaker_threshold,
                          breaker_interval_s=self.breaker_interval_s)
        replica.lifecycle_override = lifecycle_override
        with self._members_lock:
            self.replicas = self.replicas + [replica]
        if self.logger is not None:
            self.logger.infof("fleet: replica %s joined (%s) at %s",
                              name, lifecycle_override or "serving", address)
        return replica

    def remove_replica(self, name):
        """Forget a replica entirely (post-drain scale-down)."""
        with self._members_lock:
            kept = [r for r in self.replicas if r.name != name]
            removed = len(kept) != len(self.replicas)
            self.replicas = kept
        if removed:
            self.affinity_map.forget(name)
            if self.logger is not None:
                self.logger.infof("fleet: replica %s removed", name)
        return removed

    def announce_drain(self, name):
        """Mark a replica draining ON THE ANNOUNCEMENT: new sessions stop
        routing to it and its learned affinity entries drop NOW — waiting
        for the eventual DOWN would keep steering sticky sessions into a
        replica that refuses them.  Returns dropped affinity count, or
        None for an unknown replica."""
        replica = self.replica(name)
        if replica is None:
            return None
        replica.lifecycle_override = "draining"
        return self.affinity_map.forget(name)

    # -- probing --------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self.probe_once()
        self._thread = threading.Thread(target=self._probe_loop,
                                        name="fleet-probe", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.probe_s + 2.0)
            self._thread = None

    def _probe_loop(self):
        while not self._stop.wait(self.probe_s):
            try:
                self.probe_once()
            except Exception as exc:  # noqa: BLE001 - probe loop must survive
                if self.logger is not None:
                    self.logger.errorf("fleet probe loop: %s", exc)

    def probe_once(self):
        for replica in list(self.replicas):  # membership can change mid-walk
            self._probe(replica)
        self._publish_gauges()

    def _probe(self, replica):
        prev_state = replica.state
        try:
            resp = replica.probe.get(None, "/.well-known/health")
            payload = resp.json() or {}
            data = payload.get("data") or payload
            status = str(data.get("status") or STATUS_DOWN).upper()
            detail = ""
            # the aggregate de-flaps to DEGRADED even when a contributor
            # is hard DOWN (PR 3's breaker-held engine) — dig into the
            # details: an engine-DOWN replica sheds every request, so for
            # ROUTING purposes it is down.  Only engine contributors
            # count; a DOWN spill tier (kv) degrades, it doesn't unserve.
            if status != STATUS_DOWN:
                for name, contrib in (data.get("details") or {}).items():
                    if ("engine" in name and isinstance(contrib, dict)
                            and str(contrib.get("status", "")).upper()
                            == STATUS_DOWN):
                        status = STATUS_DOWN
                        detail = f"{name} DOWN"
                        break
            replica.state = status if status in _STATE_GAUGE else STATUS_DOWN
            replica.state_detail = detail
            replica.probe_error = None
        except Exception as exc:  # noqa: BLE001 - unreachable replica is DOWN
            replica.state = STATUS_DOWN
            replica.state_detail = "unreachable"
            replica.probe_error = str(exc)
            replica.last_probe_at = time.monotonic()
            return
        try:
            stats = (replica.probe.get(None, "/stats").json() or {})
            stats = stats.get("data") or stats
            replica.queue_depth = int(stats.get("queue_depth", 0) or 0)
            replica.active_slots = int(stats.get("active_slots", 0) or 0)
            fleet = stats.get("fleet") or {}
            replica.duty_cycle = float(fleet.get("duty_cycle", 0.0) or 0.0)
            was_draining = replica.effective_lifecycle == "draining"
            advertised = str(fleet.get("lifecycle") or "serving")
            if advertised in ("warming", "serving", "draining"):
                replica.lifecycle = advertised
            if (advertised == "serving"
                    and replica.lifecycle_override == "warming"):
                # boot confirmed by the replica itself; release traffic
                replica.lifecycle_override = None
            qos = fleet.get("qos") or {}
            replica.scaleout_wanted = bool(qos.get("scaleout_wanted"))
            if replica.effective_lifecycle == "draining" and not was_draining:
                # replica announced its own drain (operator hit it
                # directly): drop learned affinity on the announcement
                dropped = self.affinity_map.forget(replica.name)
                if self.logger is not None and dropped:
                    self.logger.infof(
                        "fleet: replica %s draining; dropped %d affinity entries",
                        replica.name, dropped)
            digest = fleet.get("affinity") or {}
            generation = digest.get("generation")
            if generation is not None:
                if replica.generation is not None and generation != replica.generation:
                    # replica restarted: its KV is cold, learned entries lie
                    dropped = self.affinity_map.forget(replica.name)
                    if self.logger is not None and dropped:
                        self.logger.infof(
                            "fleet: replica %s restarted; dropped %d affinity entries",
                            replica.name, dropped)
                    # a restart is a fresh boot: stale router-side drain or
                    # warming pins no longer describe this process
                    replica.lifecycle_override = None
                    replica.lifecycle = advertised if advertised in (
                        "warming", "serving", "draining") else "serving"
                replica.generation = generation
            keys = digest.get("keys") or []
            if keys:
                self.affinity_map.merge_digest(replica.name, keys)
        except Exception:  # noqa: BLE001 - /stats is best-effort enrichment
            pass
        replica.last_probe_at = time.monotonic()
        if (prev_state != replica.state and self.logger is not None
                and prev_state != "UNKNOWN"):
            self.logger.infof("fleet: replica %s %s -> %s", replica.name,
                              prev_state, replica.state)

    def _publish_gauges(self):
        if self.metrics is None:
            return
        now = time.monotonic()
        available = 0
        for r in list(self.replicas):
            value = _STATE_GAUGE.get(r.state, 0)
            if r.breaker_open:
                value = 0
            elif r.shedding(now) and value > 1:
                value = 1
            self.metrics.set_gauge("app_tpu_fleet_replica_state", value,
                                   replica=r.name)
            self.metrics.set_gauge("app_tpu_fleet_inflight", r.inflight,
                                   replica=r.name)
            if r.available(now):
                available += 1
        self.metrics.set_gauge("app_tpu_fleet_replicas_available", available)

    def snapshot(self):
        return {
            "probe_s": self.probe_s,
            "replicas": [r.snapshot() for r in self.replicas],
            "available": len(self.candidates()),
        }
