"""Router-side journey recorder + cross-hop waterfall assembly.

The fleet router is the only component that sees a request's WHOLE
story — which replicas were tried and why, where it committed, when the
first byte came back, whether the stream broke — but before this module
that story evaporated with the request. JourneyRecorder is the router's
flight-recorder analog: a bounded live/done ring of per-forward records
(route decisions, retries with reasons, upstream status, TTFB, stream
duration, terminal outcome), keyed by a router journey id AND by the
W3C trace id the tracer middleware already threads end to end.

``assemble()`` turns one record into the cross-hop waterfall: the
router's own hops (one ``route`` hop per attempt, a terminal
``finish``/``stream_break`` hop) merged with the committed replica's
``/debug/journey/{trace_id}`` payload — fetched over the registry's
existing short-timeout probe clients, never the breaker-wrapped serving
path — and causally ordered by tpu/journey.py's shared ranking. A
replica that cannot answer (restarted, ring rolled over) degrades to a
journey with ``missing`` naming it; assembly never fails the read.

Recording discipline matches tpu/flightrecorder.py: every hook the
forwarding path calls is O(1) under one short lock and swallows its own
failures — journey accounting can never break serving.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from ..tpu.journey import is_trace_id, order_hops
from ..tpu.obs import MetricsHook

DEFAULT_CAPACITY = 256

# terminal outcomes a journey can reach (docs/observability.md §12)
OUTCOME_OK = "ok"
OUTCOME_STREAM_BREAK = "stream_break"
OUTCOME_NO_REPLICA = "no_replica"
OUTCOME_UPSTREAM_ERROR = "upstream_error"


class JourneyRecord:
    """One forwarded request, as the router saw it."""

    __slots__ = ("id", "trace_id", "qos_class", "tenant", "prompt_chars",
                 "wall0", "mono0", "attempts", "replica", "status",
                 "first_chunk_at", "finished_at", "chunks", "outcome",
                 "error")

    def __init__(self, journey_id: int, trace_id: Optional[str],
                 qos_class: Optional[str], tenant: Optional[str],
                 prompt_chars: int) -> None:
        self.id = journey_id
        self.trace_id = trace_id
        self.qos_class = qos_class
        self.tenant = tenant
        self.prompt_chars = prompt_chars
        # wall/mono anchor pair (the flight-recorder idiom): stamps are
        # monotonic, rendered as epochs only at the display boundary
        self.wall0 = time.time()  # lint: clock-ok the designated wall/mono anchor pair
        self.mono0 = time.monotonic()
        self.attempts: List[Dict[str, Any]] = []
        self.replica: Optional[str] = None  # committed replica
        self.status: Optional[int] = None   # upstream HTTP status
        self.first_chunk_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.chunks = 0
        self.outcome: Optional[str] = None
        self.error: Optional[str] = None

    def wall(self, t_mono: float) -> float:
        return self.wall0 + (t_mono - self.mono0)

    def ttfb_s(self) -> Optional[float]:
        if self.first_chunk_at is None:
            return None
        return max(0.0, self.first_chunk_at - self.mono0)

    def stream_s(self) -> Optional[float]:
        if self.finished_at is None or self.first_chunk_at is None:
            return None
        return max(0.0, self.finished_at - self.first_chunk_at)

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "id": self.id,
            "started_at": round(self.wall0, 6),
            "attempts": list(self.attempts),
            "chunks": self.chunks,
        }
        for key in ("trace_id", "qos_class", "tenant", "replica", "status",
                    "outcome", "error"):
            value = getattr(self, key)
            if value is not None:
                out[key] = value
        ttfb = self.ttfb_s()
        if ttfb is not None:
            out["ttfb_s"] = round(ttfb, 6)
        stream = self.stream_s()
        if stream is not None:
            out["stream_s"] = round(stream, 6)
        if self.finished_at is not None:
            out["total_s"] = round(
                max(0.0, self.finished_at - self.mono0), 6)
        return out

    def router_hops(self) -> List[Dict[str, Any]]:
        """The router's contribution to the waterfall: one route hop per
        attempt + the terminal hop (stream_break keeps its own name so a
        broken journey is explicit at a glance)."""
        hops: List[Dict[str, Any]] = []
        for attempt in self.attempts:
            t = attempt.get("t", 0.0)
            hops.append({
                "hop": "route", "actor": "router",
                "t_start": round(self.wall(t), 6),
                "t_end": round(self.wall(t), 6), "duration_s": 0.0,
                "request_id": self.id,
                "replica": attempt.get("replica"),
                "reason": attempt.get("reason"),
                "outcome": attempt.get("outcome")})
        if self.first_chunk_at is not None:
            end = (self.finished_at if self.finished_at is not None
                   else self.first_chunk_at)
            hops.append({
                "hop": "stream", "actor": "router",
                "t_start": round(self.wall(self.first_chunk_at), 6),
                "t_end": round(self.wall(end), 6),
                "duration_s": round(max(0.0, end - self.first_chunk_at), 6),
                "request_id": self.id, "replica": self.replica,
                "chunks": self.chunks})
        if self.outcome is not None:
            t_fin = (self.finished_at if self.finished_at is not None
                     else time.monotonic())
            name = ("stream_break" if self.outcome == OUTCOME_STREAM_BREAK
                    else "finish")
            hop: Dict[str, Any] = {
                "hop": name, "actor": "router",
                "t_start": round(self.wall(t_fin), 6),
                "t_end": round(self.wall(t_fin), 6), "duration_s": 0.0,
                "request_id": self.id, "outcome": self.outcome}
            if self.error is not None:
                hop["error"] = self.error
            hops.append(hop)
        return hops


class JourneyRecorder:
    """Bounded live/done journey store + the assembly fan-out."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, metrics=None,
                 slo=None) -> None:
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self._live: Dict[int, JourneyRecord] = {}
        self._done: "collections.deque[JourneyRecord]" = collections.deque(
            maxlen=self.capacity)
        self._obs = MetricsHook(metrics)
        # fleet SLO tap (fleet/slo.py): every terminal journey feeds the
        # router-observed burn windows — completions, breaks, sheds
        self.slo = slo
        self.finished_total = 0

    def use_slo(self, slo) -> None:
        if slo is not None:
            self.slo = slo

    # -- recording (forwarding path, best-effort) -----------------------------
    def begin(self, trace_id: Optional[str], qos_class: Optional[str],
              tenant: Optional[str], prompt_chars: int = 0):
        try:
            with self._lock:
                self._seq += 1
                rec = JourneyRecord(self._seq, trace_id, qos_class,
                                    tenant, prompt_chars)
                self._live[rec.id] = rec
            return rec
        except Exception:  # noqa: BLE001 - recording is best-effort
            return None

    def attempt(self, rec, replica: str, reason: str,
                outcome: str = "committed") -> None:
        if rec is None:
            return
        try:
            with self._lock:
                rec.attempts.append({"t": time.monotonic(),
                                     "replica": replica, "reason": reason,
                                     "outcome": outcome})
        except Exception:  # noqa: BLE001
            pass

    def attempt_outcome(self, rec, outcome: str) -> None:
        """Re-label the latest attempt after its fate is known (shed /
        connect_error / breaker_open / committed)."""
        if rec is None:
            return
        try:
            with self._lock:
                if rec.attempts:
                    rec.attempts[-1]["outcome"] = outcome
        except Exception:  # noqa: BLE001
            pass

    def committed(self, rec, replica: str, status: int) -> None:
        if rec is None:
            return
        try:
            with self._lock:
                rec.replica = replica
                rec.status = status
                if rec.attempts:
                    rec.attempts[-1]["outcome"] = "committed"
        except Exception:  # noqa: BLE001
            pass

    def first_chunk(self, rec) -> None:
        if rec is None:
            return
        try:
            with self._lock:
                if rec.first_chunk_at is None:
                    rec.first_chunk_at = time.monotonic()
        except Exception:  # noqa: BLE001
            pass

    def chunk(self, rec) -> None:
        if rec is None:
            return
        rec.chunks += 1  # single writer (the pass-through generator)

    def finish(self, rec, outcome: str, error: Optional[str] = None) -> None:
        if rec is None:
            return
        try:
            with self._lock:
                live = self._live.pop(rec.id, None)
                if live is None:
                    return  # already terminal
                rec.finished_at = time.monotonic()
                rec.outcome = outcome
                if error is not None:
                    rec.error = str(error)
                self._done.append(rec)
                self.finished_total += 1
            self._obs.counter("app_tpu_journey_total", outcome=outcome)
            ttfb = rec.ttfb_s()
            if ttfb is not None:
                self._obs.hist("app_tpu_journey_ttfb_seconds", ttfb)
            if self.slo is not None:
                self.slo.observe_journey(rec)
        except Exception:  # noqa: BLE001
            pass

    # -- lookup ---------------------------------------------------------------
    def lookup(self, raw_id: str):
        """Journey record by router journey id or 32-hex trace id (the
        newest journey wins a trace shared across client retries)."""
        with self._lock:
            records = list(self._live.values()) + list(self._done)
            if is_trace_id(raw_id):
                trace_id = raw_id.strip().lower()
                matches = [r for r in records if r.trace_id == trace_id]
                return matches[-1] if matches else None
            try:
                journey_id = int(raw_id)
            except (TypeError, ValueError):
                return None
            for rec in records:
                if rec.id == journey_id:
                    return rec
            return None

    def snapshot(self, limit: int = 32) -> Dict[str, Any]:
        with self._lock:
            live = sorted(self._live.values(), key=lambda r: r.mono0)
            done = list(self._done)
        return {
            "capacity": self.capacity,
            "finished_total": self.finished_total,
            "in_flight": [r.summary() for r in live],
            "recent": [r.summary() for r in reversed(done)][:limit],
        }

    # -- cross-hop assembly ---------------------------------------------------
    def assemble(self, rec: JourneyRecord, registry) -> Dict[str, Any]:
        """One record -> the full waterfall: router hops + the committed
        replica's local journey, fetched over its probe client."""
        hops = rec.router_hops()
        replica_payloads: Dict[str, Any] = {}
        missing: List[str] = []
        names = {a.get("replica") for a in rec.attempts
                 if a.get("outcome") == "committed"}
        names.discard(None)
        if rec.replica:
            names.add(rec.replica)
        for name in sorted(names):
            replica = registry.replica(name)
            payload = None
            if replica is not None and rec.trace_id:
                try:
                    resp = replica.probe.get(
                        None, f"/debug/journey/{rec.trace_id}")
                    if resp.status_code == 200:
                        body = resp.json() or {}
                        payload = body.get("data") or body
                except Exception:  # noqa: BLE001 - degrade, never fail the read
                    payload = None
            if payload and payload.get("hops"):
                for hop in payload["hops"]:
                    hop = dict(hop)
                    hop["actor"] = f"{name}:{hop.get('actor', 'engine')}"
                    hops.append(hop)
                replica_payloads[name] = {
                    "requests": payload.get("requests", [])}
            else:
                missing.append(name)
        self._obs.counter("app_tpu_journey_assembled_total",
                          complete=str(not missing).lower())
        return {
            "journey_id": rec.id,
            "trace_id": rec.trace_id,
            "source": "router",
            "journey": rec.summary(),
            "hops": order_hops(hops),
            "replicas": replica_payloads,
            "missing": missing,
            "complete": not missing,
        }


def register_journey_metrics(metrics) -> None:
    """Idempotent registration (the register_fleet_metrics idiom)."""
    try:
        if metrics.get("app_tpu_journey_total") is None:
            metrics.new_counter(
                "app_tpu_journey_total",
                "Forwarded requests gone terminal, by journey outcome")
    except Exception:  # noqa: BLE001 - re-registration is benign
        pass
    try:
        if metrics.get("app_tpu_journey_assembled_total") is None:
            metrics.new_counter(
                "app_tpu_journey_assembled_total",
                "Cross-hop journey assemblies served, by completeness")
    except Exception:  # noqa: BLE001
        pass
    try:
        if metrics.get("app_tpu_journey_ttfb_seconds") is None:
            metrics.new_histogram(
                "app_tpu_journey_ttfb_seconds",
                "Router-observed time to first upstream byte",
                buckets=[0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0])
    except Exception:  # noqa: BLE001
        pass


def install_routes(app, router, path: str = "/debug/journey") -> None:
    """The router's journey surface: GET /debug/journey (live + recent
    index) and GET /debug/journey/{id} (assembled cross-hop waterfall,
    id = router journey id or trace id)."""
    from ..http.errors import HTTPError

    @app.get(path)
    def journey_list(ctx):  # noqa: ANN001, ARG001
        return router.journeys.snapshot()

    @app.get(path + "/{id}")
    def journey_detail(ctx):  # noqa: ANN001
        raw = ctx.request.path_param("id")
        rec = router.journeys.lookup(raw)
        if rec is None:
            raise HTTPError(
                f"no journey for {raw!r} (router journey id or 32-hex "
                f"trace id; the ring keeps the last "
                f"{router.journeys.capacity} journeys)", status_code=404)
        return router.journeys.assemble(rec, router.registry)
