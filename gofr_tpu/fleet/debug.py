"""Fleet observability: /debug/fleet + the app_tpu_fleet_* metric family.

Counters
  app_tpu_fleet_route_total{policy,reason}   every routing decision
  app_tpu_fleet_affinity_hits_total          affinity policy stuck to the map
  app_tpu_fleet_affinity_misses_total        cold / spilled / failed-over
  app_tpu_fleet_retries_total{reason}        unstarted re-attempts
                                             (shed | connect_error | breaker_open)
  app_tpu_fleet_stream_breaks_total{replica} committed streams that died upstream
  app_tpu_fleet_class_routes_total{class}    committed routes by QoS class
  app_tpu_fleet_class_sheds_total{class}     replica 503 sheds by QoS class

Gauges (published by the registry probe loop)
  app_tpu_fleet_replica_state{replica}       2=UP 1=DEGRADED/shedding 0=DOWN/open
  app_tpu_fleet_inflight{replica}            this router's in-flight per replica
  app_tpu_fleet_replicas_available           routable candidate count
"""


def register_fleet_metrics(metrics):
    """Idempotent registration (same idiom as register_disagg_metrics)."""
    counters = [
        ("app_tpu_fleet_route_total",
         "Routing decisions by policy and reason"),
        ("app_tpu_fleet_affinity_hits_total",
         "Requests routed to the replica already holding the prefix"),
        ("app_tpu_fleet_affinity_misses_total",
         "Affinity-policy requests routed cold (miss/spill/failover)"),
        ("app_tpu_fleet_retries_total",
         "Unstarted requests re-attempted on another replica, by reason"),
        ("app_tpu_fleet_stream_breaks_total",
         "Committed streams that died upstream (surfaced, never retried)"),
        ("app_tpu_fleet_class_routes_total",
         "Requests committed to a replica, by QoS class"),
        ("app_tpu_fleet_class_sheds_total",
         "Replica 503 sheds consumed by the retry loop, by QoS class"),
    ]
    gauges = [
        ("app_tpu_fleet_replica_state",
         "Per-replica routability: 2=UP 1=DEGRADED/shedding 0=DOWN/breaker-open"),
        ("app_tpu_fleet_inflight",
         "Requests this router currently has in flight per replica"),
        ("app_tpu_fleet_replicas_available",
         "Replicas currently routable (not DOWN/open/shedding)"),
    ]
    for name, desc in counters:
        try:
            if metrics.get(name) is None:
                metrics.new_counter(name, desc)
        except Exception:  # noqa: BLE001 - re-registration is benign
            pass
    for name, desc in gauges:
        try:
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        except Exception:  # noqa: BLE001
            pass


def install_routes(app, router, path="/debug/fleet"):
    """GET /debug/fleet — the replica table an operator (or obs_dump)
    reads first: health, breaker state, shedding, queue depth, in-flight,
    affinity hit rate, route/retry counters."""

    @app.get(path)
    def fleet_debug(ctx):  # noqa: ARG001 - gofr handler signature
        return router.snapshot()

    return app
