"""Routing policies: affinity (default), power-of-two-choices, round-robin.

A policy picks a replica from the AVAILABLE candidates (registry already
filtered out DOWN / breaker-open / shedding) and labels the decision
with a reason, which feeds `app_tpu_fleet_route_total{policy,reason}`:

  - ``affinity``: prompt prefix matched the map and the preferred
    replica had headroom;
  - ``spill``: prefix matched but the preferred replica is saturated
    (load >= spill_depth and another candidate is lighter) — affinity
    deliberately broken for load;
  - ``failover``: prefix matched a replica that is currently
    unavailable;
  - ``miss``: no prefix match — cold session;
  - ``p2c`` / ``round_robin``: the non-affinity policies' only reason.

Load is `Replica.load()` = last-probed queue depth + this router's
in-flight count, so spillover reacts between probes too.
"""

import itertools
import random
import threading

from .affinity import affinity_keys

DEFAULT_SPILL_DEPTH = 8


class RoutingPolicy:
    """Interface: choose(candidates, keys, affinity_map) -> (replica, reason)."""

    name = "base"

    def choose(self, candidates, keys, affinity_map):  # pragma: no cover - interface
        raise NotImplementedError


class RoundRobinPolicy(RoutingPolicy):
    name = "round_robin"

    def __init__(self):
        self._counter = itertools.count()
        self._lock = threading.Lock()

    def choose(self, candidates, keys, affinity_map):
        with self._lock:
            i = next(self._counter)
        return candidates[i % len(candidates)], "round_robin"


class P2CPolicy(RoutingPolicy):
    """Power of two choices: sample two candidates, take the lighter."""

    name = "p2c"

    def __init__(self, seed=None):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def choose(self, candidates, keys, affinity_map):
        if len(candidates) == 1:
            return candidates[0], "p2c"
        with self._lock:
            a, b = self._rng.sample(candidates, 2)
        return (a if a.load() <= b.load() else b), "p2c"


class AffinityPolicy(RoutingPolicy):
    """Prefix affinity with load spillover.

    Sticks to the replica whose KV already holds the prompt's prefix
    unless that replica is saturated (load >= spill_depth) AND some
    other candidate is strictly lighter — a hot replica that is still
    the lightest keeps its sessions.  Misses and failovers fall back to
    the spill policy (p2c by default).
    """

    name = "affinity"

    def __init__(self, spill_depth=DEFAULT_SPILL_DEPTH, fallback=None):
        self.spill_depth = spill_depth
        self.fallback = fallback if fallback is not None else P2CPolicy()

    def choose(self, candidates, keys, affinity_map):
        preferred_name, _ = affinity_map.lookup(keys)
        if preferred_name is None:
            replica, _ = self.fallback.choose(candidates, keys, affinity_map)
            return replica, "miss"
        preferred = next((c for c in candidates if c.name == preferred_name),
                         None)
        if preferred is None:
            replica, _ = self.fallback.choose(candidates, keys, affinity_map)
            return replica, "failover"
        if preferred.load() >= self.spill_depth:
            others = [c for c in candidates if c is not preferred]
            if others:
                lightest = min(others, key=lambda c: c.load())
                if lightest.load() < preferred.load():
                    return lightest, "spill"
        return preferred, "affinity"


def make_policy(name, spill_depth=DEFAULT_SPILL_DEPTH, seed=None):
    name = (name or "affinity").strip().lower()
    if name == "affinity":
        return AffinityPolicy(spill_depth=spill_depth, fallback=P2CPolicy(seed))
    if name == "p2c":
        return P2CPolicy(seed)
    if name == "round_robin":
        return RoundRobinPolicy()
    raise ValueError(f"unknown FLEET_POLICY {name!r} "
                     "(expected affinity | p2c | round_robin)")


__all__ = ["RoutingPolicy", "RoundRobinPolicy", "P2CPolicy", "AffinityPolicy",
           "make_policy", "affinity_keys", "DEFAULT_SPILL_DEPTH"]
