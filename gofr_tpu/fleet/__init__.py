"""Fleet front door: a prefix- and health-aware router tier over N
llm-server replicas.

Everything below `gofr_tpu/tpu/` serves from ONE process; this package is
the horizontal unlock (ROADMAP item 1): a router process that fronts N
replicas with

  - a replica registry driven by the replicas' existing health surfaces
    (`/.well-known/health` aggregate + `/stats` load/affinity signals),
    each backend wrapped in the GoFr outbound `service` client's
    CircuitBreaker so a dead replica is ejected and probed back in
    (PAPER.md's circuit-breaker layer, finally used for serving);
  - prefix-affinity routing: the prompt's leading char blocks hash to
    stable keys, a router-side map remembers which replica's KV already
    holds that prefix (learned from routed responses, re-warmed from the
    bounded digests each replica advertises), so multi-turn sessions and
    shared system prompts land where their pages live;
  - load spillover: queue-depth/duty-cycle snapshots break affinity when
    the preferred replica is saturated, with power-of-two-choices as the
    default spill/miss policy (`affinity` | `p2c` | `round_robin`);
  - transparent streaming: SSE/chunked bodies pass through byte-for-byte,
    traceparent propagates so one trace spans router -> replica, and only
    UNSTARTED requests (connect failure / 503 shed) are retried — a
    stream that has emitted tokens is never re-sent.

Operator surface: `GET /debug/fleet` + the `app_tpu_fleet_*` metric
family. `examples/router` is the runnable front door; docs/fleet.md has
the failure matrix.
"""

from .affinity import AffinityMap, AffinityRecorder, affinity_keys
from .capacity import FleetCapacity, register_fleet_capacity_metrics
from .debug import register_fleet_metrics
from .elastic import (FleetAutoscaler, InProcessLauncher, ReplicaLauncher,
                      SubprocessLauncher, launcher_from_config,
                      register_elastic_metrics)
from .elastic import install_routes as install_elastic_routes
from .journey import JourneyRecorder, register_journey_metrics
from .policy import (AffinityPolicy, P2CPolicy, RoundRobinPolicy,
                     RoutingPolicy, make_policy)
from .proxy import FleetRouter, install_routes
from .registry import FleetRegistry, Replica
from .slo import FleetBurnEngine, FleetSLO, register_fleet_slo_metrics

__all__ = [
    "AffinityMap", "AffinityRecorder", "affinity_keys",
    "AffinityPolicy", "P2CPolicy", "RoundRobinPolicy", "RoutingPolicy",
    "make_policy", "FleetRouter", "install_routes", "FleetRegistry",
    "Replica", "register_fleet_metrics",
    "JourneyRecorder", "register_journey_metrics",
    "FleetBurnEngine", "FleetSLO", "register_fleet_slo_metrics",
    "FleetCapacity", "register_fleet_capacity_metrics",
    "FleetAutoscaler", "ReplicaLauncher", "InProcessLauncher",
    "SubprocessLauncher", "launcher_from_config",
    "register_elastic_metrics", "install_elastic_routes",
]
