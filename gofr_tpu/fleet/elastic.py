"""Elastic control plane: the autoscaler reconciler and drain orchestrator.

ROADMAP item 2 closes here.  The fleet already *measures* what it needs —
``FleetCapacity.rollup()`` publishes ``replicas_needed`` (M/M/c sizing
below the queueing knee) and per-replica ``collapse_warnings``, and the
QoS ladder's ``request_replica`` rung advertises ``scaleout_wanted``
through /stats — but until now a human read those numbers.  The
``FleetAutoscaler`` turns them into actuation:

    desired = clamp(max(replicas_needed,
                        current+1 if anybody screams), min, max)

with dwell gating in BOTH directions (a spike must hold ``up_hold_s``
before a launch, calm must hold ``down_hold_s`` before a drain) plus a
post-actuation cooldown, so a flapping λ never oscillates the fleet —
capacity moves are expensive (a boot compiles, a drain migrates) and the
reconciler's job is to be *boring*.

Actuation goes through a ``ReplicaLauncher`` seam: ``InProcessLauncher``
boots replicas inside the router process (tests, soak), and
``SubprocessLauncher`` spawns real llm-server processes.  Launched
replicas join the registry under the ``warming`` lifecycle override —
the router never routes at a cold, still-compiling engine; the override
clears only when the replica's own /stats advertises ``serving`` (warm
boot: compile-cache reuse + peer KV pre-warm, tpu/migrate.py).

Scale-down is drain-with-migration, never a kill: mark the victim
``draining`` in the registry (new sessions stop, learned affinity drops
on the announcement), order ``POST /debug/drain`` with the surviving
peers, poll until its live sessions have migrated or finished, then
terminate and remove.  The operator path is the same machinery:
``POST /debug/fleet/drain/{replica}``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

DEFAULT_MIN_REPLICAS = 1
DEFAULT_MAX_REPLICAS = 4
DEFAULT_INTERVAL_S = 5.0
DEFAULT_UP_HOLD_S = 10.0
DEFAULT_DOWN_HOLD_S = 60.0
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_DRAIN_TIMEOUT_S = 30.0
_DECISION_RING = 64


class ReplicaLauncher:
    """Actuation seam: how the autoscaler turns "add a replica" into a
    process.  launch() returns the new replica's base URL; terminate()
    reclaims whatever launch() created (no-op for unknown names)."""

    def launch(self, name):  # pragma: no cover - interface
        raise NotImplementedError

    def terminate(self, name):  # pragma: no cover - interface
        raise NotImplementedError


class InProcessLauncher(ReplicaLauncher):
    """Boots replicas inside this process via a factory callable —
    ``factory(name) -> address`` or ``(address, stop_fn)``.  The soak
    harness and tests inject llm-server ``build_app`` closures here."""

    def __init__(self, factory):
        self._factory = factory
        self._stops = {}
        self._lock = threading.Lock()

    def launch(self, name):
        out = self._factory(name)
        address, stop = (out if isinstance(out, tuple) else (out, None))
        with self._lock:
            self._stops[name] = stop
        return address

    def terminate(self, name):
        with self._lock:
            stop = self._stops.pop(name, None)
        if stop is not None:
            try:
                stop()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass


class SubprocessLauncher(ReplicaLauncher):
    """Spawns real replica processes: ``argv`` (default: this
    interpreter + ELASTIC_LAUNCH_CMD) with HTTP_PORT assigned from
    ``port_base`` upward and ``env`` overlaid on the parent's."""

    def __init__(self, argv, env=None, host="127.0.0.1", port_base=9800):
        self.argv = list(argv)
        self.env = dict(env or {})
        self.host = host
        self._next_port = int(port_base)
        self._procs = {}
        self._lock = threading.Lock()

    def launch(self, name):
        with self._lock:
            port = self._next_port
            self._next_port += 1
        env = {**os.environ, **self.env,
               "HTTP_PORT": str(port), "METRICS_PORT": "0"}
        proc = subprocess.Popen(self.argv, env=env,  # noqa: S603 - operator
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        with self._lock:
            self._procs[name] = proc
        return f"http://{self.host}:{port}"

    def terminate(self, name):
        with self._lock:
            proc = self._procs.pop(name, None)
        if proc is None:
            return
        try:
            proc.terminate()
            proc.wait(timeout=10.0)
        except Exception:  # noqa: BLE001 - escalate a stuck process
            try:
                proc.kill()
            except Exception:  # noqa: BLE001
                pass


def launcher_from_config(config, logger=None):
    """ELASTIC_LAUNCHER: ``none`` (observe-only reconciler, the default),
    or ``subprocess`` (ELASTIC_LAUNCH_CMD argv, split on spaces).  The
    in-process launcher is constructor-injection only — it needs a
    factory no config string can express."""
    kind = (config.get_or_default("ELASTIC_LAUNCHER", "none") or "none").lower()
    if kind in ("", "none"):
        return None
    if kind == "subprocess":
        cmd = config.get_or_default("ELASTIC_LAUNCH_CMD", "")
        if not cmd.strip():
            raise ValueError("ELASTIC_LAUNCHER=subprocess needs "
                             "ELASTIC_LAUNCH_CMD")
        argv = cmd.split()
        if argv[0] == "python":
            argv[0] = sys.executable
        return SubprocessLauncher(
            argv, port_base=config.get_int("ELASTIC_PORT_BASE", 9800))
    raise ValueError(f"unknown ELASTIC_LAUNCHER {kind!r}")


class FleetAutoscaler:
    """Cron-style reconciler: every ``interval_s`` compare what the
    capacity plane says the fleet needs against what the registry holds,
    and actuate through the launcher (module docstring has the law)."""

    def __init__(self, router, launcher=None, *, capacity=None,
                 min_replicas=DEFAULT_MIN_REPLICAS,
                 max_replicas=DEFAULT_MAX_REPLICAS,
                 interval_s=DEFAULT_INTERVAL_S,
                 up_hold_s=DEFAULT_UP_HOLD_S,
                 down_hold_s=DEFAULT_DOWN_HOLD_S,
                 cooldown_s=DEFAULT_COOLDOWN_S,
                 drain_timeout_s=DEFAULT_DRAIN_TIMEOUT_S,
                 metrics=None, logger=None, clock=time.monotonic,
                 capacity_fn=None):
        self.router = router
        self.registry = router.registry
        self.launcher = launcher
        self.capacity = capacity
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.interval_s = max(0.05, float(interval_s))
        self.up_hold_s = max(0.0, float(up_hold_s))
        self.down_hold_s = max(0.0, float(down_hold_s))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.drain_timeout_s = max(1.0, float(drain_timeout_s))
        self.metrics = metrics
        self.logger = logger
        self._clock = clock
        # test seam: () -> capacity "fleet" dict, bypassing the rollup
        self._capacity_fn = capacity_fn
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._launch_seq = 0
        self._launched = set()  # names this autoscaler created
        self._pending_dir = None  # "up" | "down" while a desire dwells
        self._pending_since = 0.0
        self._cooldown_until = 0.0
        self._draining = set()
        self.decisions = []  # ring of the last _DECISION_RING evaluations
        self.scale_events = {"up": 0, "down": 0}
        self.evaluations = 0

    @classmethod
    def from_config(cls, config, router, capacity=None, metrics=None,
                    logger=None, launcher=None):
        """Build from ELASTIC_* / DRAIN_* keys (docs/configs.md)."""
        if launcher is None:
            launcher = launcher_from_config(config, logger=logger)
        return cls(
            router, launcher, capacity=capacity,
            min_replicas=config.get_int("ELASTIC_MIN_REPLICAS",
                                        DEFAULT_MIN_REPLICAS),
            max_replicas=config.get_int("ELASTIC_MAX_REPLICAS",
                                        DEFAULT_MAX_REPLICAS),
            interval_s=config.get_float("ELASTIC_INTERVAL_S",
                                        DEFAULT_INTERVAL_S),
            up_hold_s=config.get_float("ELASTIC_UP_HOLD_S",
                                       DEFAULT_UP_HOLD_S),
            down_hold_s=config.get_float("ELASTIC_DOWN_HOLD_S",
                                         DEFAULT_DOWN_HOLD_S),
            cooldown_s=config.get_float("ELASTIC_COOLDOWN_S",
                                        DEFAULT_COOLDOWN_S),
            drain_timeout_s=config.get_float("DRAIN_TIMEOUT_S",
                                             DEFAULT_DRAIN_TIMEOUT_S),
            metrics=metrics, logger=logger)

    # -- reconcile loop -------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-autoscaler", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 2.0)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception as exc:  # noqa: BLE001 - reconciler survives
                if self.logger is not None:
                    self.logger.errorf("autoscaler evaluate: %s", exc)

    def _capacity_fleet(self):
        if self._capacity_fn is not None:
            return self._capacity_fn() or {}
        if self.capacity is None:
            return {}
        try:
            return (self.capacity.rollup() or {}).get("fleet") or {}
        except Exception:  # noqa: BLE001 - capacity plane down != crash
            return {}

    def evaluate(self):
        """One reconcile step; returns the decision record it appended.
        Safe to call directly (tests drive it with a fake clock)."""
        now = self._clock()
        fleet = self._capacity_fleet()
        current = len(self.registry.replicas)
        needed = int(fleet.get("replicas_needed") or current or 1)
        collapse = list(fleet.get("collapse_warnings") or [])
        screaming = [r.name for r in list(self.registry.replicas)
                     if r.scaleout_wanted]
        desired = needed
        if collapse or screaming:
            # the shed ladder's request_replica rung (or a collapse
            # forecast) outranks the steady-state sizing: somebody is
            # about to shed standard traffic, add capacity FIRST
            desired = max(desired, current + 1)
        desired = max(self.min_replicas, min(self.max_replicas, desired))
        action = "none"
        reason = ""
        direction = ("up" if desired > current
                     else "down" if desired < current else None)
        with self._lock:
            self.evaluations += 1
            if direction is None:
                self._pending_dir = None
            elif direction != self._pending_dir:
                # desire changed direction: restart the dwell clock — this
                # is the hysteresis that keeps a flapping λ from
                # oscillating the fleet
                self._pending_dir = direction
                self._pending_since = now
                reason = "dwell"
            hold = (self.up_hold_s if direction == "up"
                    else self.down_hold_s)
            ready = (direction is not None
                     and now - self._pending_since >= hold
                     and now >= self._cooldown_until)
            if direction is not None and not ready:
                reason = reason or ("cooldown" if now < self._cooldown_until
                                    else "dwell")
        if ready:
            if direction == "up":
                action, reason = self._scale_up()
            else:
                action, reason = self._scale_down()
            if action != "none":
                with self._lock:
                    self._pending_dir = None
                    self._cooldown_until = now + self.cooldown_s
                    self.scale_events[direction] += 1
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_tpu_elastic_scale_events_total",
                        direction=direction)
        record = {
            "t": round(now, 3), "current": current, "needed": needed,
            "desired": desired, "collapse": collapse,
            "scaleout_wanted": screaming, "action": action,
            "reason": reason,
        }
        with self._lock:
            self.decisions.append(record)
            del self.decisions[:-_DECISION_RING]
        if self.metrics is not None:
            self.metrics.set_gauge("app_tpu_elastic_replicas_target",
                                   desired)
        if action != "none" and self.logger is not None:
            self.logger.infof("autoscaler: %s (current=%d desired=%d %s)",
                              action, current, desired, reason)
        return record

    def _scale_up(self):
        if self.launcher is None:
            return "none", "no_launcher"
        with self._lock:
            name = f"auto{self._launch_seq}"
            self._launch_seq += 1
        try:
            address = self.launcher.launch(name)
        except Exception as exc:  # noqa: BLE001 - failed launch, try later
            if self.logger is not None:
                self.logger.errorf("autoscaler: launch %s failed: %s",
                                   name, exc)
            return "none", f"launch_failed: {exc}"
        with self._lock:
            self._launched.add(name)
        # joins warming: the probe flips it serving once the replica's
        # warm boot finishes (tpu/migrate.py Lifecycle advertisement)
        self.registry.add_replica(name, address)
        return f"launched {name}", "scale_up"

    def _scale_down(self):
        victim = self._pick_victim()
        if victim is None:
            return "none", "no_victim"
        threading.Thread(target=self.drain, args=(victim.name,),
                         kwargs={"remove": True},
                         name=f"fleet-drain-{victim.name}",
                         daemon=True).start()
        return f"draining {victim.name}", "scale_down"

    def _pick_victim(self):
        """Least-loaded serving replica, autoscaler-launched first (drain
        in LIFO launch order so the configured floor survives)."""
        with self._lock:
            launched = set(self._launched)
            draining = set(self._draining)
        pool = [r for r in self.registry.candidates()
                if r.name not in draining]
        if len(pool) <= self.min_replicas:
            return None
        ours = [r for r in pool if r.name in launched]
        pick_from = ours or pool
        return min(pick_from, key=lambda r: (r.load(), r.name))

    # -- drain orchestration (scale-down AND operator path) -------------------
    def drain(self, name, migrate=True, remove=None):
        """Drain one replica with session migration; returns an outcome
        dict.  remove=None removes only replicas this autoscaler
        launched; operators pass remove=True/False explicitly."""
        replica = self.registry.replica(name)
        if replica is None:
            return {"error": f"unknown replica {name!r}"}
        with self._lock:
            if name in self._draining:
                return {"replica": name, "phase": "already_draining"}
            self._draining.add(name)
        try:
            return self._drain_inner(replica, migrate, remove)
        finally:
            with self._lock:
                self._draining.discard(name)

    def _drain_inner(self, replica, migrate, remove):
        name = replica.name
        # 1. announcement: no new sessions, affinity forgets NOW
        dropped = self.registry.announce_drain(name)
        self._count_drain("announced")
        peers = [r.address for r in self.registry.candidates()
                 if r.name != name]
        # 2. order the replica to migrate its live sessions to the peers
        status = None
        try:
            resp = replica.probe.request(
                None, "POST", "/debug/drain",
                body={"peers": peers, "timeout_s": self.drain_timeout_s,
                      "migrate": bool(migrate)},
                timeout_s=min(10.0, self.drain_timeout_s))
            payload = resp.json() or {}
            status = payload.get("data") or payload
        except Exception as exc:  # noqa: BLE001 - dead replica: drain is moot
            if self.logger is not None:
                self.logger.warnf("drain %s: order failed (%s); removing",
                                  name, exc)
        # 3. poll until its sessions migrated/finished (or deadline)
        deadline = time.monotonic() + self.drain_timeout_s + 10.0
        drained = False
        while status is not None and time.monotonic() < deadline:
            if status.get("drained"):
                drained = True
                break
            time.sleep(0.25)
            try:
                resp = replica.probe.request(None, "GET", "/debug/drain",
                                             timeout_s=5.0)
                payload = resp.json() or {}
                status = payload.get("data") or payload
            except Exception:  # noqa: BLE001 - process already gone
                break
        self._count_drain("drained" if drained else "timeout")
        # 4. reclaim
        with self._lock:
            ours = name in self._launched
            if ours and remove is not False:
                self._launched.discard(name)
        should_remove = ours if remove is None else bool(remove)
        if should_remove:
            if self.launcher is not None and ours:
                self.launcher.terminate(name)
            self.registry.remove_replica(name)
            self._count_drain("removed")
        out = {"replica": name, "drained": drained,
               "affinity_dropped": dropped, "peers": peers,
               "removed": should_remove, "status": status}
        if self.logger is not None:
            self.logger.infof("drain %s: drained=%s removed=%s", name,
                              drained, should_remove)
        return out

    def _count_drain(self, phase):
        if self.metrics is not None:
            self.metrics.increment_counter("app_tpu_elastic_drains_total",
                                           phase=phase)

    # -- debug surface --------------------------------------------------------
    def snapshot(self):
        with self._lock:
            pending = {"direction": self._pending_dir,
                       "since": round(self._pending_since, 3),
                       "cooldown_until": round(self._cooldown_until, 3)}
            decisions = list(self.decisions)
            draining = sorted(self._draining)
            launched = sorted(self._launched)
            events = dict(self.scale_events)
            evaluations = self.evaluations
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "interval_s": self.interval_s,
            "up_hold_s": self.up_hold_s,
            "down_hold_s": self.down_hold_s,
            "cooldown_s": self.cooldown_s,
            "launcher": (type(self.launcher).__name__
                         if self.launcher is not None else None),
            "evaluations": evaluations,
            "scale_events": events,
            "pending": pending,
            "draining": draining,
            "launched": launched,
            "decisions": decisions[-16:],
            "replicas": [
                {"name": r.name, "lifecycle": r.effective_lifecycle,
                 "scaleout_wanted": r.scaleout_wanted,
                 "available": r.available()}
                for r in list(self.registry.replicas)],
        }


def register_elastic_metrics(metrics):
    """Idempotent registration of the router-side elastic series."""
    specs = (
        ("counter", "app_tpu_elastic_scale_events_total",
         "autoscaler actuations by direction (up=launch, down=drain)"),
        ("counter", "app_tpu_elastic_drains_total",
         "drain orchestration phases: announced, drained, timeout, removed"),
        ("gauge", "app_tpu_elastic_replicas_target",
         "replica count the autoscaler currently wants"),
    )
    for kind, name, desc in specs:
        try:
            if metrics.get(name) is not None:
                continue
            if kind == "counter":
                metrics.new_counter(name, desc)
            else:
                metrics.new_gauge(name, desc)
        except Exception:  # noqa: BLE001 - already registered
            pass


def install_routes(app, autoscaler):
    """GET /debug/fleet/elastic (reconciler state) and
    POST /debug/fleet/drain/{replica} (operator drain-with-migration;
    body: ``{"migrate": true, "remove": false}``)."""

    @app.get("/debug/fleet/elastic")
    def _elastic(ctx):  # noqa: ARG001 - gofr handler shape
        return autoscaler.snapshot()

    @app.post("/debug/fleet/drain/{replica}")
    def _drain(ctx):
        from ..http.errors import EntityNotFound

        name = ctx.request.path_param("replica")
        body = ctx.bind() or {}
        out = autoscaler.drain(
            name, migrate=bool(body.get("migrate", True)),
            remove=body.get("remove"))
        if "error" in out:
            raise EntityNotFound("replica", name)
        return out

    return app


__all__ = [
    "FleetAutoscaler", "ReplicaLauncher", "InProcessLauncher",
    "SubprocessLauncher", "launcher_from_config",
    "register_elastic_metrics", "install_routes",
]
