"""Fleet timeline stitching: one Perfetto trace across router + replicas.

The replica-local exporter (tpu/timeline.py) shows one process; a real
request's story spans the router's forwarding decisions AND one or more
replicas (retries, or the prefill/decode halves of a DISAGG hop). This
module assembles them into ONE multi-process trace-event payload:

  * the router is pid 1: each journey hop (route attempts, the stream
    window, the terminal) from the JourneyRecorder becomes a slice on a
    "router" track, already in the wall-epoch domain;
  * each hop replica's ``/debug/timeline`` window — fetched over the
    registry's short-timeout probe clients, never the breaker-wrapped
    serving path (the fleet/journey.py discipline) — becomes its own pid,
    its monotonic-microsecond events CLOCK-ALIGNED into the shared wall
    epoch through the payload's flight-recorder wall/mono anchor pair
    (one linear shift per replica);
  * flow events are re-normalized across the merged set: every flow
    keyed by the request's W3C trace id gets exactly one ``s`` (the
    earliest event — the router's route attempt), one ``f`` (the
    terminal ``finished``), ``t`` steps between — so a single Perfetto
    load shows router → prefill → handoff → decode as one unbroken
    arrow chain across process boundaries.

A replica that cannot answer (restarted, ring rolled over) degrades to a
``missing`` entry naming it; stitching never fails the read.

Operator surface (install_routes):

    GET /debug/fleet/timeline/{id}[?steps=N]  -> the stitched payload,
         id = router journey id or 32-hex trace id
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from ..tpu.obs import MetricsHook
from ..tpu.timeline import TimelineExporter

ROUTER_PID = 1
ROUTER_TID = 1
DEFAULT_REPLICA_STEPS = 64


def _wall_us(t_wall: float) -> float:
    return round(t_wall * 1e6, 1)


def router_events(journey: Dict[str, Any],
                  hops: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The router's contribution: journey hops as slices + flow events on
    pid 1. `journey` is JourneyRecord.summary(), `hops` its
    router_hops() — both already wall-epoch."""
    trace_id = journey.get("trace_id")
    fid = trace_id or f"journey-{journey.get('id')}"
    events: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": ROUTER_PID, "tid": 0,
         "ts": 0, "args": {"name": "router"}},
        {"ph": "M", "name": "thread_name", "pid": ROUTER_PID,
         "tid": ROUTER_TID, "ts": 0, "args": {"name": "router"}},
    ]
    for hop in hops:
        t0, t1 = hop.get("t_start", 0.0), hop.get("t_end", 0.0)
        args = {k: v for k, v in hop.items()
                if k not in ("t_start", "t_end", "hop", "actor")}
        events.append({"ph": "X", "name": hop.get("hop", "hop"),
                       "cat": "journey", "pid": ROUTER_PID,
                       "tid": ROUTER_TID, "ts": _wall_us(t0),
                       "dur": round(max(0.0, t1 - t0) * 1e6, 1),
                       "args": args})
        milestone = hop.get("hop")
        if milestone in ("route", "finish", "stream_break"):
            ev = {"ph": "t", "cat": "flow", "id": fid, "name": "request",
                  "pid": ROUTER_PID, "tid": ROUTER_TID,
                  "ts": _wall_us(t0),
                  "args": {"milestone": milestone,
                           "outcome": hop.get("outcome")}}
            if milestone != "route":
                ev["args"]["milestone"] = "finished"
            events.append(ev)
    return events


def align_replica(payload: Dict[str, Any], pid: int,
                  name: str) -> Tuple[List[Dict[str, Any]], bool]:
    """One replica /debug/timeline payload -> wall-epoch events under
    `pid`. Returns (events, aligned): without the anchor pair the events
    are unusable on a shared axis, so the replica degrades to missing."""
    anchor = payload.get("anchor") or {}
    wall0, mono0 = anchor.get("wall0"), anchor.get("mono0")
    if wall0 is None or mono0 is None:
        return [], False
    # monotonic-µs -> wall-µs: one linear shift through the anchor
    shift_us = (wall0 - mono0) * 1e6
    events: List[Dict[str, Any]] = []
    for ev in payload.get("traceEvents", []):
        ev = dict(ev)
        ev["pid"] = pid
        if ev.get("ph") == "M":
            if ev.get("name") == "process_name":
                ev["args"] = {"name": name}
        else:
            ev["ts"] = round(ev.get("ts", 0.0) + shift_us, 1)
        events.append(ev)
    return events, True


def stitch_payloads(replica_payloads: Dict[str, Dict[str, Any]],
                    journey: Optional[Dict[str, Any]] = None,
                    hops: Optional[List[Dict[str, Any]]] = None,
                    trace_id: Optional[str] = None) -> Dict[str, Any]:
    """The pure core (no I/O — soak harnesses and tests feed it fetched
    payloads directly): merge router hops + replica timelines into one
    multi-pid trace with normalized cross-process flows."""
    events: List[Dict[str, Any]] = []
    if journey is not None:
        events += router_events(journey, hops or [])
    pids: Dict[str, int] = {}
    missing: List[str] = []
    for i, name in enumerate(sorted(replica_payloads)):
        pid = ROUTER_PID + 1 + i
        aligned, ok = align_replica(replica_payloads[name], pid, name)
        if not ok:
            missing.append(name)
            continue
        pids[name] = pid
        events += aligned
    TimelineExporter._normalize_flows(events)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "clock_domain": "wall_us",
        "trace_id": trace_id,
        "pids": pids,
        "missing": missing,
        "complete": not missing,
        "events_total": len(events),
        "stitched_at": round(time.time(), 6),  # lint: clock-ok operator-facing stitch timestamp, already in the wall domain
    }


def assemble(router, rec, steps: int = DEFAULT_REPLICA_STEPS,
             metrics=None) -> Dict[str, Any]:
    """One journey record -> the stitched fleet trace: fetch each
    committed replica's /debug/timeline over its probe client, align,
    merge with the router's hops. Degrades per-replica, never fails."""
    obs = MetricsHook(metrics)
    names = {a.get("replica") for a in rec.attempts
             if a.get("outcome") == "committed"}
    names.discard(None)
    if rec.replica:
        names.add(rec.replica)
    payloads: Dict[str, Dict[str, Any]] = {}
    unreachable: List[str] = []
    for name in sorted(names):
        replica = router.registry.replica(name)
        payload = None
        if replica is not None:
            try:
                resp = replica.probe.get(
                    None, f"/debug/timeline?steps={int(steps)}")
                if resp.status_code == 200:
                    body = resp.json() or {}
                    payload = body.get("data") or body
            except Exception:  # noqa: BLE001 - degrade, never fail the read
                payload = None
        if payload and payload.get("traceEvents") is not None:
            payloads[name] = payload
        else:
            unreachable.append(name)
    stitched = stitch_payloads(payloads, journey=rec.summary(),
                               hops=rec.router_hops(),
                               trace_id=rec.trace_id)
    stitched["missing"] = sorted(set(stitched["missing"]) | set(unreachable))
    stitched["complete"] = not stitched["missing"]
    stitched["journey_id"] = rec.id
    obs.counter("app_tpu_timeline_stitched_total",
                complete=str(stitched["complete"]).lower())
    return stitched


def register_fleet_timeline_metrics(metrics) -> None:
    """Idempotent registration (the register_journey_metrics idiom)."""
    try:
        if metrics.get("app_tpu_timeline_stitched_total") is None:
            metrics.new_counter(
                "app_tpu_timeline_stitched_total",
                "fleet timeline stitches served, by completeness")
    except Exception:  # noqa: BLE001 - re-registration is benign
        pass


def install_routes(app, router,
                   path: str = "/debug/fleet/timeline",
                   steps: int = DEFAULT_REPLICA_STEPS) -> None:
    """The router's stitched-timeline surface: GET
    /debug/fleet/timeline/{id}, id = router journey id or trace id (the
    journey-detail idiom, fleet/journey.py). Requires the journey plane
    (router.journeys) — the journey record names the hop replicas."""
    from ..http.errors import HTTPError

    metrics = app.container.metrics_manager

    @app.get(path + "/{id}")
    def fleet_timeline(ctx):  # noqa: ANN001
        journeys = getattr(router, "journeys", None)
        if journeys is None:
            raise HTTPError("fleet timeline needs the journey plane "
                            "(FLEET_JOURNEY=true)", status_code=404)
        raw = ctx.request.path_param("id")
        rec = journeys.lookup(raw)
        if rec is None:
            raise HTTPError(
                f"no journey for {raw!r} (router journey id or 32-hex "
                f"trace id; the ring keeps the last {journeys.capacity} "
                f"journeys)", status_code=404)
        try:
            n = int(ctx.request.param("steps") or 0)
        except (TypeError, ValueError):
            n = 0
        return assemble(router, rec, steps=n or steps, metrics=metrics)
