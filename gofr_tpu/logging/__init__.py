"""Leveled structured logger: pretty-colored on terminals, JSON lines otherwise.

Parity: reference pkg/gofr/logging/logger.go (15-method Logger interface :22-38,
terminal/JSON switch :54-84,146-160, PrettyPrint hook :17-19, file logger
:177-196) and logging/level.go:12-19 (DEBUG..FATAL).
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time
from enum import IntEnum
from typing import Any, Optional, TextIO


class Level(IntEnum):
    DEBUG = 1
    INFO = 2
    NOTICE = 3
    WARN = 4
    ERROR = 5
    FATAL = 6

    @property
    def color(self) -> int:
        return {
            Level.DEBUG: 36,   # cyan
            Level.INFO: 32,    # green
            Level.NOTICE: 35,  # magenta
            Level.WARN: 33,    # yellow
            Level.ERROR: 31,   # red
            Level.FATAL: 31,
        }[self]


def parse_level(name: str, default: "Level" = Level.INFO) -> Level:
    try:
        return Level[name.strip().upper()]
    except (KeyError, AttributeError):
        return default


class PrettyPrint:
    """Objects implementing this render their own terminal line (logger.go:17-19)."""

    def pretty_print(self, fp: TextIO) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class Logger:
    """Leveled logger writing to normal_out (<= NOTICE) or error_out (>= WARN)."""

    def __init__(
        self,
        level: Level = Level.INFO,
        normal_out: Optional[TextIO] = None,
        error_out: Optional[TextIO] = None,
        is_terminal: Optional[bool] = None,
    ):
        self.level = level
        self.normal_out = normal_out if normal_out is not None else sys.stdout
        self.error_out = error_out if error_out is not None else sys.stderr
        if is_terminal is None:
            try:
                is_terminal = self.normal_out.isatty()
            except (AttributeError, ValueError):
                is_terminal = False
        self.is_terminal = is_terminal
        self._lock = threading.Lock()

    # -- core ---------------------------------------------------------------
    def _log(self, level: Level, *args: Any) -> None:
        if level < self.level:
            return
        out = self.error_out if level >= Level.WARN else self.normal_out
        now = time.time()
        with self._lock:
            try:
                if self.is_terminal:
                    self._pretty(out, level, now, args)
                else:
                    self._json(out, level, now, args)
                out.flush()
            except (OSError, ValueError):
                pass

    def _pretty(self, out: TextIO, level: Level, now: float, args: tuple) -> None:
        ts = time.strftime("%H:%M:%S", time.localtime(now))
        out.write(f"\x1b[{level.color}m{level.name:<6}\x1b[0m [{ts}] ")
        for a in args:
            if isinstance(a, PrettyPrint):
                a.pretty_print(out)
            elif isinstance(a, (dict, list)):
                out.write(json.dumps(a, default=str))
            else:
                out.write(str(a))
            out.write(" ")
        out.write("\n")

    def _json(self, out: TextIO, level: Level, now: float, args: tuple) -> None:
        msg: Any
        rendered = []
        for a in args:
            if isinstance(a, PrettyPrint):
                buf = io.StringIO()
                a.pretty_print(buf)
                rendered.append(buf.getvalue().strip())
            else:
                rendered.append(a)
        if len(rendered) == 1:
            msg = rendered[0]
        else:
            msg = " ".join(str(r) for r in rendered)
        record = {"level": level.name, "time": now, "message": msg}
        out.write(json.dumps(record, default=str) + "\n")

    # -- public API (reference Logger 15-method surface) --------------------
    def debug(self, *args: Any) -> None:
        self._log(Level.DEBUG, *args)

    def debugf(self, fmt: str, *args: Any) -> None:
        self._log(Level.DEBUG, fmt % args if args else fmt)

    def info(self, *args: Any) -> None:
        self._log(Level.INFO, *args)

    def infof(self, fmt: str, *args: Any) -> None:
        self._log(Level.INFO, fmt % args if args else fmt)

    def notice(self, *args: Any) -> None:
        self._log(Level.NOTICE, *args)

    def noticef(self, fmt: str, *args: Any) -> None:
        self._log(Level.NOTICE, fmt % args if args else fmt)

    def warn(self, *args: Any) -> None:
        self._log(Level.WARN, *args)

    def warnf(self, fmt: str, *args: Any) -> None:
        self._log(Level.WARN, fmt % args if args else fmt)

    def error(self, *args: Any) -> None:
        self._log(Level.ERROR, *args)

    def errorf(self, fmt: str, *args: Any) -> None:
        self._log(Level.ERROR, fmt % args if args else fmt)

    def fatal(self, *args: Any) -> None:
        self._log(Level.FATAL, *args)
        raise SystemExit(1)

    def fatalf(self, fmt: str, *args: Any) -> None:
        self.fatal(fmt % args if args else fmt)

    def log(self, *args: Any) -> None:
        self._log(Level.INFO, *args)

    def logf(self, fmt: str, *args: Any) -> None:
        self.infof(fmt, *args)

    def change_level(self, level: Level) -> None:
        self.level = level


def new_logger(level: Level = Level.INFO) -> Logger:
    return Logger(level=level)


def new_file_logger(path: str, level: Level = Level.INFO) -> Logger:
    """CMD apps log to a file (logger.go:177-196). Caller owns the file's lifetime."""
    fp = open(path, "a", encoding="utf-8")  # noqa: SIM115 - long-lived sink
    return Logger(level=level, normal_out=fp, error_out=fp, is_terminal=False)


class MockLogger(Logger):
    """Captures log records for assertions. Parity: logging/mock_logger.go."""

    def __init__(self, level: Level = Level.DEBUG):
        self.buffer = io.StringIO()
        super().__init__(level=level, normal_out=self.buffer, error_out=self.buffer, is_terminal=False)

    def output(self) -> str:
        return self.buffer.getvalue()
