"""Remote log-level updater: polls a URL and applies level changes live.

Parity: reference pkg/gofr/logging/remotelogger/dynamicLevelLogger.go:23-106
(poll REMOTE_LOG_URL every REMOTE_LOG_FETCH_INTERVAL seconds, parse the level
from the JSON body, call ChangeLevel). Accepted response shapes:
`{"data": [{"serviceName": ..., "logLevel": {"LOG_LEVEL": "DEBUG"}}]}` (the
reference's), `{"data": {"LOG_LEVEL": "DEBUG"}}`, or a bare `"DEBUG"` string.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from . import Logger, parse_level


def _extract_level(payload) -> Optional[str]:
    if isinstance(payload, str):
        return payload
    if isinstance(payload, dict):
        data = payload.get("data", payload)
        if isinstance(data, list) and data:
            data = data[0]
        if isinstance(data, dict):
            lvl = data.get("logLevel", data)
            if isinstance(lvl, dict):
                return lvl.get("LOG_LEVEL")
            if isinstance(lvl, str):
                return lvl
            return data.get("LOG_LEVEL")
    return None


def fetch_and_update_level(logger: Logger, url: str) -> None:
    try:
        import requests

        resp = requests.get(url, timeout=3)
        if resp.status_code != 200:
            return
        name = _extract_level(json.loads(resp.text))
        if not name:
            return
        new_level = parse_level(name, logger.level)
        if new_level != logger.level:
            logger.infof("LOG_LEVEL updated from %s to %s", logger.level.name, new_level.name)
            logger.change_level(new_level)
    except Exception:  # noqa: BLE001 - remote logging must never break the app
        pass


def start_remote_level_updater(logger: Logger, url: str, interval_s: float = 15.0) -> threading.Thread:
    def loop() -> None:
        import time

        while True:
            fetch_and_update_level(logger, url)
            time.sleep(interval_s)

    t = threading.Thread(target=loop, name="remote-log-level", daemon=True)
    t.start()
    return t
