"""DI Container: the central holder of logger, metrics, tracer, and datasources.

Parity: reference pkg/gofr/container/container.go — Container struct :27-40,
`Create` building datasources from config :57-132 (pub/sub backend switch
:86-131), framework metric registration :144-176, aggregate Health
(container/health.go:39-59), GetHTTPService / publisher / subscriber accessors.

TPU mapping (SURVEY.md §1): the TPU device client is a first-class datasource
here — built from config when MODEL/TPU settings exist, or injected via
App.add_tpu() following the reference's Mongo provider pattern
(externalDB.go:5-12, datasource/mongo.go:142-155).
"""

from __future__ import annotations

import gc
import threading
import time
from typing import Any, Dict, Optional

from .. import version
from ..config import Config, MockConfig
from ..datasource import Health, STATUS_DEGRADED, STATUS_DOWN, STATUS_UP
from ..logging import Level, Logger, MockLogger, new_logger, parse_level
from ..metrics import Manager as MetricsManager
from ..tracing import Tracer, exporter_from_config

HTTP_BUCKETS = (0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1, 3, 10, 30)        # container.go:154
SQL_BUCKETS = (5e-5, 1e-4, 3e-4, 1e-3, 2e-3, 3e-3, 5e-3, 7.5e-3, 1e-2)   # container.go:160
KV_BUCKETS = (5e-5, 1e-4, 3e-4, 5e-4, 1e-3, 2e-3, 3e-3)                  # container.go:166


class Container:
    def __init__(self, config: Config, logger: Optional[Logger] = None):
        self.config = config
        self.logger = logger or new_logger(parse_level(config.get_or_default("LOG_LEVEL", "INFO")))
        self.metrics_manager: Optional[MetricsManager] = None
        self.tracer: Optional[Tracer] = None
        self.sql = None
        self.kv = None
        self.pubsub = None
        self.tpu = None
        self.docstore = None
        self.services: Dict[str, Any] = {}
        # app-level components in the aggregate health report (the serving
        # engines register here; see add_health_contributor)
        self._health_contributors: Dict[str, Any] = {}
        # name-keyed callables run at every metrics scrape (see
        # add_scrape_hook); a dict so re-registration is idempotent, like
        # the health contributors
        self._scrape_hooks: Dict[str, Any] = {}
        self.app_name = config.get_or_default("APP_NAME", "gofr-tpu-app")
        self.app_version = config.get_or_default("APP_VERSION", "dev")
        self._started_at = time.time()
        # consecutive health() calls that saw a DEGRADED (not DOWN)
        # contributor — see health() for the de-flap rule
        self._degraded_streak = 0

    # -- construction ---------------------------------------------------------
    @classmethod
    def create(cls, config: Config) -> "Container":
        c = cls(config)
        c.logger.debugf("container created for app %s", c.app_name)

        c.metrics_manager = MetricsManager(logger=c.logger)
        c.register_framework_metrics()
        c.metrics_manager.increment_counter(
            "app_info", 1, app_name=c.app_name, app_version=c.app_version,
            framework_version=version.FRAMEWORK)

        exporter = exporter_from_config(config, c.logger)
        if hasattr(exporter, "use_metrics"):
            # the async exporters count queue-overflow drops in
            # app_obs_dropped_spans_total (registered below)
            exporter.use_metrics(c.metrics_manager)
        c.tracer = Tracer(service_name=c.app_name, exporter=exporter)

        remote_url = config.get_or_default("REMOTE_LOG_URL", "")
        if remote_url:
            from ..logging.remote import start_remote_level_updater
            interval = config.get_float("REMOTE_LOG_FETCH_INTERVAL", 15.0)
            start_remote_level_updater(c.logger, remote_url, interval)

        if config.get_or_default("DB_DIALECT", "") or config.get_or_default("DB_PATH", ""):
            from ..datasource.sql import SQL
            c.sql = SQL(config, c.logger, c.metrics_manager)

        kv_backend = config.get_or_default("KV_STORE", "").lower()
        if kv_backend == "redis":
            # network twin, gated on redis-py (reference redis.go:35-64)
            from ..datasource.kvredis import RedisKVStore
            c.kv = RedisKVStore(config, c.logger, c.metrics_manager)
        elif config.get_bool("KV_ENABLED", False) or kv_backend:
            from ..datasource.kvstore import KVStore
            c.kv = KVStore(config, c.logger, c.metrics_manager)

        backend = config.get_or_default("PUBSUB_BACKEND", "").lower()
        if backend in ("inproc", "memory"):
            from ..pubsub.inproc import InProcBroker
            c.pubsub = InProcBroker(config, c.logger, c.metrics_manager)
        elif backend == "file":
            from ..pubsub.filebroker import FileBroker
            c.pubsub = FileBroker(config, c.logger, c.metrics_manager)
        elif backend in ("kafka", "mqtt", "google"):
            # external drivers resolve lazily; boot survives a missing one
            # the same way a misconfigured SQL datasource stays nil
            # (reference sql/sql.go:33-36)
            try:
                from ..pubsub import external
                cls = {"kafka": external.KafkaAdapter,
                       "mqtt": external.MQTTAdapter,
                       "google": external.GooglePubSubAdapter}[backend]
                c.pubsub = cls(config, c.logger, c.metrics_manager)
            except Exception as exc:  # noqa: BLE001
                c.logger.errorf("could not initialise %s pub/sub: %s", backend, exc)
        elif backend:
            c.logger.errorf("unsupported PUBSUB_BACKEND %r (bundled: inproc, file; "
                            "external: kafka, mqtt, google); pub/sub disabled", backend)

        if config.get_bool("TPU_ENABLED", False) or config.get_or_default("MODEL_NAME", ""):
            # join the multi-host job (if configured) BEFORE the first device
            # query so jax.devices() is the global set. A configured rank
            # that cannot join must fail LOUDLY — degrading to single-process
            # would leave the other ranks blocked at the coordination
            # barrier (unlike a missing Redis, this is not survivable).
            from ..parallel.multihost import initialize_from_config
            initialize_from_config(config, c.logger)
            try:
                from ..tpu.device import TPUClient
                c.tpu = TPUClient.from_config(config, c.logger, c.metrics_manager)
            except Exception as exc:  # noqa: BLE001 - boot survives a bad datasource
                if config.get_or_default("JAX_COORDINATOR_ADDR", ""):
                    # this host already joined the global device set; serving
                    # without a TPU client would hang the pod's collectives
                    raise
                c.logger.errorf("could not initialise TPU client: %s", exc)

        return c

    def register_framework_metrics(self) -> None:
        m = self.metrics_manager
        m.new_counter("app_info", "static app information")
        m.new_gauge("app_python_threads", "live python threads")
        m.new_gauge("app_python_gc_objects", "objects tracked by gc")
        m.new_gauge("app_uptime_seconds", "seconds since container start")
        m.new_histogram("app_http_response", "http response time in seconds", HTTP_BUCKETS)
        m.new_histogram("app_http_service_response", "outbound http call time in seconds", HTTP_BUCKETS)
        m.new_histogram("app_sql_stats", "sql query time in seconds", SQL_BUCKETS)
        m.new_histogram("app_kv_stats", "kv command time in seconds", KV_BUCKETS)
        m.new_histogram("app_doc_stats", "document store op time in seconds", SQL_BUCKETS)
        m.new_counter("app_pubsub_publish_total_count", "messages published")
        m.new_counter("app_pubsub_subscribe_total_count", "messages received")
        m.new_counter("app_pubsub_commit_total_count", "messages committed")
        m.new_counter("app_pubsub_subscribe_failure_count", "handler failures")
        m.new_counter("app_obs_dropped_spans_total",
                      "finished spans dropped by the async trace exporter's "
                      "bounded queue (a dead/slow collector sheds spans "
                      "instead of blocking the span-ending thread)")

    def add_scrape_hook(self, name: str, fn) -> None:
        """fn() runs at every metrics scrape — for gauges whose owner
        cannot push them (the engine's stall gauge: a loop stuck inside a
        wedged device call cannot update its own metric, so the scrape
        pulls the host-side reading instead). Name-keyed: re-registering
        replaces, so every engine-construction path can register without
        duplicate hooks."""
        self._scrape_hooks[name] = fn

    def refresh_runtime_metrics(self) -> None:
        """Refreshed per metrics scrape (metrics/handler.go:21-35)."""
        m = self.metrics_manager
        if m is None:
            return
        m.set_gauge("app_python_threads", threading.active_count())
        m.set_gauge("app_python_gc_objects", len(gc.get_objects()) if gc.isenabled() else 0)
        m.set_gauge("app_uptime_seconds", time.time() - self._started_at)
        if self.tpu is not None and hasattr(self.tpu, "refresh_memory_metrics"):
            # scrape-time HBM refresh: memory_stats is a host-side PJRT
            # read (no device round-trip), so every scrape sees current
            # occupancy even between MemorySampler intervals
            try:
                self.tpu.refresh_memory_metrics()
            except Exception as exc:  # noqa: BLE001 - never break the scrape
                self.logger.errorf("HBM metrics refresh failed: %s", exc)
        for hook in self._scrape_hooks.values():
            try:
                hook()
            except Exception as exc:  # noqa: BLE001 - a broken hook must
                # never break the scrape (every exporter would go blind)
                self.logger.errorf("scrape hook failed: %s", exc)

    # -- accessors ------------------------------------------------------------
    def metrics(self) -> MetricsManager:
        return self.metrics_manager

    def get_http_service(self, name: str):
        svc = self.services.get(name)
        if svc is None:
            self.logger.errorf("http service %s not registered", name)
        return svc

    def get_publisher(self):
        return self.pubsub

    def get_subscriber(self):
        return self.pubsub

    # -- aggregate health (container/health.go:39-59) -------------------------
    def add_health_contributor(self, name: str, fn) -> None:
        """Register an app-level component in the aggregate health report.

        fn() -> Health (or a dict with a "status" key). The reference's
        aggregate health covers exactly the datasources the container
        built; runtime components this framework adds on top (the serving
        engines, whose failure modes — device wedge, page exhaustion — are
        invisible to any datasource probe) report through here. DEGRADED
        contributors degrade the aggregate the same way a DOWN datasource
        does."""
        self._health_contributors[name] = fn

    def health(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.app_name,
            "version": self.app_version,
            "framework": version.FRAMEWORK,
            "status": STATUS_UP,
            "uptime_s": round(time.time() - self._started_at, 1),
        }
        details: Dict[str, Any] = {}
        statuses = []
        for name, source in (("sql", self.sql), ("kv", self.kv),
                             ("pubsub", self.pubsub), ("tpu", self.tpu),
                             ("docstore", self.docstore)):
            if source is None:
                continue
            try:
                h = source.health_check()
            except Exception as exc:  # noqa: BLE001 - a broken probe is DOWN
                h = Health(status=STATUS_DOWN, details={"error": str(exc)})
            details[name] = h.to_dict() if isinstance(h, Health) else h
            statuses.append(h.status if isinstance(h, Health) else h.get("status", STATUS_DOWN))
        for name, svc in self.services.items():
            try:
                h = svc.health_check()
            except Exception as exc:  # noqa: BLE001
                h = Health(status=STATUS_DOWN, details={"error": str(exc)})
            details.setdefault("services", {})[name] = h.to_dict()
            statuses.append(h.status)
        for name, fn in self._health_contributors.items():
            try:
                h = fn()
            except Exception as exc:  # noqa: BLE001 - a broken probe is DOWN
                h = Health(status=STATUS_DOWN, details={"error": str(exc)})
            details[name] = h.to_dict() if isinstance(h, Health) else h
            statuses.append(h.status if isinstance(h, Health)
                            else h.get("status", STATUS_DOWN))
        # de-flap (ADVICE r5): DOWN degrades the aggregate immediately, but
        # a DEGRADED contributor must persist across >= 2 consecutive
        # checks — a single slow device probe (first-probe compile, a 3s
        # timeout under momentary load) must not make a load balancer pull
        # a healthy node off rotation
        if any(s == STATUS_DOWN for s in statuses):
            self._degraded_streak = 0
            out["status"] = STATUS_DEGRADED
        elif any(s == STATUS_DEGRADED for s in statuses):
            self._degraded_streak += 1
            if self._degraded_streak >= 2:
                out["status"] = STATUS_DEGRADED
            else:
                out["degrading"] = True  # visible, but not yet actionable
        else:
            self._degraded_streak = 0
        out["details"] = details
        return out

    def close(self) -> None:
        # drain the async trace exporter FIRST: spans ended during the
        # datasource teardown below are lost either way, but everything
        # already queued must reach the collector
        tracer = self.tracer
        if tracer is not None and hasattr(tracer.exporter, "close"):
            try:
                tracer.exporter.close()
            except Exception:  # noqa: BLE001
                pass
        for source in (self.sql, self.kv, self.pubsub, self.tpu, self.docstore):
            if source is not None and hasattr(source, "close"):
                try:
                    source.close()
                except Exception:  # noqa: BLE001
                    pass


def new_mock_container(config: Optional[Dict[str, str]] = None) -> Container:
    """Fully-faked container for handler unit tests.

    Parity: container/mock_container.go:19-55 — real Container shape, fake infra:
    in-memory SQL (sqlite :memory:), in-proc KV + broker, capturing logger.
    """
    cfg = MockConfig(dict(config or {}))
    c = Container(cfg, logger=MockLogger(level=Level.DEBUG))
    c.metrics_manager = MetricsManager(logger=c.logger)
    c.register_framework_metrics()
    c.tracer = Tracer(service_name="test")

    from ..datasource.kvstore import KVStore
    from ..datasource.sql import SQL
    from ..pubsub.inproc import InProcBroker

    c.sql = SQL(MockConfig({"DB_PATH": ":memory:"}), c.logger, c.metrics_manager)
    c.kv = KVStore(cfg, c.logger, c.metrics_manager)
    c.pubsub = InProcBroker(cfg, c.logger, c.metrics_manager)
    return c
