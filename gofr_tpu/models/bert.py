"""BERT-family encoder: bidirectional transformer for embeddings/classification.

North-star config 3 in BASELINE.md: a BERT-base `/embed` endpoint behind the
dynamic batcher. Built TPU-first like the Llama decoder (models/llama.py):
stacked [n_layers, ...] weights consumed by lax.scan (one-layer trace, fast
XLA compiles), bfloat16 matmuls for the MXU with float32 LayerNorm/softmax
accumulation, and an explicit padding mask so the batcher's sequence-bucket
padding is numerically invisible (padded rows attend nothing, pooling masks
them out) — no data-dependent shapes anywhere.

Reference parity: the reference framework (pure-Go microservice toolkit) has
no models at all (SURVEY.md §2); this file is new TPU-native capability that
the BASELINE.md target ladder requires.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_seq_len: int = 512
    n_segments: int = 2
    layer_norm_eps: float = 1e-12
    pad_id: int = 0
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def debug(cls) -> "BertConfig":
        """CI-sized model: compiles in seconds on CPU."""
        return cls(vocab_size=512, dim=64, n_layers=2, n_heads=4, ffn_dim=128,
                   max_seq_len=128, dtype="float32")

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def large(cls) -> "BertConfig":
        return cls(dim=1024, n_layers=24, n_heads=16, ffn_dim=4096)

    def param_count(self) -> int:
        embed = (self.vocab_size + self.max_seq_len + self.n_segments) * self.dim
        per_layer = (4 * self.dim * self.dim          # wq wk wv wo
                     + 2 * self.dim * self.ffn_dim    # ffn in/out
                     + 4 * self.dim                   # 2 LayerNorms (scale+bias)
                     + 4 * self.dim + self.ffn_dim + self.dim)  # biases
        pooler = self.dim * self.dim + self.dim
        return embed + 2 * self.dim + self.n_layers * per_layer + pooler


def _np_dtype(name: str):
    import jax.numpy as jnp

    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
            "float16": jnp.float16}[name]


def bert_init(cfg: BertConfig, seed: int = 0) -> Dict[str, Any]:
    """Random-init params pytree with stacked [L, ...] layer weights."""
    import jax
    import jax.numpy as jnp

    dtype = _np_dtype(cfg.dtype)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 10)
    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dtype)

    return {
        "tok_emb": init(keys[0], (cfg.vocab_size, D), D),
        "pos_emb": init(keys[1], (cfg.max_seq_len, D), D),
        "seg_emb": init(keys[2], (cfg.n_segments, D), D),
        "emb_norm_scale": jnp.ones((D,), dtype=dtype),
        "emb_norm_bias": jnp.zeros((D,), dtype=dtype),
        "layers": {
            "wq": init(keys[3], (L, D, D), D),
            "bq": jnp.zeros((L, D), dtype=dtype),
            "wk": init(keys[4], (L, D, D), D),
            "bk": jnp.zeros((L, D), dtype=dtype),
            "wv": init(keys[5], (L, D, D), D),
            "bv": jnp.zeros((L, D), dtype=dtype),
            "wo": init(keys[6], (L, D, D), D),
            "bo": jnp.zeros((L, D), dtype=dtype),
            "attn_norm_scale": jnp.ones((L, D), dtype=dtype),
            "attn_norm_bias": jnp.zeros((L, D), dtype=dtype),
            "w_in": init(keys[7], (L, D, F), D),
            "b_in": jnp.zeros((L, F), dtype=dtype),
            "w_out": init(keys[8], (L, F, D), F),
            "b_out": jnp.zeros((L, D), dtype=dtype),
            "ffn_norm_scale": jnp.ones((L, D), dtype=dtype),
            "ffn_norm_bias": jnp.zeros((L, D), dtype=dtype),
        },
        "pooler_w": init(keys[9], (D, D), D),
        "pooler_b": jnp.zeros((D,), dtype=dtype),
    }


import jax  # noqa: E402  (after dataclass defs so module import stays light)
import jax.numpy as jnp  # noqa: E402


def layer_norm(x, scale, bias, eps: float):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    normed = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (normed * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _encoder_layer(x, layer, attn_bias, cfg: BertConfig):
    """Post-LN encoder layer. x: [B, T, D]; attn_bias: [B, 1, 1, T] f32."""
    B, T, D = x.shape
    H, dh = cfg.n_heads, cfg.head_dim

    q = (x @ layer["wq"] + layer["bq"]).reshape(B, T, H, dh)
    k = (x @ layer["wk"] + layer["bk"]).reshape(B, T, H, dh)
    v = (x @ layer["wv"] + layer["bv"]).reshape(B, T, H, dh)
    scores = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(dh)
    probs = jax.nn.softmax(scores + attn_bias, axis=-1)
    attn = jnp.einsum("bhts,bshd->bthd", probs,
                      v.astype(jnp.float32)).astype(x.dtype)
    attn = attn.reshape(B, T, D) @ layer["wo"] + layer["bo"]
    x = layer_norm(x + attn, layer["attn_norm_scale"], layer["attn_norm_bias"],
                   cfg.layer_norm_eps)

    h = jax.nn.gelu(x @ layer["w_in"] + layer["b_in"], approximate=True)
    h = h @ layer["w_out"] + layer["b_out"]
    return layer_norm(x + h, layer["ffn_norm_scale"], layer["ffn_norm_bias"],
                      cfg.layer_norm_eps)


def bert_encode(params, cfg: BertConfig, tokens, segments=None):
    """Full encoder stack. tokens: [B, T] int32 (pad_id marks padding).

    Returns hidden states [B, T, D] in cfg.dtype. Padded positions carry
    garbage activations but are masked out of attention reads and pooling.
    """
    B, T = tokens.shape
    mask = tokens != cfg.pad_id                                  # [B, T]
    attn_bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)[:, None, None, :]

    positions = jnp.arange(T, dtype=jnp.int32)
    seg = segments if segments is not None else jnp.zeros_like(tokens)
    x = (params["tok_emb"][tokens]
         + params["pos_emb"][positions][None, :, :]
         + params["seg_emb"][seg])
    x = layer_norm(x, params["emb_norm_scale"], params["emb_norm_bias"],
                   cfg.layer_norm_eps)

    def body(x, layer):
        return _encoder_layer(x, layer, attn_bias, cfg), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


def bert_embed(params, cfg: BertConfig, tokens):
    """Masked mean-pooled sentence embedding, L2-normalised.

    The /embed endpoint's model_fn: [B, T] int32 -> [B, D] float32. Pooling
    weights only non-pad positions, so a sequence padded to a longer bucket by
    the dynamic batcher embeds identically to the unpadded one.
    """
    hidden = bert_encode(params, cfg, tokens).astype(jnp.float32)  # [B, T, D]
    mask = (tokens != cfg.pad_id).astype(jnp.float32)[:, :, None]  # [B, T, 1]
    summed = jnp.sum(hidden * mask, axis=1)
    counts = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    pooled = summed / counts
    return pooled / jnp.maximum(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)


def bert_pool_cls(params, cfg: BertConfig, tokens):
    """Classic BERT pooler: tanh(W @ h[CLS]). [B, T] -> [B, D]."""
    hidden = bert_encode(params, cfg, tokens)
    cls = hidden[:, 0, :]
    return jnp.tanh((cls @ params["pooler_w"] + params["pooler_b"])
                    .astype(jnp.float32))
