"""Model zoo: pure-functional JAX models (params pytrees + apply fns).

TPU-first choices: stacked per-layer weights consumed by lax.scan (one trace
for all layers, fast compiles, pipeline-shardable), bfloat16 params with
float32 softmax/norm accumulation, static shapes everywhere.
"""

from .bert import BertConfig, bert_embed, bert_encode, bert_init, bert_pool_cls
from .llama import LlamaConfig, llama_decode_step, llama_forward, llama_init, llama_prefill
from .mlp import MLPConfig, mlp_forward, mlp_init

__all__ = [
    "BertConfig", "bert_embed", "bert_encode", "bert_init", "bert_pool_cls",
    "LlamaConfig", "llama_decode_step", "llama_forward", "llama_init",
    "llama_prefill", "MLPConfig", "mlp_forward", "mlp_init",
]
