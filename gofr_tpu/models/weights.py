"""Real-weights ingestion: safetensors -> llama params tree, streaming.

The serving stack (engine, paged engine, OpenAI surface) consumes the
params pytree produced by `models.llama.llama_init`; until this module the
only producers were random initializers, so "serve Llama-3-8B" was a claim
about an 8B-SHAPED model, never the model itself. This closes that gap with
a zero-dependency path from weights-on-disk to a bootable engine:

  read_safetensors / SafetensorsFile   pure-numpy reader for the standard
      safetensors container (8-byte LE header length + JSON header + raw
      little-endian tensor bytes). bf16 decodes through ml_dtypes (a jax
      dependency, always present). Multi-shard checkpoints resolve through
      the standard `*.safetensors.index.json` weight_map.
  write_safetensors                    the mirror writer — tests synthesize
      HF-layout checkpoints with it, and it gives deployments a way to
      persist converted/quantized trees.
  load_llama_safetensors               HF-layout names -> llama tree, ONE
      LEAF AT A TIME: each target leaf is assembled in host RAM, pushed to
      device, and (optionally) quantized to int8 on device before the next
      leaf is touched — the float tree never fully materializes on device,
      the same peak-HBM discipline as llama_init_quantized
      (models/llama.py:304-354). An 8B checkpoint loads into ~8.5 GiB of
      int8 leaves with one ~1 GiB float temp in flight.

Parity target: the reference boots services from versioned on-disk
artifacts rather than in-process state (migration watermark discipline,
/root/reference/pkg/gofr/migration/migration.go:18-79); here the artifact
is the model checkpoint and the version is the safetensors header itself
(shape+dtype validated leaf-by-leaf against the LlamaConfig before boot).

HF tensor layout (torch Linear stores [out, in]; our matmuls are x @ W with
W [in, out], so every projection transposes on load):

    model.embed_tokens.weight            [V, D]   -> tok_emb          [V, D]
    model.layers.{l}.self_attn.q_proj    [H*dh, D]-> layers.wq[l]     [D, H*dh]
    ...k_proj/v_proj                     [Hkv*dh,D]-> wk/wv[l]        [D, Hkv*dh]
    ...self_attn.o_proj                  [D, H*dh]-> wo[l]            [H*dh, D]
    ...mlp.gate_proj/up_proj             [F, D]   -> w_gate/w_up[l]   [D, F]
    ...mlp.down_proj                     [D, F]   -> w_down[l]        [F, D]
    ...input_layernorm.weight            [D]      -> layers.attn_norm[l]
    ...post_attention_layernorm.weight   [D]      -> layers.ffn_norm[l]
    model.norm.weight                    [D]      -> final_norm       [D]
    lm_head.weight                       [V, D]   -> lm_head          [D, V]
        (absent when embeddings are tied: lm_head = tok_emb.T)

HF Llama checkpoints use the rotate-half RoPE convention (q/k projections
pre-permuted by the HF conversion), which is exactly what models.llama.rope
computes — weights load with no head permutation.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

# safetensors dtype tag -> numpy dtype. BF16 has no numpy builtin; ml_dtypes
# (shipped with jax) provides a bit-exact one.
_DTYPES: Dict[str, Any] = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _np_dtype(tag: str):
    if tag == "BF16":
        return _bf16()
    try:
        return np.dtype(_DTYPES[tag])
    except KeyError:
        raise ValueError(f"unsupported safetensors dtype {tag!r}") from None


def _dtype_tag(dt: np.dtype) -> str:
    if dt == _bf16():
        return "BF16"
    for tag, npdt in _DTYPES.items():
        if np.dtype(npdt) == dt:
            return tag
    raise ValueError(f"cannot serialize dtype {dt} to safetensors")


class SafetensorsFile:
    """Lazy reader over one .safetensors container.

    Parses the header once; `tensor(name)` reads exactly that tensor's byte
    range (seek + frombuffer), so loading a 16 GiB checkpoint leaf-by-leaf
    never holds more than one tensor in memory beyond the OS page cache.
    """

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as fp:
            (header_len,) = struct.unpack("<Q", fp.read(8))
            if header_len > 100 * 1024 * 1024:
                raise ValueError(f"{path}: implausible header size {header_len}")
            header = json.loads(fp.read(header_len).decode("utf-8"))
        self.metadata: Dict[str, str] = header.pop("__metadata__", {})
        self._entries: Dict[str, Tuple[str, Tuple[int, ...], int, int]] = {}
        data_start = 8 + header_len
        for name, ent in header.items():
            begin, end = ent["data_offsets"]
            self._entries[name] = (ent["dtype"], tuple(ent["shape"]),
                                   data_start + begin, data_start + end)

    def keys(self) -> Iterable[str]:
        return self._entries.keys()

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def info(self, name: str) -> Tuple[str, Tuple[int, ...]]:
        dtype, shape, _, _ = self._entries[name]
        return dtype, shape

    def tensor(self, name: str) -> np.ndarray:
        dtype, shape, begin, end = self._entries[name]
        npdt = _np_dtype(dtype)
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = count * npdt.itemsize
        if nbytes != end - begin:
            raise ValueError(
                f"{self.path}:{name}: byte range {end - begin} != "
                f"shape/dtype size {nbytes}")
        with open(self.path, "rb") as fp:
            fp.seek(begin)
            buf = fp.read(nbytes)
        arr = np.frombuffer(buf, dtype=npdt, count=count).reshape(shape)
        return arr


class CheckpointReader:
    """Uniform view over a single file OR a sharded HF checkpoint directory.

    Accepts: a .safetensors file, a .safetensors.index.json file, or a
    directory containing either `model.safetensors` or
    `model.safetensors.index.json` (the HF hub layout).
    """

    def __init__(self, path: str):
        index_path = None
        if os.path.isdir(path):
            single = os.path.join(path, "model.safetensors")
            index = os.path.join(path, "model.safetensors.index.json")
            if os.path.exists(index):
                index_path = index
            elif os.path.exists(single):
                path = single
            else:
                sts = sorted(f for f in os.listdir(path)
                             if f.endswith(".safetensors"))
                if len(sts) == 1:
                    path = os.path.join(path, sts[0])
                else:
                    raise FileNotFoundError(
                        f"{path}: no model.safetensors[.index.json] "
                        f"({len(sts)} .safetensors files)")
        elif path.endswith(".index.json"):
            index_path = path

        self._files: Dict[str, SafetensorsFile] = {}
        self._where: Dict[str, str] = {}
        if index_path:
            base = os.path.dirname(index_path)
            with open(index_path, "r", encoding="utf-8") as fp:
                weight_map = json.load(fp)["weight_map"]
            for name, fname in weight_map.items():
                self._where[name] = os.path.join(base, fname)
        else:
            f = SafetensorsFile(path)
            self._files[path] = f
            for name in f.keys():
                self._where[name] = path

    def keys(self) -> Iterable[str]:
        return self._where.keys()

    def __contains__(self, name: str) -> bool:
        return name in self._where

    def _file(self, name: str) -> SafetensorsFile:
        path = self._where[name]
        if path not in self._files:
            self._files[path] = SafetensorsFile(path)
        return self._files[path]

    def info(self, name: str) -> Tuple[str, Tuple[int, ...]]:
        return self._file(name).info(name)

    def tensor(self, name: str) -> np.ndarray:
        return self._file(name).tensor(name)


def write_safetensors(path: str, tensors: Dict[str, np.ndarray],
                      metadata: Optional[Dict[str, str]] = None) -> None:
    """Serialize {name: numpy array} to one safetensors container.

    Arrays are written little-endian C-contiguous in sorted-name order
    (deterministic bytes for a given tree — artifact diffing stays honest).
    """
    header: Dict[str, Any] = {}
    if metadata:
        header["__metadata__"] = dict(metadata)
    blobs: List[bytes] = []
    offset = 0
    for name in sorted(tensors):
        # ascontiguousarray promotes 0-d to 1-d; reshape restores the
        # original shape (contiguity is preserved)
        arr = np.ascontiguousarray(tensors[name]).reshape(
            np.shape(tensors[name]))
        tag = _dtype_tag(arr.dtype)
        blob = arr.tobytes()
        header[name] = {"dtype": tag, "shape": list(arr.shape),
                        "data_offsets": [offset, offset + len(blob)]}
        blobs.append(blob)
        offset += len(blob)
    hbytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    tmp = path + ".tmp"
    with open(tmp, "wb") as fp:
        fp.write(struct.pack("<Q", len(hbytes)))
        fp.write(hbytes)
        for blob in blobs:
            fp.write(blob)
    os.replace(tmp, path)  # atomic publish, checkpoint.py's discipline


# ---------------------------------------------------------------------------
# HF-layout llama loading
# ---------------------------------------------------------------------------

# gofr stacked-leaf name -> (HF per-layer name, transpose?)
_LAYER_MAP = {
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "w_gate": ("mlp.gate_proj.weight", True),
    "w_up": ("mlp.up_proj.weight", True),
    "w_down": ("mlp.down_proj.weight", True),
    "attn_norm": ("input_layernorm.weight", False),
    "ffn_norm": ("post_attention_layernorm.weight", False),
}


def _expected_shapes(cfg) -> Dict[str, Tuple[int, ...]]:
    L, D, H, Hkv, dh, F, V = (cfg.n_layers, cfg.dim, cfg.n_heads,
                              cfg.n_kv_heads, cfg.head_dim, cfg.ffn_dim,
                              cfg.vocab_size)
    return {
        "tok_emb": (V, D),
        "wq": (L, D, H * dh), "wk": (L, D, Hkv * dh), "wv": (L, D, Hkv * dh),
        "wo": (L, H * dh, D),
        "w_gate": (L, D, F), "w_up": (L, D, F), "w_down": (L, F, D),
        "attn_norm": (L, D), "ffn_norm": (L, D),
        "final_norm": (D,),
        "lm_head": (D, V),
    }


def _stack_layers(reader: CheckpointReader, cfg, leaf: str,
                  np_target) -> np.ndarray:
    hf_suffix, transpose = _LAYER_MAP[leaf]
    slices = []
    for l in range(cfg.n_layers):
        name = f"model.layers.{l}.{hf_suffix}"
        if name not in reader:
            raise KeyError(f"checkpoint missing tensor {name!r}")
        t = reader.tensor(name)
        slices.append(np.ascontiguousarray(t.T) if transpose else t)
    return np.stack(slices).astype(np_target, copy=False)


def load_llama_safetensors(cfg, path: str,
                           weight_dtype: Optional[str] = None,
                           logger=None) -> Dict[str, Any]:
    """Load an HF-layout Llama checkpoint into the serving params tree.

    cfg: LlamaConfig (shapes are VALIDATED against the checkpoint header
    before any bytes are read — a preset/checkpoint mismatch fails fast
    with the offending tensor named). weight_dtype: None keeps cfg.dtype
    storage; "int8" quantizes each leaf on device as it loads
    (per-output-channel scales, models.llama._quantize_leaf) so peak device
    memory is the int8 tree plus ONE float leaf.

    Returns the same pytree structure as llama_init / quantize_weights —
    every downstream consumer (engines, TP sharding via
    parallel.sharding.serving_param_specs, checkpoint.py) works unchanged.
    """
    import jax

    from .llama import _QUANT_AXES, _np_dtype as jax_dtype, _quantize_leaf

    reader = CheckpointReader(path)
    # jnp scalar types are numpy/ml_dtypes types — np.dtype() accepts both
    np_target = np.dtype(jax_dtype(cfg.dtype))
    tied = "lm_head.weight" not in reader

    # ---- preflight: every tensor present with the right shape ------------
    exp = _expected_shapes(cfg)
    problems: List[str] = []

    def check(hf_name: str, want: Tuple[int, ...]):
        if hf_name not in reader:
            problems.append(f"missing {hf_name}")
            return
        _, shape = reader.info(hf_name)
        if tuple(shape) != tuple(want):
            problems.append(f"{hf_name}: shape {shape} != expected {want}")

    check("model.embed_tokens.weight", exp["tok_emb"])
    check("model.norm.weight", exp["final_norm"])
    if not tied:
        check("lm_head.weight", (cfg.vocab_size, cfg.dim))
    for leaf, (suffix, transpose) in _LAYER_MAP.items():
        want = exp[leaf][1:]
        per_layer = tuple(reversed(want)) if transpose else want
        for l in range(cfg.n_layers):
            check(f"model.layers.{l}.{suffix}", per_layer)
    if problems:
        head = "; ".join(problems[:5])
        more = f" (+{len(problems) - 5} more)" if len(problems) > 5 else ""
        raise ValueError(f"checkpoint {path!r} does not match config: "
                         f"{head}{more}")

    if weight_dtype not in (None, "int8"):
        raise ValueError(f"weight_dtype must be int8 or None, "
                         f"got {weight_dtype!r}")
    quantize = weight_dtype == "int8"
    q = jax.jit(_quantize_leaf, static_argnums=1) if quantize else None

    def log(msg, *args):
        if logger is not None:
            logger.debugf(msg, *args)

    def place(leaf_name: str, host: np.ndarray, quant_axis: Optional[int]):
        """Host array -> device leaf (optionally int8+scale), float temp
        freed before return (block_until_ready, llama_init_quantized's
        one-float-leaf-in-flight discipline)."""
        dev = jax.device_put(host)
        if quantize and quant_axis is not None:
            w8, s = q(dev, quant_axis)
            jax.block_until_ready(w8)
            del dev
            log("loaded %s int8 %s", leaf_name, w8.shape)
            return w8, s
        jax.block_until_ready(dev)
        log("loaded %s %s %s", leaf_name, dev.dtype, dev.shape)
        return dev, None

    params: Dict[str, Any] = {}
    layers: Dict[str, Any] = {}

    emb_host = reader.tensor("model.embed_tokens.weight").astype(
        np_target, copy=False)
    emb, emb_s = place("tok_emb", emb_host, -1 if quantize else None)
    params["tok_emb"] = emb
    if emb_s is not None:
        params["tok_emb_s"] = emb_s
    if not tied:
        # only the tied branch reuses the host embedding for lm_head; drop
        # it now so peak host RAM stays one large array during layer loads
        del emb_host

    for leaf in _LAYER_MAP:
        host = _stack_layers(reader, cfg, leaf, np_target)
        axis = _QUANT_AXES.get(leaf)
        dev, s = place(f"layers.{leaf}", host, axis)
        del host
        layers[leaf] = dev
        if s is not None:
            layers[leaf + "_s"] = s
    params["layers"] = layers

    params["final_norm"] = jax.device_put(
        reader.tensor("model.norm.weight").astype(np_target, copy=False))

    if tied:
        head_host = np.ascontiguousarray(emb_host.T)
        del emb_host
    else:
        head_host = np.ascontiguousarray(
            reader.tensor("lm_head.weight").astype(np_target, copy=False).T)
    head, head_s = place("lm_head", head_host, -2 if quantize else None)
    params["lm_head"] = head
    if head_s is not None:
        params["lm_head_s"] = head_s
    return params


def export_llama_safetensors(params, path: str,
                             metadata: Optional[Dict[str, str]] = None) -> None:
    """Write a (float) llama params tree back out in HF layout.

    The inverse of load_llama_safetensors for float trees — tests round-trip
    through it, and it converts random-init trees into on-disk fixtures.
    Rejects int8 trees: HF layout has no scale-tensor convention, and an
    int8 tree should persist via checkpoint.py's native pytree format.
    """
    if "lm_head_s" in params:
        raise ValueError("export_llama_safetensors handles float trees only; "
                         "persist quantized trees with gofr_tpu.checkpoint")
    tensors: Dict[str, np.ndarray] = {}

    def host(x) -> np.ndarray:
        arr = np.asarray(x)
        return arr

    tensors["model.embed_tokens.weight"] = host(params["tok_emb"])
    tensors["model.norm.weight"] = host(params["final_norm"])
    tensors["lm_head.weight"] = np.ascontiguousarray(host(params["lm_head"]).T)
    layers = params["layers"]
    n_layers = layers["wq"].shape[0]
    for leaf, (suffix, transpose) in _LAYER_MAP.items():
        stacked = host(layers[leaf])
        for l in range(n_layers):
            t = stacked[l]
            tensors[f"model.layers.{l}.{suffix}"] = (
                np.ascontiguousarray(t.T) if transpose else t)
    write_safetensors(path, tensors, metadata)
