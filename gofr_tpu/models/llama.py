"""Llama-family decoder: GQA + RoPE + RMSNorm + SwiGLU, cache-aware forward.

Built TPU-first rather than ported: weights are stacked [n_layers, ...] and
consumed by lax.scan (single-layer trace -> fast XLA compiles, natural
pipeline sharding axis); matmuls stay bfloat16 for the MXU with float32
softmax/norm accumulation; the KV cache is an explicit argument so serving
code can donate it for in-place HBM updates (no torch-style module state).

The unified `llama_forward` serves both phases of LLM serving:
  - prefill: T>1 tokens written at positions [0..T), causal within the window
  - decode:  T=1 token written at its absolute position, attending the cache
Masking needs only `j <= q_pos` because cache slots are written contiguously
from 0 — slot index IS absolute position.

Config presets cover the BASELINE.md north-star ladder (debug CI model,
1B bench model, Llama-3-8B, Llama-3-70B).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"
    # "xla" | "flash" — selects the attention impl for the no-cache forward
    # (training/eval) AND the serving prefill (full-window T == S case in
    # _attention_block)
    attn_impl: str = "xla"
    # "xla" | "kernel" — the cached T=1 decode read. "xla" is the masked
    # einsum over the whole allocated cache; "kernel" is the Pallas
    # streaming read (ops/decode_attention) whose per-step HBM traffic is
    # bounded by each row's LIVE length, not the allocated S (the einsum
    # also reads the S-minor storage well below DMA peak — see the kernel
    # module docstring for the measured gap)
    decode_attn: str = "xla"
    # None (= cfg.dtype) | "int8" — the serving KV cache's storage dtype.
    # int8 halves cache HBM bytes (the decode bandwidth bound) and doubles
    # context capacity per GiB; values quantize on write with per-token
    # per-head scales and dequantize inside the decode kernels' dots.
    # Dense engine: requires decode_attn == "kernel". Paged engine: the
    # paged kernel dequant-folds natively (pool + page capacity both halve)
    kv_dtype: Optional[str] = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    @classmethod
    def debug(cls) -> "LlamaConfig":
        """CI-sized model: compiles in seconds on CPU."""
        return cls(vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                   ffn_dim=128, max_seq_len=256, dtype="float32")

    @classmethod
    def llama1b(cls) -> "LlamaConfig":
        """Llama-3.2-1B shape: the single-v5e-chip bench model."""
        return cls(vocab_size=128256, dim=2048, n_layers=16, n_heads=32,
                   n_kv_heads=8, ffn_dim=8192, max_seq_len=8192)

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, ffn_dim=14336, max_seq_len=8192)

    @classmethod
    def llama3_70b(cls) -> "LlamaConfig":
        return cls(vocab_size=128256, dim=8192, n_layers=80, n_heads=64,
                   n_kv_heads=8, ffn_dim=28672, max_seq_len=8192)

    def param_count(self) -> int:
        embed = self.vocab_size * self.dim
        per_layer = (self.dim * self.n_heads * self.head_dim          # wq
                     + 2 * self.dim * self.n_kv_heads * self.head_dim  # wk, wv
                     + self.n_heads * self.head_dim * self.dim         # wo
                     + 3 * self.dim * self.ffn_dim                     # gate/up/down
                     + 2 * self.dim)                                   # norms
        return 2 * embed + self.n_layers * per_layer + self.dim


def _np_dtype(name: str):
    import jax.numpy as jnp

    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
            "float16": jnp.float16, "int8": jnp.int8}[name]


def llama_init(cfg: LlamaConfig, seed: int = 0) -> Dict[str, Any]:
    """Random-init params pytree with stacked [L, ...] layer weights."""
    import jax
    import jax.numpy as jnp

    dtype = _np_dtype(cfg.dtype)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 8)
    L, D, H, Hkv, dh, F, V = (cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads,
                              cfg.head_dim, cfg.ffn_dim, cfg.vocab_size)

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dtype)

    return {
        "tok_emb": init(keys[0], (V, D), D),
        "layers": {
            "wq": init(keys[1], (L, D, H * dh), D),
            "wk": init(keys[2], (L, D, Hkv * dh), D),
            "wv": init(keys[3], (L, D, Hkv * dh), D),
            "wo": init(keys[4], (L, H * dh, D), H * dh),
            "w_gate": init(keys[5], (L, D, F), D),
            "w_up": init(keys[6], (L, D, F), D),
            "w_down": init(keys[7], (L, F, D), F),
            "attn_norm": jnp.ones((L, D), dtype=dtype),
            "ffn_norm": jnp.ones((L, D), dtype=dtype),
        },
        "final_norm": jnp.ones((D,), dtype=dtype),
        "lm_head": init(keys[0], (D, V), D),
    }


def init_kv_cache(cfg: LlamaConfig, batch: int, seq_len: Optional[int] = None,
                  dtype: Optional[str] = None) -> Tuple[Any, Any]:
    """Zeroed (k, v) caches shaped [L, B, Hkv, dh, S].

    S is the MINOR axis on purpose: TPU tiles the two minor dims to
    (8 sublanes, 128 lanes), so a [.., Hkv, dh=64]-minor cache pads dh
    64->128 and physically DOUBLES every cache buffer in HBM (measured in
    the round-2 OOM dump: 4.00G padded vs 2.00G unpadded per buffer).
    With [.., dh, S] minor, S is always a multiple of 128 in serving
    (power-of-two buckets >= 128; smaller allocations are tiny) and dh=64
    divides the 8-sublane tile — zero padding waste, and the decode
    einsums contract/broadcast directly on this layout.
    """
    import jax.numpy as jnp

    S = seq_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, cfg.head_dim, S)
    dt = _np_dtype(dtype or cfg.dtype)
    return jnp.zeros(shape, dtype=dt), jnp.zeros(shape, dtype=dt)


def rms_norm(x, weight, eps: float):
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def rope(x, positions, theta: float):
    """Rotate-half RoPE. x: [B, T, H, dh]; positions: [B, T] int32."""
    import jax.numpy as jnp

    dh = x.shape[-1]
    half = dh // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, T, 1, half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


import jax  # noqa: E402  (after dataclass defs so module import stays light)
import jax.numpy as jnp  # noqa: E402


# ---------------------------------------------------------------------------
# INT8 weight quantization (weight-only storage, W8A8-dynamic compute)
#
# The north-star model (Llama-3-8B, BASELINE.md config 4) is ~15 GiB in bf16
# — it does not fit one 16 GiB v5e chip at all. Per-output-channel int8
# weights halve that to ~8 GiB AND halve the per-step weight HBM read, which
# is the other half of the decode bandwidth bound next to the KV cache.
#
# Design (TPU-first, not a dequant-copy):
#   - storage: W -> int8 with per-output-channel scales s = absmax/127.
#     A "dequantize then matmul" lowering would materialize a bf16 copy of
#     the weight as a fusion output every step — MORE HBM traffic than bf16
#     weights. Instead activations quantize dynamically per row (absmax
#     over the contraction dim) and the dot runs int8 x int8 -> int32 on
#     the MXU natively (2x bf16 peak on v5e), reading the int8 weights
#     straight from HBM. Output rescales by (row_scale ⊗ channel_scale).
#   - mode selection: the weights' dtype IS the switch. Every matmul site
#     goes through _mm/_embed/_head, which branch on `w.dtype == int8` at
#     trace time — no config plumbing, and a bf16 tree serves identically
#     to before.
#   - norms stay float (tiny); embedding gathers int8 rows and rescales
#     per token (a [B, T, D] elementwise — negligible).
# ---------------------------------------------------------------------------


def _q_matmul(x, w8, s, out_dtype=None):
    """Weight-only int8 matmul with dynamic per-row activation quantization.

    x: [..., Din] float; w8: [Din, Dout] int8; s: [Dout] f32 per-output-
    channel weight scales. Returns [..., Dout] in out_dtype (default
    x.dtype). Under tensor parallelism the row absmax over a tp-sharded
    contraction dim lowers to a tiny [rows, 1] collective max — XLA
    propagates the sharding; no manual collectives here.
    """
    xf = x.astype(jnp.float32)
    ax = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True), 1e-12)
    x8 = jnp.round(xf * (127.0 / ax)).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x8, w8, (((x8.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (ax / 127.0) * s
    return out.astype(out_dtype or x.dtype)


def _mm(x, tree, name):
    """x @ tree[name], through the int8 path when the weight is quantized."""
    w = tree[name]
    if w.dtype == jnp.int8:
        return _q_matmul(x, w, tree[name + "_s"])
    return x @ w


def _embed(params, cfg: LlamaConfig, tokens):
    """Token embedding gather; dequantizes per-row when tok_emb is int8."""
    e = params["tok_emb"][tokens]
    if e.dtype == jnp.int8:
        scale = params["tok_emb_s"][tokens]          # [...,] f32 per row
        return (e.astype(jnp.float32) * scale[..., None]).astype(
            _np_dtype(cfg.dtype))
    return e


def _head(x, params):
    """lm_head projection to float32 logits (int8-aware)."""
    w = params["lm_head"]
    if w.dtype == jnp.int8:
        return _q_matmul(x, w, params["lm_head_s"], out_dtype=jnp.float32)
    return (x @ w).astype(jnp.float32)


def _quantize_leaf(w, axis: int):
    """Symmetric per-channel int8: returns (w8, scale) with scale shaped as
    w minus `axis` (the contraction dim)."""
    wf = w.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(wf), axis=axis), 1e-12) / 127.0
    w8 = jnp.clip(jnp.round(wf / jnp.expand_dims(s, axis)), -127, 127
                  ).astype(jnp.int8)
    return w8, s


# weight name -> contraction axis reduced away by its scale. Layer weights
# are stacked [L, in, out]; tok_emb [V, D] scales per row (gather dim);
# lm_head [D, V] per output channel. Norm vectors stay float.
_QUANT_AXES = {"wq": -2, "wk": -2, "wv": -2, "wo": -2,
               "w_gate": -2, "w_up": -2, "w_down": -2}


def quantize_weights(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize a bf16/f32 params tree to int8 storage, leaf by leaf.

    CONSUMES the input tree: each float leaf is popped out of the nested
    dicts as its int8 twin is built, so (given the caller holds no other
    references to the leaves) peak HBM is the float tree plus ONE leaf's
    int8 copy — not two full trees. For models whose float tree already
    crowds the chip, use llama_init_quantized, which never materializes
    the float tree at all.
    """
    q = jax.jit(_quantize_leaf, static_argnums=1)

    out_layers = {}
    layers = params["layers"]
    for name in list(_QUANT_AXES):
        w8, s = q(layers.pop(name), _QUANT_AXES[name])
        jax.block_until_ready(w8)
        out_layers[name] = w8
        out_layers[name + "_s"] = s
    out_layers["attn_norm"] = layers["attn_norm"]
    out_layers["ffn_norm"] = layers["ffn_norm"]
    tok8, tok_s = q(params.pop("tok_emb"), -1)
    jax.block_until_ready(tok8)   # embed-sized float temps must not overlap
    head8, head_s = q(params.pop("lm_head"), -2)
    return {
        "tok_emb": tok8, "tok_emb_s": tok_s,
        "layers": out_layers,
        "final_norm": params["final_norm"],
        "lm_head": head8, "lm_head_s": head_s,
    }


def llama_init_quantized(cfg: LlamaConfig, seed: int = 0) -> Dict[str, Any]:
    """Random-init DIRECTLY to int8 storage, one leaf at a time.

    Generates each float leaf inside a jit whose only outputs are the int8
    weight and its scales, so the float tensor is a program temporary —
    peak HBM is the accumulated int8 tree plus one float leaf (~13 GiB for
    8B vs ~17 GiB for init-then-quantize, which OOMs a 16 GiB chip).
    Numerically identical to quantize_weights(llama_init(cfg, seed)).
    """
    dtype = _np_dtype(cfg.dtype)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 8)
    L, D, H, Hkv, dh, F, V = (cfg.n_layers, cfg.dim, cfg.n_heads,
                              cfg.n_kv_heads, cfg.head_dim, cfg.ffn_dim,
                              cfg.vocab_size)

    import functools

    @functools.partial(jax.jit, static_argnums=(1, 2, 3))
    def gen_q(k, shape, fan_in, axis):
        w = (jax.random.normal(k, shape, dtype=jnp.float32)
             * (1.0 / math.sqrt(fan_in))).astype(dtype)
        return _quantize_leaf(w, axis)

    # (key, shape, fan_in, scale axis) — mirrors llama_init's spec table
    spec = {
        "wq": (keys[1], (L, D, H * dh), D, -2),
        "wk": (keys[2], (L, D, Hkv * dh), D, -2),
        "wv": (keys[3], (L, D, Hkv * dh), D, -2),
        "wo": (keys[4], (L, H * dh, D), H * dh, -2),
        "w_gate": (keys[5], (L, D, F), D, -2),
        "w_up": (keys[6], (L, D, F), D, -2),
        "w_down": (keys[7], (L, F, D), F, -2),
    }
    layers: Dict[str, Any] = {}
    for name, (k, shape, fan, axis) in spec.items():
        w8, s = gen_q(k, shape, fan, axis)
        jax.block_until_ready(w8)    # keep at most one float temp live
        layers[name] = w8
        layers[name + "_s"] = s
    layers["attn_norm"] = jnp.ones((L, D), dtype=dtype)
    layers["ffn_norm"] = jnp.ones((L, D), dtype=dtype)
    tok8, tok_s = gen_q(keys[0], (V, D), D, -1)
    jax.block_until_ready(tok8)   # embed-sized float temps must not overlap
    head8, head_s = gen_q(keys[0], (D, V), D, -2)
    return {
        "tok_emb": tok8, "tok_emb_s": tok_s,
        "layers": layers,
        "final_norm": jnp.ones((D,), dtype=dtype),
        "lm_head": head8, "lm_head_s": head_s,
    }


def params_nbytes(params) -> int:
    """Actual HBM bytes of a params tree (int8-aware, unlike the analytic
    cfg-based estimate in tpu/capacity.params_bytes)."""
    return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(params)
               if hasattr(leaf, "nbytes"))


def _attention_block(x, layer, k_cache_l, v_cache_l, positions, cfg: LlamaConfig):
    """One attention sublayer with cache write + masked read.

    x: [B, T, D]; k/v_cache_l: [B, Hkv, dh, S] (S-minor, see init_kv_cache);
    positions: [B, T]. Returns (out [B, T, D], k_cache_l, v_cache_l).

    Per-step HBM traffic scales with the ALLOCATED seq dim S, so the engine
    allocates the cache at the bucket covering the live contexts and grows
    it on demand (engine._grow_cache) instead of sizing for max_seq_len.

    When T == S (a full-window prefill: positions are arange over the
    window, so the cache after the write IS this chunk's k/v) and
    cfg.attn_impl == "flash", attention runs through the Pallas flash
    kernel on the fresh k/v tensors — no [T, S] score materialization in
    HBM and no layout shuffling of the cache.
    """
    B, T, D = x.shape
    S = k_cache_l.shape[-1]
    H, Hkv, dh, G = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.q_per_kv

    normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
    q = _mm(normed, layer, "wq").reshape(B, T, H, dh)
    k = _mm(normed, layer, "wk").reshape(B, T, Hkv, dh)
    v = _mm(normed, layer, "wv").reshape(B, T, Hkv, dh)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)

    # scatter this chunk's k/v into the cache at its absolute positions
    # (advanced indexing on dims 0+3 puts the [B, T] index dims first, so
    # the value shape is [B, T, Hkv, dh] — k/v as produced, no transpose)
    batch_idx = jnp.arange(B)[:, None]
    k_cache_l = k_cache_l.at[batch_idx, :, :, positions].set(k)
    v_cache_l = v_cache_l.at[batch_idx, :, :, positions].set(v)

    if T == S and cfg.attn_impl == "flash":
        from ..ops.flash_attention import flash_attention

        attn = flash_attention(q, k, v, True)  # [B, T, H, dh]
        out = _mm(attn.reshape(B, T, H * dh), layer, "wo")
        return out, k_cache_l, v_cache_l

    if T == 1 and cfg.decode_attn == "kernel":
        from ..ops.decode_attention import decode_attention

        # the scatter above put this step's k/v at `positions`, so the live
        # window is [0, positions] inclusive — lengths = positions + 1
        attn = decode_attention(q[:, 0], k_cache_l, v_cache_l,
                                positions[:, 0] + 1)        # [B, H, dh]
        out = _mm(attn.reshape(B, 1, H * dh), layer, "wo")
        return out, k_cache_l, v_cache_l

    # GQA attention over the cache: q grouped [B, T, Hkv, G, dh].
    # Keep the matmul inputs in the cache dtype (bf16 on the MXU's fast
    # path) and accumulate f32 via preferred_element_type — upcasting the
    # INPUTS would force a full-f32 matmul at a fraction of MXU throughput.
    qg = q.reshape(B, T, Hkv, G, dh)
    scores = jnp.einsum("bthgd,bhds->bhgts", qg, k_cache_l,
                        preferred_element_type=jnp.float32) / math.sqrt(dh)
    # mask: query at absolute pos p sees cache slot j iff j <= p
    cache_pos = jnp.arange(S)[None, None, :]                  # [1, 1, S]
    visible = cache_pos <= positions[:, :, None]              # [B, T, S]
    scores = jnp.where(visible[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgts,bhds->bthgd", probs.astype(v_cache_l.dtype),
                     v_cache_l,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    out = _mm(out.reshape(B, T, H * dh), layer, "wo")
    return out, k_cache_l, v_cache_l


def _ffn_block(x, layer, cfg: LlamaConfig):
    normed = rms_norm(x, layer["ffn_norm"], cfg.rms_eps)
    gate = jax.nn.silu(_mm(normed, layer, "w_gate"))
    up = _mm(normed, layer, "w_up")
    return _mm(gate * up, layer, "w_down")


def llama_forward_hidden(params, cfg: LlamaConfig, tokens, positions, k_cache,
                         v_cache):
    """Cache-writing forward returning final-norm hidden states, NOT logits.

    tokens: [B, T] int32; positions: [B, T] absolute positions (row-wise
    monotonic); k/v_cache: [L, B, Hkv, dh, S] (S-minor).
    Returns (hidden [B, T, D], k_cache, v_cache).

    The lm_head projection is split out so callers that only need a few
    positions (serving prefill samples ONE token per row) can gather those
    hidden rows first and project [K, D] @ [D, V] instead of materializing
    [B, T, V] float32 logits — at Llama-3 vocab (128256) the full-logits
    buffer is GBs per fused admission and the dominant prefill FLOP waste.
    """
    x = _embed(params, cfg, tokens)

    def body(x, scan_in):
        layer, k_l, v_l = scan_in
        attn_out, k_l, v_l = _attention_block(x, layer, k_l, v_l, positions, cfg)
        x = x + attn_out
        x = x + _ffn_block(x, layer, cfg)
        return x, (k_l, v_l)

    x, (k_cache, v_cache) = jax.lax.scan(
        body, x, (params["layers"], k_cache, v_cache))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    return x, k_cache, v_cache


def llama_forward(params, cfg: LlamaConfig, tokens, positions, k_cache, v_cache):
    """Cache-writing forward over a token chunk.

    tokens: [B, T] int32; positions: [B, T] absolute positions (row-wise
    monotonic); k/v_cache: [L, B, Hkv, dh, S] (S-minor).
    Returns (logits [B, T, V] float32, k_cache, v_cache).
    """
    x, k_cache, v_cache = llama_forward_hidden(params, cfg, tokens, positions,
                                               k_cache, v_cache)
    logits = _head(x, params)
    return logits, k_cache, v_cache


def llama_prefill_last(params, cfg: LlamaConfig, tokens, positions, lengths,
                       k_cache, v_cache):
    """Prefill forward that projects ONLY each row's last prompt position.

    tokens: [B, T]; positions: [B, T]; lengths: [B] true prompt lengths.
    Returns (last_logits [B, V] float32, k_cache, v_cache).

    Gathering the [B, D] last-position hidden rows BEFORE the lm_head matmul
    keeps the vocab projection at [B, D] @ [D, V] — no [B, T, V] buffer, no
    T× wasted head FLOPs (VERDICT r2 missing #3).
    """
    hidden, k_cache, v_cache = llama_forward_hidden(
        params, cfg, tokens, positions, k_cache, v_cache)
    B = hidden.shape[0]
    last = hidden[jnp.arange(B), lengths - 1]  # [B, D]
    logits = _head(last, params)
    return logits, k_cache, v_cache


def llama_prefill(params, cfg: LlamaConfig, tokens, k_cache, v_cache):
    """Prefill from empty cache: positions are [0..T) for every row."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    return llama_forward(params, cfg, tokens, positions, k_cache, v_cache)


def llama_decode_step(params, cfg: LlamaConfig, tokens, positions, k_cache,
                      v_cache):
    """One decode step for every batch row.

    tokens: [B] current token per row; positions: [B] its absolute position.
    Returns (logits [B, V], k_cache, v_cache).
    """
    logits, k_cache, v_cache = llama_forward(
        params, cfg, tokens[:, None], positions[:, None], k_cache, v_cache)
    return logits[:, 0, :], k_cache, v_cache


def init_kv_cache_layers(cfg: LlamaConfig, batch: int,
                         seq_len: Optional[int] = None,
                         dtype: Optional[str] = None) -> Tuple[Tuple, Tuple]:
    """Per-LAYER zeroed (k, v) caches: tuples of L arrays [B, Hkv, dh, S].

    The serving engine's decode representation. A stacked [L, ...] cache
    must be sliced per layer inside the loop (lax.scan xs or
    dynamic_index+DUS), and on v5e that slicing throttled decode to
    ~36 GB/s effective — 167 ms/step at B=128, S=1024 — while separate
    per-layer buffers with an unrolled layer loop run the same math at
    35 ms/step (measured). Trace/compile time grows with n_layers; decode
    compiles once per cache size, so the trade is right for serving.
    """
    import jax.numpy as jnp

    S = seq_len or cfg.max_seq_len
    shape = (batch, cfg.n_kv_heads, cfg.head_dim, S)
    dt = _np_dtype(dtype or cfg.dtype)
    k = tuple(jnp.zeros(shape, dtype=dt) for _ in range(cfg.n_layers))
    v = tuple(jnp.zeros(shape, dtype=dt) for _ in range(cfg.n_layers))
    return k, v


def llama_decode_step_unrolled(params, cfg: LlamaConfig, tokens, positions,
                               k_layers, v_layers):
    """One decode step over PER-LAYER cache buffers (python-unrolled loop).

    tokens: [B]; positions: [B]; k/v_layers: tuples of L [B, Hkv, dh, S]
    arrays (init_kv_cache_layers). Returns (logits [B, V] f32, k_layers,
    v_layers). Same math as llama_decode_step; the representation exists
    purely so XLA never slices a stacked cache in the hot loop (see
    init_kv_cache_layers).
    """
    x = _embed(params, cfg, tokens)[:, None]               # [B, 1, D]
    pos_grid = positions[:, None]
    k_out, v_out = [], []
    for l in range(cfg.n_layers):
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        attn, k_l, v_l = _attention_block(x, layer, k_layers[l], v_layers[l],
                                          pos_grid, cfg)
        x = x + attn
        x = x + _ffn_block(x, layer, cfg)
        k_out.append(k_l)
        v_out.append(v_l)
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _head(x[:, 0], params)
    return logits, tuple(k_out), tuple(v_out)


def init_kv_scale_layers(cfg: LlamaConfig, batch: int,
                         seq_len: Optional[int] = None) -> Tuple[Tuple, Tuple]:
    """Per-layer (k_scale, v_scale) buffers for the int8 cache: tuples of
    L arrays [B, Hkv, S] float32 (dequant value = int8 * scale). ~6% of the
    int8 cache's bytes at dh=64."""
    import jax.numpy as jnp

    S = seq_len or cfg.max_seq_len
    shape = (batch, cfg.n_kv_heads, S)
    k = tuple(jnp.zeros(shape, dtype=jnp.float32) for _ in range(cfg.n_layers))
    v = tuple(jnp.zeros(shape, dtype=jnp.float32) for _ in range(cfg.n_layers))
    return k, v


def llama_decode_step_unrolled_q8(params, cfg: LlamaConfig, tokens, positions,
                                  k_layers, v_layers, ks_layers, vs_layers):
    """One decode step over INT8 per-layer caches with per-token scales.

    tokens/positions: [B]; k/v_layers: tuples of [B, Hkv, dh, S] int8;
    ks/vs_layers: tuples of [B, Hkv, S] float32 scales. Returns
    (logits [B, V] f32, k_layers, v_layers, ks_layers, vs_layers).

    The cache crosses HBM as int8 — half the bf16 bytes, so the
    bandwidth-bound decode step's cache term halves. The new token's K/V
    quantize on write (symmetric per-token-per-head, ops/decode_attention.
    quantize_kv); the read is the Pallas kernel with dequant FOLDED into
    its two dots (k's scale multiplies scores, v's folds into probs).
    Requires cfg.decode_attn == "kernel" — there is no efficient XLA-einsum
    dequant read (it would materialize the full cache in bf16).
    """
    from ..ops.decode_attention import decode_attention, quantize_kv

    B = tokens.shape[0]
    H, Hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = _embed(params, cfg, tokens)[:, None]               # [B, 1, D]
    pos_grid = positions[:, None]
    batch_idx = jnp.arange(B)
    k_out, v_out = list(k_layers), list(v_layers)
    ks_out, vs_out = list(ks_layers), list(vs_layers)
    for l in range(cfg.n_layers):
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q = _mm(normed, layer, "wq").reshape(B, 1, H, dh)
        k = _mm(normed, layer, "wk").reshape(B, 1, Hkv, dh)
        v = _mm(normed, layer, "wv").reshape(B, 1, Hkv, dh)
        q = rope(q, pos_grid, cfg.rope_theta)
        k = rope(k, pos_grid, cfg.rope_theta)
        k8, ks = quantize_kv(k[:, 0], axis=-1)             # [B,Hkv,dh], [B,Hkv]
        v8, vs = quantize_kv(v[:, 0], axis=-1)
        k_out[l] = k_out[l].at[batch_idx, :, :, positions].set(k8)
        v_out[l] = v_out[l].at[batch_idx, :, :, positions].set(v8)
        ks_out[l] = ks_out[l].at[batch_idx, :, positions].set(ks)
        vs_out[l] = vs_out[l].at[batch_idx, :, positions].set(vs)
        attn = decode_attention(q[:, 0], k_out[l], v_out[l], positions + 1,
                                ks_out[l], vs_out[l])      # [B, H, dh]
        x = x + _mm(attn.reshape(B, 1, H * dh), layer, "wo")
        x = x + _ffn_block(x, layer, cfg)
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _head(x[:, 0], params)
    return (logits, tuple(k_out), tuple(v_out), tuple(ks_out),
            tuple(vs_out))


def llama_decode_step_inplace(params, cfg: LlamaConfig, tokens, positions,
                              k_cache, v_cache):
    """One decode step with the caches updated IN PLACE per layer.

    Same math as llama_decode_step, different loop structure: a fori_loop
    over layers with dynamic_update_slice on the FULL [L, ...] caches,
    instead of lax.scan consuming cache slices as xs and re-stacking ys.
    The scan form makes XLA double-buffer the stacked cache outputs across
    the serving engine's block-decode loop — two cache-sized AllocateBuffer
    temps that OOM'd the round-2/3 benches at S=1024 (B=128, Llama-1B) —
    while DUS-on-carry aliases cleanly. Measured on v5e at S=512/B=128:
    47 ms/step vs 60 ms/step and 4.3 GiB vs 12.3 GiB program temps.

    tokens: [B]; positions: [B]. Returns (logits [B, V] f32, k, v).
    """
    B = tokens.shape[0]
    x = _embed(params, cfg, tokens)[:, None]               # [B, 1, D]
    pos_grid = positions[:, None]

    def layer_body(l, state):
        x, k_cache, v_cache = state
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        k_l = jax.lax.dynamic_index_in_dim(k_cache, l, 0, keepdims=False)
        v_l = jax.lax.dynamic_index_in_dim(v_cache, l, 0, keepdims=False)
        attn, k_l, v_l = _attention_block(x, layer, k_l, v_l, pos_grid, cfg)
        x = x + attn
        x = x + _ffn_block(x, layer, cfg)
        k_cache = jax.lax.dynamic_update_index_in_dim(k_cache, k_l, l, 0)
        v_cache = jax.lax.dynamic_update_index_in_dim(v_cache, v_l, l, 0)
        return x, k_cache, v_cache

    x, k_cache, v_cache = jax.lax.fori_loop(
        0, cfg.n_layers, layer_body, (x, k_cache, v_cache))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _head(x[:, 0], params)
    return logits, k_cache, v_cache


def llama_prefill_chunk(params, cfg: LlamaConfig, tokens, positions,
                        k_layers, v_layers, slots, project_last=None):
    """One CHUNK of a cached prefill over the per-layer serving caches.

    tokens: [K, C] the chunk's token ids; positions: [K, C] their absolute
    positions (a later chunk attends the earlier chunks' KV already written
    in the cache rows — the mask `j <= q_pos` needs nothing more);
    k/v_layers: per-layer cache tuples ([B, Hkv, dh, S]); slots: [K] row
    ids. Gathers the K rows, runs the cache-aware attention for the chunk,
    scatters the rows back.

    project_last: int32 [K] of within-chunk last indices — gathers those
    hidden rows and projects [K, V] logits. The engine passes it for EVERY
    chunk (a short row's true last position may fall in any chunk; the
    carried `selected` buffer keeps the right one). None skips the lm_head
    projection entirely for callers that only need the cache side effect.

    This is the building block for chunked prefill: a long prompt is
    admitted as several bounded dispatches so decode blocks (and other
    admissions) interleave instead of stalling behind one huge prefill —
    the TTFT lever for mixed traffic.
    Returns (logits [K, V] or None, k_layers, v_layers).
    """
    k_out = list(k_layers)
    v_out = list(v_layers)
    x = _embed(params, cfg, tokens)                        # [K, C, D]
    for l in range(cfg.n_layers):
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        k_rows = k_out[l][slots]                           # [K, Hkv, dh, S]
        v_rows = v_out[l][slots]
        attn, k_rows, v_rows = _attention_block(x, layer, k_rows, v_rows,
                                                positions, cfg)
        x = x + attn
        x = x + _ffn_block(x, layer, cfg)
        k_out[l] = k_out[l].at[slots].set(k_rows)
        v_out[l] = v_out[l].at[slots].set(v_rows)
    if project_last is None:
        return None, tuple(k_out), tuple(v_out)
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    K = x.shape[0]
    last = x[jnp.arange(K), project_last]                  # [K, D]
    logits = _head(last, params)
    return logits, tuple(k_out), tuple(v_out)


def llama_verify_step(params, cfg: LlamaConfig, tokens, drafts, positions,
                      k_layers, v_layers):
    """Speculative-decode VERIFY: score the current token plus d drafted
    tokens for every slot in ONE forward.

    tokens: [B] each slot's current (already-sampled) token; drafts: [B, d]
    proposed continuations (junk rows allowed — acceptance is decided by
    the caller); positions: [B] the current token's absolute position;
    k/v_layers: per-layer serving caches.

    Window = [tokens | drafts] at positions [pos .. pos+d]. The forward
    writes the window's K/V into the cache — for the accepted prefix these
    ARE the tokens decode would have written (a draft is only accepted when
    it equals the model's own greedy choice), and rejected positions hold
    junk that is overwritten by their eventual real occupant before any
    query attends them (the engine's standard lock-step junk-write
    invariant).

    Returns (greedy [B, d+1] int32 — argmax continuation after each window
    position, logits0 [B, V] float32 — position-0 logits for temperature
    sampling, k_layers, v_layers).

    The lm_head projects one window position at a time ([B, D] @ [D, V],
    then argmax) so no [B, d+1, V] logits buffer ever materializes — at
    Llama-3 vocab that buffer would be ~0.5 GB per dispatch.

    NOTE: the window's cached attention is the dense masked einsum (a
    T=d+1 read never hits the T==1 decode kernel branch), so each verify
    dispatch reads the full allocated cache per layer regardless of
    cfg.decode_attn — speculation trades the kernel's live-length
    streaming read for multi-token verification. Favorable when acceptance
    is high or contexts are short; long-context random text prefers plain
    kernel-mode block decode.
    """
    B, d = drafts.shape
    window = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [B, d+1]
    pos_grid = positions[:, None] + jnp.arange(d + 1, dtype=jnp.int32)[None, :]

    x = _embed(params, cfg, window)
    k_out, v_out = [], []
    for l in range(cfg.n_layers):
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        attn, k_l, v_l = _attention_block(x, layer, k_layers[l], v_layers[l],
                                          pos_grid, cfg)
        x = x + attn
        x = x + _ffn_block(x, layer, cfg)
        k_out.append(k_l)
        v_out.append(v_l)
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)       # [B, d+1, D]

    greedy_cols = []
    logits0 = None
    for i in range(d + 1):
        logits_i = _head(x[:, i], params)
        if i == 0:
            logits0 = logits_i
        greedy_cols.append(jnp.argmax(logits_i, axis=-1).astype(jnp.int32))
    greedy = jnp.stack(greedy_cols, axis=1)                  # [B, d+1]
    return greedy, logits0, tuple(k_out), tuple(v_out)


def llama_prefill_chunk_q8(params, cfg: LlamaConfig, tokens, positions,
                           k_layers, v_layers, ks_layers, vs_layers, slots,
                           project_last=None):
    """One CHUNK of a cached prefill over INT8 per-layer caches.

    MIRRORS llama_prefill_chunk with the quantized storage: gathers the K
    slots' int8 rows + scales, quantizes THIS chunk's fresh K/V into them
    (old tokens keep their original quantization — no requantize drift),
    and runs the chunk's attention over the dequantized gathered rows.
    Dequant materializes only [K, Hkv, dh, S] per layer — K gathered rows,
    not the whole B-row cache, so the int8 cache's HBM win is preserved.
    The read uses the dequant-of-quantized values for this chunk too, so
    numerics match what later chunks and decode steps will read.

    tokens: [K, C]; positions: [K, C]; k/v_layers: int8 cache tuples;
    ks/vs_layers: [B, Hkv, S] f32 scale tuples; slots: [K].
    Returns (logits [K, V] or None, k_layers, v_layers, ks_layers,
    vs_layers).
    """
    from ..ops.decode_attention import quantize_kv

    K, C = tokens.shape
    H, Hkv, dh, G = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.q_per_kv
    dt = _np_dtype(cfg.dtype)
    k_out, v_out = list(k_layers), list(v_layers)
    ks_out, vs_out = list(ks_layers), list(vs_layers)
    x = _embed(params, cfg, tokens)                        # [K, C, D]
    batch_idx = jnp.arange(K)[:, None]
    for l in range(cfg.n_layers):
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        k_rows8 = k_out[l][slots]                          # [K, Hkv, dh, S]
        v_rows8 = v_out[l][slots]
        ks_rows = ks_out[l][slots]                         # [K, Hkv, S]
        vs_rows = vs_out[l][slots]

        normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q = _mm(normed, layer, "wq").reshape(K, C, H, dh)
        k = _mm(normed, layer, "wk").reshape(K, C, Hkv, dh)
        v = _mm(normed, layer, "wv").reshape(K, C, Hkv, dh)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        k8c, ksc = quantize_kv(k, axis=-1)                 # [K,C,Hkv,dh],[K,C,Hkv]
        v8c, vsc = quantize_kv(v, axis=-1)
        k_rows8 = k_rows8.at[batch_idx, :, :, positions].set(k8c)
        v_rows8 = v_rows8.at[batch_idx, :, :, positions].set(v8c)
        ks_rows = ks_rows.at[batch_idx, :, positions].set(ksc)
        vs_rows = vs_rows.at[batch_idx, :, positions].set(vsc)

        k_deq = (k_rows8.astype(jnp.float32)
                 * ks_rows[:, :, None, :]).astype(dt)
        v_deq = (v_rows8.astype(jnp.float32)
                 * vs_rows[:, :, None, :]).astype(dt)
        # GQA masked read over the dequantized rows — the dense branch of
        # _attention_block, inlined (the write above had to target the
        # int8 storage, not the float rows that function scatters into)
        S = k_deq.shape[-1]
        qg = q.reshape(K, C, Hkv, G, dh)
        scores = jnp.einsum("bthgd,bhds->bhgts", qg, k_deq,
                            preferred_element_type=jnp.float32) / math.sqrt(dh)
        cache_pos = jnp.arange(S)[None, None, :]
        visible = cache_pos <= positions[:, :, None]
        scores = jnp.where(visible[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhgts,bhds->bthgd", probs.astype(v_deq.dtype),
                          v_deq,
                          preferred_element_type=jnp.float32).astype(x.dtype)
        x = x + _mm(attn.reshape(K, C, H * dh), layer, "wo")
        x = x + _ffn_block(x, layer, cfg)

        k_out[l] = k_out[l].at[slots].set(k_rows8)
        v_out[l] = v_out[l].at[slots].set(v_rows8)
        ks_out[l] = ks_out[l].at[slots].set(ks_rows)
        vs_out[l] = vs_out[l].at[slots].set(vs_rows)
    out_caches = (tuple(k_out), tuple(v_out), tuple(ks_out), tuple(vs_out))
    if project_last is None:
        return (None,) + out_caches
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    last = x[jnp.arange(K), project_last]                  # [K, D]
    logits = _head(last, params)
    return (logits,) + out_caches


def llama_decode_step_paged(params, cfg: LlamaConfig, tokens, positions,
                            k_pool, v_pool, table):
    """One decode step against a PAGED KV cache.

    tokens: [B]; positions: [B] absolute write positions; k/v_pool:
    [L, P, Hkv, dh, page_size]; table: [B, NP] page ids per slot (entries
    past a slot's reservation must hold a valid id, e.g. 0).
    Returns (logits [B, V] float32, k_pool, v_pool).

    Per-layer: write this token's K/V into its page (paged_write_decode),
    then read attention through the block table with the scalar-prefetch
    Pallas kernel (paged_attention) — per-step HBM traffic tracks the
    table width (live pages), not a dense [B, S] allocation.

    Pools are carried through a fori_loop with per-layer DUS (not scan
    xs/ys) for the same in-place aliasing reason as
    llama_decode_step_inplace.
    """
    from ..ops.paged_attention import paged_attention, paged_write_decode

    B = tokens.shape[0]
    H, Hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = _embed(params, cfg, tokens)[:, None]               # [B, 1, D]
    pos_grid = positions[:, None]                          # [B, 1]

    def layer_body(l, state):
        x, k_pool, v_pool = state
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        kp_l = jax.lax.dynamic_index_in_dim(k_pool, l, 0, keepdims=False)
        vp_l = jax.lax.dynamic_index_in_dim(v_pool, l, 0, keepdims=False)
        normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q = rope(_mm(normed, layer, "wq").reshape(B, 1, H, dh), pos_grid,
                 cfg.rope_theta)
        k = rope(_mm(normed, layer, "wk").reshape(B, 1, Hkv, dh), pos_grid,
                 cfg.rope_theta)
        v = _mm(normed, layer, "wv").reshape(B, 1, Hkv, dh)
        kp_l, vp_l = paged_write_decode(kp_l, vp_l, k[:, 0], v[:, 0],
                                        table, positions)
        attn = paged_attention(q[:, 0], kp_l, vp_l, table, positions + 1)
        x = x + _mm(attn.reshape(B, 1, H * dh), layer, "wo")
        x = x + _ffn_block(x, layer, cfg)
        k_pool = jax.lax.dynamic_update_index_in_dim(k_pool, kp_l, l, 0)
        v_pool = jax.lax.dynamic_update_index_in_dim(v_pool, vp_l, l, 0)
        return x, k_pool, v_pool

    x, k_pool, v_pool = jax.lax.fori_loop(
        0, cfg.n_layers, layer_body, (x, k_pool, v_pool))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _head(x[:, 0], params)
    return logits, k_pool, v_pool


def llama_decode_step_paged_q8(params, cfg: LlamaConfig, tokens, positions,
                               k_pool, v_pool, ks_pool, vs_pool, table):
    """One decode step against an INT8 paged KV pool.

    MIRRORS llama_decode_step_paged with per-token scales: k/v_pool are
    [L, P, Hkv, dh, ps] int8, ks/vs_pool [L, P, Hkv, ps] float32. The new
    token's K/V quantize on write; the paged kernel reads the int8 pages
    with dequant folded into its dots — pool HBM bytes halve, so both the
    per-step read AND the page capacity per GiB double.
    Returns (logits [B, V] f32, k_pool, v_pool, ks_pool, vs_pool).
    """
    from ..ops.decode_attention import quantize_kv
    from ..ops.paged_attention import paged_attention, paged_write_decode

    B = tokens.shape[0]
    H, Hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = _embed(params, cfg, tokens)[:, None]               # [B, 1, D]
    pos_grid = positions[:, None]
    ps = k_pool.shape[-1]
    # scale writes share the value writer's index rule (paged_write_decode)
    page_ids = table[jnp.arange(B), positions // ps]       # [B]
    offsets = positions % ps

    def layer_body(l, state):
        x, k_pool, v_pool, ks_pool, vs_pool = state
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        kp_l = jax.lax.dynamic_index_in_dim(k_pool, l, 0, keepdims=False)
        vp_l = jax.lax.dynamic_index_in_dim(v_pool, l, 0, keepdims=False)
        ksp_l = jax.lax.dynamic_index_in_dim(ks_pool, l, 0, keepdims=False)
        vsp_l = jax.lax.dynamic_index_in_dim(vs_pool, l, 0, keepdims=False)
        normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q = rope(_mm(normed, layer, "wq").reshape(B, 1, H, dh), pos_grid,
                 cfg.rope_theta)
        k = rope(_mm(normed, layer, "wk").reshape(B, 1, Hkv, dh), pos_grid,
                 cfg.rope_theta)
        v = _mm(normed, layer, "wv").reshape(B, 1, Hkv, dh)
        k8, ks = quantize_kv(k[:, 0], axis=-1)             # [B,Hkv,dh],[B,Hkv]
        v8, vs = quantize_kv(v[:, 0], axis=-1)
        kp_l, vp_l = paged_write_decode(kp_l, vp_l, k8, v8, table, positions)
        ksp_l = ksp_l.at[page_ids, :, offsets].set(ks)
        vsp_l = vsp_l.at[page_ids, :, offsets].set(vs)
        attn = paged_attention(q[:, 0], kp_l, vp_l, table, positions + 1,
                               ksp_l, vsp_l)
        x = x + _mm(attn.reshape(B, 1, H * dh), layer, "wo")
        x = x + _ffn_block(x, layer, cfg)
        k_pool = jax.lax.dynamic_update_index_in_dim(k_pool, kp_l, l, 0)
        v_pool = jax.lax.dynamic_update_index_in_dim(v_pool, vp_l, l, 0)
        ks_pool = jax.lax.dynamic_update_index_in_dim(ks_pool, ksp_l, l, 0)
        vs_pool = jax.lax.dynamic_update_index_in_dim(vs_pool, vsp_l, l, 0)
        return x, k_pool, v_pool, ks_pool, vs_pool

    x, k_pool, v_pool, ks_pool, vs_pool = jax.lax.fori_loop(
        0, cfg.n_layers, layer_body, (x, k_pool, v_pool, ks_pool, vs_pool))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _head(x[:, 0], params)
    return logits, k_pool, v_pool, ks_pool, vs_pool


def llama_verify_step_paged(params, cfg: LlamaConfig, tokens, drafts,
                            positions, k_pool, v_pool, table):
    """Speculative-decode VERIFY against the PAGED pool.

    Same contract as llama_verify_step (score current token + d drafts in
    one forward, cache-writing), re-shaped for paged storage:

      - the window's K/V scatter into pages via paged_write_decode, one
        window position at a time — positions past a slot's reservation
        map to zero table entries, i.e. the garbage page, so overrun junk
        can never land in a live page (the allocator invariant)
      - the window attention gathers each slot's pages into contiguous
        [B, Hkv, dh, NP*ps] rows (ONE pool read per layer — the paged
        kernel is a T=1 read; d+1 kernel calls would re-stream the live
        pages d+1 times) and runs the dense masked einsum over them.
        Page j of a slot's table covers absolute positions [j*ps, (j+1)*ps),
        so gathered offset IS absolute position and the `j <= q_pos` mask
        carries over unchanged.

    Junk-safety mirrors the dense verify: rejected window positions hold
    junk that the eventual real occupant overwrites before any query
    attends it (lock-step invariant), and garbage-page content is only
    reachable at offsets the mask already excludes for live queries.

    tokens: [B]; drafts: [B, d]; positions: [B]; k/v_pool:
    [L, P, Hkv, dh, ps]; table: [B, NP].
    Returns (greedy [B, d+1] int32, logits0 [B, V] f32, k_pool, v_pool).
    """
    from ..ops.paged_attention import paged_write_decode

    B, d = drafts.shape
    H, Hkv, dh, G = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.q_per_kv
    ps = k_pool.shape[-1]
    NP = table.shape[1]
    S = NP * ps
    window = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [B, d+1]
    pos_grid = positions[:, None] + jnp.arange(d + 1, dtype=jnp.int32)[None, :]
    x = _embed(params, cfg, window)

    def layer_body(l, state):
        x, k_pool, v_pool = state
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        kp_l = jax.lax.dynamic_index_in_dim(k_pool, l, 0, keepdims=False)
        vp_l = jax.lax.dynamic_index_in_dim(v_pool, l, 0, keepdims=False)
        normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q = rope(_mm(normed, layer, "wq").reshape(B, d + 1, H, dh),
                 pos_grid, cfg.rope_theta)
        k = rope(_mm(normed, layer, "wk").reshape(B, d + 1, Hkv, dh),
                 pos_grid, cfg.rope_theta)
        v = _mm(normed, layer, "wv").reshape(B, d + 1, Hkv, dh)
        # window scatter BEFORE the gather so the gathered rows already
        # contain this window's fresh K/V (the dense verify's .at[].set)
        for i in range(d + 1):
            kp_l, vp_l = paged_write_decode(kp_l, vp_l, k[:, i], v[:, i],
                                            table, positions + i)
        k_rows = jnp.moveaxis(kp_l[table], 1, 3).reshape(B, Hkv, dh, S)
        v_rows = jnp.moveaxis(vp_l[table], 1, 3).reshape(B, Hkv, dh, S)
        qg = q.reshape(B, d + 1, Hkv, G, dh)
        scores = jnp.einsum("bthgd,bhds->bhgts", qg, k_rows,
                            preferred_element_type=jnp.float32
                            ) / math.sqrt(dh)
        cache_pos = jnp.arange(S)[None, None, :]                 # [1, 1, S]
        visible = cache_pos <= pos_grid[:, :, None]              # [B, d+1, S]
        scores = jnp.where(visible[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhgts,bhds->bthgd", probs.astype(v_rows.dtype),
                          v_rows,
                          preferred_element_type=jnp.float32).astype(x.dtype)
        x = x + _mm(attn.reshape(B, d + 1, H * dh), layer, "wo")
        x = x + _ffn_block(x, layer, cfg)
        k_pool = jax.lax.dynamic_update_index_in_dim(k_pool, kp_l, l, 0)
        v_pool = jax.lax.dynamic_update_index_in_dim(v_pool, vp_l, l, 0)
        return x, k_pool, v_pool

    x, k_pool, v_pool = jax.lax.fori_loop(
        0, cfg.n_layers, layer_body, (x, k_pool, v_pool))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)           # [B, d+1, D]
    greedy_cols = []
    logits0 = None
    for i in range(d + 1):
        logits_i = _head(x[:, i], params)
        if i == 0:
            logits0 = logits_i
        greedy_cols.append(jnp.argmax(logits_i, axis=-1).astype(jnp.int32))
    greedy = jnp.stack(greedy_cols, axis=1)                      # [B, d+1]
    return greedy, logits0, k_pool, v_pool


def llama_prefill_paged_prefix(params, cfg: LlamaConfig, tokens, prefix_lens,
                               lengths, k_pool, v_pool, table, project_last):
    """Prefill ONLY a prompt's un-cached TAIL against the paged pool.

    The prefix-cache hit path: each row's first `prefix_lens[k]` tokens
    (a whole number of pages) are already in shared pages referenced by
    its block table, so this forward computes K/V for the tail window
    alone — prefill FLOPs and writes scale with the UNSHARED tail, which
    is the entire point of prefix caching.

    tokens: [K, T] tail token ids (row k's tail starts at absolute
    position prefix_lens[k]); prefix_lens: [K] int32 multiples of the
    page size; lengths: [K] FULL prompt lengths; k/v_pool:
    [L, P, Hkv, dh, ps]; table: [K, NP] page ids (shared prefix pages
    first, then the row's fresh pages); project_last: [K] within-window
    index of each row's last prompt token.

    Per layer: tail K/V scatter into their pages (pad positions past
    lengths[k] divert to the garbage page), then the tail queries attend
    the GATHERED pages ([K, Hkv, dh, NP*ps] contiguous rows, one pool
    read per layer — the same shape trick as llama_verify_step_paged)
    under the standard `j <= q_pos` mask, which covers the shared prefix
    and the tail's own causal window in one rule.

    Returns (last_logits [K, V] float32, k_pool, v_pool).
    """
    K, T = tokens.shape
    H, Hkv, dh, G = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.q_per_kv
    ps = k_pool.shape[-1]
    NP = table.shape[1]
    S = NP * ps
    pos_grid = prefix_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    in_prompt = pos_grid < lengths[:, None]                     # [K, T]
    # scatter rule (shared with _prefill_scatter_indices' semantics):
    # token at absolute pos -> (table[k, pos // ps], pos % ps); pads -> 0
    page_slot = jnp.clip(pos_grid // ps, 0, NP - 1)
    page_ids = jnp.take_along_axis(table, page_slot, axis=1)    # [K, T]
    page_ids = jnp.where(in_prompt, page_ids, jnp.int32(0))
    offsets = pos_grid % ps
    x = _embed(params, cfg, tokens)

    def layer_body(l, state):
        x, k_pool, v_pool = state
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        kp_l = jax.lax.dynamic_index_in_dim(k_pool, l, 0, keepdims=False)
        vp_l = jax.lax.dynamic_index_in_dim(v_pool, l, 0, keepdims=False)
        normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q = rope(_mm(normed, layer, "wq").reshape(K, T, H, dh),
                 pos_grid, cfg.rope_theta)
        k = rope(_mm(normed, layer, "wk").reshape(K, T, Hkv, dh),
                 pos_grid, cfg.rope_theta)
        v = _mm(normed, layer, "wv").reshape(K, T, Hkv, dh)
        # advanced indices on pool dims 0+3 -> value shape [K, T, Hkv, dh]
        kp_l = kp_l.at[page_ids, :, :, offsets].set(k)
        vp_l = vp_l.at[page_ids, :, :, offsets].set(v)
        k_rows = jnp.moveaxis(kp_l[table], 1, 3).reshape(K, Hkv, dh, S)
        v_rows = jnp.moveaxis(vp_l[table], 1, 3).reshape(K, Hkv, dh, S)
        qg = q.reshape(K, T, Hkv, G, dh)
        scores = jnp.einsum("bthgd,bhds->bhgts", qg, k_rows,
                            preferred_element_type=jnp.float32
                            ) / math.sqrt(dh)
        cache_pos = jnp.arange(S)[None, None, :]
        visible = cache_pos <= pos_grid[:, :, None]             # [K, T, S]
        scores = jnp.where(visible[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhgts,bhds->bthgd", probs.astype(v_rows.dtype),
                          v_rows,
                          preferred_element_type=jnp.float32).astype(x.dtype)
        x = x + _mm(attn.reshape(K, T, H * dh), layer, "wo")
        x = x + _ffn_block(x, layer, cfg)
        k_pool = jax.lax.dynamic_update_index_in_dim(k_pool, kp_l, l, 0)
        v_pool = jax.lax.dynamic_update_index_in_dim(v_pool, vp_l, l, 0)
        return x, k_pool, v_pool

    x, k_pool, v_pool = jax.lax.fori_loop(
        0, cfg.n_layers, layer_body, (x, k_pool, v_pool))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    last = x[jnp.arange(K), project_last]                       # [K, D]
    logits = _head(last, params)
    return logits, k_pool, v_pool


def llama_prefill_paged_prefix_q8(params, cfg: LlamaConfig, tokens,
                                  prefix_lens, lengths, k_pool, v_pool,
                                  ks_pool, vs_pool, table, project_last):
    """llama_prefill_paged_prefix over INT8 pools with per-token scales.

    MIRRORS the fp variant with quantized storage: the tail's K/V quantize
    on write (so the pages hold exactly what later decode reads), then the
    gathered rows dequantize [K, Hkv, dh, NP*ps] for the tail window's
    attention — prefix pages keep the DONOR's quantization (no requantize
    drift), the same posture as the dense engine's chunked-q8 path.

    k/v_pool: [L, P, Hkv, dh, ps] int8; ks/vs_pool: [L, P, Hkv, ps] f32.
    Returns (last_logits [K, V] f32, k_pool, v_pool, ks_pool, vs_pool).
    """
    from ..ops.decode_attention import quantize_kv

    K, T = tokens.shape
    H, Hkv, dh, G = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.q_per_kv
    ps = k_pool.shape[-1]
    NP = table.shape[1]
    S = NP * ps
    dt = _np_dtype(cfg.dtype)
    pos_grid = prefix_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    in_prompt = pos_grid < lengths[:, None]
    page_slot = jnp.clip(pos_grid // ps, 0, NP - 1)
    page_ids = jnp.take_along_axis(table, page_slot, axis=1)
    page_ids = jnp.where(in_prompt, page_ids, jnp.int32(0))
    offsets = pos_grid % ps
    x = _embed(params, cfg, tokens)

    def layer_body(l, state):
        x, k_pool, v_pool, ks_pool, vs_pool = state
        layer = jax.tree_util.tree_map(lambda w: w[l], params["layers"])
        kp_l = jax.lax.dynamic_index_in_dim(k_pool, l, 0, keepdims=False)
        vp_l = jax.lax.dynamic_index_in_dim(v_pool, l, 0, keepdims=False)
        ksp_l = jax.lax.dynamic_index_in_dim(ks_pool, l, 0, keepdims=False)
        vsp_l = jax.lax.dynamic_index_in_dim(vs_pool, l, 0, keepdims=False)
        normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q = rope(_mm(normed, layer, "wq").reshape(K, T, H, dh),
                 pos_grid, cfg.rope_theta)
        k = rope(_mm(normed, layer, "wk").reshape(K, T, Hkv, dh),
                 pos_grid, cfg.rope_theta)
        v = _mm(normed, layer, "wv").reshape(K, T, Hkv, dh)
        k8, ks = quantize_kv(k, axis=-1)           # [K,T,Hkv,dh], [K,T,Hkv]
        v8, vs = quantize_kv(v, axis=-1)
        kp_l = kp_l.at[page_ids, :, :, offsets].set(k8)
        vp_l = vp_l.at[page_ids, :, :, offsets].set(v8)
        ksp_l = ksp_l.at[page_ids, :, offsets].set(ks)
        vsp_l = vsp_l.at[page_ids, :, offsets].set(vs)
        k_rows = jnp.moveaxis(kp_l[table], 1, 3).reshape(K, Hkv, dh, S)
        v_rows = jnp.moveaxis(vp_l[table], 1, 3).reshape(K, Hkv, dh, S)
        ks_rows = jnp.moveaxis(ksp_l[table], 1, 2).reshape(K, Hkv, S)
        vs_rows = jnp.moveaxis(vsp_l[table], 1, 2).reshape(K, Hkv, S)
        k_deq = (k_rows.astype(jnp.float32)
                 * ks_rows[:, :, None, :]).astype(dt)
        v_deq = (v_rows.astype(jnp.float32)
                 * vs_rows[:, :, None, :]).astype(dt)
        qg = q.reshape(K, T, Hkv, G, dh)
        scores = jnp.einsum("bthgd,bhds->bhgts", qg, k_deq,
                            preferred_element_type=jnp.float32
                            ) / math.sqrt(dh)
        cache_pos = jnp.arange(S)[None, None, :]
        visible = cache_pos <= pos_grid[:, :, None]
        scores = jnp.where(visible[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhgts,bhds->bthgd", probs.astype(v_deq.dtype),
                          v_deq,
                          preferred_element_type=jnp.float32).astype(x.dtype)
        x = x + _mm(attn.reshape(K, T, H * dh), layer, "wo")
        x = x + _ffn_block(x, layer, cfg)
        k_pool = jax.lax.dynamic_update_index_in_dim(k_pool, kp_l, l, 0)
        v_pool = jax.lax.dynamic_update_index_in_dim(v_pool, vp_l, l, 0)
        ks_pool = jax.lax.dynamic_update_index_in_dim(ks_pool, ksp_l, l, 0)
        vs_pool = jax.lax.dynamic_update_index_in_dim(vs_pool, vsp_l, l, 0)
        return x, k_pool, v_pool, ks_pool, vs_pool

    x, k_pool, v_pool, ks_pool, vs_pool = jax.lax.fori_loop(
        0, cfg.n_layers, layer_body, (x, k_pool, v_pool, ks_pool, vs_pool))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    last = x[jnp.arange(K), project_last]
    logits = _head(last, params)
    return logits, k_pool, v_pool, ks_pool, vs_pool


def _attention_block_nocache(x, layer, positions, cfg: LlamaConfig,
                             attn_fn=None):
    """Plain causal attention sublayer (no cache). x: [B, T, D] -> [B, T, D].

    attn_fn overrides the attention primitive (q, k, v) -> [B, T, H, dh] —
    how the sequence-parallel forward swaps in ring/Ulysses attention while
    sharing every projection with the dense path."""
    B, T, _ = x.shape
    H, Hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
    q = rope(_mm(normed, layer, "wq").reshape(B, T, H, dh), positions, cfg.rope_theta)
    k = rope(_mm(normed, layer, "wk").reshape(B, T, Hkv, dh), positions, cfg.rope_theta)
    v = _mm(normed, layer, "wv").reshape(B, T, Hkv, dh)
    if attn_fn is not None:
        attn = attn_fn(q, k, v)
    elif cfg.attn_impl == "flash":
        from ..ops.flash_attention import flash_attention

        attn = flash_attention(q, k, v, True)
    else:
        from ..ops.flash_attention import attention_reference

        attn = attention_reference(q, k, v, causal=True)
    return _mm(attn.reshape(B, T, H * dh), layer, "wo")


def forward_nocache_at(params, cfg: LlamaConfig, tokens, positions,
                       attn_fn=None):
    """Cache-free forward over a token chunk at explicit absolute positions.

    The shared body behind llama_forward_nocache and the sequence-parallel
    forward (parallel/longcontext.py), which calls it per device with its
    chunk's position offset and a collective attention primitive."""
    x = _embed(params, cfg, tokens)

    def body(x, layer):
        x = x + _attention_block_nocache(x, layer, positions, cfg, attn_fn)
        x = x + _ffn_block(x, layer, cfg)
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    return _head(x, params)


def llama_forward_nocache(params, cfg: LlamaConfig, tokens):
    """Training/eval forward without a cache: plain causal attention.

    Kept separate from the serving path so the training step doesn't carry
    cache plumbing; shares every sublayer weight and math with llama_forward.
    """
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    return forward_nocache_at(params, cfg, tokens, positions)
