"""Llama-family decoder: GQA + RoPE + RMSNorm + SwiGLU, cache-aware forward.

Built TPU-first rather than ported: weights are stacked [n_layers, ...] and
consumed by lax.scan (single-layer trace -> fast XLA compiles, natural
pipeline sharding axis); matmuls stay bfloat16 for the MXU with float32
softmax/norm accumulation; the KV cache is an explicit argument so serving
code can donate it for in-place HBM updates (no torch-style module state).

The unified `llama_forward` serves both phases of LLM serving:
  - prefill: T>1 tokens written at positions [0..T), causal within the window
  - decode:  T=1 token written at its absolute position, attending the cache
Masking needs only `j <= q_pos` because cache slots are written contiguously
from 0 — slot index IS absolute position.

Config presets cover the BASELINE.md north-star ladder (debug CI model,
1B bench model, Llama-3-8B, Llama-3-70B).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"
    # "xla" | "flash" — selects the attention impl for the NO-CACHE forward
    # (training/eval); the cached serving path keeps its scatter+masked-read
    # attention regardless (flash prefill over the cache is future work)
    attn_impl: str = "xla"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    @classmethod
    def debug(cls) -> "LlamaConfig":
        """CI-sized model: compiles in seconds on CPU."""
        return cls(vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                   ffn_dim=128, max_seq_len=256, dtype="float32")

    @classmethod
    def llama1b(cls) -> "LlamaConfig":
        """Llama-3.2-1B shape: the single-v5e-chip bench model."""
        return cls(vocab_size=128256, dim=2048, n_layers=16, n_heads=32,
                   n_kv_heads=8, ffn_dim=8192, max_seq_len=8192)

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, ffn_dim=14336, max_seq_len=8192)

    @classmethod
    def llama3_70b(cls) -> "LlamaConfig":
        return cls(vocab_size=128256, dim=8192, n_layers=80, n_heads=64,
                   n_kv_heads=8, ffn_dim=28672, max_seq_len=8192)

    def param_count(self) -> int:
        embed = self.vocab_size * self.dim
        per_layer = (self.dim * self.n_heads * self.head_dim          # wq
                     + 2 * self.dim * self.n_kv_heads * self.head_dim  # wk, wv
                     + self.n_heads * self.head_dim * self.dim         # wo
                     + 3 * self.dim * self.ffn_dim                     # gate/up/down
                     + 2 * self.dim)                                   # norms
        return 2 * embed + self.n_layers * per_layer + self.dim


def _np_dtype(name: str):
    import jax.numpy as jnp

    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
            "float16": jnp.float16}[name]


def llama_init(cfg: LlamaConfig, seed: int = 0) -> Dict[str, Any]:
    """Random-init params pytree with stacked [L, ...] layer weights."""
    import jax
    import jax.numpy as jnp

    dtype = _np_dtype(cfg.dtype)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 8)
    L, D, H, Hkv, dh, F, V = (cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads,
                              cfg.head_dim, cfg.ffn_dim, cfg.vocab_size)

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dtype)

    return {
        "tok_emb": init(keys[0], (V, D), D),
        "layers": {
            "wq": init(keys[1], (L, D, H * dh), D),
            "wk": init(keys[2], (L, D, Hkv * dh), D),
            "wv": init(keys[3], (L, D, Hkv * dh), D),
            "wo": init(keys[4], (L, H * dh, D), H * dh),
            "w_gate": init(keys[5], (L, D, F), D),
            "w_up": init(keys[6], (L, D, F), D),
            "w_down": init(keys[7], (L, F, D), F),
            "attn_norm": jnp.ones((L, D), dtype=dtype),
            "ffn_norm": jnp.ones((L, D), dtype=dtype),
        },
        "final_norm": jnp.ones((D,), dtype=dtype),
        "lm_head": init(keys[0], (D, V), D),
    }


def init_kv_cache(cfg: LlamaConfig, batch: int, seq_len: Optional[int] = None,
                  dtype: Optional[str] = None) -> Tuple[Any, Any]:
    """Zeroed (k, v) caches shaped [L, B, S, Hkv, dh]."""
    import jax.numpy as jnp

    S = seq_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch, S, cfg.n_kv_heads, cfg.head_dim)
    dt = _np_dtype(dtype or cfg.dtype)
    return jnp.zeros(shape, dtype=dt), jnp.zeros(shape, dtype=dt)


def rms_norm(x, weight, eps: float):
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def rope(x, positions, theta: float):
    """Rotate-half RoPE. x: [B, T, H, dh]; positions: [B, T] int32."""
    import jax.numpy as jnp

    dh = x.shape[-1]
    half = dh // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, T, 1, half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


import jax  # noqa: E402  (after dataclass defs so module import stays light)
import jax.numpy as jnp  # noqa: E402


def _attention_block(x, layer, k_cache_l, v_cache_l, positions, cfg: LlamaConfig):
    """One attention sublayer with cache write + masked read.

    x: [B, T, D]; k/v_cache_l: [B, S, Hkv, dh]; positions: [B, T].
    Returns (out [B, T, D], k_cache_l, v_cache_l).

    Per-step HBM traffic scales with the ALLOCATED seq dim S, so the engine
    allocates the cache at the bucket covering the live contexts and grows
    it on demand (engine._grow_cache) instead of sizing for max_seq_len.
    """
    B, T, D = x.shape
    S = k_cache_l.shape[1]
    H, Hkv, dh, G = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.q_per_kv

    normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
    q = (normed @ layer["wq"]).reshape(B, T, H, dh)
    k = (normed @ layer["wk"]).reshape(B, T, Hkv, dh)
    v = (normed @ layer["wv"]).reshape(B, T, Hkv, dh)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)

    # scatter this chunk's k/v into the cache at its absolute positions
    batch_idx = jnp.arange(B)[:, None]
    k_cache_l = k_cache_l.at[batch_idx, positions].set(k)
    v_cache_l = v_cache_l.at[batch_idx, positions].set(v)

    # GQA attention over the cache: q grouped [B, T, Hkv, G, dh].
    # Keep the matmul inputs in the cache dtype (bf16 on the MXU's fast
    # path) and accumulate f32 via preferred_element_type — upcasting the
    # INPUTS would force a full-f32 matmul at a fraction of MXU throughput.
    qg = q.reshape(B, T, Hkv, G, dh)
    scores = jnp.einsum("bthgd,bshd->bhgts", qg, k_cache_l,
                        preferred_element_type=jnp.float32) / math.sqrt(dh)
    # mask: query at absolute pos p sees cache slot j iff j <= p
    cache_pos = jnp.arange(S)[None, None, :]                  # [1, 1, S]
    visible = cache_pos <= positions[:, :, None]              # [B, T, S]
    scores = jnp.where(visible[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgts,bshd->bthgd", probs.astype(v_cache_l.dtype),
                     v_cache_l,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    out = out.reshape(B, T, H * dh) @ layer["wo"]
    return out, k_cache_l, v_cache_l


def _ffn_block(x, layer, cfg: LlamaConfig):
    normed = rms_norm(x, layer["ffn_norm"], cfg.rms_eps)
    gate = jax.nn.silu(normed @ layer["w_gate"])
    up = normed @ layer["w_up"]
    return (gate * up) @ layer["w_down"]


def llama_forward(params, cfg: LlamaConfig, tokens, positions, k_cache, v_cache):
    """Cache-writing forward over a token chunk.

    tokens: [B, T] int32; positions: [B, T] absolute positions (row-wise
    monotonic); k/v_cache: [L, B, S, Hkv, dh].
    Returns (logits [B, T, V] float32, k_cache, v_cache).
    """
    x = params["tok_emb"][tokens]

    def body(x, scan_in):
        layer, k_l, v_l = scan_in
        attn_out, k_l, v_l = _attention_block(x, layer, k_l, v_l, positions, cfg)
        x = x + attn_out
        x = x + _ffn_block(x, layer, cfg)
        return x, (k_l, v_l)

    x, (k_cache, v_cache) = jax.lax.scan(
        body, x, (params["layers"], k_cache, v_cache))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, k_cache, v_cache


def llama_prefill(params, cfg: LlamaConfig, tokens, k_cache, v_cache):
    """Prefill from empty cache: positions are [0..T) for every row."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    return llama_forward(params, cfg, tokens, positions, k_cache, v_cache)


def llama_decode_step(params, cfg: LlamaConfig, tokens, positions, k_cache,
                      v_cache):
    """One decode step for every batch row.

    tokens: [B] current token per row; positions: [B] its absolute position.
    Returns (logits [B, V], k_cache, v_cache).
    """
    logits, k_cache, v_cache = llama_forward(
        params, cfg, tokens[:, None], positions[:, None], k_cache, v_cache)
    return logits[:, 0, :], k_cache, v_cache


def _attention_block_nocache(x, layer, positions, cfg: LlamaConfig,
                             attn_fn=None):
    """Plain causal attention sublayer (no cache). x: [B, T, D] -> [B, T, D].

    attn_fn overrides the attention primitive (q, k, v) -> [B, T, H, dh] —
    how the sequence-parallel forward swaps in ring/Ulysses attention while
    sharing every projection with the dense path."""
    B, T, _ = x.shape
    H, Hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    normed = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
    q = rope((normed @ layer["wq"]).reshape(B, T, H, dh), positions, cfg.rope_theta)
    k = rope((normed @ layer["wk"]).reshape(B, T, Hkv, dh), positions, cfg.rope_theta)
    v = (normed @ layer["wv"]).reshape(B, T, Hkv, dh)
    if attn_fn is not None:
        attn = attn_fn(q, k, v)
    elif cfg.attn_impl == "flash":
        from ..ops.flash_attention import flash_attention

        attn = flash_attention(q, k, v, True)
    else:
        from ..ops.flash_attention import attention_reference

        attn = attention_reference(q, k, v, causal=True)
    return attn.reshape(B, T, H * dh) @ layer["wo"]


def forward_nocache_at(params, cfg: LlamaConfig, tokens, positions,
                       attn_fn=None):
    """Cache-free forward over a token chunk at explicit absolute positions.

    The shared body behind llama_forward_nocache and the sequence-parallel
    forward (parallel/longcontext.py), which calls it per device with its
    chunk's position offset and a collective attention primitive."""
    x = params["tok_emb"][tokens]

    def body(x, layer):
        x = x + _attention_block_nocache(x, layer, positions, cfg, attn_fn)
        x = x + _ffn_block(x, layer, cfg)
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


def llama_forward_nocache(params, cfg: LlamaConfig, tokens):
    """Training/eval forward without a cache: plain causal attention.

    Kept separate from the serving path so the training step doesn't carry
    cache plumbing; shares every sublayer weight and math with llama_forward.
    """
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    return forward_nocache_at(params, cfg, tokens, positions)
