"""MNIST-class MLP: the minimum end-to-end model (BASELINE.md config 2)."""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden_dims: Tuple[int, ...] = (512, 256)
    out_dim: int = 10
    dtype: str = "float32"


def mlp_init(cfg: MLPConfig, seed: int = 0) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[cfg.dtype]
    dims = (cfg.in_dim,) + cfg.hidden_dims + (cfg.out_dim,)
    key = jax.random.PRNGKey(seed)
    params = []
    for i in range(len(dims) - 1):
        key, sub = jax.random.split(key)
        params.append({
            "w": (jax.random.normal(sub, (dims[i], dims[i + 1]), dtype=jnp.float32)
                  * (1.0 / math.sqrt(dims[i]))).astype(dtype),
            "b": jnp.zeros((dims[i + 1],), dtype=dtype),
        })
    return {"layers": params}


def mlp_forward(params, x):
    """x: [B, in_dim] -> logits [B, out_dim]."""
    import jax
    import jax.numpy as jnp

    layers = params["layers"]
    for layer in layers[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return (x @ last["w"] + last["b"]).astype(jnp.float32)
