"""Tokenizers for serving examples: byte-level (zero-dependency) + BPE loader.

A serving framework needs a tokenizer in the request path (SURVEY.md §7.5
"tokenizer in Go" -> here in the serving process, no Python-ecosystem
dependency at runtime). ByteTokenizer is exact and reversible for any UTF-8
text; BPETokenizer loads a vocab/merges file when a real model vocabulary is
available (none ships in this zero-egress environment).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence


class ByteTokenizer:
    """256 byte tokens + specials. vocab: [bytes 0..255, <pad>, <bos>, <eos>]."""

    PAD = 256
    BOS = 257
    EOS = 258

    vocab_size = 259

    def encode(self, text: str, bos: bool = True, eos: bool = False) -> List[int]:
        ids = list(text.encode("utf-8"))
        if bos:
            ids = [self.BOS] + ids
        if eos:
            ids = ids + [self.EOS]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")

    def decode_token(self, token: int) -> str:
        """Single-token streaming decode; multibyte UTF-8 may yield ''."""
        if 0 <= token < 256:
            return bytes([token]).decode("utf-8", errors="ignore")
        return ""


class DebugTokenizer:
    """Round-trip tokenizer for synthetic model vocabularies (the `debug`
    preset's vocab_size=512).

    ByteTokenizer silently DROPS ids >= 256 and random-weight byte
    emissions form invalid UTF-8 that collapses to replacement chars — so
    under the debug preset, 12 sampled tokens could decode to 3 visible
    characters and anything measuring text length against token count
    (min_tokens stop-string gating, SSE chunk accounting) tested nothing.
    Here every non-special id decodes to EXACTLY ONE printable character:

      * ids 0..255 ride the GPT-2 byte<->unicode table (bytes_to_unicode):
        printable ASCII maps to itself, so ordinary prompt text encodes to
        the same ids ByteTokenizer produces;
      * PAD/BOS/EOS decode to "" (specials are invisible, as in real
        vocabs);
      * ids 259..vocab_size-1 map into the Unicode private use area
        (U+E000 + id), distinct and reversible.

    decode(encode(text)) == text for any text of mapped characters, and
    encode(decode([id])) == [id] for every non-special id."""

    PAD = 256
    BOS = 257
    EOS = 258

    _PUA = 0xE000

    def __init__(self, vocab_size: int = 512):
        if vocab_size < 259:
            raise ValueError("DebugTokenizer needs vocab_size >= 259")
        self.vocab_size = vocab_size
        b2u, u2b = _byte_maps()
        self._id2ch = {i: b2u[i] for i in range(256)}
        for i in range(259, vocab_size):
            self._id2ch[i] = chr(self._PUA + i)
        self._ch2id = {c: i for i, c in self._id2ch.items()}

    def encode(self, text: str, bos: bool = True,
               eos: bool = False) -> List[int]:
        ids = []
        for ch in text:
            known = self._ch2id.get(ch)
            if known is not None:
                ids.append(known)
            else:
                # unmapped chars fall back to their UTF-8 bytes (byte ids
                # round-trip through the table), same ids ByteTokenizer
                # would produce
                ids.extend(ch.encode("utf-8"))
        if bos:
            ids = [self.BOS] + ids
        if eos:
            ids = ids + [self.EOS]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return "".join(self._id2ch.get(i, "") for i in ids)

    def decode_token(self, token: int) -> str:
        return self._id2ch.get(token, "")


class StreamingDecoder:
    """Accumulates byte tokens and yields complete UTF-8 characters — what the
    SSE token stream sends so clients never see broken codepoints.

    BPE tokenizers emit whole string pieces per token, so their streaming
    decode is just decode_token; only byte-level tokenizers need the UTF-8
    boundary buffering."""

    def __init__(self, tokenizer=None):
        self.tokenizer = tokenizer or ByteTokenizer()
        self._buf = bytearray()
        # byte-level BPE pieces are raw bytes that can split a codepoint
        # mid-token (decode_token_bytes) — they buffer like byte tokens;
        # char-level BPE pieces are whole strings (no buffering needed)
        self._byte_pieces = hasattr(self.tokenizer, "decode_token_bytes")
        self._piecewise = (not self._byte_pieces
                           and not isinstance(self.tokenizer, ByteTokenizer))

    def push(self, token: int) -> str:
        from .. import native

        if self._piecewise:
            return self.tokenizer.decode_token(token)
        if self._byte_pieces:
            self._buf.extend(self.tokenizer.decode_token_bytes(token))
        elif not (0 <= token < 256):
            return ""
        else:
            self._buf.append(token)
        # boundary scan in C (pure-python mirror when the lib is absent):
        # emit every complete codepoint, keep the valid-but-incomplete tail
        n = native.utf8_complete_prefix(bytes(self._buf))
        if n == 0:
            return ""
        text = bytes(self._buf[:n]).decode("utf-8", errors="replace")
        del self._buf[:n]
        return text

    def flush(self) -> str:
        text = self._buf.decode("utf-8", errors="replace")
        self._buf.clear()
        return text


class BPETokenizer:
    """Greedy byte-pair tokenizer over a {token_string: id} vocab + ranked merges.

    File format: JSON {"vocab": {...}, "merges": ["a b", ...]} — the common
    interchange shape. Used when a real model vocabulary is provided at deploy
    time; examples default to ByteTokenizer.
    """

    def __init__(self, vocab: Dict[str, int], merges: List[str],
                 bos_token: str = "<s>", eos_token: str = "</s>"):
        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.ranks = {tuple(m.split(" ")): i for i, m in enumerate(merges)}
        self.bos_id = vocab.get(bos_token)
        self.eos_id = vocab.get(eos_token)
        self.vocab_size = max(vocab.values()) + 1 if vocab else 0
        self._native = self._build_native(merges)

    # ByteTokenizer-compatible special-token surface, so serving code can
    # swap tokenizers via config without branching (-1 = "no such token",
    # which never matches a generated id)
    @property
    def BOS(self) -> int:
        return self.bos_id if self.bos_id is not None else -1

    @property
    def EOS(self) -> int:
        return self.eos_id if self.eos_id is not None else -1

    def decode_token(self, token: int) -> str:
        """Single-token streaming decode: BPE pieces are whole strings."""
        if token in (self.bos_id, self.eos_id):
            return ""
        return self.inv_vocab.get(token, "")

    def _build_native(self, merges: List[str]):
        """Hot-path merge loop in C++ when every merge is id-representable
        (left, right, AND merged piece all in vocab — true for real model
        vocabs); otherwise stay on the python string-level path."""
        from .. import native

        if not merges or not native.available():
            return None
        triples = []
        for m in merges:
            left, _, right = m.partition(" ")
            lid, rid = self.vocab.get(left), self.vocab.get(right)
            mid = self.vocab.get(left + right)
            if lid is None or rid is None or mid is None:
                return None
            triples.append((lid, rid, mid))
        try:
            return native.BPECore(triples)
        except RuntimeError:
            return None

    @classmethod
    def from_file(cls, path: str, **kw) -> "BPETokenizer":
        with open(path, "r", encoding="utf-8") as fp:
            data = json.load(fp)
        return cls(data["vocab"], data.get("merges", []), **kw)

    def _bpe(self, word: List[str]) -> List[str]:
        while len(word) > 1:
            pairs = [(self.ranks.get((word[i], word[i + 1]), float("inf")), i)
                     for i in range(len(word) - 1)]
            best_rank, best_i = min(pairs)
            if best_rank == float("inf"):
                break
            word = word[:best_i] + [word[best_i] + word[best_i + 1]] + word[best_i + 2:]
        return word

    def encode(self, text: str, bos: bool = True, eos: bool = False) -> List[int]:
        ids: List[int] = []
        if bos and self.bos_id is not None:
            ids.append(self.bos_id)
        char_ids = ([self.vocab[ch] for ch in text]
                    if self._native is not None and
                    all(ch in self.vocab for ch in text) else None)
        if char_ids is not None:
            ids.extend(self._native.encode(char_ids))
        else:
            for piece in self._bpe(list(text)):
                if piece in self.vocab:
                    ids.append(self.vocab[piece])
                else:
                    ids.extend(self.vocab.get(ch, 0) for ch in piece)
        if eos and self.eos_id is not None:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return "".join(self.inv_vocab.get(i, "") for i in ids
                       if i not in (self.bos_id, self.eos_id))


# ---------------------------------------------------------------------------
# Byte-level BPE (the real Llama-3 / GPT-2 vocab family)
# ---------------------------------------------------------------------------

def bytes_to_unicode() -> Dict[int, str]:
    """The standard GPT-2 byte<->unicode table: every one of the 256 byte
    values maps to a printable unicode char so BPE vocab pieces are plain
    strings. Printable ASCII/latin ranges map to themselves; the rest shift
    up past 255 in discovery order. This is the published convention every
    byte-level vocab (GPT-2, Llama-3, Qwen) is keyed in — reimplementing it
    is the price of reading those vocab files with zero deps."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


_B2U = None
_U2B = None


def _byte_maps():
    global _B2U, _U2B
    if _B2U is None:
        _B2U = bytes_to_unicode()
        _U2B = {c: b for b, c in _B2U.items()}
    return _B2U, _U2B


# Llama-3's pre-tokenizer split pattern (the tiktoken cl100k family).
# Needs the `regex` module for \p classes; a conservative fallback splits
# on whitespace boundaries only (less compression, identical reversibility).
_LLAMA3_SPLIT = (r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+"
                 r"|\p{N}{1,3}| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+"
                 r"|\s+(?!\S)|\s+")


class ByteLevelBPETokenizer:
    """Byte-level BPE over a real model vocabulary (Llama-3/GPT-2 family).

    vocab keys are strings in the byte-unicode space (bytes_to_unicode);
    merges rank adjacent-pair fusions. When merges are absent (tiktoken-
    format vocabs) the token id IS the rank — the two schemes produce the
    same greedy segmentation because tiktoken vocabs are rank-ordered by
    construction.

    Encoding: text -> pre-tokenizer split (regex) -> per-piece UTF-8 bytes
    -> byte-unicode chars -> greedy lowest-rank merges -> ids. Special
    tokens (<|begin_of_text|> etc.) are matched exactly BEFORE the split so
    prompt templates tokenize correctly.

    Parity target: the reference keeps request-path text processing inside
    the serving process rather than a sidecar (SURVEY §7.5); this class is
    what VOCAB_PATH deploys for real checkpoints, next to
    weights.load_llama_safetensors.
    """

    def __init__(self, vocab: Dict[str, int], merges: Optional[List[str]] = None,
                 special_tokens: Optional[Dict[str, int]] = None,
                 bos_token: str = "<|begin_of_text|>",
                 eos_token: str = "<|end_of_text|>"):
        self.vocab = vocab
        self.special_tokens = dict(special_tokens or {})
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.inv_special = {v: k for k, v in self.special_tokens.items()}
        # Merge lookup is keyed by the (left, right) PAIR, not the fused
        # string — two different pairs can concatenate to the same piece
        # and only the listed pair is a rule (HF BPE semantics). In
        # tiktoken rank-mode there are no explicit rules: any adjacent pair
        # whose fusion exists in the vocab merges, ranked by the fused
        # piece's id (tiktoken's own algorithm), so the key IS the fusion.
        if merges:
            self._pair_ranks: Optional[Dict[tuple, int]] = {}
            for i, m in enumerate(merges):
                left, _, right = m.partition(" ")
                self._pair_ranks.setdefault((left, right), i)
        else:
            self._pair_ranks = None
        self._fused_ranks = dict(vocab)
        self.bos_id = self.special_tokens.get(bos_token, vocab.get(bos_token))
        self.eos_id = self.special_tokens.get(eos_token, vocab.get(eos_token))
        all_ids = list(vocab.values()) + list(self.special_tokens.values())
        self.vocab_size = max(all_ids) + 1 if all_ids else 0
        self._split = self._compile_split()
        # longest-first exact matcher for special tokens inside encode()
        import re as _re

        self._special_re = (_re.compile("|".join(
            _re.escape(t) for t in sorted(self.special_tokens,
                                          key=len, reverse=True)))
            if self.special_tokens else None)

    @staticmethod
    def _compile_split():
        try:
            import regex

            return regex.compile(_LLAMA3_SPLIT)
        except ImportError:  # pragma: no cover - regex ships with jax deps
            import re

            return re.compile(r"\s+|\S+")

    # ByteTokenizer-compatible surface
    @property
    def BOS(self) -> int:
        return self.bos_id if self.bos_id is not None else -1

    @property
    def EOS(self) -> int:
        return self.eos_id if self.eos_id is not None else -1

    def _bpe(self, chars: List[str]) -> List[str]:
        """Greedy lowest-rank adjacent merge until no fusable pair remains."""
        pair_ranks = self._pair_ranks
        fused_ranks = self._fused_ranks
        word = chars
        while len(word) > 1:
            best_rank = None
            best_i = -1
            for i in range(len(word) - 1):
                if pair_ranks is not None:
                    r = pair_ranks.get((word[i], word[i + 1]))
                else:
                    r = fused_ranks.get(word[i] + word[i + 1])
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_i = r, i
            if best_i < 0:
                break
            word = (word[:best_i] + [word[best_i] + word[best_i + 1]]
                    + word[best_i + 2:])
        return word

    def _encode_text(self, text: str) -> List[int]:
        b2u, _ = _byte_maps()
        ids: List[int] = []
        for piece in self._split.findall(text):
            mapped = [b2u[b] for b in piece.encode("utf-8")]
            for tok in self._bpe(mapped):
                tid = self.vocab.get(tok)
                if tid is None:
                    # byte-level vocabs contain every single byte; this
                    # only triggers on truncated vocab fixtures
                    ids.extend(self.vocab.get(c, 0) for c in tok)
                else:
                    ids.append(tid)
        return ids

    def encode(self, text: str, bos: bool = True, eos: bool = False,
               parse_special: bool = False) -> List[int]:
        """parse_special=False (the default) treats special-token strings in
        `text` as plain text — the safe mode for untrusted request prompts
        (a client typing '<|eot_id|>' must not forge a turn boundary;
        tiktoken's allowed_special discipline). Chat-template builders that
        intentionally embed specials pass parse_special=True."""
        ids: List[int] = []
        if bos and self.bos_id is not None:
            ids.append(self.bos_id)
        if self._special_re is None or not parse_special:
            ids.extend(self._encode_text(text))
        else:
            pos = 0
            for m in self._special_re.finditer(text):
                if m.start() > pos:
                    ids.extend(self._encode_text(text[pos:m.start()]))
                ids.append(self.special_tokens[m.group()])
                pos = m.end()
            if pos < len(text):
                ids.extend(self._encode_text(text[pos:]))
        if eos and self.eos_id is not None:
            ids.append(self.eos_id)
        return ids

    def decode_token_bytes(self, token: int) -> bytes:
        """Raw bytes of one token (StreamingDecoder buffers these so SSE
        never emits a torn codepoint — byte-level pieces can split UTF-8)."""
        if token in self.inv_special or token in (self.bos_id, self.eos_id):
            return b""
        piece = self.inv_vocab.get(token)
        if piece is None:
            return b""
        _, u2b = _byte_maps()
        return bytes(u2b[c] for c in piece)

    def decode_token(self, token: int) -> str:
        return self.decode_token_bytes(token).decode("utf-8", errors="ignore")

    def decode(self, ids: Sequence[int]) -> str:
        data = b"".join(self.decode_token_bytes(i) for i in ids)
        return data.decode("utf-8", errors="replace")

    # ---- loaders ---------------------------------------------------------

    @classmethod
    def from_tokenizer_json(cls, path: str, data: Optional[dict] = None,
                            **kw) -> "ByteLevelBPETokenizer":
        """Load an HF `tokenizer.json` (the file real Llama-3 checkpoints
        ship): model.vocab + model.merges + added_tokens. Merges appear as
        "a b" strings (classic) or [a, b] pairs (tokenizers>=0.20).
        `data` skips the re-parse when the caller already json.load()ed the
        file (a real tokenizer.json is ~9 MB)."""
        if data is None:
            with open(path, "r", encoding="utf-8") as fp:
                data = json.load(fp)
        model = data.get("model", {})
        vocab = model.get("vocab", {})
        merges_raw = model.get("merges", [])
        merges = [m if isinstance(m, str) else " ".join(m)
                  for m in merges_raw]
        specials = {t["content"]: t["id"]
                    for t in data.get("added_tokens", [])
                    if t.get("special", True)}
        return cls(vocab, merges, special_tokens=specials, **kw)

    @classmethod
    def from_tiktoken(cls, path: str,
                      special_tokens: Optional[Dict[str, int]] = None,
                      **kw) -> "ByteLevelBPETokenizer":
        """Load a tiktoken-format vocab (Meta's llama-3 distribution:
        one `base64(token_bytes) rank` pair per line). Pieces arrive as raw
        bytes; they re-key into the byte-unicode space so one encode path
        serves both formats. Merge ranks are the ids themselves."""
        import base64

        b2u, _ = _byte_maps()
        vocab: Dict[str, int] = {}
        with open(path, "r", encoding="utf-8") as fp:
            for line in fp:
                line = line.strip()
                if not line:
                    continue
                b64, _, rank = line.partition(" ")
                piece = "".join(b2u[b] for b in base64.b64decode(b64))
                vocab[piece] = int(rank)
        if special_tokens is None:
            # Meta's llama-3 special-token layout: specials start right
            # after the base vocab (n=128000 for the real model)
            n = len(vocab)
            special_tokens = {
                "<|begin_of_text|>": n, "<|end_of_text|>": n + 1,
                "<|finetune_right_pad_id|>": n + 4,
                "<|start_header_id|>": n + 6, "<|end_header_id|>": n + 7,
                "<|eom_id|>": n + 8, "<|eot_id|>": n + 9,
                "<|python_tag|>": n + 10,
            }
        return cls(vocab, None, special_tokens=special_tokens, **kw)
