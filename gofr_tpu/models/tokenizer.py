"""Tokenizers for serving examples: byte-level (zero-dependency) + BPE loader.

A serving framework needs a tokenizer in the request path (SURVEY.md §7.5
"tokenizer in Go" -> here in the serving process, no Python-ecosystem
dependency at runtime). ByteTokenizer is exact and reversible for any UTF-8
text; BPETokenizer loads a vocab/merges file when a real model vocabulary is
available (none ships in this zero-egress environment).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence


class ByteTokenizer:
    """256 byte tokens + specials. vocab: [bytes 0..255, <pad>, <bos>, <eos>]."""

    PAD = 256
    BOS = 257
    EOS = 258

    vocab_size = 259

    def encode(self, text: str, bos: bool = True, eos: bool = False) -> List[int]:
        ids = list(text.encode("utf-8"))
        if bos:
            ids = [self.BOS] + ids
        if eos:
            ids = ids + [self.EOS]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")

    def decode_token(self, token: int) -> str:
        """Single-token streaming decode; multibyte UTF-8 may yield ''."""
        if 0 <= token < 256:
            return bytes([token]).decode("utf-8", errors="ignore")
        return ""


class StreamingDecoder:
    """Accumulates byte tokens and yields complete UTF-8 characters — what the
    SSE token stream sends so clients never see broken codepoints.

    BPE tokenizers emit whole string pieces per token, so their streaming
    decode is just decode_token; only byte-level tokenizers need the UTF-8
    boundary buffering."""

    def __init__(self, tokenizer=None):
        self.tokenizer = tokenizer or ByteTokenizer()
        self._buf = bytearray()
        self._piecewise = not isinstance(self.tokenizer, ByteTokenizer)

    def push(self, token: int) -> str:
        from .. import native

        if self._piecewise:
            return self.tokenizer.decode_token(token)
        if not (0 <= token < 256):
            return ""
        self._buf.append(token)
        # boundary scan in C (pure-python mirror when the lib is absent):
        # emit every complete codepoint, keep the valid-but-incomplete tail
        n = native.utf8_complete_prefix(bytes(self._buf))
        if n == 0:
            return ""
        text = bytes(self._buf[:n]).decode("utf-8", errors="replace")
        del self._buf[:n]
        return text

    def flush(self) -> str:
        text = self._buf.decode("utf-8", errors="replace")
        self._buf.clear()
        return text


class BPETokenizer:
    """Greedy byte-pair tokenizer over a {token_string: id} vocab + ranked merges.

    File format: JSON {"vocab": {...}, "merges": ["a b", ...]} — the common
    interchange shape. Used when a real model vocabulary is provided at deploy
    time; examples default to ByteTokenizer.
    """

    def __init__(self, vocab: Dict[str, int], merges: List[str],
                 bos_token: str = "<s>", eos_token: str = "</s>"):
        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.ranks = {tuple(m.split(" ")): i for i, m in enumerate(merges)}
        self.bos_id = vocab.get(bos_token)
        self.eos_id = vocab.get(eos_token)
        self.vocab_size = max(vocab.values()) + 1 if vocab else 0
        self._native = self._build_native(merges)

    # ByteTokenizer-compatible special-token surface, so serving code can
    # swap tokenizers via config without branching (-1 = "no such token",
    # which never matches a generated id)
    @property
    def BOS(self) -> int:
        return self.bos_id if self.bos_id is not None else -1

    @property
    def EOS(self) -> int:
        return self.eos_id if self.eos_id is not None else -1

    def decode_token(self, token: int) -> str:
        """Single-token streaming decode: BPE pieces are whole strings."""
        if token in (self.bos_id, self.eos_id):
            return ""
        return self.inv_vocab.get(token, "")

    def _build_native(self, merges: List[str]):
        """Hot-path merge loop in C++ when every merge is id-representable
        (left, right, AND merged piece all in vocab — true for real model
        vocabs); otherwise stay on the python string-level path."""
        from .. import native

        if not merges or not native.available():
            return None
        triples = []
        for m in merges:
            left, _, right = m.partition(" ")
            lid, rid = self.vocab.get(left), self.vocab.get(right)
            mid = self.vocab.get(left + right)
            if lid is None or rid is None or mid is None:
                return None
            triples.append((lid, rid, mid))
        try:
            return native.BPECore(triples)
        except RuntimeError:
            return None

    @classmethod
    def from_file(cls, path: str, **kw) -> "BPETokenizer":
        with open(path, "r", encoding="utf-8") as fp:
            data = json.load(fp)
        return cls(data["vocab"], data.get("merges", []), **kw)

    def _bpe(self, word: List[str]) -> List[str]:
        while len(word) > 1:
            pairs = [(self.ranks.get((word[i], word[i + 1]), float("inf")), i)
                     for i in range(len(word) - 1)]
            best_rank, best_i = min(pairs)
            if best_rank == float("inf"):
                break
            word = word[:best_i] + [word[best_i] + word[best_i + 1]] + word[best_i + 2:]
        return word

    def encode(self, text: str, bos: bool = True, eos: bool = False) -> List[int]:
        ids: List[int] = []
        if bos and self.bos_id is not None:
            ids.append(self.bos_id)
        char_ids = ([self.vocab[ch] for ch in text]
                    if self._native is not None and
                    all(ch in self.vocab for ch in text) else None)
        if char_ids is not None:
            ids.extend(self._native.encode(char_ids))
        else:
            for piece in self._bpe(list(text)):
                if piece in self.vocab:
                    ids.append(self.vocab[piece])
                else:
                    ids.extend(self.vocab.get(ch, 0) for ch in piece)
        if eos and self.eos_id is not None:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return "".join(self.inv_vocab.get(i, "") for i in ids
                       if i not in (self.bos_id, self.eos_id))
