"""Mixture-of-Experts Llama variant: top-k routed FFN, expert-parallel ready.

Expert parallelism (the "EP" strategy, SURVEY.md §2.5): expert weights carry a
leading E axis sharded over the "ep" mesh axis (parallel/sharding.py). Routing
uses the dense-dispatch formulation — every expert computes every token,
gating weights zero the non-selected — which keeps shapes static and lets XLA
shard the E axis with a psum-style combine; capacity-based sparse dispatch is
a later optimisation, not a semantic change.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, _attention_block_nocache, _np_dtype, rms_norm


@dataclasses.dataclass(frozen=True)
class MoELlamaConfig(LlamaConfig):
    n_experts: int = 8
    experts_per_token: int = 2

    @classmethod
    def debug(cls) -> "MoELlamaConfig":
        return cls(vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                   ffn_dim=128, max_seq_len=256, dtype="float32",
                   n_experts=4, experts_per_token=2)


def moe_llama_init(cfg: MoELlamaConfig, seed: int = 0) -> Dict[str, Any]:
    dtype = _np_dtype(cfg.dtype)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 9)
    L, D, H, Hkv, dh, F, V, E = (cfg.n_layers, cfg.dim, cfg.n_heads,
                                 cfg.n_kv_heads, cfg.head_dim, cfg.ffn_dim,
                                 cfg.vocab_size, cfg.n_experts)

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dtype)

    return {
        "tok_emb": init(keys[0], (V, D), D),
        "layers": {
            "wq": init(keys[1], (L, D, H * dh), D),
            "wk": init(keys[2], (L, D, Hkv * dh), D),
            "wv": init(keys[3], (L, D, Hkv * dh), D),
            "wo": init(keys[4], (L, H * dh, D), H * dh),
            "w_router": init(keys[8], (L, D, E), D),
            "w_gate": init(keys[5], (L, E, D, F), D),
            "w_up": init(keys[6], (L, E, D, F), D),
            "w_down": init(keys[7], (L, E, F, D), F),
            "attn_norm": jnp.ones((L, D), dtype=dtype),
            "ffn_norm": jnp.ones((L, D), dtype=dtype),
        },
        "final_norm": jnp.ones((D,), dtype=dtype),
        "lm_head": init(keys[0], (D, V), D),
    }


def moe_ffn(x, layer, cfg: MoELlamaConfig):
    """Top-k routed SwiGLU experts, dense dispatch.

    x: [B, T, D] -> [B, T, D]. Also returns the router's load-balancing
    auxiliary loss (Switch-style: E * sum_e f_e * p_e).
    """
    E, K = cfg.n_experts, cfg.experts_per_token
    normed = rms_norm(x, layer["ffn_norm"], cfg.rms_eps)

    router_logits = (normed @ layer["w_router"]).astype(jnp.float32)  # [B,T,E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, K)                        # [B,T,K]
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    gates = jnp.sum(jax.nn.one_hot(top_idx, E, dtype=probs.dtype)
                    * top_vals[..., None], axis=-2)                    # [B,T,E]

    # dense dispatch: every expert processes every token, gate weights select
    gate_proj = jnp.einsum("btd,edf->betf", normed, layer["w_gate"])
    up_proj = jnp.einsum("btd,edf->betf", normed, layer["w_up"])
    expert_out = jnp.einsum("betf,efd->betd",
                            jax.nn.silu(gate_proj) * up_proj, layer["w_down"])
    out = jnp.einsum("betd,bte->btd", expert_out, gates.astype(expert_out.dtype))

    # load-balancing aux loss: fraction of tokens routed vs router mass
    me = jnp.mean(gates > 0, axis=(0, 1)).astype(jnp.float32)  # routed fraction
    ce = jnp.mean(probs, axis=(0, 1))
    aux_loss = E * jnp.sum(me * ce)
    return out.astype(x.dtype), aux_loss


def moe_llama_forward_nocache(params, cfg: MoELlamaConfig, tokens):
    """Training forward: causal attention + MoE FFN. Returns (logits, aux_loss)."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    x = params["tok_emb"][tokens]

    def body(carry, layer):
        x, aux = carry
        x = x + _attention_block_nocache(x, layer, positions, cfg)
        ffn_out, layer_aux = moe_ffn(x, layer, cfg)
        x = x + ffn_out
        return (x, aux + layer_aux), None

    (x, aux), _ = jax.lax.scan(body, (x, jnp.float32(0.0)), params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, aux / cfg.n_layers
