"""Cron scheduler: 5-field spec parser + 1-second-resolution minute ticker.

Parity: reference pkg/gofr/cron.go — parser supporting wildcards, steps (*/5),
ranges (1-5), lists (1,3,5) (:86-216); a ticker fires due jobs in their own
threads with a fresh root span and a no-op Request (:218-278, 326-347);
AddJob validation (:281-295).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set

from .context import Context

FIELD_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]  # min hour dom month dow


class CronParseError(ValueError):
    pass


def _parse_field(field: str, lo: int, hi: int) -> Set[int]:
    out: Set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, _, step_s = part.partition("/")
            try:
                step = int(step_s)
            except ValueError as exc:
                raise CronParseError(f"invalid step {step_s!r}") from exc
            if step <= 0:
                raise CronParseError(f"invalid step {step}")
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, _, b = part.partition("-")
            try:
                start, end = int(a), int(b)
            except ValueError as exc:
                raise CronParseError(f"invalid range {part!r}") from exc
        else:
            try:
                start = end = int(part)
            except ValueError as exc:
                raise CronParseError(f"invalid value {part!r}") from exc
        if start < lo or end > hi or start > end:
            raise CronParseError(f"value {part!r} out of range [{lo},{hi}]")
        out.update(range(start, end + 1, step))
    return out


class Schedule:
    def __init__(self, spec: str):
        fields = spec.split()
        if len(fields) != 5:
            raise CronParseError(f"cron spec must have 5 fields, got {len(fields)}")
        self.minutes, self.hours, self.days, self.months, self.weekdays = (
            _parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, FIELD_RANGES))

    def matches(self, t: Optional[time.struct_time] = None) -> bool:
        t = t or time.localtime()
        dow = (t.tm_wday + 1) % 7  # python: Mon=0; cron: Sun=0
        return (t.tm_min in self.minutes and t.tm_hour in self.hours
                and t.tm_mday in self.days and t.tm_mon in self.months
                and dow in self.weekdays)


class _NoopRequest:
    """The empty Request cron handlers receive (cron.go:326-347)."""

    def param(self, key: str) -> str:
        return ""

    def path_param(self, key: str) -> str:
        return ""

    def host_name(self) -> str:
        return "cron://"

    def bind(self, target=None):
        return target if target is not None else {}


class Crontab:
    def __init__(self, container):
        self.container = container
        self.jobs: List[tuple] = []  # (name, Schedule, fn)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_job(self, spec: str, name: str, fn: Callable[[Context], None]) -> None:
        schedule = Schedule(spec)  # raises CronParseError on a bad spec
        with self._lock:
            self.jobs.append((name, schedule, fn))

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, name="cron", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        last_minute = -1
        while not self._stop.is_set():
            now = time.localtime()
            if now.tm_min != last_minute:
                last_minute = now.tm_min
                self._tick(now)
            self._stop.wait(1.0)

    def _tick(self, now: time.struct_time) -> None:
        with self._lock:
            due = [(name, fn) for name, sched, fn in self.jobs if sched.matches(now)]
        for name, fn in due:
            threading.Thread(target=self._run_job, args=(name, fn),
                             name=f"cron-{name}", daemon=True).start()

    def _run_job(self, name: str, fn) -> None:
        container = self.container
        span = None
        if container.tracer is not None:
            span = container.tracer.start_span(f"cron {name}")
        request = _NoopRequest()
        request.span = span
        ctx = Context(request=request, container=container)
        try:
            fn(ctx)
        except Exception as exc:  # noqa: BLE001 - a failing job must not kill cron
            container.logger.errorf("cron job %s failed: %s", name, exc)
            if span is not None:
                span.set_status(False, str(exc))
        finally:
            if span is not None:
                span.end()
