"""Router: `{param}` path patterns, method dispatch, middleware chain.

Parity: reference pkg/gofr/http/router.go:14-49 (gorilla/mux wrapper installing
the default Tracer -> Logging -> CORS -> Metrics chain, per-route otel wrap,
UseMiddleware appending user middleware).
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .request import Request
from .responder import Response

# The terminal handler type middleware wrap: Request -> Response.
WireHandler = Callable[[Request], Response]
Middleware = Callable[[WireHandler], WireHandler]


def _compile(pattern: str) -> re.Pattern:
    # "/users/{id}/posts/{pid}" -> ^/users/(?P<id>[^/]+)/posts/(?P<pid>[^/]+)$
    out = []
    for part in re.split(r"(\{[a-zA-Z_][a-zA-Z0-9_]*\})", pattern):
        if part.startswith("{") and part.endswith("}"):
            out.append(f"(?P<{part[1:-1]}>[^/]+)")
        else:
            out.append(re.escape(part))
    return re.compile("^" + "".join(out) + "/?$")


class Route:
    def __init__(self, method: str, pattern: str, handler: WireHandler):
        self.method = method.upper()
        self.pattern = pattern
        self.regex = _compile(pattern)
        self.handler = handler


class Router:
    def __init__(self):
        self._routes: List[Route] = []
        self._middleware: List[Middleware] = []
        self._lock = threading.Lock()
        self._chain_cache: Optional[WireHandler] = None
        self.not_found: Optional[WireHandler] = None

    def add(self, method: str, pattern: str, handler: WireHandler) -> None:
        with self._lock:
            self._routes.append(Route(method, pattern, handler))
            self._chain_cache = None

    def use_middleware(self, *mws: Middleware) -> None:
        with self._lock:
            self._middleware.extend(mws)
            self._chain_cache = None

    def routes(self) -> List[Tuple[str, str]]:
        return [(r.method, r.pattern) for r in self._routes]

    # -- dispatch -------------------------------------------------------------
    def _match(self, request: Request) -> Tuple[Optional[Route], bool]:
        """Returns (route, path_matched_any_method)."""
        path_matched = False
        for route in self._routes:
            m = route.regex.match(request.path)
            if not m:
                continue
            path_matched = True
            if route.method == request.method or (request.method == "HEAD" and route.method == "GET"):
                request.path_params = {k: v for k, v in m.groupdict().items() if v is not None}
                request.route_pattern = route.pattern
                return route, True
        return None, path_matched

    def _terminal(self, request: Request) -> Response:
        route, path_matched = self._match(request)
        if route is not None:
            return route.handler(request)
        if path_matched:
            return Response(status=405, headers={"Content-Type": "application/json"},
                            body=b'{"error":{"message":"method not allowed"}}')
        if self.not_found is not None:
            return self.not_found(request)
        return Response(status=404, headers={"Content-Type": "application/json"},
                        body=b'{"error":{"message":"route not registered"}}')

    def dispatch(self, request: Request) -> Response:
        with self._lock:
            chain = self._chain_cache
            if chain is None:
                chain = self._terminal
                for mw in reversed(self._middleware):
                    chain = mw(chain)
                self._chain_cache = chain
        return chain(request)
