"""HTTP Request wrapper: params, path params, JSON/multipart bind.

Parity: reference pkg/gofr/http/request.go:34-121 (NewRequest, Param/PathParam
via mux.Vars, Bind JSON or multipart with a 32 MB cap) and
pkg/gofr/request.go:8-15 (the transport-agnostic Request interface:
Context, Param, PathParam, Bind, HostName).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs, urlsplit

from .errors import HTTPError

MAX_BODY_BYTES = 32 << 20  # request.go:18


class BindError(HTTPError):
    status_code = 400


class Request:
    """One inbound HTTP request. Instances are built by the server glue and
    enriched by the router (path_params) and middleware (span)."""

    def __init__(
        self,
        method: str,
        target: str,
        headers: Optional[Dict[str, str]] = None,
        body: bytes = b"",
        client_addr: str = "",
    ):
        self.method = method.upper()
        split = urlsplit(target)
        self.path = split.path or "/"
        self.query: Dict[str, List[str]] = parse_qs(split.query, keep_blank_values=True)
        self.headers = {k.lower(): v for k, v in (headers or {}).items()}
        self.body = body or b""
        self.client_addr = client_addr
        self.path_params: Dict[str, str] = {}
        self.route_pattern: Optional[str] = None  # set by the router on match
        self.span = None  # set by tracer middleware
        self.traceparent: Optional[str] = None  # raw W3C header, ditto
        self.auth_subject: Optional[str] = None  # set by auth middleware
        self.context: Dict[str, Any] = {}  # request-scoped values

    # -- reference Request interface -----------------------------------------
    def param(self, key: str) -> str:
        vals = self.query.get(key)
        return vals[0] if vals else ""

    def params(self, key: str) -> List[str]:
        return list(self.query.get(key, []))

    def path_param(self, key: str) -> str:
        return self.path_params.get(key, "")

    def host_name(self) -> str:
        proto = self.headers.get("x-forwarded-proto", "http")
        return f"{proto}://{self.headers.get('host', '')}"

    def header(self, key: str) -> str:
        return self.headers.get(key.lower(), "")

    def bind(self, target: Any = None) -> Any:
        """Decode the body into `target`.

        - no target: returns parsed JSON (dict/list/scalar)
        - a dataclass type: instantiates it from the JSON object's fields
        - a dict instance: updated in place
        - any other instance: JSON object keys set as attributes
        Content-Type multipart/form-data binds form fields instead (file parts
        exposed as bytes), mirroring bindMultipart (request.go:97-121).
        """
        if len(self.body) > MAX_BODY_BYTES:
            raise BindError("request body exceeds 32 MB limit")
        ctype = self.headers.get("content-type", "")
        if ctype.startswith("multipart/form-data"):
            data = self._parse_multipart(ctype)
        else:
            try:
                data = json.loads(self.body.decode("utf-8")) if self.body else {}
            except (json.JSONDecodeError, UnicodeDecodeError) as exc:
                raise BindError(f"invalid JSON body: {exc}") from exc

        if target is None:
            return data
        if isinstance(target, type) and dataclasses.is_dataclass(target):
            if not isinstance(data, dict):
                raise BindError("JSON object required to bind a dataclass")
            field_names = {f.name for f in dataclasses.fields(target)}
            try:
                return target(**{k: v for k, v in data.items() if k in field_names})
            except TypeError as exc:
                raise BindError(f"missing or invalid fields: {exc}") from exc
        if isinstance(target, dict):
            if not isinstance(data, dict):
                raise BindError("JSON object required to bind a dict")
            target.update(data)
            return target
        if not isinstance(data, dict):
            raise BindError("JSON object required to bind an object")
        for k, v in data.items():
            setattr(target, k, v)
        return target

    def _parse_multipart(self, ctype: str) -> Dict[str, Any]:
        import email.parser
        import email.policy

        raw = b"Content-Type: " + ctype.encode() + b"\r\n\r\n" + self.body
        msg = email.parser.BytesParser(policy=email.policy.default).parsebytes(raw)
        out: Dict[str, Any] = {}
        for part in msg.iter_parts():
            name = part.get_param("name", header="content-disposition")
            if not name:
                continue
            filename = part.get_filename()
            payload = part.get_payload(decode=True)
            if filename:
                out[name] = {"filename": filename, "content": payload}
            else:
                out[name] = payload.decode("utf-8", "replace") if payload else ""
        return out
