"""Response types + the uniform JSON-envelope Responder.

Parity: reference pkg/gofr/http/responder.go:24-74 (Respond -> {data}/{error}
envelope, status from method POST->201 DELETE->204 and from error type) and
pkg/gofr/http/response/{raw.go,file.go} passthrough types.

TPU-era extension (SURVEY.md §7.5): `Stream` — a generator-backed chunked or
SSE response used by /generate token streaming. The reference's Raw/File
passthrough (responder.go:29-37) is the hook this generalises.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

from .errors import HTTPError, status_from_method


class Response:
    """Wire-level response handed to the server glue."""

    def __init__(self, status: int = 200, headers: Optional[Dict[str, str]] = None,
                 body: bytes = b"", stream: Optional[Iterator[bytes]] = None):
        self.status = status
        self.headers = headers or {}
        self.body = body
        self.stream = stream  # when set, body is ignored and chunks are flushed as produced


# -- passthrough result types a handler may return ---------------------------
class Raw:
    """Marshal `data` as JSON without the {data: ...} envelope (response/raw.go:3-5)."""

    def __init__(self, data: Any):
        self.data = data


class File:
    """Raw bytes with a content type (response/file.go:3-6)."""

    def __init__(self, content: bytes, content_type: str = "application/octet-stream",
                 status: int = 200):
        self.content = content
        self.content_type = content_type
        self.status = status


class Redirect:
    def __init__(self, url: str, status: int = 302):
        self.url = url
        self.status = status


class Stream:
    """Generator-backed streaming body. `sse=True` wraps each chunk as a
    `data: ...\n\n` server-sent event (the /generate token stream)."""

    def __init__(self, chunks: Iterable[Any], content_type: str = "application/octet-stream",
                 sse: bool = False, on_close: Optional[Callable[[], None]] = None):
        self.chunks = chunks
        self.sse = sse
        self.content_type = "text/event-stream" if sse else content_type
        self.on_close = on_close

    def iter_bytes(self) -> Iterator[bytes]:
        try:
            for chunk in self.chunks:
                if self.sse:
                    if not isinstance(chunk, (str, bytes)):
                        chunk = json.dumps(chunk, default=str)
                    if isinstance(chunk, bytes):
                        chunk = chunk.decode("utf-8", "replace")
                    yield f"data: {chunk}\n\n".encode()
                else:
                    if isinstance(chunk, str):
                        chunk = chunk.encode()
                    elif not isinstance(chunk, bytes):
                        chunk = json.dumps(chunk, default=str).encode()
                    yield chunk
        finally:
            if self.on_close is not None:
                self.on_close()


class Responder:
    """Builds the uniform envelope; one per request (created by the handler adapter)."""

    def __init__(self, method: str):
        self.method = method

    def respond(self, data: Any, err: Optional[BaseException]) -> Response:
        if err is not None:
            # duck-typed status_code lets non-HTTP layers (the TPU engine's
            # draining rejection) map to a proper status without importing
            # the transport package
            status = getattr(err, "status_code", None)
            if not isinstance(status, int):
                status = err.status_code if isinstance(err, HTTPError) else 500
            payload = {"error": {"message": getattr(err, "message", None) or str(err)}}
            response = self._json(status, payload)
            # duck-typed retry_after_s (engine sheds: draining, stalled,
            # breaker-open DeviceLostError) becomes the Retry-After header
            # RFC-compliant clients and SDK retry policies act on
            retry_after = getattr(err, "retry_after_s", None)
            if isinstance(retry_after, (int, float)) and retry_after > 0:
                import math

                response.headers["Retry-After"] = str(
                    max(1, int(math.ceil(retry_after))))
            return response

        if isinstance(data, Response):
            return data
        if isinstance(data, Raw):
            return self._json(status_from_method(self.method), data.data)
        if isinstance(data, File):
            return Response(status=data.status, headers={"Content-Type": data.content_type},
                            body=data.content)
        if isinstance(data, Redirect):
            return Response(status=data.status, headers={"Location": data.url})
        if isinstance(data, Stream):
            return Response(status=200, headers={"Content-Type": data.content_type},
                            stream=data.iter_bytes())

        status = status_from_method(self.method)
        if status == 204:
            return Response(status=204)
        return self._json(status, {"data": data})

    @staticmethod
    def _json(status: int, payload: Any) -> Response:
        body = json.dumps(payload, default=_json_default).encode()
        return Response(status=status, headers={"Content-Type": "application/json"}, body=body)


def _json_default(obj: Any) -> Any:
    import dataclasses

    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    if hasattr(obj, "tolist"):  # numpy / jax arrays
        return obj.tolist()
    if hasattr(obj, "item"):
        return obj.item()
    if hasattr(obj, "__dict__"):
        return {k: v for k, v in vars(obj).items() if not k.startswith("_")}
    return str(obj)
