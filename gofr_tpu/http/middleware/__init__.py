"""Default middleware chain: Tracer -> Logging -> CORS -> Metrics, plus auth.

Parity: reference pkg/gofr/http/middleware/ — tracer.go:15-32 (extract W3C
traceparent, span per request), logger.go:69-150 (status-capturing request log
+ panic recovery -> 500), cors.go:6-22, metrics.go:21-42 (app_http_response
histogram by path/method/status), basic_auth.go:18-72, apikey_auth.go:11-57,
oauth.go:53-140 (JWT w/ background JWKS refresh -> oauth_jwks_middleware
validates RS256 against a kid-indexed, background-refreshed JWKSKeySet;
oauth_middleware keeps an HS256 shared-secret path for zero-egress deploys),
validate.go:5-7 (/.well-known bypass for auth).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import time
from typing import Callable, Dict, Iterable, Optional

from ...logging import PrettyPrint
from ..errors import PanicRecovery
from ..request import Request
from ..responder import Response
from ..router import WireHandler

WELL_KNOWN_PREFIX = "/.well-known/"


def _is_well_known(request: Request) -> bool:
    return request.path.startswith(WELL_KNOWN_PREFIX)


# -- tracing ------------------------------------------------------------------
def tracer_middleware(tracer) -> Callable[[WireHandler], WireHandler]:
    def mw(inner: WireHandler) -> WireHandler:
        def handle(request: Request) -> Response:
            # keep the raw header too: handlers thread it through
            # engine.submit(traceparent=...) so the flight recorder can
            # parent engine child spans under the caller's trace even
            # after this span has closed (streamed responses end it
            # before admission)
            request.traceparent = request.headers.get("traceparent")
            span = tracer.start_span(
                f"{request.method} {request.path}",
                traceparent=request.traceparent,
            )
            span.set_attribute("http.method", request.method)
            span.set_attribute("http.target", request.path)
            request.span = span
            try:
                resp = inner(request)
                span.set_attribute("http.status_code", resp.status)
                span.set_status(resp.status < 500)
                resp.headers.setdefault("X-Trace-Id", span.trace_id)
                return resp
            finally:
                span.end()

        return handle

    return mw


# -- request logging + panic recovery ----------------------------------------
class RequestLog(PrettyPrint):
    """Structured request log record (middleware/logger.go:27-42)."""

    def __init__(self, trace_id: str, method: str, uri: str, status: int, duration_us: int, ip: str):
        self.trace_id = trace_id
        self.method = method
        self.uri = uri
        self.status = status
        self.response_time_us = duration_us
        self.ip = ip

    def pretty_print(self, fp) -> None:
        color = 32 if self.status < 400 else (33 if self.status < 500 else 31)
        fp.write(f"{self.trace_id} \x1b[{color}m{self.status}\x1b[0m "
                 f"{self.response_time_us:>8}µs {self.method} {self.uri}")


def logging_middleware(logger) -> Callable[[WireHandler], WireHandler]:
    def mw(inner: WireHandler) -> WireHandler:
        def handle(request: Request) -> Response:
            start = time.time()
            try:
                resp = inner(request)
            except Exception as exc:  # noqa: BLE001 - panic recovery -> 500
                logger.error({"error": str(exc), "path": request.path,
                              "method": request.method, "panic": True})
                err = PanicRecovery()
                resp = Response(status=err.status_code,
                                headers={"Content-Type": "application/json"},
                                body=json.dumps({"error": {"message": err.message}}).encode())
            duration_us = int((time.time() - start) * 1e6)
            trace_id = request.span.trace_id if request.span is not None else ""
            record = RequestLog(trace_id, request.method, request.path, resp.status,
                                duration_us, request.client_addr)
            if resp.status >= 500:
                logger.error(record)
            else:
                logger.info(record)
            return resp

        return handle

    return mw


# -- CORS ---------------------------------------------------------------------
def cors_middleware(allowed_headers: str = "Authorization, Content-Type, x-requested-with, origin, true-client-ip, X-Correlation-ID",
                    allowed_methods: str = "PUT, POST, GET, DELETE, OPTIONS, PATCH") -> Callable[[WireHandler], WireHandler]:
    def mw(inner: WireHandler) -> WireHandler:
        def handle(request: Request) -> Response:
            if request.method == "OPTIONS":
                resp = Response(status=200)
            else:
                resp = inner(request)
            resp.headers.setdefault("Access-Control-Allow-Origin", "*")
            resp.headers.setdefault("Access-Control-Allow-Headers", allowed_headers)
            resp.headers.setdefault("Access-Control-Allow-Methods", allowed_methods)
            return resp

        return handle

    return mw


# -- metrics ------------------------------------------------------------------
def metrics_middleware(metrics) -> Callable[[WireHandler], WireHandler]:
    def mw(inner: WireHandler) -> WireHandler:
        def handle(request: Request) -> Response:
            start = time.time()
            resp = inner(request)
            # label by the matched route template, not the raw path, to bound
            # series cardinality (the reference labels by mux route the same way)
            route = getattr(request, "route_pattern", None) or "unmatched"
            metrics.record_histogram("app_http_response", time.time() - start,
                                     path=route, method=request.method,
                                     status=str(resp.status))
            return resp

        return handle

    return mw


# -- auth ---------------------------------------------------------------------
def _unauthorized(message: str = "Unauthorized") -> Response:
    return Response(status=401, headers={"Content-Type": "application/json",
                                         "WWW-Authenticate": "Basic"},
                    body=json.dumps({"error": {"message": message}}).encode())


def basic_auth_middleware(users: dict, validate_func: Optional[Callable[[str, str], bool]] = None):
    """users: {username: password}. Optional custom validator like the reference's
    EnableBasicAuthWithFunc (basic_auth.go:34-55)."""

    def mw(inner: WireHandler) -> WireHandler:
        def handle(request: Request) -> Response:
            if _is_well_known(request):
                return inner(request)
            header = request.headers.get("authorization", "")
            if not header.startswith("Basic "):
                return _unauthorized()
            try:
                decoded = base64.b64decode(header[6:]).decode("utf-8")
                user, _, password = decoded.partition(":")
            except Exception:  # noqa: BLE001
                return _unauthorized()
            if validate_func is not None:
                ok = validate_func(user, password)
            else:
                expected = users.get(user)
                ok = expected is not None and hmac.compare_digest(expected, password)
            if not ok:
                return _unauthorized()
            request.auth_subject = user
            return inner(request)

        return handle

    return mw


def api_key_auth_middleware(keys: Iterable[str] = (), validate_func: Optional[Callable[[str], bool]] = None):
    keyset = set(keys)

    def mw(inner: WireHandler) -> WireHandler:
        def handle(request: Request) -> Response:
            if _is_well_known(request):
                return inner(request)
            key = request.headers.get("x-api-key", "")
            if not key:
                return _unauthorized()
            ok = validate_func(key) if validate_func is not None else key in keyset
            if not ok:
                return _unauthorized()
            request.auth_subject = "api-key"
            return inner(request)

        return handle

    return mw


# -- JWT (HS256) --------------------------------------------------------------
def _b64url_decode(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def _b64url_encode(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def jwt_encode(claims: dict, secret: str) -> str:
    header = _b64url_encode(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url_encode(json.dumps(claims).encode())
    signing = f"{header}.{payload}".encode()
    sig = hmac.new(secret.encode(), signing, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url_encode(sig)}"


def jwt_decode(token: str, secret: str) -> Optional[dict]:
    parts = token.split(".")
    if len(parts) != 3:
        return None
    signing = f"{parts[0]}.{parts[1]}".encode()
    expected = hmac.new(secret.encode(), signing, hashlib.sha256).digest()
    try:
        if not hmac.compare_digest(expected, _b64url_decode(parts[2])):
            return None
        claims = json.loads(_b64url_decode(parts[1]))
    except Exception:  # noqa: BLE001
        return None
    exp = claims.get("exp")
    if exp is not None and time.time() > float(exp):
        return None
    return claims


# -- JWT (RS256 via JWKS) -----------------------------------------------------
class JWKSKeySet:
    """kid-indexed RSA public keys fetched from a JWKS endpoint, refreshed in
    the background — parity with the reference's OAuth provider polling
    (oauth.go:53-140: NewOAuth spawns a refresh goroutine on an interval).

    Gated on the `cryptography` package for the signature math; construction
    raises cleanly when it is absent (the reference's nil-on-misconfig
    posture is handled by enable_oauth logging and skipping)."""

    def __init__(self, url: str, refresh_interval_s: float = 300.0,
                 logger=None, fetch=None):
        try:
            from cryptography.hazmat.primitives.asymmetric import rsa  # noqa: F401
        except ImportError as exc:  # pragma: no cover - env has it
            raise RuntimeError(
                "RS256 JWKS requires the 'cryptography' package") from exc
        self.url = url
        self.refresh_interval_s = refresh_interval_s
        self.logger = logger
        self._fetch = fetch or self._http_fetch
        self._keys: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.refresh()
        self._thread = threading.Thread(target=self._refresh_loop,
                                        name="jwks-refresh", daemon=True)
        self._thread.start()

    def _http_fetch(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(self.url, timeout=10) as resp:
            return json.loads(resp.read().decode())

    def refresh(self) -> None:
        try:
            doc = self._fetch()
            keys = {}
            for key in doc.get("keys", []):
                if key.get("kty") != "RSA":
                    continue
                kid = key.get("kid", "")
                n = int.from_bytes(_b64url_decode(key["n"]), "big")
                e = int.from_bytes(_b64url_decode(key["e"]), "big")
                keys[kid] = (n, e)
            with self._lock:
                self._keys = keys
        except Exception as exc:  # noqa: BLE001 - keep serving old keys
            if self.logger is not None:
                self.logger.errorf("JWKS refresh from %s failed: %s",
                                   self.url, exc)

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            self.refresh()

    def close(self) -> None:
        self._stop.set()

    def get(self, kid: str):
        with self._lock:
            return self._keys.get(kid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)


def rs256_verify(signing_input: bytes, signature: bytes, n: int, e: int) -> bool:
    """RSASSA-PKCS1-v1_5 SHA-256 verification against a public (n, e)."""
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa

    try:
        pub = rsa.RSAPublicNumbers(e, n).public_key()
        pub.verify(signature, signing_input, padding.PKCS1v15(),
                   hashes.SHA256())
        return True
    except (InvalidSignature, ValueError):
        return False


def jwt_decode_rs256(token: str, keyset: JWKSKeySet) -> Optional[dict]:
    """Validate an RS256 bearer JWT against the JWKS keys (kid-matched)."""
    parts = token.split(".")
    if len(parts) != 3:
        return None
    try:
        header = json.loads(_b64url_decode(parts[0]))
        if header.get("alg") != "RS256":  # no alg-confusion downgrades
            return None
        key = keyset.get(header.get("kid", ""))
        if key is None:
            return None
        signing = f"{parts[0]}.{parts[1]}".encode()
        if not rs256_verify(signing, _b64url_decode(parts[2]), *key):
            return None
        claims = json.loads(_b64url_decode(parts[1]))
    except Exception:  # noqa: BLE001
        return None
    exp = claims.get("exp")
    if exp is not None and time.time() > float(exp):
        return None
    return claims


def oauth_jwks_middleware(keyset: JWKSKeySet):
    """Bearer-JWT validation against background-refreshed RSA JWKS — the
    reference's OAuth shape (oauth.go:53-140)."""

    def mw(inner: WireHandler) -> WireHandler:
        def handle(request: Request) -> Response:
            if _is_well_known(request):
                return inner(request)
            header = request.headers.get("authorization", "")
            if not header.startswith("Bearer "):
                return _unauthorized()
            claims = jwt_decode_rs256(header[7:], keyset)
            if claims is None:
                return _unauthorized("invalid or expired token")
            request.auth_subject = str(claims.get("sub", ""))
            request.context["jwt_claims"] = claims
            return inner(request)

        return handle

    return mw


def oauth_middleware(secret: str):
    """Bearer-JWT validation (HS256 shared secret). For provider-issued RSA
    tokens use oauth_jwks_middleware, which validates RS256 against a
    background-refreshed JWKS endpoint like the reference (oauth.go:53-140);
    HS256 remains for zero-egress deployments. Claim checks (exp) and claim
    propagation are identical on both paths."""

    def mw(inner: WireHandler) -> WireHandler:
        def handle(request: Request) -> Response:
            if _is_well_known(request):
                return inner(request)
            header = request.headers.get("authorization", "")
            if not header.startswith("Bearer "):
                return _unauthorized()
            claims = jwt_decode(header[7:], secret)
            if claims is None:
                return _unauthorized("invalid or expired token")
            request.auth_subject = str(claims.get("sub", ""))
            request.context["jwt_claims"] = claims
            return inner(request)

        return handle

    return mw
