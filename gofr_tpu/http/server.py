"""Threaded HTTP server over the Router, with chunked/SSE streaming support.

Parity: reference pkg/gofr/httpServer.go:24-36 (http.Server on HTTP_PORT
wrapping the Router; one goroutine per connection -> here one thread per
connection via ThreadingHTTPServer).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .request import Request
from .router import Router


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    router: Router = None  # type: ignore[assignment]
    logger = None

    # silence default stderr access logs; the logging middleware owns request logs
    def log_message(self, fmt: str, *args) -> None:
        pass

    def _dispatch(self) -> None:
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        request = Request(
            method=self.command,
            target=self.path,
            headers=dict(self.headers.items()),
            body=body,
            client_addr=self.client_address[0],
        )
        try:
            resp = self.router.dispatch(request)
        except Exception as exc:  # noqa: BLE001 - last-ditch guard below middleware
            if self.logger is not None:
                self.logger.error(f"unhandled server error: {exc}")
            self.send_response(500)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return

        try:
            self.send_response(resp.status)
            for key, val in resp.headers.items():
                self.send_header(key, val)
            if resp.stream is not None:
                self.send_header("Transfer-Encoding", "chunked")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                for chunk in resp.stream:
                    if not chunk:
                        continue
                    self.wfile.write(f"{len(chunk):X}\r\n".encode() + chunk + b"\r\n")
                    self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            else:
                self.send_header("Content-Length", str(len(resp.body)))
                self.end_headers()
                if self.command != "HEAD" and resp.body:
                    self.wfile.write(resp.body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response (common for cancelled streams)

    # route every verb through the same dispatch
    do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = do_OPTIONS = do_HEAD = _dispatch


class _Server(ThreadingHTTPServer):
    # The socketserver default listen backlog is 5; a burst of simultaneous
    # connects (concurrent SSE clients, fleet fan-out) overflows it on a busy
    # host and the kernel RSTs connections before accept() ever sees them.
    request_queue_size = 128


class HTTPServer:
    def __init__(self, router: Router, port: int, logger=None, host: str = "0.0.0.0"):
        self.router = router
        self.port = port
        self.host = host
        self.logger = logger
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        handler = type("BoundHandler", (_Handler,), {"router": self.router, "logger": self.logger})
        self._server = _Server((self.host, self.port), handler)
        self._server.daemon_threads = True
        if self.port == 0:
            self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name=f"http-server-{self.port}", daemon=True)
        self._thread.start()
        if self.logger is not None:
            self.logger.infof("HTTP server started on port %d", self.port)

    def serve_forever(self) -> None:
        if self._thread is not None:
            self._thread.join()

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
