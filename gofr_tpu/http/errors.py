"""HTTP error types mapped to status codes by the Responder.

Parity: reference pkg/gofr/http/responder.go:53-74 (HTTPStatusFromError) and the
error types under pkg/gofr/http (ErrorMissingParam, ErrorInvalidParam,
ErrorEntityNotFound, ErrorEntityAlreadyExist, ErrorInvalidRoute,
ErrorRequestTimeout, ErrorPanicRecovery).
"""

from __future__ import annotations

from typing import Sequence


class HTTPError(Exception):
    status_code = 500
    # when set (seconds), the Responder adds a Retry-After header — the
    # hint that turns a 503 into an actionable backoff for SDK retry
    # policies instead of a dead end
    retry_after_s: float | None = None

    def __init__(self, message: str = "", status_code: int | None = None):
        super().__init__(message or self.__class__.__name__)
        self.message = message or str(self)
        if status_code is not None:
            self.status_code = status_code


class MissingParam(HTTPError):
    status_code = 400

    def __init__(self, params: Sequence[str] = ()):
        self.params = list(params)
        super().__init__(f"Parameter(s) {','.join(self.params)} required for this request")


class InvalidParam(HTTPError):
    status_code = 400

    def __init__(self, params: Sequence[str] = ()):
        self.params = list(params)
        super().__init__(f"Incorrect value for parameter(s): {','.join(self.params)}")


class EntityNotFound(HTTPError):
    status_code = 404

    def __init__(self, name: str = "entity", value: str = ""):
        super().__init__(f"No entity found with {name}: {value}")


class EntityAlreadyExists(HTTPError):
    status_code = 409

    def __init__(self, message: str = "entity already exists"):
        super().__init__(message)


class InvalidRoute(HTTPError):
    status_code = 404

    def __init__(self):
        super().__init__("route not registered")


class RequestTimeout(HTTPError):
    status_code = 408

    def __init__(self):
        super().__init__("request timed out")


class PanicRecovery(HTTPError):
    status_code = 500

    def __init__(self):
        super().__init__("some unexpected error has occurred")


class ServiceUnavailable(HTTPError):
    status_code = 503

    def __init__(self, message: str = "service unavailable",
                 retry_after_s: float | None = None):
        super().__init__(message)
        if retry_after_s is not None:
            self.retry_after_s = retry_after_s


def status_from_error(err: BaseException, method: str) -> int:
    if isinstance(err, HTTPError):
        return err.status_code
    return 500


def status_from_method(method: str) -> int:
    if method == "POST":
        return 201
    if method == "DELETE":
        return 204
    return 200
