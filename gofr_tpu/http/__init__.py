"""HTTP transport layer: router, request/responder, middleware, server."""

from .errors import (EntityAlreadyExists, EntityNotFound, HTTPError, InvalidParam,
                     InvalidRoute, MissingParam, PanicRecovery, RequestTimeout,
                     ServiceUnavailable)
from .request import Request
from .responder import File, Raw, Redirect, Responder, Response, Stream
from .router import Router
from .server import HTTPServer

__all__ = [
    "EntityAlreadyExists", "EntityNotFound", "HTTPError", "InvalidParam",
    "InvalidRoute", "MissingParam", "PanicRecovery", "RequestTimeout",
    "ServiceUnavailable", "Request", "File", "Raw", "Redirect", "Responder",
    "Response", "Stream", "Router", "HTTPServer",
]
