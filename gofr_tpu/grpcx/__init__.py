"""gRPC server with logging + recovery + tracing interceptors.

Parity: reference pkg/gofr/grpc.go:20-46 (grpc.Server on GRPC_PORT, started
only when a service is registered) and pkg/gofr/grpc/log.go:58-94 (interceptor
opening a span and emitting an RPCLog per call).

Services register via `GenericService`: a (service_name, {method: handler})
pair with pluggable serializers. Default is JSON bytes; passing a
protoc-generated Message's SerializeToString/FromString speaks the real
protobuf wire format (exercised end-to-end in tests/test_grpc_proto.py with
protoc-generated stubs). Handlers receive a Context whose request carries
the deserialized message — the same handler shape as HTTP. Objects exposing
`__grpc_service_name__` and `__grpc_methods__` register identically.

ALL FOUR RPC SHAPES register (reference grpc.go:20-46 hosts arbitrary
protoc services, every shape included):
  - unary: `methods` — handler returns one response
  - server-streaming: `stream_methods` — handler returns an ITERATOR;
    each item is one stream message (how token generation travels over
    gRPC with the same chunk payloads as SSE; examples/llm-server)
  - client-streaming: `client_stream_methods` — ctx.request.payload is
    the iterator of inbound messages; handler aggregates to one response
  - bidi: `bidi_methods` — inbound iterator in, handler yields out
GRPCClient.call/stream/client_stream/bidi are the consuming counterparts.
"""

from __future__ import annotations

import json
import time
from concurrent import futures
from typing import Any, Callable, Dict, Optional

from ..context import Context
from ..logging import PrettyPrint


class RPCLog(PrettyPrint):
    def __init__(self, method: str, status: str, duration_us: int, trace_id: str = ""):
        self.method = method
        self.status = status
        self.response_time_us = duration_us
        self.trace_id = trace_id

    def pretty_print(self, fp) -> None:
        fp.write(f"{self.trace_id} \x1b[34mRPC\x1b[0m {self.status} "
                 f"{self.response_time_us:>8}µs {self.method}")


class GRPCRequest:
    """Adapts a deserialized gRPC message to the framework Request interface."""

    def __init__(self, payload: Any, method: str, metadata: Dict[str, str]):
        self.payload = payload
        self.method = method
        self.metadata = metadata
        self.span = None
        self.context: Dict[str, Any] = {}

    def param(self, key: str) -> str:
        if isinstance(self.payload, dict):
            return str(self.payload.get(key, ""))
        return ""

    def path_param(self, key: str) -> str:
        return self.method if key == "method" else ""

    def host_name(self) -> str:
        return "grpc://" + self.metadata.get(":authority", "")

    def bind(self, target: Any = None) -> Any:
        import dataclasses

        data = self.payload
        if target is None:
            return data
        if isinstance(target, type) and dataclasses.is_dataclass(target):
            names = {f.name for f in dataclasses.fields(target)}
            return target(**{k: v for k, v in data.items() if k in names})
        if isinstance(target, dict):
            target.update(data)
            return target
        for k, v in data.items():
            setattr(target, k, v)
        return target


class GenericService:
    def __init__(self, name: str, methods: Dict[str, Callable[[Context], Any]],
                 serializer: Optional[Callable[[Any], bytes]] = None,
                 deserializer: Optional[Callable[[bytes], Any]] = None,
                 stream_methods: Optional[Dict[str, Callable[[Context], Any]]]
                 = None,
                 client_stream_methods: Optional[Dict[str, Callable[[Context],
                                                                    Any]]]
                 = None,
                 bidi_methods: Optional[Dict[str, Callable[[Context], Any]]]
                 = None):
        self.__grpc_service_name__ = name
        self.__grpc_methods__ = methods
        # server-streaming: handler returns an iterator; each item goes
        # through the serializer as one stream message
        self.__grpc_stream_methods__ = stream_methods or {}
        # client-streaming: ctx.request.payload is an ITERATOR of
        # deserialized messages; the handler consumes it and returns one
        # response. bidi: same inbound iterator, handler YIELDS responses
        # (free interleaving — grpc delivers each yield as it happens)
        self.__grpc_client_stream_methods__ = client_stream_methods or {}
        self.__grpc_bidi_methods__ = bidi_methods or {}
        self.serializer = serializer or (lambda obj: json.dumps(obj, default=str).encode())
        self.deserializer = deserializer or (lambda raw: json.loads(raw.decode()) if raw else {})


class GRPCServer:
    def __init__(self, container, port: int, logger):
        import grpc

        self.container = container
        self.port = port
        self.logger = logger
        self._grpc = grpc
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=32))

    def register(self, service) -> None:
        grpc = self._grpc
        name = service.__grpc_service_name__
        methods = service.__grpc_methods__
        serializer = getattr(service, "serializer", lambda o: json.dumps(o, default=str).encode())
        deserializer = getattr(service, "deserializer", lambda raw: json.loads(raw.decode()) if raw else {})

        handlers = {}
        for method_name, fn in methods.items():
            handlers[method_name] = grpc.unary_unary_rpc_method_handler(
                self._adapt(f"/{name}/{method_name}", fn, serializer),
                request_deserializer=deserializer,
                response_serializer=lambda b: b,
            )
        for method_name, fn in getattr(service, "__grpc_stream_methods__",
                                       {}).items():
            handlers[method_name] = grpc.unary_stream_rpc_method_handler(
                self._adapt_stream(f"/{name}/{method_name}", fn, serializer),
                request_deserializer=deserializer,
                response_serializer=lambda b: b,
            )
        for method_name, fn in getattr(service,
                                       "__grpc_client_stream_methods__",
                                       {}).items():
            handlers[method_name] = grpc.stream_unary_rpc_method_handler(
                self._adapt_client_stream(f"/{name}/{method_name}", fn,
                                          serializer),
                request_deserializer=deserializer,
                response_serializer=lambda b: b,
            )
        for method_name, fn in getattr(service, "__grpc_bidi_methods__",
                                       {}).items():
            handlers[method_name] = grpc.stream_stream_rpc_method_handler(
                self._adapt_bidi(f"/{name}/{method_name}", fn, serializer),
                request_deserializer=deserializer,
                response_serializer=lambda b: b,
            )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(name, handlers),))

    def _status_for(self, exc: BaseException):
        """Client-input errors abort INVALID_ARGUMENT; everything else is a
        server fault (INTERNAL). Mirrors the HTTP surface, where the same
        engine.submit validation raises map to 400 (ADVICE r4): a gRPC
        client must be able to tell a bad request from a broken server.
        Shed/overload errors (duck-typed status_code 503: draining engine,
        wedged device) map to UNAVAILABLE — the retry-elsewhere signal."""
        from ..http.errors import InvalidParam

        if isinstance(exc, (ValueError, InvalidParam)):
            return self._grpc.StatusCode.INVALID_ARGUMENT
        if getattr(exc, "status_code", None) == 503:
            return self._grpc.StatusCode.UNAVAILABLE
        return self._grpc.StatusCode.INTERNAL

    def _adapt(self, full_method: str, fn, serializer):
        def handle(payload, grpc_ctx):
            start = time.time()
            metadata = {k: v for k, v in (grpc_ctx.invocation_metadata() or [])}
            request = GRPCRequest(payload, full_method, metadata)
            span = None
            if self.container.tracer is not None:
                span = self.container.tracer.start_span(
                    f"grpc {full_method}", traceparent=metadata.get("traceparent"))
                request.span = span
            ctx = Context(request=request, container=self.container)
            status = "OK"
            try:
                result = fn(ctx)
                return serializer(result)
            except Exception as exc:  # noqa: BLE001 - recovery interceptor (grpc.go:23-25)
                status = "ERROR"
                self.logger.errorf("grpc handler %s failed: %s", full_method, exc)
                grpc_ctx.abort(self._status_for(exc), str(exc))
            finally:
                duration_us = int((time.time() - start) * 1e6)
                trace_id = span.trace_id if span else ""
                self.logger.info(RPCLog(full_method, status, duration_us, trace_id))
                if span is not None:
                    span.set_status(status == "OK")
                    span.end()

        return handle

    def _adapt_stream(self, full_method: str, fn, serializer):
        """Server-streaming twin of _adapt: the handler's return value is
        iterated and each item serialized as one stream message. The RPC
        log records total duration and message count at stream end; a
        handler exception mid-stream aborts the RPC (INVALID_ARGUMENT for
        client-input errors, INTERNAL otherwise — the recovery interceptor
        posture, never a silent truncation)."""
        def handle(payload, grpc_ctx):
            start = time.time()
            metadata = {k: v for k, v in (grpc_ctx.invocation_metadata() or [])}
            request = GRPCRequest(payload, full_method, metadata)
            span = None
            if self.container.tracer is not None:
                span = self.container.tracer.start_span(
                    f"grpc {full_method}", traceparent=metadata.get("traceparent"))
                request.span = span
            ctx = Context(request=request, container=self.container)
            status = "OK"
            sent = 0
            try:
                for item in fn(ctx):
                    yield serializer(item)
                    sent += 1
            except Exception as exc:  # noqa: BLE001 - recovery interceptor
                status = "ERROR"
                self.logger.errorf("grpc stream %s failed after %d messages: %s",
                                   full_method, sent, exc)
                grpc_ctx.abort(self._status_for(exc), str(exc))
            finally:
                duration_us = int((time.time() - start) * 1e6)
                trace_id = span.trace_id if span else ""
                self.logger.info(RPCLog(f"{full_method} [{sent} msgs]",
                                        status, duration_us, trace_id))
                if span is not None:
                    span.set_attribute("grpc.stream_messages", sent)
                    span.set_status(status == "OK")
                    span.end()

        return handle

    def _adapt_client_stream(self, full_method: str, fn, serializer):
        """Client-streaming: the request payload IS the (lazily consumed)
        iterator of deserialized messages; the handler aggregates and
        returns one response. Completes the RPC-shape matrix the reference
        gets from protoc service registration (grpc.go:20-46)."""
        def handle(request_iterator, grpc_ctx):
            start = time.time()
            metadata = {k: v for k, v in (grpc_ctx.invocation_metadata() or [])}
            request = GRPCRequest(request_iterator, full_method, metadata)
            span = None
            if self.container.tracer is not None:
                span = self.container.tracer.start_span(
                    f"grpc {full_method}",
                    traceparent=metadata.get("traceparent"))
                request.span = span
            ctx = Context(request=request, container=self.container)
            status = "OK"
            try:
                return serializer(fn(ctx))
            except Exception as exc:  # noqa: BLE001 - recovery interceptor
                status = "ERROR"
                self.logger.errorf("grpc client-stream %s failed: %s",
                                   full_method, exc)
                grpc_ctx.abort(self._status_for(exc), str(exc))
            finally:
                duration_us = int((time.time() - start) * 1e6)
                trace_id = span.trace_id if span else ""
                self.logger.info(RPCLog(full_method, status, duration_us,
                                        trace_id))
                if span is not None:
                    span.set_status(status == "OK")
                    span.end()

        return handle

    def _adapt_bidi(self, full_method: str, fn, serializer):
        """Bidirectional streaming: inbound iterator as the payload, the
        handler yields responses whenever it likes (echo-per-message,
        batch-then-flush, or fully decoupled)."""
        def handle(request_iterator, grpc_ctx):
            start = time.time()
            metadata = {k: v for k, v in (grpc_ctx.invocation_metadata() or [])}
            request = GRPCRequest(request_iterator, full_method, metadata)
            span = None
            if self.container.tracer is not None:
                span = self.container.tracer.start_span(
                    f"grpc {full_method}",
                    traceparent=metadata.get("traceparent"))
                request.span = span
            ctx = Context(request=request, container=self.container)
            status = "OK"
            sent = 0
            try:
                for item in fn(ctx):
                    yield serializer(item)
                    sent += 1
            except Exception as exc:  # noqa: BLE001 - recovery interceptor
                status = "ERROR"
                self.logger.errorf("grpc bidi %s failed after %d messages: %s",
                                   full_method, sent, exc)
                grpc_ctx.abort(self._status_for(exc), str(exc))
            finally:
                duration_us = int((time.time() - start) * 1e6)
                trace_id = span.trace_id if span else ""
                self.logger.info(RPCLog(f"{full_method} [{sent} msgs]",
                                        status, duration_us, trace_id))
                if span is not None:
                    span.set_attribute("grpc.stream_messages", sent)
                    span.set_status(status == "OK")
                    span.end()

        return handle

    def start(self) -> None:
        bound = self._server.add_insecure_port(f"0.0.0.0:{self.port}")
        if self.port == 0:
            self.port = bound
        self._server.start()
        self.logger.infof("gRPC server started on port %d", self.port)

    def stop(self) -> None:
        self._server.stop(grace=0.5)


class GRPCClient:
    """Counterpart client for GenericService endpoints. JSON by default;
    pass protobuf Message serializers (SerializeToString/FromString) to
    speak the binary wire format of protoc-generated stubs."""

    def __init__(self, address: str):
        import grpc

        self._grpc = grpc
        self.channel = grpc.insecure_channel(address)

    def call(self, service: str, method: str, payload: Any, timeout_s: float = 5.0,
             metadata: Optional[Dict[str, str]] = None,
             serializer: Optional[Callable[[Any], bytes]] = None,
             deserializer: Optional[Callable[[bytes], Any]] = None) -> Any:
        fn = self.channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=serializer or (
                lambda obj: json.dumps(obj, default=str).encode()),
            response_deserializer=deserializer or (
                lambda raw: json.loads(raw.decode()) if raw else None),
        )
        md = list((metadata or {}).items())
        return fn(payload, timeout=timeout_s, metadata=md)

    def stream(self, service: str, method: str, payload: Any,
               timeout_s: float = 30.0,
               metadata: Optional[Dict[str, str]] = None,
               serializer: Optional[Callable[[Any], bytes]] = None,
               deserializer: Optional[Callable[[bytes], Any]] = None):
        """Server-streaming call: yields deserialized messages as they
        arrive (the gRPC twin of reading an SSE response line by line)."""
        fn = self.channel.unary_stream(
            f"/{service}/{method}",
            request_serializer=serializer or (
                lambda obj: json.dumps(obj, default=str).encode()),
            response_deserializer=deserializer or (
                lambda raw: json.loads(raw.decode()) if raw else None),
        )
        md = list((metadata or {}).items())
        return fn(payload, timeout=timeout_s, metadata=md)

    def client_stream(self, service: str, method: str, payloads,
                      timeout_s: float = 30.0,
                      metadata: Optional[Dict[str, str]] = None,
                      serializer: Optional[Callable[[Any], bytes]] = None,
                      deserializer: Optional[Callable[[bytes], Any]] = None
                      ) -> Any:
        """Client-streaming call: sends every item of `payloads` (any
        iterable), returns the server's single aggregated response."""
        fn = self.channel.stream_unary(
            f"/{service}/{method}",
            request_serializer=serializer or (
                lambda obj: json.dumps(obj, default=str).encode()),
            response_deserializer=deserializer or (
                lambda raw: json.loads(raw.decode()) if raw else None),
        )
        md = list((metadata or {}).items())
        return fn(iter(payloads), timeout=timeout_s, metadata=md)

    def bidi(self, service: str, method: str, payloads,
             timeout_s: float = 30.0,
             metadata: Optional[Dict[str, str]] = None,
             serializer: Optional[Callable[[Any], bytes]] = None,
             deserializer: Optional[Callable[[bytes], Any]] = None):
        """Bidirectional call: sends `payloads` (any iterable — a generator
        can block to interleave with responses) and yields the server's
        messages as they arrive."""
        fn = self.channel.stream_stream(
            f"/{service}/{method}",
            request_serializer=serializer or (
                lambda obj: json.dumps(obj, default=str).encode()),
            response_deserializer=deserializer or (
                lambda raw: json.loads(raw.decode()) if raw else None),
        )
        md = list((metadata or {}).items())
        return fn(iter(payloads), timeout=timeout_s, metadata=md)

    def close(self) -> None:
        self.channel.close()
