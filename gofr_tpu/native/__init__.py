"""ctypes loader for the native runtime helpers (libgofr_native.so).

The shared library is built from gofr_native.cc on first import when a C++
toolchain is present (auto-build, cached next to the source); every consumer
degrades to its pure-Python path when `available()` is False, so the
framework never hard-requires the toolchain — the same graceful-nil posture
datasources take on misconfiguration (reference sql/sql.go:33-36).

API:
  available() -> bool
  BPECore(merge_triples)   — id-level greedy BPE merges (hot encode loop)
  pad_batch(rows, max_len, pad_id) -> np.ndarray[int32]
  utf8_complete_prefix(buf) -> int
  propose_draft(history, d) -> list[int]  — speculative prompt-lookup scan
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libgofr_native.so")
_SRC = os.path.join(_DIR, "gofr_native.cc")

_lib = None
_load_lock = threading.Lock()
_load_attempted = False

_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)


def _build() -> bool:
    cxx = os.environ.get("CXX", "g++")
    try:
        result = subprocess.run(
            [cxx, "-O3", "-std=c++17", "-fPIC", "-shared", "-o", _SO, _SRC],
            capture_output=True, timeout=120)
        return result.returncode == 0 and os.path.exists(_SO)
    except (OSError, subprocess.TimeoutExpired):
        return False


def _bind(lib) -> None:
    lib.gn_version.restype = ctypes.c_char_p
    lib.gn_bpe_new.restype = ctypes.c_void_p
    lib.gn_bpe_new.argtypes = [ctypes.c_int32, _i32p, _i32p, _i32p]
    lib.gn_bpe_free.argtypes = [ctypes.c_void_p]
    lib.gn_bpe_encode.restype = ctypes.c_int32
    lib.gn_bpe_encode.argtypes = [ctypes.c_void_p, _i32p, ctypes.c_int32, _i32p]
    lib.gn_pad_batch.restype = ctypes.c_int32
    lib.gn_pad_batch.argtypes = [_i32p, _i64p, ctypes.c_int32, ctypes.c_int32,
                                 ctypes.c_int32, _i32p]
    lib.gn_utf8_complete_prefix.restype = ctypes.c_int32
    lib.gn_utf8_complete_prefix.argtypes = [ctypes.POINTER(ctypes.c_uint8),
                                            ctypes.c_int32]
    lib.gn_propose_draft.restype = ctypes.c_int32
    lib.gn_propose_draft.argtypes = [_i32p, ctypes.c_int32, ctypes.c_int32,
                                     _i32p]


def _load():
    global _lib, _load_attempted
    if _lib is not None:  # fast path: no lock once loaded (hot callers)
        return _lib
    with _load_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if not os.path.exists(_SO) or (os.path.exists(_SRC) and
                                       os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not _build():
                return None
        for attempt in (0, 1):
            lib = None
            try:
                lib = ctypes.CDLL(_SO)
                _bind(lib)
                _lib = lib
                break
            except (OSError, AttributeError):
                # AttributeError: a stale cached .so missing a newly added
                # symbol (same-second mtimes can defeat the rebuild check).
                # Delete the stale artifact and rebuild ONCE — a silent
                # permanent fallback would also disable the helpers the
                # stale library did support (BPE, pad_batch)
                _lib = None
                if attempt == 0:
                    if lib is not None:
                        # dlopen dedups by pathname: without closing the
                        # failed handle, the retry's CDLL would rebind the
                        # SAME stale in-memory image, not the rebuilt file
                        try:
                            import _ctypes

                            _ctypes.dlclose(lib._handle)
                        except Exception:  # noqa: BLE001
                            break
                    try:
                        os.remove(_SO)
                    except OSError:
                        break
                    if not _build():
                        break
        return _lib


def available() -> bool:
    return _load() is not None


def version() -> str:
    lib = _load()
    return lib.gn_version().decode() if lib else "unavailable"


class BPECore:
    """Native greedy BPE over token ids.

    merge_triples: ordered [(left_id, right_id, merged_id)] — index is rank.
    """

    def __init__(self, merge_triples: Sequence[Tuple[int, int, int]]):
        lib = _load()
        if lib is None:
            raise RuntimeError("gofr_native unavailable (no C++ toolchain?)")
        self._lib = lib
        arr = np.asarray(merge_triples, dtype=np.int32).reshape(-1, 3)
        left = np.ascontiguousarray(arr[:, 0])
        right = np.ascontiguousarray(arr[:, 1])
        merged = np.ascontiguousarray(arr[:, 2])
        self._handle = lib.gn_bpe_new(
            len(arr), left.ctypes.data_as(_i32p), right.ctypes.data_as(_i32p),
            merged.ctypes.data_as(_i32p))

    def encode(self, ids: Sequence[int]) -> List[int]:
        src = np.asarray(ids, dtype=np.int32)
        if src.size == 0:
            return []
        src = np.ascontiguousarray(src)
        out = np.empty(src.size, dtype=np.int32)
        n = self._lib.gn_bpe_encode(self._handle, src.ctypes.data_as(_i32p),
                                    src.size, out.ctypes.data_as(_i32p))
        return out[:n].tolist()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.gn_bpe_free(handle)
            self._handle = None


def pad_batch(rows: Sequence[Sequence[int]], max_len: int,
              pad_id: int = 0) -> Optional[np.ndarray]:
    """Pack variable-length token rows into a padded [n, max_len] int32 matrix.

    Overlong rows keep their tail. Returns None when the library is missing
    (callers fall back to numpy loops).
    """
    lib = _load()
    if lib is None:
        return None
    lengths = np.asarray([len(r) for r in rows], dtype=np.int64)
    flat = (np.concatenate([np.asarray(r, dtype=np.int32) for r in rows])
            if len(rows) and lengths.sum() else np.empty(0, dtype=np.int32))
    flat = np.ascontiguousarray(flat)
    out = np.empty((len(rows), max_len), dtype=np.int32)
    rc = lib.gn_pad_batch(flat.ctypes.data_as(_i32p),
                          lengths.ctypes.data_as(_i64p), len(rows), max_len,
                          pad_id, out.ctypes.data_as(_i32p))
    if rc != 0:
        raise ValueError("gn_pad_batch failed (negative length or max_len)")
    return out


def utf8_complete_prefix(buf: bytes) -> int:
    """Bytes of `buf` that form whole UTF-8 codepoints (SSE chunk boundary)."""
    lib = _load()
    if lib is None:
        # pure-Python mirror of the C algorithm: back up over at most three
        # continuation bytes; an incomplete-but-valid tail sequence is cut,
        # anything invalid counts as complete (replacement char on decode)
        if not buf:
            return 0
        i = len(buf) - 1
        back = 0
        while i > 0 and (buf[i] & 0xC0) == 0x80 and back < 3:
            i -= 1
            back += 1
        lead = buf[i]
        if (lead & 0x80) == 0:
            need = 1
        elif (lead & 0xE0) == 0xC0:
            need = 2
        elif (lead & 0xF0) == 0xE0:
            need = 3
        elif (lead & 0xF8) == 0xF0:
            need = 4
        else:
            return len(buf)
        return len(buf) if i + need <= len(buf) else i
    arr = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf) if buf else \
        (ctypes.c_uint8 * 1)()
    return lib.gn_utf8_complete_prefix(arr, len(buf))


def propose_draft(history, d: int) -> Optional[List[int]]:
    """Prompt-lookup draft: tokens that followed the most recent earlier
    occurrence of history's trailing bigram (speculative decoding's host
    side). Returns None when the library is missing (callers fall back to
    the pure-Python scan in the engine)."""
    lib = _load()
    if lib is None:
        return None
    n = len(history)
    if n < 3 or d <= 0:
        return []
    hist = np.ascontiguousarray(np.asarray(history, dtype=np.int32))
    out = np.empty(d, dtype=np.int32)
    count = lib.gn_propose_draft(hist.ctypes.data_as(_i32p), n, d,
                                 out.ctypes.data_as(_i32p))
    return out[:count].tolist()
