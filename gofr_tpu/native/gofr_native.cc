// gofr_native: C++ runtime helpers for the serving hot path, exposed over a
// plain C ABI and loaded from Python via ctypes (no pybind11 in this image).
//
// The reference framework is pure Go with zero native code (SURVEY.md §2.5);
// this build's runtime-around-the-compute-path is where native belongs:
//  - BPE encode: the greedy merge loop runs per request before the model ever
//    sees a token; pure-Python is O(n^2) interpreter-bound.
//  - pad_batch: assembles the padded [rows, max_len] int32 matrix the
//    dynamic-batching scheduler ships to the device.
//  - utf8_complete_prefix: how many bytes of a buffer form whole codepoints —
//    the SSE streaming decoder's boundary scan.
//
// Build: `make -C gofr_tpu/native` or the auto-build in native/__init__.py.

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
  size_t operator()(const std::pair<int32_t, int32_t>& p) const {
    return (static_cast<size_t>(static_cast<uint32_t>(p.first)) << 32) ^
           static_cast<uint32_t>(p.second);
  }
};

struct BPE {
  // (left, right) -> (rank, merged id); lower rank merges first
  std::unordered_map<std::pair<int32_t, int32_t>, std::pair<int32_t, int32_t>,
                     PairHash>
      merges;
};

}  // namespace

extern "C" {

const char* gn_version() { return "gofr_native 1.0"; }

void* gn_bpe_new(int32_t n_merges, const int32_t* left, const int32_t* right,
                 const int32_t* merged) {
  BPE* bpe = new BPE();
  bpe->merges.reserve(static_cast<size_t>(n_merges) * 2);
  for (int32_t i = 0; i < n_merges; ++i) {
    // last occurrence wins, matching the python dict-comprehension ranks
    bpe->merges[std::make_pair(left[i], right[i])] = std::make_pair(i, merged[i]);
  }
  return bpe;
}

void gn_bpe_free(void* handle) { delete static_cast<BPE*>(handle); }

// Greedy lowest-rank-first merging over a doubly-linked list of tokens.
// Returns the output length written into `out` (capacity must be >= n).
int32_t gn_bpe_encode(void* handle, const int32_t* ids, int32_t n,
                      int32_t* out) {
  const BPE* bpe = static_cast<const BPE*>(handle);
  if (n <= 0) return 0;
  std::vector<int32_t> tok(ids, ids + n);
  std::vector<int32_t> prev(n), next(n);
  for (int32_t i = 0; i < n; ++i) {
    prev[i] = i - 1;
    next[i] = (i + 1 < n) ? i + 1 : -1;
  }
  int32_t head = 0;
  while (true) {
    // scan live pairs for the lowest-rank merge
    int32_t best_rank = INT32_MAX, best_i = -1, best_merged = 0;
    for (int32_t i = head; i != -1 && next[i] != -1; i = next[i]) {
      auto it = bpe->merges.find({tok[i], tok[next[i]]});
      if (it != bpe->merges.end() && it->second.first < best_rank) {
        best_rank = it->second.first;
        best_i = i;
        best_merged = it->second.second;
      }
    }
    if (best_i == -1) break;
    int32_t j = next[best_i];
    tok[best_i] = best_merged;
    next[best_i] = next[j];
    if (next[j] != -1) prev[next[j]] = best_i;
  }
  int32_t n_out = 0;
  for (int32_t i = head; i != -1; i = next[i]) out[n_out++] = tok[i];
  return n_out;
}

// Pack `n_rows` variable-length rows (concatenated in `flat`, row i spanning
// lengths[i] elements) into out[n_rows * max_len], right-padded with pad_id.
// Rows longer than max_len keep their TAIL (decode context) — matching the
// scheduler's truncation rule. Returns 0 on success.
int32_t gn_pad_batch(const int32_t* flat, const int64_t* lengths,
                     int32_t n_rows, int32_t max_len, int32_t pad_id,
                     int32_t* out) {
  if (n_rows < 0 || max_len <= 0) return -1;
  const int32_t* src = flat;
  for (int32_t r = 0; r < n_rows; ++r) {
    int64_t len = lengths[r];
    if (len < 0) return -1;
    int32_t* row = out + static_cast<int64_t>(r) * max_len;
    int64_t copy = len < max_len ? len : max_len;
    const int32_t* start = src + (len - copy);  // tail when truncating
    std::memcpy(row, start, copy * sizeof(int32_t));
    for (int64_t c = copy; c < max_len; ++c) row[c] = pad_id;
    src += len;
  }
  return 0;
}

// Prompt-lookup draft proposal (speculative decoding): find the most recent
// earlier occurrence of the history's trailing bigram and copy up to `d`
// tokens that followed it into `out`. Returns tokens written (0 = no match).
// Runs once per active slot per verify dispatch — at 128 slots and serving
// dispatch rates the pure-Python scan is interpreter-bound.
int32_t gn_propose_draft(const int32_t* hist, int32_t n, int32_t d,
                         int32_t* out) {
  if (n < 3 || d <= 0) return 0;
  const int32_t a = hist[n - 2], b = hist[n - 1];
  for (int32_t i = n - 3; i >= 0; --i) {
    if (hist[i] == a && hist[i + 1] == b) {
      const int32_t start = i + 2;  // <= n-1, so at least one token follows
      const int32_t avail = n - start;
      const int32_t count = avail < d ? avail : d;
      std::memcpy(out, hist + start, count * sizeof(int32_t));
      return count;
    }
  }
  return 0;
}

// Length of the longest prefix of buf[0..len) that ends on a UTF-8 codepoint
// boundary. Invalid lead bytes count as complete (replacement on decode).
int32_t gn_utf8_complete_prefix(const uint8_t* buf, int32_t len) {
  if (len <= 0) return 0;
  int32_t i = len - 1;
  // back up over at most 3 continuation bytes to the lead byte
  int32_t back = 0;
  while (i > 0 && (buf[i] & 0xC0) == 0x80 && back < 3) {
    --i;
    ++back;
  }
  uint8_t lead = buf[i];
  int32_t need;
  if ((lead & 0x80) == 0)
    need = 1;
  else if ((lead & 0xE0) == 0xC0)
    need = 2;
  else if ((lead & 0xF0) == 0xE0)
    need = 3;
  else if ((lead & 0xF8) == 0xF0)
    need = 4;
  else
    return len;  // invalid lead (or stray continuation): treat as complete
  return (i + need <= len) ? len : i;
}

}  // extern "C"
