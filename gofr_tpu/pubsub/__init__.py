"""Pub/Sub abstraction: Publisher/Subscriber/Committer interfaces + Message.

Parity: reference pkg/gofr/datasource/pubsub/interface.go:11-30 (Publisher,
Subscriber, Client, Committer), message.go:8-49 (Message implements the
transport-agnostic Request so handlers bind it like an HTTP body), log.go:8-20
(structured PUB/SUB records). Backends: reference ships kafka/google/mqtt over
the network; this build ships an in-process broker with consumer-group +
committed-offset semantics (the CI tier the reference mocks), and the backend
switch in the container mirrors container.go:86-131.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from ..logging import PrettyPrint


class PubSubLog(PrettyPrint):
    def __init__(self, mode: str, topic: str, message: str):
        self.mode = mode  # PUB / SUB
        self.topic = topic
        self.message = message

    def pretty_print(self, fp) -> None:
        fp.write(f"\x1b[35m{self.mode}\x1b[0m {self.topic} {self.message[:80]}")


class Message:
    """One consumed message; doubles as a handler Request (message.go:8-49)."""

    def __init__(self, topic: str, value: bytes, key: str = "",
                 metadata: Optional[Dict[str, Any]] = None, committer=None):
        self.topic = topic
        self.value = value
        self.key = key
        self.metadata = metadata or {}
        self._committer = committer
        self.span = None
        self.context: Dict[str, Any] = {}

    # -- Request interface so newContext(msg) works like HTTP -----------------
    def param(self, key: str) -> str:
        return str(self.metadata.get(key, ""))

    def path_param(self, key: str) -> str:
        if key == "topic":
            return self.topic
        return ""

    def host_name(self) -> str:
        return "pubsub://" + self.topic

    def bind(self, target: Any = None) -> Any:
        data = json.loads(self.value.decode("utf-8")) if self.value else {}
        if target is None:
            return data
        import dataclasses

        if isinstance(target, type) and dataclasses.is_dataclass(target):
            names = {f.name for f in dataclasses.fields(target)}
            return target(**{k: v for k, v in data.items() if k in names})
        if isinstance(target, dict):
            target.update(data)
            return target
        for k, v in data.items():
            setattr(target, k, v)
        return target

    def commit(self) -> None:
        if self._committer is not None:
            self._committer()


class Client:
    """Backend interface: publish/subscribe/create_topic/delete_topic/health/close."""

    def publish(self, topic: str, message: bytes, key: str = "") -> None:  # pragma: no cover
        raise NotImplementedError

    def subscribe(self, topic: str, group: str = "default",
                  timeout_s: Optional[float] = None) -> Optional[Message]:  # pragma: no cover
        raise NotImplementedError

    def create_topic(self, topic: str) -> None:  # pragma: no cover
        raise NotImplementedError

    def delete_topic(self, topic: str) -> None:  # pragma: no cover
        raise NotImplementedError

    def health_check(self):  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        pass
