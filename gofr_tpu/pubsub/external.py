"""Gated adapters for external brokers: Kafka, MQTT, Google Pub/Sub.

The reference ships three network pub/sub backends (kafka/kafka.go:45-92,
mqtt/mqtt.go:57-80, google/google.go:36-61).  This environment bakes in none
of their client libraries, so each adapter here resolves its driver lazily at
construction: if the library is importable the adapter speaks the bundled
`Client` interface over the real broker; otherwise it raises a clear
`MissingDriverError` naming the pip package — mirroring how the reference
keeps Google Pub/Sub mock-only in CI (SURVEY.md §4) while the code path
stays first-class.

All three adapters normalise to the same semantics the bundled brokers have:
`subscribe` returns one `Message` whose `commit()` acknowledges it; handler
failure without commit leads to redelivery per the broker's own rules.
"""

from __future__ import annotations

import importlib
import time
from typing import Optional

from ..datasource import Health, STATUS_DOWN, STATUS_UP
from . import Client, Message


class MissingDriverError(ImportError):
    def __init__(self, backend: str, packages: str):
        super().__init__(
            f"pub/sub backend {backend!r} needs an external driver; install one of: "
            f"{packages} (this image bakes in none — use PUBSUB_BACKEND=inproc or "
            f"file for the bundled brokers)")
        self.backend = backend


def _need(backend: str, module: str, packages: str):
    try:
        return importlib.import_module(module)
    except ImportError as exc:
        raise MissingDriverError(backend, packages) from exc


class KafkaAdapter(Client):
    """kafka-python-backed adapter (reference kafka/kafka.go:45-92).

    Config: PUBSUB_BROKER (host:port), CONSUMER_ID (group), PUBSUB_OFFSET.
    """

    def __init__(self, config=None, logger=None, metrics=None,
                 brokers: str = "", group: str = ""):
        kafka = _need("kafka", "kafka", "kafka-python")
        self.logger = logger
        self.metrics = metrics
        if config is not None:
            brokers = brokers or config.get_or_default("PUBSUB_BROKER", "localhost:9092")
            group = group or config.get_or_default("CONSUMER_ID", "gofr")
        self.brokers = (brokers or "localhost:9092").split(",")
        self.group = group or "gofr"
        self._producer = kafka.KafkaProducer(bootstrap_servers=self.brokers)
        self._consumers = {}
        self._kafka = kafka

    def publish(self, topic: str, message: bytes, key: str = "") -> None:
        if isinstance(message, str):
            message = message.encode()
        self._producer.send(topic, value=message, key=key.encode() or None)
        self._producer.flush()
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count", topic=topic)

    def _consumer(self, topic: str, group: str):
        if (topic, group) not in self._consumers:
            self._consumers[(topic, group)] = self._kafka.KafkaConsumer(
                topic, bootstrap_servers=self.brokers, group_id=group,
                enable_auto_commit=False)
        return self._consumers[(topic, group)]

    def subscribe(self, topic: str, group: str = "default",
                  timeout_s: Optional[float] = None) -> Optional[Message]:
        # the Client interface's "default" group maps to the configured
        # CONSUMER_ID; explicit groups get their own offset cursor, matching
        # the bundled brokers' semantics
        consumer = self._consumer(topic, self.group if group == "default" else group)
        # bundled-broker contract: timeout_s=None blocks until a message
        deadline = None if timeout_s is None else time.time() + timeout_s
        while True:
            remaining = 1.0 if deadline is None else max(deadline - time.time(), 0)
            batch = consumer.poll(timeout_ms=int(remaining * 1000), max_records=1)
            if not batch:
                if deadline is not None and time.time() >= deadline:
                    return None
                continue
            break
        for records in batch.values():
            for rec in records:
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_pubsub_subscribe_total_count", topic=topic)

                def _commit(rec=rec):
                    # commit THIS record's offset, not the consumer position:
                    # a later successful handler must not mark an earlier
                    # failed (uncommitted) message as done
                    from kafka import TopicPartition
                    from kafka.structs import OffsetAndMetadata

                    consumer.commit({
                        TopicPartition(rec.topic, rec.partition):
                            OffsetAndMetadata(rec.offset + 1, None)})

                return Message(
                    topic=topic, value=rec.value,
                    key=(rec.key or b"").decode("utf-8", "replace"),
                    metadata={"offset": rec.offset, "partition": rec.partition},
                    committer=_commit)
        return None

    def create_topic(self, topic: str) -> None:
        admin = self._kafka.KafkaAdminClient(bootstrap_servers=self.brokers)
        try:
            from kafka.admin import NewTopic
            admin.create_topics([NewTopic(name=topic, num_partitions=1,
                                          replication_factor=1)])
        finally:
            admin.close()

    def delete_topic(self, topic: str) -> None:
        admin = self._kafka.KafkaAdminClient(bootstrap_servers=self.brokers)
        try:
            admin.delete_topics([topic])
        finally:
            admin.close()

    def health_check(self) -> Health:
        try:
            ok = self._producer.bootstrap_connected()
        except Exception:  # noqa: BLE001
            ok = False
        return Health(status=STATUS_UP if ok else STATUS_DOWN,
                      details={"backend": "kafka", "brokers": self.brokers})

    def close(self) -> None:
        self._producer.close()
        for consumer in self._consumers.values():
            consumer.close()


class MQTTAdapter(Client):
    """paho-mqtt-backed adapter (reference mqtt/mqtt.go:57-80,145-198).

    MQTT pushes; the adapter bridges push -> pull with a per-topic queue the
    way the reference buffers into channels (mqtt.go:145-198).
    """

    def __init__(self, config=None, logger=None, metrics=None,
                 host: str = "", port: int = 0, qos: int = 1):
        mqtt = _need("mqtt", "paho.mqtt.client", "paho-mqtt")
        import queue

        self.logger = logger
        self.metrics = metrics
        if config is not None:
            host = host or config.get_or_default("MQTT_HOST", "localhost")
            port = port or int(config.get_or_default("MQTT_PORT", "1883"))
            qos = int(config.get_or_default("MQTT_QOS", str(qos)))
        self.qos = qos
        self._queues = {}
        self._queue_mod = queue
        self._client = mqtt.Client()
        self._client.on_message = self._on_message
        self._client.connect(host or "localhost", port or 1883)
        self._client.loop_start()

    @staticmethod
    def _filter_matches(pattern: str, topic: str) -> bool:
        """MQTT topic-filter match: `+` = one level, `#` = rest (trailing)."""
        p_parts = pattern.split("/")
        t_parts = topic.split("/")
        for i, p in enumerate(p_parts):
            if p == "#":
                return True
            if i >= len(t_parts):
                return False
            if p != "+" and p != t_parts[i]:
                return False
        return len(p_parts) == len(t_parts)

    def _on_message(self, _client, _userdata, msg) -> None:
        # route by SUBSCRIPTION FILTER, not concrete topic, so wildcard
        # subscriptions ('sensors/+') receive their matches
        delivered = False
        for pattern, q in list(self._queues.items()):
            if self._filter_matches(pattern, msg.topic):
                q.put(msg)
                delivered = True
        if not delivered:
            self._queues.setdefault(msg.topic, self._queue_mod.Queue()).put(msg)

    def publish(self, topic: str, message: bytes, key: str = "") -> None:
        if isinstance(message, str):
            message = message.encode()
        self._client.publish(topic, message, qos=self.qos)
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count", topic=topic)

    def subscribe(self, topic: str, group: str = "default",
                  timeout_s: Optional[float] = None) -> Optional[Message]:
        if topic not in self._queues:
            self._queues[topic] = self._queue_mod.Queue()
            self._client.subscribe(topic, qos=self.qos)
        try:
            msg = self._queues[topic].get(timeout=timeout_s)
        except self._queue_mod.Empty:
            return None
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_subscribe_total_count", topic=topic)
        return Message(topic=topic, value=msg.payload, key="",
                       metadata={"qos": msg.qos}, committer=None)

    def create_topic(self, topic: str) -> None:  # topics are implicit in MQTT
        pass

    def delete_topic(self, topic: str) -> None:
        self._client.unsubscribe(topic)
        self._queues.pop(topic, None)

    def health_check(self) -> Health:
        ok = self._client.is_connected()
        return Health(status=STATUS_UP if ok else STATUS_DOWN,
                      details={"backend": "mqtt"})

    def close(self) -> None:
        self._client.loop_stop()
        self._client.disconnect()


class GooglePubSubAdapter(Client):
    """google-cloud-pubsub-backed adapter (reference google/google.go:36-61).

    Auto-creates topic + per-group subscription on first use
    (google.go:170-207); `subscribe` pulls one message and its `commit()`
    acks it (google.go:117-169).
    """

    def __init__(self, config=None, logger=None, metrics=None, project: str = ""):
        pubsub_v1 = _need("google", "google.cloud.pubsub_v1", "google-cloud-pubsub")
        self.logger = logger
        self.metrics = metrics
        if config is not None:
            project = project or config.get_or_default("GOOGLE_PROJECT_ID", "")
        if not project:
            raise ValueError("GooglePubSubAdapter needs GOOGLE_PROJECT_ID")
        self.project = project
        self._publisher = pubsub_v1.PublisherClient()
        self._subscriber = pubsub_v1.SubscriberClient()
        self._ensured_topics = set()
        self._ensured_subs = set()

    def _topic_path(self, topic: str) -> str:
        return self._publisher.topic_path(self.project, topic)

    def _sub_path(self, topic: str, group: str) -> str:
        return self._subscriber.subscription_path(self.project, f"{topic}.{group}")

    def publish(self, topic: str, message: bytes, key: str = "") -> None:
        if isinstance(message, str):
            message = message.encode()
        self.create_topic(topic)
        self._publisher.publish(self._topic_path(topic), message, key=key).result()
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count", topic=topic)

    def subscribe(self, topic: str, group: str = "default",
                  timeout_s: Optional[float] = None) -> Optional[Message]:
        self.create_topic(topic)
        sub_path = self._sub_path(topic, group)
        if sub_path not in self._ensured_subs:  # admin RPC once, not per poll
            try:
                self._subscriber.create_subscription(
                    name=sub_path, topic=self._topic_path(topic))
            except Exception:  # noqa: BLE001 - already exists
                pass
            self._ensured_subs.add(sub_path)
        # bundled-broker contract: timeout_s=None blocks until a message
        deadline = None if timeout_s is None else time.time() + timeout_s
        while True:
            remaining = 5.0 if deadline is None else max(deadline - time.time(), 0.1)
            try:
                resp = self._subscriber.pull(subscription=sub_path,
                                             max_messages=1, timeout=remaining)
            except Exception as exc:  # noqa: BLE001
                # an empty pull surfaces as DeadlineExceeded in the google
                # client — that is "no message yet", not an error; anything
                # else is a real failure and must propagate, not be spun on
                if type(exc).__name__ != "DeadlineExceeded":
                    raise
                resp = None
            if resp is not None and resp.received_messages:
                break
            if deadline is not None and time.time() >= deadline:
                return None
        received = resp.received_messages[0]

        def _commit():
            self._subscriber.acknowledge(subscription=sub_path,
                                         ack_ids=[received.ack_id])

        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_subscribe_total_count", topic=topic)
        return Message(topic=topic, value=received.message.data,
                       key=received.message.attributes.get("key", ""),
                       metadata=dict(received.message.attributes), committer=_commit)

    def create_topic(self, topic: str) -> None:
        if topic in self._ensured_topics:
            return
        try:
            self._publisher.create_topic(name=self._topic_path(topic))
        except Exception:  # noqa: BLE001 - already exists
            pass
        self._ensured_topics.add(topic)

    def delete_topic(self, topic: str) -> None:
        self._publisher.delete_topic(topic=self._topic_path(topic))
        self._ensured_topics.discard(topic)
        self._ensured_subs = {s for s in self._ensured_subs
                              if f"/{topic}." not in s}

    def health_check(self) -> Health:
        try:
            list(self._publisher.list_topics(project=f"projects/{self.project}",
                                             timeout=2.0))
            return Health(status=STATUS_UP, details={"backend": "google"})
        except Exception:  # noqa: BLE001
            return Health(status=STATUS_DOWN, details={"backend": "google"})
