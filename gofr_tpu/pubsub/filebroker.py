"""Durable file-backed broker: append-only per-topic logs + committed offsets.

This is the build's Kafka analog (reference pkg/gofr/datasource/pubsub/kafka/
kafka.go:30-237): topics are append-only logs that survive restarts, each
(topic, group) pair has a durably-committed offset advanced only when the
handler commits (reference subscriber.go:51-53, kafka/message.go:25-31), and
uncommitted messages are redelivered after a crash.  Unlike the reference it
speaks no network protocol — durability lives on the local filesystem, with
`fcntl` file locks making publish safe across processes (multiple gofr_tpu
apps on one host can share a broker directory the way reference apps share a
Kafka cluster; cross-host ingress stays on the gRPC/HTTP layer per
SURVEY.md §5 "Distributed communication backend").

Log format: one file per topic, a stream of records
    [u32 key_len][u32 val_len][f64 unix_ts][key bytes][value bytes]
Committed offsets: one small text file per (topic, group), written atomically
(tmp + rename) so a crash never leaves a torn offset.

Cross-process consumer groups: a per-(topic, group) state file (flock'd
read-modify-write) holds PER-RECORD claims {index: owner pid + instance id +
expiry} and the set of acked indices above the committed watermark.
Processes sharing a broker directory in the same group work-share: each
subscribe claims the lowest unacked, unclaimed record; commit acks that
record and advances the watermark over the contiguous acked prefix — so a
crashed or expired owner's records are redelivered (its claims stop being
live) while commits from other consumers can never skip them (Kafka's
session-timeout rebalance, in one file, without partitions).
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ..datasource import Health, STATUS_DOWN, STATUS_UP
from . import Client, Message, PubSubLog

_HEADER = struct.Struct("<IId")

try:
    import fcntl

    def _lock(fp):
        fcntl.flock(fp.fileno(), fcntl.LOCK_EX)

    def _unlock(fp):
        fcntl.flock(fp.fileno(), fcntl.LOCK_UN)
except ImportError:  # non-POSIX: single-process use only
    def _lock(fp):
        pass

    def _unlock(fp):
        pass


def _safe_topic(topic: str) -> str:
    if not topic or "/" in topic or topic.startswith("."):
        raise ValueError(f"invalid topic name {topic!r}")
    return topic


class FileBroker(Client):
    """Append-log broker rooted at PUBSUB_DIR (default ./.gofr_pubsub)."""

    def __init__(self, config=None, logger=None, metrics=None, root: str = ""):
        self.logger = logger
        self.metrics = metrics
        if not root and config is not None:
            root = config.get_or_default("PUBSUB_DIR", "")
        self.root = root or ".gofr_pubsub"
        os.makedirs(self.root, exist_ok=True)
        # per-process index: topic -> (record start offsets, bytes indexed);
        # bodies stay on disk and are read on demand, so memory is O(records)
        # pointers, never O(log bytes)
        self._index: Dict[str, Tuple[List[int], int]] = {}
        # instance id distinguishes this broker from an earlier one in the
        # same pid (a restart): the old instance's claims are not honoured
        self._iid = uuid.uuid4().hex
        self._mu = threading.Lock()
        self._poll_s = 0.05
        self._lease_ttl = 30.0
        if config is not None:
            self._poll_s = float(config.get_or_default("PUBSUB_POLL_INTERVAL_S", "0.05"))
            self._lease_ttl = float(config.get_or_default("PUBSUB_LEASE_TTL_S", "30"))

    # ---- paths --------------------------------------------------------------
    def _topic_dir(self, topic: str) -> str:
        return os.path.join(self.root, _safe_topic(topic))

    def _log_path(self, topic: str) -> str:
        return os.path.join(self._topic_dir(topic), "log")

    def _offset_path(self, topic: str, group: str) -> str:
        return os.path.join(self._topic_dir(topic), f"offset.{group}")

    def _lease_path(self, topic: str, group: str) -> str:
        return os.path.join(self._topic_dir(topic), f"lease.{group}")

    # ---- admin --------------------------------------------------------------
    def create_topic(self, topic: str) -> None:
        os.makedirs(self._topic_dir(topic), exist_ok=True)
        path = self._log_path(topic)
        if not os.path.exists(path):
            open(path, "ab").close()

    def delete_topic(self, topic: str) -> None:
        shutil.rmtree(self._topic_dir(topic), ignore_errors=True)
        with self._mu:
            self._index.pop(topic, None)

    # ---- produce ------------------------------------------------------------
    def publish(self, topic: str, message: bytes, key: str = "") -> None:
        if isinstance(message, str):
            message = message.encode()
        self.create_topic(topic)
        kb = key.encode()
        record = _HEADER.pack(len(kb), len(message), time.time()) + kb + message
        with open(self._log_path(topic), "ab") as fp:
            _lock(fp)
            try:
                fp.write(record)
                fp.flush()
                os.fsync(fp.fileno())
            finally:
                _unlock(fp)
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count", topic=topic)
        if self.logger is not None:
            self.logger.debug(PubSubLog("PUB", topic, message.decode("utf-8", "replace")))

    # ---- consume ------------------------------------------------------------
    def _refresh(self, topic: str) -> List[int]:
        """Index record offsets appended since the last refresh (under _mu)."""
        offsets, consumed = self._index.get(topic, ([], 0))
        path = self._log_path(topic)
        try:
            size = os.path.getsize(path)
        except OSError:
            return offsets
        if size <= consumed:
            return offsets
        with open(path, "rb") as fp:
            fp.seek(consumed)
            while consumed + _HEADER.size <= size:
                header = fp.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                klen, vlen, _ts = _HEADER.unpack(header)
                end = consumed + _HEADER.size + klen + vlen
                if end > size:  # torn tail from a concurrent writer; retry later
                    break
                offsets.append(consumed)
                fp.seek(end)
                consumed = end
        self._index[topic] = (offsets, consumed)
        return offsets

    def _read_record(self, topic: str, offset: int) -> Tuple[str, bytes]:
        with open(self._log_path(topic), "rb") as fp:
            fp.seek(offset)
            klen, vlen, _ts = _HEADER.unpack(fp.read(_HEADER.size))
            key = fp.read(klen).decode("utf-8", "replace")
            return key, fp.read(vlen)

    def _committed(self, topic: str, group: str) -> int:
        try:
            with open(self._offset_path(topic, group)) as fp:
                return int(fp.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def _write_offset(self, topic: str, group: str, offset: int) -> None:
        path = self._offset_path(topic, group)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fp:
            fp.write(str(offset))
            fp.flush()
            os.fsync(fp.fileno())
        os.replace(tmp, path)

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
            return True
        except PermissionError:
            return True  # exists, owned by another user
        except (OSError, ProcessLookupError):
            return False

    def _read_state(self, lf) -> Dict:
        """Group delivery state: {"claims": {idx: {pid, iid, expires}},
        "acked": [indices above the committed watermark]}."""
        lf.seek(0)
        raw = lf.read()
        if not raw:
            return {"claims": {}, "acked": []}
        try:
            state = json.loads(raw.decode())
            if "claims" not in state:  # unknown / legacy layout: start clean
                return {"claims": {}, "acked": []}
            return state
        except (ValueError, UnicodeDecodeError):
            return {"claims": {}, "acked": []}

    @staticmethod
    def _write_state(lf, state: Dict) -> None:
        lf.seek(0)
        lf.truncate()
        lf.write(json.dumps(state).encode())
        lf.flush()

    def _claim_live(self, claim: Dict) -> bool:
        """A claim blocks redelivery while its owner is alive and unexpired.
        A claim from this pid but a DIFFERENT broker instance is a leftover
        from a restart in-process and is not honoured."""
        if time.time() >= claim.get("expires", 0):
            return False
        pid = claim.get("pid", -1)
        if pid == os.getpid():
            return claim.get("iid") == self._iid
        return self._pid_alive(pid)

    def subscribe(self, topic: str, group: str = "default",
                  timeout_s: Optional[float] = None) -> Optional[Message]:
        self.create_topic(topic)
        deadline = None if timeout_s is None else time.time() + timeout_s
        while True:
            with self._mu:
                offsets = self._refresh(topic)
            idx = None
            with open(self._lease_path(topic, group), "a+b") as lf:
                _lock(lf)
                try:
                    committed = self._committed(topic, group)
                    state = self._read_state(lf)
                    acked = set(state.get("acked", []))
                    claims = {int(k): v for k, v in state.get("claims", {}).items()
                              if int(k) >= committed and self._claim_live(v)}
                    # lowest record not committed, not acked, not live-claimed
                    for cand in range(committed, len(offsets)):
                        if cand not in acked and cand not in claims:
                            idx = cand
                            break
                    if idx is not None:
                        claims[idx] = {"pid": os.getpid(), "iid": self._iid,
                                       "expires": time.time() + self._lease_ttl}
                        self._write_state(lf, {
                            "claims": {str(k): v for k, v in claims.items()},
                            "acked": sorted(acked)})
                finally:
                    _unlock(lf)
            if idx is not None:
                key, value = self._read_record(topic, offsets[idx])
                break
            if deadline is not None and time.time() >= deadline:
                return None
            time.sleep(self._poll_s)

        def _commit(idx=idx):
            # ack THIS record under the group flock, then advance the durable
            # watermark over the contiguous acked prefix — commits from other
            # consumers can neither regress the offset nor skip an unacked
            # record owned by a crashed peer
            with open(self._lease_path(topic, group), "a+b") as lf:
                _lock(lf)
                try:
                    state = self._read_state(lf)
                    acked = set(state.get("acked", []))
                    acked.add(idx)
                    claims = dict(state.get("claims", {}))
                    claims.pop(str(idx), None)
                    committed = self._committed(topic, group)
                    new_committed = committed
                    while new_committed in acked:
                        acked.discard(new_committed)
                        new_committed += 1
                    if new_committed > committed:
                        self._write_offset(topic, group, new_committed)
                    # prune acks below the watermark (stale double-acks from
                    # crashed peers) so the persisted list cannot grow
                    # unboundedly over the broker's lifetime
                    acked = {i for i in acked if i >= new_committed}
                    self._write_state(lf, {"claims": claims,
                                           "acked": sorted(acked)})
                finally:
                    _unlock(lf)
            if self.metrics is not None:
                self.metrics.increment_counter("app_pubsub_commit_total_count", topic=topic)

        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_subscribe_total_count", topic=topic)
        if self.logger is not None:
            self.logger.debug(PubSubLog("SUB", topic, value.decode("utf-8", "replace")))
        return Message(topic=topic, value=value, key=key,
                       metadata={"offset": idx, "group": group}, committer=_commit)

    def requeue(self, topic: str, group: str = "default") -> None:
        """Release every claim THIS broker instance holds on the group, so
        its delivered-uncommitted records become claimable again."""
        try:
            with open(self._lease_path(topic, group), "a+b") as lf:
                _lock(lf)
                try:
                    state = self._read_state(lf)
                    state["claims"] = {
                        k: v for k, v in state.get("claims", {}).items()
                        if not (v.get("pid") == os.getpid()
                                and v.get("iid") == self._iid)}
                    self._write_state(lf, state)
                finally:
                    _unlock(lf)
        except OSError:
            pass

    # ---- health -------------------------------------------------------------
    def health_check(self) -> Health:
        if not os.path.isdir(self.root):
            return Health(status=STATUS_DOWN, details={"backend": "file", "root": self.root})
        topics = {}
        groups = {}
        with self._mu:
            for topic in sorted(os.listdir(self.root)):
                try:
                    tdir = self._topic_dir(topic)
                except ValueError:  # stray dot-entry / editor artifact: not a topic
                    continue
                if not os.path.isdir(tdir):
                    continue
                topics[topic] = len(self._refresh(topic))
                for entry in os.listdir(tdir):
                    if entry.startswith("offset.") and ".tmp." not in entry:
                        group = entry[len("offset."):]
                        groups[f"{topic}/{group}"] = self._committed(topic, group)
        return Health(status=STATUS_UP, details={
            "backend": "file", "root": self.root, "topics": topics, "groups": groups,
        })
