"""In-process broker with per-(topic, group) committed offsets.

Semantics mirror a Kafka consumer group for the single-process case
(reference kafka/kafka.go:140-218): messages are appended to a per-topic log;
each (topic, group) has a committed offset; `subscribe` returns the next
uncommitted message and only advances the offset when the handler commits
(subscriber.go:51-53). Uncommitted messages are redelivered.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..datasource import Health, STATUS_UP
from . import Client, Message, PubSubLog


class InProcBroker(Client):
    def __init__(self, config=None, logger=None, metrics=None):
        self.logger = logger
        self.metrics = metrics
        self._topics: Dict[str, List[Tuple[str, bytes, float]]] = {}
        self._offsets: Dict[Tuple[str, str], int] = {}   # committed
        self._inflight: Dict[Tuple[str, str], int] = {}  # delivered-not-committed
        self._cond = threading.Condition()

    def create_topic(self, topic: str) -> None:
        with self._cond:
            self._topics.setdefault(topic, [])

    def delete_topic(self, topic: str) -> None:
        with self._cond:
            self._topics.pop(topic, None)
            for key in [k for k in self._offsets if k[0] == topic]:
                self._offsets.pop(key)
                self._inflight.pop(key, None)

    def publish(self, topic: str, message: bytes, key: str = "") -> None:
        if isinstance(message, str):
            message = message.encode()
        with self._cond:
            self._topics.setdefault(topic, []).append((key, message, time.time()))
            self._cond.notify_all()
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count", topic=topic)
        if self.logger is not None:
            self.logger.debug(PubSubLog("PUB", topic, message.decode("utf-8", "replace")))

    def subscribe(self, topic: str, group: str = "default",
                  timeout_s: Optional[float] = None) -> Optional[Message]:
        deadline = None if timeout_s is None else time.time() + timeout_s
        gkey = (topic, group)
        with self._cond:
            while True:
                log = self._topics.setdefault(topic, [])
                committed = self._offsets.get(gkey, 0)
                delivered = max(committed, self._inflight.get(gkey, 0))
                if delivered < len(log):
                    idx = delivered
                    self._inflight[gkey] = delivered + 1
                    key, value, _ts = log[idx]
                    break
                remaining = None if deadline is None else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(timeout=remaining)

        def _commit(offset=idx + 1):
            with self._cond:
                if self._offsets.get(gkey, 0) < offset:
                    self._offsets[gkey] = offset
            if self.metrics is not None:
                self.metrics.increment_counter("app_pubsub_commit_total_count", topic=topic)

        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_subscribe_total_count", topic=topic)
        if self.logger is not None:
            self.logger.debug(PubSubLog("SUB", topic, value.decode("utf-8", "replace")))
        return Message(topic=topic, value=value, key=key,
                       metadata={"offset": idx, "group": group}, committer=_commit)

    def requeue(self, topic: str, group: str = "default") -> None:
        """Roll delivered-not-committed back to the committed offset (handler failed)."""
        with self._cond:
            gkey = (topic, group)
            self._inflight[gkey] = self._offsets.get(gkey, 0)
            self._cond.notify_all()

    def health_check(self) -> Health:
        with self._cond:
            return Health(status=STATUS_UP, details={
                "backend": "inproc",
                "topics": {t: len(log) for t, log in self._topics.items()},
                "groups": {f"{t}/{g}": off for (t, g), off in self._offsets.items()},
            })
