"""INT8 KV-cache serving: quantized engine vs the full-precision engine.

The int8 path quantizes K/V on write (per-token per-head scales) and
dequantizes inside the Pallas decode kernel's dots; the prefill forward is
full-precision (temps quantize only at the splice), so the FIRST sampled
token must match the fp engine exactly. Later tokens may drift where two
logits are near-ties — asserted as high agreement, plus determinism.
"""

import dataclasses

import pytest

from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.tpu.engine import LLMEngine

CFG = LlamaConfig.debug()
CFG_Q8 = dataclasses.replace(CFG, decode_attn="kernel", kv_dtype="int8")

PROMPTS = [list(range(1, 9)), [7, 5, 3], list(range(20, 50)), [11]]


def _serve(cfg, prompts, max_new=12):
    params = llama_init(CFG, seed=0)
    eng = LLMEngine(params, cfg, n_slots=4, max_seq_len=128,
                    prefill_buckets=(8, 32), decode_block_size=4)
    eng.start()
    try:
        reqs = [eng.submit(p, max_new_tokens=max_new, temperature=0.0)
                for p in prompts]
        return [r.result(timeout_s=300) for r in reqs]
    finally:
        eng.stop()


def test_q8_engine_serves_and_matches_fp_closely():
    fp = _serve(dataclasses.replace(CFG, decode_attn="kernel"), PROMPTS)
    q8 = _serve(CFG_Q8, PROMPTS)
    assert [len(t) for t in q8] == [len(t) for t in fp]
    # prefill is full-precision in both: first sampled token identical
    for fp_toks, q8_toks in zip(fp, q8):
        assert fp_toks[0] == q8_toks[0]
    # decode reads differ only by int8 rounding: near-ties may flip, the
    # bulk must agree
    total = sum(len(t) for t in fp)
    agree = sum(a == b for fp_t, q8_t in zip(fp, q8)
                for a, b in zip(fp_t, q8_t))
    assert agree / total > 0.7, f"only {agree}/{total} tokens agree"


@pytest.mark.slow  # tier-1 wall-clock budget; lighter in-lane representative kept
def test_q8_engine_deterministic():
    a = _serve(CFG_Q8, PROMPTS)
    b = _serve(CFG_Q8, PROMPTS)
    assert a == b


@pytest.mark.slow  # tier-1 wall-clock budget; lighter in-lane representative kept
def test_q8_engine_grows_cache():
    """Admission past the boot allocation forces a q8 grow (values AND
    scales pad together)."""
    params = llama_init(CFG, seed=0)
    eng = LLMEngine(params, CFG_Q8, n_slots=2, max_seq_len=128,
                    prefill_buckets=(8, 64), decode_block_size=4)
    eng.start()
    try:
        small = eng.submit([1, 2, 3], max_new_tokens=4, temperature=0.0)
        small.result(timeout_s=300)
        grown = eng.submit(list(range(1, 60)), max_new_tokens=4,
                           temperature=0.0)
        out = grown.result(timeout_s=300)
        assert len(out) == 4
        assert eng._cache_len >= 64
        assert eng.k_scale[0].shape[-1] == eng._cache_len
    finally:
        eng.stop()


def test_q8_requires_kernel_decode():
    params = llama_init(CFG, seed=0)
    with pytest.raises(ValueError, match="decode_attn"):
        LLMEngine(params, dataclasses.replace(CFG, kv_dtype="int8"),
                  n_slots=2, max_seq_len=64, prefill_buckets=(8,))


@pytest.mark.slow  # tier-1 wall-clock budget; lighter in-lane representative kept
def test_q8_chunked_prefill_matches_fused():
    """Chunked admission over the int8 cache: same lengths and (near) the
    fused-q8 tokens. Exact equality is not guaranteed for multi-chunk
    prompts — the fused path runs full-precision prefill attention and
    quantizes once at the splice, while chunk N reads chunks 1..N-1 through
    their quantized values (what decode will read too) — so near-ties may
    flip; lengths, determinism, and bulk agreement are the contract."""
    fused = _serve(CFG_Q8, PROMPTS)

    def serve_chunked():
        params = llama_init(CFG, seed=0)
        eng = LLMEngine(params, CFG_Q8, n_slots=4, max_seq_len=128,
                        prefill_buckets=(8, 32), decode_block_size=4,
                        chunk_prefill_tokens=8)
        eng.start()
        try:
            reqs = [eng.submit(p, max_new_tokens=12, temperature=0.0)
                    for p in PROMPTS]
            return [r.result(timeout_s=300) for r in reqs]
        finally:
            eng.stop()

    chunked = serve_chunked()
    assert [len(t) for t in chunked] == [len(t) for t in fused]
    assert chunked == serve_chunked()          # deterministic
    total = sum(len(t) for t in fused)
    agree = sum(a == b for f, c in zip(fused, chunked)
                for a, b in zip(f, c))
    assert agree / total > 0.6, f"only {agree}/{total} tokens agree"


@pytest.mark.slow  # tier-1 wall-clock budget; lighter in-lane representative kept
def test_q8_engine_tp_mesh_matches_single_device():
    """int8 KV under a tp mesh: values shard KV heads (kv_cache_layer_spec),
    scales shard alongside (kv_scale_layer_spec); greedy decode must match
    the single-device q8 engine token-for-token."""
    import jax

    from gofr_tpu.parallel import MeshPlan, make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    cfg = dataclasses.replace(
        LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=8,
                    n_kv_heads=8, ffn_dim=128, max_seq_len=128,
                    dtype="float32"),
        decode_attn="kernel", kv_dtype="int8")
    mesh = make_mesh(MeshPlan(tp=8))
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [17]]

    def serve(m):
        params = llama_init(dataclasses.replace(cfg, kv_dtype=None), seed=0)
        eng = LLMEngine(params, cfg, n_slots=4, max_seq_len=64,
                        prefill_buckets=(8,), mesh=m)
        eng.start()
        try:
            reqs = [eng.submit(p, max_new_tokens=6, temperature=0.0)
                    for p in prompts]
            return [r.result(timeout_s=240) for r in reqs]
        finally:
            eng.stop()

    assert serve(mesh) == serve(None)


def test_q8_tp_scale_sharding_survives_growth():
    """k/v_scale shard their KV-head axis over tp and KEEP that sharding
    through _grow_cache's q8 re-pad (the regression class the old
    init-time guard existed to prevent)."""
    import jax

    from gofr_tpu.parallel import MeshPlan, make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    cfg = dataclasses.replace(
        LlamaConfig(vocab_size=128, dim=32, n_layers=2, n_heads=4,
                    n_kv_heads=2, ffn_dim=64, max_seq_len=128,
                    dtype="float32"),
        decode_attn="kernel", kv_dtype="int8")
    mesh = make_mesh(MeshPlan(tp=2), devices=jax.devices()[:2])
    params = llama_init(dataclasses.replace(cfg, kv_dtype=None), seed=0)
    eng = LLMEngine(params, cfg, n_slots=2, max_seq_len=128,
                    prefill_buckets=(8,), mesh=mesh)
    ks0 = eng.k_scale[0]
    assert ks0.sharding.shard_shape(ks0.shape)[1] == 1  # Hkv=2 over tp=2
    eng._grow_cache(64)
    assert eng._cache_len == 64
    for scales in (eng.k_scale, eng.v_scale):
        for s in scales:
            assert s.shape[-1] == 64
            assert s.sharding.shard_shape(s.shape)[1] == 1, \
                "scale sharding dropped by growth"
    k0 = eng.k_cache[0]
    assert k0.sharding.shard_shape(k0.shape)[1] == 1


def test_kernel_decode_rounds_incompatible_max_seq_len():
    """decode_attn='kernel' reads the cache in min(512, S)-wide blocks; a
    max_seq_len like 1000 would make the clamped grow target indivisible
    and raise MID-SERVING. The engine must round the cap down at boot
    (ADVICE r3 medium)."""
    params = llama_init(CFG, seed=0)
    cfg = dataclasses.replace(CFG, max_seq_len=8192, decode_attn="kernel")
    eng = LLMEngine(params, cfg, n_slots=2, max_seq_len=1000,
                    prefill_buckets=(8, 512))
    assert eng.max_seq_len == 512
    assert all(b <= 512 for b in eng.prefill_buckets)
    # multiples of 512 and small caps pass through untouched
    assert LLMEngine(params, cfg, n_slots=2, max_seq_len=1536,
                     prefill_buckets=(8,)).max_seq_len == 1536
    assert LLMEngine(params, cfg, n_slots=2, max_seq_len=300,
                     prefill_buckets=(8,)).max_seq_len == 300
    # the xla read has no block constraint: untouched
    xla_cfg = dataclasses.replace(CFG, max_seq_len=8192)
    assert LLMEngine(params, xla_cfg, n_slots=2, max_seq_len=1000,
                     prefill_buckets=(8,)).max_seq_len == 1000


def test_kernel_rounding_cannot_strand_requests():
    """If the 512-rounding leaves NO prefill bucket under the cap, boot
    must fail loudly — r4 review repro: requests were accepted (admission
    limit fell back to max_seq_len-1) but no bucket could ever admit
    them, hanging clients until timeout."""
    params = llama_init(CFG, seed=0)
    cfg = dataclasses.replace(CFG, max_seq_len=8192, decode_attn="kernel")
    with pytest.raises(ValueError, match="no prefill bucket"):
        LLMEngine(params, cfg, n_slots=2, max_seq_len=1000,
                  prefill_buckets=(768,))
