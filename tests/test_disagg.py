"""Disaggregated prefill/decode (tpu/disagg.py): the two-engine split.

The load-bearing assertions (ISSUE 9 "done" criteria):
  - a hand-off round-trips the transport bit-exactly (envelope + page
    blobs), and the disagg pair's served tokens equal the colocated
    engine's goldens token-for-token
  - the decode pool's step ledger contains ZERO prefill steps on the
    healthy path — the invariant the whole split exists to buy
  - every failure mode (corrupt blob, lost payload, dead prefill worker)
    degrades to a recompute fallback on the decode pool: counted, traced,
    and NEVER a failed stream
"""

import json
import threading
import time
import types

import numpy as np
import pytest

from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.tpu.disagg import (HANDOFF_VERSION, DisaggRouter,
                                 QueueTransport, decode_handoff,
                                 encode_handoff)
from gofr_tpu.tpu.kvtier import PageBlob, decode_blob
from gofr_tpu.tpu.paging import PagedLLMEngine

CFG = LlamaConfig.debug()

# greedy max_new=8 goldens for llama_init(debug, seed=0) — same tokens a
# colocated PagedLLMEngine serves (asserted in test_paging's parity tier)
GOLDENS = [
    ([5, 6, 7], [435, 48, 235, 272, 186, 312, 185, 26]),
    ([9, 10, 11, 12, 13, 14, 15, 16, 17], [392, 189, 106, 61, 48, 26, 433, 61]),
    ([1, 2], [417, 417, 417, 417, 480, 223, 509, 417]),
]


class MockLogger:
    def debugf(self, *a): pass
    def infof(self, *a): pass
    def warnf(self, *a): pass
    def errorf(self, *a): pass


def _engine(role, **kw):
    base = dict(n_slots=4, max_seq_len=64, prefill_buckets=(8, 16),
                page_size=8, logger=MockLogger())
    base.update(kw)
    eng = PagedLLMEngine(llama_init(CFG, seed=0), CFG, disagg_role=role,
                         **base)
    eng.start()
    return eng


def _pair(**router_kw):
    pre = _engine("prefill")
    dec = _engine("decode")
    router = DisaggRouter(pre, dec, **router_kw)
    router.start()
    return pre, dec, router


def _teardown(pre, dec, router):
    router.stop()
    if router.worker.alive:
        pre.stop()
    dec.stop()


def _collect(req, timeout_s=120):
    return list(req.stream(timeout_s=timeout_s))


# -- fast no-engine units (`-m disagg` inner loop) ----------------------------


@pytest.mark.disagg
def test_handoff_envelope_round_trips_the_queue():
    rng = np.random.default_rng(0)
    blobs = [PageBlob(tokens=[3, 1, 4, 1, 5],
                      k=rng.normal(size=(2, 2, 4, 8)).astype(np.float32),
                      v=rng.normal(size=(2, 2, 4, 8)).astype(np.float32))
             for _ in range(2)]
    request = types.SimpleNamespace(
        id=7, prompt_tokens=[3, 1, 4, 1, 5], emitted=[9],
        max_new_tokens=16, temperature=0.0, stop_tokens={2},
        priority=1, min_tokens=0, top_p=0.0, top_k=0,
        traceparent="00-" + "ab" * 16 + "-" + "cd" * 8 + "-01",
        gen_span=None)

    transport = QueueTransport(maxsize=4)
    assert transport.publish(encode_handoff(request, blobs, n_ctx=6))
    body = decode_handoff(transport.poll(timeout_s=1.0))

    assert body is not None and body["v"] == HANDOFF_VERSION
    assert body["rid"] == 7 and body["n_ctx"] == 6
    assert body["traceparent"] == request.traceparent
    assert body["spec"]["prompt"] == [3, 1, 4, 1, 5]
    assert body["spec"]["emitted"] == [9]
    assert body["spec"]["stop"] == [2]
    for raw, original in zip(body["blobs"], blobs):
        decoded = decode_blob(raw)
        assert decoded is not None
        assert decoded.tokens == original.tokens
        np.testing.assert_array_equal(decoded.k, original.k)
        np.testing.assert_array_equal(decoded.v, original.v)


@pytest.mark.disagg
def test_decode_handoff_rejects_torn_and_foreign_payloads():
    assert decode_handoff(b"\xff\xfe not json") is None
    assert decode_handoff("[1, 2, 3]") is None
    assert decode_handoff(json.dumps({"v": HANDOFF_VERSION + 1,
                                      "rid": 1, "spec": {}})) is None
    assert decode_handoff(json.dumps({"v": HANDOFF_VERSION,
                                      "spec": {}})) is None


@pytest.mark.disagg
def test_queue_transport_sheds_when_full():
    transport = QueueTransport(maxsize=1)
    assert transport.publish("a")
    assert not transport.publish("b")  # full == False, never blocks
    assert transport.depth() == 1


# -- the split pair on a real (CPU) engine ------------------------------------


def test_disagg_pair_matches_colocated_goldens_with_zero_decode_prefills():
    pre, dec, router = _pair()
    try:
        reqs = [router.submit(prompt, max_new_tokens=len(golden),
                              temperature=0.0)
                for prompt, golden in GOLDENS]
        for (prompt, golden), req in zip(GOLDENS, reqs):
            assert _collect(req) == golden, f"prompt {prompt}"
        assert pre.handoffs_total == len(GOLDENS)
        assert router.coordinator.consumed_total == len(GOLDENS)
        assert (router.fallbacks_total + pre.handoff_fallbacks_total
                + dec.handoff_fallbacks_total) == 0
    finally:
        _teardown(pre, dec, router)
    # the invariant the split buys: the decode pool NEVER ran a prefill
    snap = dec.steps.snapshot(recent=0)
    assert snap["summary"].get("prefill", {}).get("steps", 0) == 0
    assert snap["summary"].get("decode", {}).get("steps", 0) > 0
    # and the prefill pool never burned a decode step on handed-off work
    pre_snap = pre.steps.snapshot(recent=0)
    assert pre_snap["summary"].get("prefill", {}).get("steps", 0) > 0


class _CorruptTransport(QueueTransport):
    """Delivers every hand-off, but flips bytes inside the first page
    blob — crc32 on the decode side must catch it per-page."""

    def publish(self, payload):
        body = json.loads(payload)
        if body.get("blobs"):
            body["blobs"][0] = body["blobs"][0][:-8] + "AAAAAAAA"
        return super().publish(json.dumps(body))


def test_corrupt_blob_degrades_to_recompute_not_failure():
    pre, dec, router = _pair(transport=_CorruptTransport(maxsize=8))
    try:
        prompt, golden = GOLDENS[0]
        req = router.submit(prompt, max_new_tokens=len(golden),
                            temperature=0.0)
        assert _collect(req) == golden  # recompute serves the SAME tokens
        assert router.fallbacks_total >= 1
    finally:
        _teardown(pre, dec, router)


class _LossyTransport(QueueTransport):
    """Claims success and drops every payload — the stale reaper must
    rescue the request (recompute) before the client notices."""

    def publish(self, payload):
        return True


def test_lost_handoff_rescued_by_stale_reaper():
    pre, dec, router = _pair(transport=_LossyTransport(),
                             handoff_timeout_s=0.3)
    try:
        prompt, golden = GOLDENS[1]
        req = router.submit(prompt, max_new_tokens=len(golden),
                            temperature=0.0)
        assert _collect(req) == golden
        assert router.fallbacks_total >= 1
        assert router.coordinator.consumed_total == 0  # nothing arrived
    finally:
        _teardown(pre, dec, router)


def test_prefill_worker_death_never_fails_a_stream():
    pre, dec, router = _pair()
    try:
        in_flight = [router.submit(prompt, max_new_tokens=len(golden),
                                   temperature=0.0)
                     for prompt, golden in GOLDENS * 2]
        router.worker.kill()  # mid-flight: sweep + drain re-route survivors
        post_kill = [router.submit(prompt, max_new_tokens=len(golden),
                                   temperature=0.0)
                     for prompt, golden in GOLDENS]
        for (prompt, golden), req in zip(GOLDENS * 3, in_flight + post_kill):
            assert _collect(req) == golden, f"prompt {prompt}"
            assert req.error is None
        assert router.fallbacks_total >= len(GOLDENS)  # post-kill at least
    finally:
        _teardown(pre, dec, router)


def test_traceparent_survives_the_hop():
    sent = "00-" + "1234567890abcdef" * 2 + "-" + "fedcba0987654321" + "-01"
    captured = []

    class _Tap(QueueTransport):
        def publish(self, payload):
            captured.append(payload)
            return super().publish(payload)

    pre, dec, router = _pair(transport=_Tap())
    try:
        prompt, golden = GOLDENS[2]
        req = router.submit(prompt, max_new_tokens=len(golden),
                            temperature=0.0, traceparent=sent)
        assert _collect(req) == golden
    finally:
        _teardown(pre, dec, router)
    assert len(captured) == 1
    assert decode_handoff(captured[0])["traceparent"] == sent
