"""Migrations, service client + circuit breaker, pub/sub broker, CLI, tracing."""

import threading
import time

import pytest

from gofr_tpu import new_mock_container
from gofr_tpu.migration import MigrationError, run as run_migrations
from gofr_tpu.pubsub.inproc import InProcBroker
from gofr_tpu.service import (CircuitBreaker, CircuitBreakerConfig, CircuitOpenError,
                              DefaultHeaders, HTTPService, new_http_service)
from gofr_tpu.tracing import InMemoryExporter, Tracer, parse_traceparent


# -- migrations ---------------------------------------------------------------
def test_migrations_run_in_order_and_watermark():
    c = new_mock_container()
    order = []

    def m1(ds):
        ds.sql.exec("CREATE TABLE users (id INTEGER)")
        order.append(1)

    def m2(ds):
        ds.kv.set("migrated", "yes")
        order.append(2)

    run_migrations({2: m2, 1: m1}, c)
    assert order == [1, 2]
    assert c.kv.get("migrated") == "yes"
    # watermark persisted; re-run is a no-op
    run_migrations({1: m1, 2: m2}, c)
    assert order == [1, 2]
    versions = {int(r["version"]) for r in c.sql.select(dict, "SELECT * FROM gofr_migrations")}
    assert versions == {1, 2}


def test_migration_failure_rolls_back():
    c = new_mock_container()

    def bad(ds):
        ds.sql.exec("CREATE TABLE halfway (id INTEGER)")
        raise RuntimeError("boom")

    with pytest.raises(MigrationError):
        run_migrations({1: bad}, c)
    # table create rolled back with the tx
    rows = c.sql.query("SELECT name FROM sqlite_master WHERE name='halfway'")
    assert rows == []
    # next run retries version 1
    ran = []
    run_migrations({1: lambda ds: ran.append(1)}, c)
    assert ran == [1]


def test_invalid_migration_version():
    c = new_mock_container()
    with pytest.raises(MigrationError):
        run_migrations({0: lambda ds: None}, c)


# -- circuit breaker ----------------------------------------------------------
def test_circuit_breaker_opens_after_threshold():
    svc = HTTPService("http://127.0.0.1:1")  # nothing listens here
    svc.timeout_s = 0.05
    breaker = CircuitBreakerConfig(threshold=2, interval_s=100).apply(svc)
    for _ in range(3):
        with pytest.raises(Exception):
            breaker.get(None, "x")
    assert breaker.open
    with pytest.raises(CircuitOpenError):
        breaker.get(None, "x")
    assert breaker.health_check().status == "DOWN"


def test_circuit_breaker_success_resets_count():
    svc = HTTPService("http://example.invalid")
    breaker = CircuitBreaker(svc, threshold=3, interval_s=100)
    breaker.failure_count = 2
    breaker._execute(lambda: "ok")
    assert breaker.failure_count == 0


def test_service_options_compose():
    svc = new_http_service("http://x", None, None, DefaultHeaders(a="1", b="2"))
    assert svc.default_headers == {"a": "1", "b": "2"}


# -- in-proc broker -----------------------------------------------------------
def test_broker_publish_subscribe_commit():
    broker = InProcBroker()
    broker.publish("t", b"m1")
    broker.publish("t", b"m2")
    msg = broker.subscribe("t", group="g", timeout_s=1)
    assert msg.value == b"m1"
    msg.commit()
    msg = broker.subscribe("t", group="g", timeout_s=1)
    assert msg.value == b"m2"
    # uncommitted -> requeue redelivers
    broker.requeue("t", group="g")
    assert broker.subscribe("t", group="g", timeout_s=1).value == b"m2"


def test_broker_independent_groups():
    broker = InProcBroker()
    broker.publish("t", b"x")
    m1 = broker.subscribe("t", group="g1", timeout_s=1)
    m2 = broker.subscribe("t", group="g2", timeout_s=1)
    assert m1.value == m2.value == b"x"


def test_broker_blocks_until_publish():
    broker = InProcBroker()
    result = {}

    def consume():
        result["msg"] = broker.subscribe("late", timeout_s=5)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)
    broker.publish("late", b"hello")
    t.join(timeout=5)
    assert result["msg"].value == b"hello"


def test_broker_timeout_returns_none():
    broker = InProcBroker()
    assert broker.subscribe("empty", timeout_s=0.05) is None


def test_message_bind():
    from gofr_tpu.pubsub import Message

    msg = Message("t", b'{"a": 5}')
    assert msg.bind() == {"a": 5}


# -- CLI ----------------------------------------------------------------------
def test_cmd_app_routes_and_flags(capsys):
    from gofr_tpu.cmd import CMDApp

    c = new_mock_container()
    app = CMDApp(container=c)

    @app.sub_command("hello")
    def hello(ctx):
        return f"hello {ctx.param('name')}"

    assert app.run(["hello", "-name=ada"]) == 0
    assert "hello ada" in capsys.readouterr().out
    assert app.run(["unknown"]) == 1
    assert "No Command Found" in capsys.readouterr().err


def test_cmd_bind_dataclass():
    import dataclasses

    from gofr_tpu.cmd import CMDRequest

    @dataclasses.dataclass
    class Args:
        count: int = 0
        verbose: bool = False

    req = CMDRequest(["-count=3", "--verbose"])
    args = req.bind(Args)
    assert args.count == 3 and args.verbose is True


# -- tracing ------------------------------------------------------------------
def test_span_hierarchy_and_export():
    exporter = InMemoryExporter()
    tracer = Tracer(exporter=exporter)
    with tracer.start_span("parent") as parent:
        with tracer.start_span("child", parent=parent) as child:
            child.set_attribute("k", "v")
    assert len(exporter.spans) == 2
    child_span, parent_span = exporter.spans
    assert child_span.trace_id == parent_span.trace_id
    assert child_span.parent_id == parent_span.span_id


def test_parse_traceparent():
    assert parse_traceparent("00-" + "a" * 32 + "-" + "b" * 16 + "-01") == ("a" * 32, "b" * 16)
    assert parse_traceparent("garbage") is None
