import dataclasses
import time

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.datasource.kvstore import KVStore
from gofr_tpu.datasource.sql import SQL
from gofr_tpu.logging import MockLogger
from gofr_tpu.metrics import Manager


@pytest.fixture()
def db():
    metrics = Manager()
    metrics.new_histogram("app_sql_stats", "")
    return SQL(MockConfig({"DB_PATH": ":memory:"}), MockLogger(), metrics)


@pytest.fixture()
def kv():
    metrics = Manager()
    metrics.new_histogram("app_kv_stats", "")
    return KVStore(MockConfig(), MockLogger(), metrics)


# -- SQL ----------------------------------------------------------------------
def test_sql_exec_query_select(db):
    db.exec("CREATE TABLE t (id INTEGER PRIMARY KEY, name TEXT)")
    db.exec("INSERT INTO t (id, name) VALUES (?, ?)", 1, "a")
    db.exec("INSERT INTO t (id, name) VALUES (?, ?)", 2, "b")
    assert db.query_row("SELECT name FROM t WHERE id = ?", 2)["name"] == "b"

    @dataclasses.dataclass
    class Row:
        id: int
        name: str

    rows = db.select(Row, "SELECT * FROM t ORDER BY id")
    assert rows == [Row(1, "a"), Row(2, "b")]
    assert db.select(dict, "SELECT * FROM t")[0]["name"] == "a"


def test_sql_transaction_commit_rollback(db):
    db.exec("CREATE TABLE t (id INTEGER)")
    with db.begin() as tx:
        tx.exec("INSERT INTO t VALUES (1)")
    assert len(db.query("SELECT * FROM t")) == 1
    try:
        with db.begin() as tx:
            tx.exec("INSERT INTO t VALUES (2)")
            raise RuntimeError("abort")
    except RuntimeError:
        pass
    assert len(db.query("SELECT * FROM t")) == 1  # rolled back


def test_sql_health(db):
    health = db.health_check()
    assert health.status == "UP"
    assert health.details["dialect"] == "sqlite"


def test_sql_metrics_recorded(db):
    db.exec("CREATE TABLE t (id INTEGER)")
    db.query("SELECT * FROM t")
    text = db.metrics.expose()
    assert 'type="SELECT"' in text and 'type="CREATE"' in text


# -- KV -----------------------------------------------------------------------
def test_kv_basic_ops(kv):
    kv.set("a", "1")
    assert kv.get("a") == "1"
    assert kv.exists("a")
    assert kv.delete("a") == 1
    assert kv.get("a") is None
    assert kv.incr("n") == 1
    assert kv.incr("n", 5) == 6
    assert kv.decr("n") == 5


def test_kv_ttl(kv):
    kv.set("x", "v", ttl_s=0.05)
    assert kv.get("x") == "v"
    assert 0 < kv.ttl("x") <= 0.05
    time.sleep(0.06)
    assert kv.get("x") is None
    assert kv.ttl("x") == -2.0
    kv.set("y", "v")
    assert kv.ttl("y") == -1.0
    assert kv.expire("y", 10)
    assert kv.ttl("y") > 9


def test_kv_hashes_and_keys(kv):
    kv.hset("h", "f1", "v1")
    kv.hset("h", "f2", "v2")
    assert kv.hget("h", "f1") == "v1"
    assert kv.hgetall("h") == {"f1": "v1", "f2": "v2"}
    kv.set("other", 1)
    assert sorted(kv.keys("*")) == ["h", "other"]
    assert kv.keys("h*") == ["h"]


def test_kv_pipeline_atomic(kv):
    pipe = kv.pipeline()
    pipe.set("a", 1).hset("h", "f", 2).set("b", 3)
    assert kv.get("a") is None  # not applied yet
    pipe.exec()
    assert kv.get("a") == 1 and kv.hget("h", "f") == 2 and kv.get("b") == 3


def test_kv_health(kv):
    kv.set("k", "v")
    health = kv.health_check()
    assert health.status == "UP"
    assert health.details["keys"] == 1
