"""Worker for the LIVE-TRAFFIC multi-host serving test.

The successor to multihost_serving_worker.py's determinism contract: here
NOTHING is pre-queued. Rank 0 is the only ingress — a submitter thread
feeds it requests WHILE the tp=2 engine loop runs (staggered arrivals, a
mid-flight cancel) — and every wave's composition reaches rank 1 over the
jax.distributed coordination-service KV store (tpu/admission.py), the same
DCN plane that formed the global device set. Rank 1 reconstructs shadow
requests from the waves alone and must mirror the leader token-for-token;
rank 0 additionally checks itself against a pre-computed single-device
oracle. VERDICT r4 next-round #4.

Usage: python multihost_live_worker.py <rank> <coordinator_port>
"""

import os
import sys
import threading
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001
    pass

from gofr_tpu.config import MockConfig  # noqa: E402
from gofr_tpu.models.llama import LlamaConfig, llama_init  # noqa: E402
from gofr_tpu.parallel import MeshPlan, make_mesh  # noqa: E402
from gofr_tpu.parallel.multihost import initialize_from_config  # noqa: E402
from gofr_tpu.tpu.admission import AdmissionPlane  # noqa: E402
from gofr_tpu.tpu.engine import LLMEngine  # noqa: E402

PROMPTS = [[1, 2, 3, 4], [9, 8, 7], [5], [11, 12, 13, 14], [3, 1]]
CANCEL_INDEX = 3          # cancelled after its 2nd token, mid-generation
# the victim gets a DEEP budget: under CPU contention the canceling
# consumer thread can lag many decode blocks behind the engine, and the
# cancel must still provably cut the generation short
BUDGETS = [6, 6, 6, 96, 6]
CFG = LlamaConfig(vocab_size=128, dim=32, n_layers=2, n_heads=2,
                  n_kv_heads=2, ffn_dim=64, max_seq_len=128, dtype="float32")


def _engine(mesh, plane):
    return LLMEngine(llama_init(CFG, seed=0), CFG, n_slots=4,
                     max_seq_len=128, prefill_buckets=(8,),
                     decode_block_size=4, mesh=mesh, admission_plane=plane)


def _checksum(token_lists):
    return sum(t * (i + 1) for i, toks in enumerate(token_lists)
               for t in toks)


def _lead(mesh):
    # construct the TP engine FIRST: sharded placement forms the
    # cross-process collective context, and rank 1 builds its twin at
    # process start — running the slow oracle first would leave rank 1
    # alone at the rendezvous until its connect timeout (observed: Gloo
    # context initialization failure under host load)
    eng = _engine(mesh, AdmissionPlane(kv=None))

    # oracle: single-device, no plane — the expected token streams
    oracle_eng = _engine(None, None)
    oracle_eng.start()
    try:
        oracle = [oracle_eng.generate(p, max_new_tokens=budget,
                                      temperature=0.0)
                  for p, budget in zip(PROMPTS, BUDGETS)]
    finally:
        oracle_eng.stop()

    eng.start()
    requests = []
    try:
        def submitter():
            for p, budget in zip(PROMPTS, BUDGETS):
                requests.append(eng.submit(p, max_new_tokens=budget,
                                           temperature=0.0))
                time.sleep(0.15)  # arrivals land across many live waves

        t = threading.Thread(target=submitter)
        t.start()
        t.join()
        victim = requests[CANCEL_INDEX]
        got_victim = []
        for tok in victim.stream(timeout_s=240):
            got_victim.append(tok)
            if len(got_victim) == 2:
                victim.cancel()
        served = [got_victim if i == CANCEL_INDEX
                  else r.result(timeout_s=240)
                  for i, r in enumerate(requests)]
        # uncancelled requests must match the oracle exactly; the victim
        # must be a strict prefix, cut short
        for i, toks in enumerate(served):
            if i == CANCEL_INDEX:
                assert 2 <= len(toks) < BUDGETS[i], toks
                assert toks == oracle[i][:len(toks)], (toks, oracle[i])
            else:
                assert toks == oracle[i], (i, toks, oracle[i])
        return served
    finally:
        eng.stop()  # publishes the stop sentinel for rank 1


def _follow(mesh):
    plane = AdmissionPlane(kv=None)
    shadows = []
    plane.on_shadow = shadows.append
    eng = _engine(mesh, plane)
    eng.start()
    try:
        deadline = time.time() + 240
        while time.time() < deadline:
            if plane.closed and len(shadows) == len(PROMPTS) and all(
                    s.finished_at is not None for s in shadows):
                break
            time.sleep(0.05)
        assert len(shadows) == len(PROMPTS), len(shadows)
        by_order = sorted(shadows, key=lambda s: s.id)
        return [list(s.stream(timeout_s=5)) for s in by_order]
    finally:
        eng.stop()


def main() -> None:
    rank, port = int(sys.argv[1]), sys.argv[2]
    spec = initialize_from_config(MockConfig({
        "JAX_COORDINATOR_ADDR": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
        "JAX_PROCESS_ID": str(rank),
        # generous: under a fully-loaded CI box (the whole suite runs in
        # parallel with 8-device compiles) rank startup skew alone has
        # blown a 60s rendezvous
        "JAX_COORDINATOR_TIMEOUT_S": "150",
    }))
    assert spec is not None and spec.process_id == rank
    assert jax.process_count() == 2

    mesh = make_mesh(MeshPlan(tp=2), devices=jax.devices())
    served = _lead(mesh) if rank == 0 else _follow(mesh)
    print(f"RANK{rank}_LIVE_OK checksum={_checksum(served)}", flush=True)
    # exit barrier: unlike the pre-queued worker, the two ranks finish at
    # different times here (rank 0 stops first) — if rank 0's process (it
    # hosts the coordination service) exits while rank 1 is still busy,
    # rank 1's distributed-shutdown handshake aborts the interpreter
    from jax._src import distributed

    distributed.global_state.client.wait_at_barrier("live-worker-exit",
                                                    120_000)
    # hard-exit past interpreter teardown: the asymmetric shutdown (the
    # leader stops serving before the follower finishes mirroring) leaves
    # the distributed runtime's internal threads in states its destructor
    # aborts on (pthread-cancel of a parked poller -> "exception not
    # rethrown"). Both ranks have printed and synced; nothing of value
    # runs after this line.
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
