import dataclasses
import json

import pytest

from gofr_tpu.http.errors import EntityNotFound, HTTPError, status_from_method
from gofr_tpu.http.request import BindError, Request
from gofr_tpu.http.responder import File, Raw, Responder, Response, Stream
from gofr_tpu.http.router import Router


def make_request(method="GET", target="/", body=b"", headers=None):
    return Request(method, target, headers=headers or {}, body=body)


# -- request ------------------------------------------------------------------
def test_query_and_path_params():
    req = make_request(target="/items?x=1&x=2&y=hi")
    assert req.param("x") == "1"
    assert req.params("x") == ["1", "2"]
    assert req.param("missing") == ""
    req.path_params = {"id": "42"}
    assert req.path_param("id") == "42"


def test_bind_json_dict_and_dataclass():
    @dataclasses.dataclass
    class Person:
        name: str = ""
        age: int = 0

    body = json.dumps({"name": "ada", "age": 36, "extra": True}).encode()
    req = make_request("POST", "/p", body=body)
    assert req.bind()["name"] == "ada"
    person = req.bind(Person)
    assert person.name == "ada" and person.age == 36


def test_bind_invalid_json():
    req = make_request("POST", "/p", body=b"{nope")
    with pytest.raises(BindError):
        req.bind()


def test_bind_multipart():
    boundary = "XXX"
    body = (
        f"--{boundary}\r\nContent-Disposition: form-data; name=\"field\"\r\n\r\nvalue\r\n"
        f"--{boundary}\r\nContent-Disposition: form-data; name=\"f\"; filename=\"a.txt\"\r\n"
        f"Content-Type: text/plain\r\n\r\nfilebytes\r\n--{boundary}--\r\n"
    ).encode()
    req = make_request("POST", "/u", body=body,
                       headers={"Content-Type": f"multipart/form-data; boundary={boundary}"})
    data = req.bind()
    assert data["field"] == "value"
    assert data["f"]["filename"] == "a.txt"
    assert data["f"]["content"] == b"filebytes"


# -- responder ----------------------------------------------------------------
def test_envelope_success_and_status_by_method():
    resp = Responder("GET").respond({"k": 1}, None)
    assert resp.status == 200
    assert json.loads(resp.body) == {"data": {"k": 1}}
    assert Responder("POST").respond("x", None).status == 201
    assert Responder("DELETE").respond(None, None).status == 204


def test_envelope_error_mapping():
    resp = Responder("GET").respond(None, EntityNotFound("id", "9"))
    assert resp.status == 404
    assert "No entity found" in json.loads(resp.body)["error"]["message"]
    assert Responder("GET").respond(None, ValueError("x")).status == 500
    assert Responder("GET").respond(None, HTTPError("teapot", 418)).status == 418


def test_raw_and_file_passthrough():
    resp = Responder("GET").respond(Raw([1, 2]), None)
    assert json.loads(resp.body) == [1, 2]
    resp = Responder("GET").respond(File(b"PNG", content_type="image/png"), None)
    assert resp.body == b"PNG" and resp.headers["Content-Type"] == "image/png"


def test_stream_sse():
    resp = Responder("GET").respond(Stream(iter(["a", {"t": 1}]), sse=True), None)
    chunks = list(resp.stream)
    assert chunks[0] == b"data: a\n\n"
    assert chunks[1] == b'data: {"t": 1}\n\n'
    assert resp.headers["Content-Type"] == "text/event-stream"


def test_status_from_method():
    assert status_from_method("POST") == 201
    assert status_from_method("GET") == 200


# -- router -------------------------------------------------------------------
def ok_handler(body=b"ok"):
    return lambda req: Response(status=200, body=body)


def test_router_match_and_path_params():
    router = Router()
    router.add("GET", "/users/{id}/posts/{pid}", lambda req: Response(
        status=200, body=f"{req.path_param('id')}:{req.path_param('pid')}".encode()))
    resp = router.dispatch(make_request(target="/users/7/posts/9"))
    assert resp.body == b"7:9"


def test_router_404_405():
    router = Router()
    router.add("GET", "/a", ok_handler())
    assert router.dispatch(make_request(target="/missing")).status == 404
    assert router.dispatch(make_request("POST", "/a")).status == 405


def test_router_trailing_slash():
    router = Router()
    router.add("GET", "/a", ok_handler())
    assert router.dispatch(make_request(target="/a/")).status == 200


def test_middleware_order_and_wrap():
    router = Router()
    calls = []

    def mw(tag):
        def middleware(inner):
            def handle(req):
                calls.append(tag)
                return inner(req)
            return handle
        return middleware

    router.use_middleware(mw("outer"), mw("inner"))
    router.add("GET", "/x", ok_handler())
    router.dispatch(make_request(target="/x"))
    assert calls == ["outer", "inner"]
