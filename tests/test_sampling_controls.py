"""Per-request top_p / top_k: the [B, 3] row-control sampling plane.

sampling_controls=True widens the engine's per-row sampling state from [B]
temperatures to [B, 3] (temperature, top_p, top_k) — every program signature
is unchanged (the state travels as one array), and a row's 0 disables that
control. Key deterministic property used throughout: top_k=1 (or a
vanishingly small top_p) at ANY temperature must reproduce greedy output
exactly, because the truncated distribution has one survivor.
"""

import dataclasses

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.tpu.engine import LLMEngine
from gofr_tpu.tpu.paging import PagedLLMEngine
from gofr_tpu.tpu.sampling import pack_controls, sample_tokens, temperature_of

CFG = LlamaConfig.debug()
PROMPTS = [[5, 6, 7, 8, 5, 6, 7, 8], [9, 8, 7], list(range(20, 50)), [11]]


def test_sampler_per_row_top_k_one_is_greedy():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(jax.random.PRNGKey(1), (6, 64),
                               dtype=jnp.float32)
    greedy = jnp.argmax(logits, axis=-1)
    samp = jnp.asarray(pack_controls(
        temperature=[1.0] * 6, top_p=[0.0] * 6, top_k=[1] * 6))
    toks, _ = sample_tokens(logits, rng, samp)
    assert jnp.array_equal(toks, greedy)


def test_sampler_per_row_tiny_top_p_is_greedy():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(jax.random.PRNGKey(2), (6, 64),
                               dtype=jnp.float32) * 4.0
    greedy = jnp.argmax(logits, axis=-1)
    samp = jnp.asarray(pack_controls(
        temperature=[0.9] * 6, top_p=[1e-4] * 6, top_k=[0] * 6))
    toks, _ = sample_tokens(logits, rng, samp)
    assert jnp.array_equal(toks, greedy)


def test_sampler_rows_are_independent():
    """One dispatch, mixed rows: greedy row, top_k=1 row, unrestricted
    sampled row — each row's control applies to that row only."""
    rng = jax.random.PRNGKey(3)
    logits = jax.random.normal(jax.random.PRNGKey(4), (3, 256),
                               dtype=jnp.float32)
    greedy = jnp.argmax(logits, axis=-1)
    samp = jnp.asarray(pack_controls(
        temperature=[0.0, 1.0, 50.0],   # row 2: near-uniform sampling
        top_p=[0.0, 0.0, 0.0],
        top_k=[0, 1, 0]))
    toks, _ = sample_tokens(logits, rng, samp)
    assert toks[0] == greedy[0]
    assert toks[1] == greedy[1]
    # row 2 at temperature 50 over 256 logits: overwhelmingly unlikely to
    # hit the argmax across several rng draws — prove it CAN differ
    differed = False
    r = rng
    for _ in range(8):
        t, r = sample_tokens(logits, r, samp)
        differed = differed or int(t[2]) != int(greedy[2])
    assert differed, "unrestricted sampled row never left the argmax"


def test_temperature_of_both_shapes():
    flat = jnp.asarray([0.0, 0.7])
    wide = jnp.asarray(pack_controls([0.0, 0.7], [0.5, 0.0], [3, 0]))
    assert jnp.array_equal(temperature_of(flat), flat)
    assert jnp.array_equal(temperature_of(wide), flat)


def _serve(cls=LLMEngine, controls=True, submits=None, **kw):
    params = llama_init(CFG, seed=0)
    if cls is PagedLLMEngine:
        kw.setdefault("page_size", 16)
    eng = cls(params, CFG, n_slots=4, max_seq_len=128,
              prefill_buckets=(8, 32), decode_block_size=4,
              sampling_controls=controls, **kw)
    eng.start()
    try:
        reqs = [eng.submit(p, **(s or {"max_new_tokens": 10,
                                      "temperature": 0.0}))
                for p, s in zip(PROMPTS, submits or [None] * len(PROMPTS))]
        return [r.result(timeout_s=300) for r in reqs]
    finally:
        eng.stop()


def test_controls_engine_greedy_parity():
    """Pure-greedy traffic must be identical with and without the widened
    sampling state (the [B, 3] plane changes nothing for temperature 0)."""
    assert _serve(controls=True) == _serve(controls=False)


@pytest.mark.parametrize("cls", [
    LLMEngine,
    # tier-1 wall-clock budget: dense variant stays as the in-lane rep
    pytest.param(PagedLLMEngine, marks=pytest.mark.slow),
])
def test_top_k_one_matches_greedy_end_to_end(cls):
    """temperature 1.0 + top_k=1 leaves one survivor per step: the served
    tokens must equal the greedy run's token-for-token, on both engines."""
    want = _serve(cls=cls, controls=False)
    sub = [{"max_new_tokens": 10, "temperature": 1.0, "top_k": 1}
           for _ in PROMPTS]
    assert _serve(cls=cls, submits=sub) == want


@pytest.mark.slow  # tier-1 wall-clock budget; lighter in-lane representative kept
def test_tiny_top_p_matches_greedy_end_to_end():
    want = _serve(controls=False)
    sub = [{"max_new_tokens": 10, "temperature": 0.8, "top_p": 1e-4}
           for _ in PROMPTS]
    assert _serve(submits=sub) == want


def test_speculative_composes_with_controls():
    """Spec mode + sampling controls: greedy rows still match the plain
    engine exactly (the verify's greedy-row rule reads temperature through
    temperature_of)."""
    params = llama_init(CFG, seed=0)
    eng = LLMEngine(params, CFG, n_slots=4, max_seq_len=128,
                    prefill_buckets=(8, 32), speculative_tokens=4,
                    sampling_controls=True)
    eng.start()
    try:
        reqs = [eng.submit(p, max_new_tokens=12, temperature=0.0)
                for p in PROMPTS]
        got = [r.result(timeout_s=300) for r in reqs]
    finally:
        eng.stop()
    want = _serve(controls=False, submits=[
        {"max_new_tokens": 12, "temperature": 0.0} for _ in PROMPTS])
    assert got == want


def test_submit_validation():
    params = llama_init(CFG, seed=0)
    eng = LLMEngine(params, CFG, n_slots=2, max_seq_len=64,
                    prefill_buckets=(8,))
    with pytest.raises(ValueError, match="sampling_controls"):
        eng.submit([1, 2], top_p=0.5)
    with pytest.raises(ValueError, match="sampling_controls"):
        eng.submit([1, 2], top_k=5)
    eng2 = LLMEngine(params, CFG, n_slots=2, max_seq_len=64,
                     prefill_buckets=(8,), sampling_controls=True)
    with pytest.raises(ValueError, match="top_p"):
        eng2.submit([1, 2], top_p=1.5)
    with pytest.raises(ValueError, match="top_k"):
        eng2.submit([1, 2], top_k=-1)


def test_paged_speculative_composes_with_controls():
    """The exact OpenAI-server default stack: paged pool + speculation +
    sampling controls. The verify program must run (r4 review repro: the
    paged acceptance used a raw `temps <= 0.0` against [B, 3] controls and
    crashed on the first proposed draft)."""
    params = llama_init(CFG, seed=0)
    eng = PagedLLMEngine(params, CFG, n_slots=4, max_seq_len=128,
                         prefill_buckets=(8, 32), page_size=16,
                         speculative_tokens=4, sampling_controls=True)
    eng.start()
    try:
        reqs = [eng.submit(p, max_new_tokens=12, temperature=0.0)
                for p in PROMPTS]
        got = [r.result(timeout_s=300) for r in reqs]
    finally:
        eng.stop()
    assert got == _serve(controls=False, submits=[
        {"max_new_tokens": 12, "temperature": 0.0} for _ in PROMPTS])


def test_control_row_clears_when_slot_frees():
    """A finished top_p/top_k request must not leave its device-side
    control row behind — the sampler gates its [B, V] sort on ANY row's
    controls, so a stale row would tax every later all-greedy batch."""
    params = llama_init(CFG, seed=0)
    eng = LLMEngine(params, CFG, n_slots=2, max_seq_len=64,
                    prefill_buckets=(8,), sampling_controls=True)
    eng.start()
    try:
        eng.submit([1, 2, 3], max_new_tokens=4, temperature=0.9,
                   top_p=0.5, top_k=3).result(timeout_s=300)
        deadline = 300
        import time as _t
        end = _t.time() + deadline
        while any(s.active for s in eng.slots) and _t.time() < end:
            _t.sleep(0.01)
        controls = np.asarray(eng._temps)[:, 1:]
        assert (controls == 0.0).all(), controls
    finally:
        eng.stop()


def test_row_top_k_then_top_p_composition():
    """ADVICE r4: when a row sets BOTH filters, the nucleus mass must be
    computed over the top_k-FILTERED renormalized distribution (HF/vLLM
    composition). Construct logits where the two orders provably differ:
    probs ~ [0.4, 0.3, 0.2, 0.1]; top_k=2 renormalizes to [0.571, 0.429];
    top_p=0.5 must then keep ONLY token 0 (0.571 >= 0.5) — whereas top_p
    over the unfiltered distribution keeps tokens {0, 1} (0.4 < 0.5).
    Sampling at any seed must therefore always return token 0."""
    p = np.array([0.4, 0.3, 0.2, 0.1] + [1e-9] * 60)
    logits = jnp.asarray(np.log(p / p.sum()), dtype=jnp.float32)[None, :]
    samp = jnp.asarray(pack_controls(temperature=[1.0], top_p=[0.5],
                                     top_k=[2]))
    rng = jax.random.PRNGKey(0)
    for _ in range(20):
        toks, rng = sample_tokens(logits, rng, samp)
        assert int(toks[0]) == 0


def test_row_top_p_alone_keeps_small_prefix():
    """Same distribution, top_p=0.5 with no top_k: nucleus over the raw
    distribution is {0, 1} (0.4 < 0.5 <= 0.7) — token 2 never samples."""
    p = np.array([0.4, 0.3, 0.2, 0.1] + [1e-9] * 60)
    logits = jnp.asarray(np.log(p / p.sum()), dtype=jnp.float32)[None, :]
    samp = jnp.asarray(pack_controls(temperature=[1.0], top_p=[0.5],
                                     top_k=[0]))
    rng = jax.random.PRNGKey(0)
    seen = set()
    for _ in range(40):
        toks, rng = sample_tokens(logits, rng, samp)
        seen.add(int(toks[0]))
    assert seen <= {0, 1} and 0 in seen
