"""Real-weights ingestion: safetensors round-trip, streaming load, int8.

The r4 verdict's Missing #1: every served model was a random tree because
no weights-on-disk import path existed. These tests synthesize HF-layout
checkpoints with the module's own writer, then prove the loader boots a
model that is logits-EXACT vs the from-memory oracle — float, int8
quantize-on-load, tied embeddings, sharded index files, and the engine
end-to-end (greedy tokens identical from disk vs from memory).
"""

import json
import os
import struct

import numpy as np
import pytest

from gofr_tpu.models.llama import (LlamaConfig, llama_init, llama_prefill,
                                   init_kv_cache, quantize_weights)
from gofr_tpu.models.weights import (CheckpointReader, SafetensorsFile,
                                     export_llama_safetensors,
                                     load_llama_safetensors,
                                     write_safetensors)

CFG = LlamaConfig.debug()


def _logits(params, cfg, tokens):
    k, v = init_kv_cache(cfg, tokens.shape[0], tokens.shape[1])
    out, _, _ = llama_prefill(params, cfg, tokens, k, v)
    return np.asarray(out)


def _tokens(cfg, batch=2, t=16, seed=3):
    rng = np.random.default_rng(seed)
    import jax.numpy as jnp

    return jnp.asarray(rng.integers(1, cfg.vocab_size, size=(batch, t)),
                       dtype=jnp.int32)


# ---------------------------------------------------------------------------
# container format
# ---------------------------------------------------------------------------

def test_safetensors_roundtrip_dtypes(tmp_path):
    import ml_dtypes

    path = str(tmp_path / "t.safetensors")
    tensors = {
        "f32": np.arange(12, dtype=np.float32).reshape(3, 4),
        "f16": np.linspace(-2, 2, 8, dtype=np.float16),
        "bf16": np.linspace(-1, 1, 6).astype(ml_dtypes.bfloat16).reshape(2, 3),
        "i8": np.arange(-5, 5, dtype=np.int8),
        "i64": np.array([2**40, -7], dtype=np.int64),
        "scalar": np.float32(7.5).reshape(()),
    }
    write_safetensors(path, tensors, metadata={"format": "pt"})
    f = SafetensorsFile(path)
    assert f.metadata == {"format": "pt"}
    assert set(f.keys()) == set(tensors)
    for name, want in tensors.items():
        got = f.tensor(name)
        assert got.dtype == want.dtype, name
        assert got.shape == want.shape, name
        np.testing.assert_array_equal(np.asarray(got, np.float64),
                                      np.asarray(want, np.float64))


def test_safetensors_header_is_standard(tmp_path):
    """Byte-level check against the published container layout: 8-byte LE
    length, JSON header, offsets relative to the data section."""
    path = str(tmp_path / "t.safetensors")
    write_safetensors(path, {"a": np.zeros((2, 2), np.float32)})
    raw = open(path, "rb").read()
    (hlen,) = struct.unpack("<Q", raw[:8])
    header = json.loads(raw[8:8 + hlen])
    assert header["a"]["dtype"] == "F32"
    assert header["a"]["shape"] == [2, 2]
    assert header["a"]["data_offsets"] == [0, 16]
    assert len(raw) == 8 + hlen + 16


def test_reader_rejects_corrupt_range(tmp_path):
    path = str(tmp_path / "t.safetensors")
    write_safetensors(path, {"a": np.zeros(4, np.float32)})
    raw = bytearray(open(path, "rb").read())
    (hlen,) = struct.unpack("<Q", raw[:8])
    header = json.loads(raw[8:8 + hlen])
    header["a"]["shape"] = [8]  # lies about the shape
    hb = json.dumps(header).encode()
    with open(path, "wb") as fp:
        fp.write(struct.pack("<Q", len(hb)))
        fp.write(hb)
        fp.write(raw[8 + hlen:])
    f = SafetensorsFile(path)
    with pytest.raises(ValueError, match="byte range"):
        f.tensor("a")


# ---------------------------------------------------------------------------
# HF-layout llama loading
# ---------------------------------------------------------------------------

def test_load_float_logits_exact(tmp_path):
    params = llama_init(CFG, seed=0)
    path = str(tmp_path / "model.safetensors")
    export_llama_safetensors(params, path)
    loaded = load_llama_safetensors(CFG, path)
    toks = _tokens(CFG)
    np.testing.assert_array_equal(_logits(params, CFG, toks),
                                  _logits(loaded, CFG, toks))


def test_load_directory_form(tmp_path):
    params = llama_init(CFG, seed=1)
    export_llama_safetensors(params, str(tmp_path / "model.safetensors"))
    loaded = load_llama_safetensors(CFG, str(tmp_path))
    toks = _tokens(CFG)
    np.testing.assert_array_equal(_logits(params, CFG, toks),
                                  _logits(loaded, CFG, toks))


def test_load_int8_matches_quantize_weights(tmp_path):
    """Quantize-on-load == init-then-quantize, leaf for leaf and in logits."""
    path = str(tmp_path / "model.safetensors")
    export_llama_safetensors(llama_init(CFG, seed=2), path)
    loaded8 = load_llama_safetensors(CFG, path, weight_dtype="int8")
    oracle8 = quantize_weights(llama_init(CFG, seed=2))
    assert loaded8["lm_head"].dtype == np.int8
    assert loaded8["layers"]["wq"].dtype == np.int8
    np.testing.assert_array_equal(np.asarray(loaded8["layers"]["wq"]),
                                  np.asarray(oracle8["layers"]["wq"]))
    np.testing.assert_array_equal(np.asarray(loaded8["tok_emb_s"]),
                                  np.asarray(oracle8["tok_emb_s"]))
    toks = _tokens(CFG)
    np.testing.assert_array_equal(_logits(oracle8, CFG, toks),
                                  _logits(loaded8, CFG, toks))


def test_tied_embeddings(tmp_path):
    """No lm_head.weight in the file -> lm_head = tok_emb.T (Llama-3.2-1B
    ships tied)."""
    params = llama_init(CFG, seed=4)
    path = str(tmp_path / "model.safetensors")
    export_llama_safetensors(params, path)
    # rewrite without the head tensor
    f = SafetensorsFile(path)
    tensors = {n: f.tensor(n) for n in f.keys() if n != "lm_head.weight"}
    write_safetensors(path, tensors)
    loaded = load_llama_safetensors(CFG, path)
    np.testing.assert_array_equal(np.asarray(loaded["lm_head"]),
                                  np.asarray(loaded["tok_emb"]).T)


def test_sharded_index_checkpoint(tmp_path):
    """HF multi-shard layout: weight_map in model.safetensors.index.json."""
    params = llama_init(CFG, seed=5)
    whole = str(tmp_path / "whole.safetensors")
    export_llama_safetensors(params, whole)
    f = SafetensorsFile(whole)
    names = sorted(f.keys())
    half = len(names) // 2
    shards = {"model-00001-of-00002.safetensors": names[:half],
              "model-00002-of-00002.safetensors": names[half:]}
    weight_map = {}
    for fname, members in shards.items():
        write_safetensors(str(tmp_path / fname),
                          {n: f.tensor(n) for n in members})
        weight_map.update({n: fname for n in members})
    with open(tmp_path / "model.safetensors.index.json", "w") as fp:
        json.dump({"weight_map": weight_map}, fp)
    os.remove(whole)
    loaded = load_llama_safetensors(CFG, str(tmp_path))
    toks = _tokens(CFG)
    np.testing.assert_array_equal(_logits(params, CFG, toks),
                                  _logits(loaded, CFG, toks))


def test_config_mismatch_fails_fast(tmp_path):
    import dataclasses

    path = str(tmp_path / "model.safetensors")
    export_llama_safetensors(llama_init(CFG, seed=6), path)
    wrong = dataclasses.replace(CFG, ffn_dim=CFG.ffn_dim * 2)
    with pytest.raises(ValueError, match="does not match config"):
        load_llama_safetensors(wrong, path)


def test_missing_tensor_named_in_error(tmp_path):
    path = str(tmp_path / "model.safetensors")
    export_llama_safetensors(llama_init(CFG, seed=7), path)
    f = SafetensorsFile(path)
    tensors = {n: f.tensor(n) for n in f.keys()
               if n != "model.layers.1.mlp.up_proj.weight"}
    write_safetensors(path, tensors)
    with pytest.raises(ValueError, match="up_proj"):
        load_llama_safetensors(CFG, path)


def test_export_rejects_quantized_tree(tmp_path):
    q = quantize_weights(llama_init(CFG, seed=8))
    with pytest.raises(ValueError, match="float trees only"):
        export_llama_safetensors(q, str(tmp_path / "x.safetensors"))


# ---------------------------------------------------------------------------
# engine end-to-end from disk
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("weight_dtype", [None, "int8"])
def test_engine_boots_from_disk_token_parity(tmp_path, weight_dtype):
    """The serving engine fed from disk generates the SAME tokens as the
    engine fed the in-memory tree (greedy, so parity is exact)."""
    from gofr_tpu.tpu.engine import LLMEngine

    path = str(tmp_path / "model.safetensors")
    export_llama_safetensors(llama_init(CFG, seed=9), path)
    loaded = load_llama_safetensors(CFG, path, weight_dtype=weight_dtype)
    oracle_params = (quantize_weights(llama_init(CFG, seed=9))
                     if weight_dtype == "int8" else llama_init(CFG, seed=9))

    prompts = [[5, 6, 7, 8], [9, 10, 11, 12, 13, 14]]
    outs = []
    for params in (oracle_params, loaded):
        eng = LLMEngine(params, CFG, n_slots=2, max_seq_len=64,
                        prefill_buckets=(8,))
        eng.start()
        try:
            handles = [eng.submit(p, max_new_tokens=12) for p in prompts]
            outs.append([h.result(timeout_s=120) for h in handles])
        finally:
            eng.stop()
    assert outs[0] == outs[1]
