"""Performance timeline: the trace-event contract, flow normalization,
/debug/timeline, and fleet stitching into one multi-process trace.

ISSUE 20's acceptance surface: exported traces honor the Chrome
trace-event contract (monotone timestamps per track, balanced B/E
nesting, flow ids that resolve to well-formed s→t→f chains); each step
slice's segment children reproduce the ledger's sum identity; and a
replica behind the real router stitches into one multi-pid trace whose
cross-process flow chain is unbroken.
"""

import importlib.util
import json
import os
import urllib.request

import pytest

from gofr_tpu.app import App
from gofr_tpu.config import MockConfig
from gofr_tpu.fleet.timeline import (align_replica, router_events,
                                     stitch_payloads)
from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.tpu.flightrecorder import FlightRecorder
from gofr_tpu.tpu.timeline import (TimelineExporter,
                                   register_timeline_metrics)

pytestmark = pytest.mark.timeline

CFG = LlamaConfig.debug()
EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def _engine(**kw):
    from gofr_tpu.tpu.engine import LLMEngine

    kw.setdefault("n_slots", 2)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("decode_block_size", 1)
    kw.setdefault("pipeline_depth", 1)
    return LLMEngine(llama_init(CFG, seed=0), CFG, **kw)


# -- the trace-event contract, asserted structurally --------------------------
def _by_track(events):
    tracks = {}
    for ev in events:
        if ev.get("ph") == "M":
            continue
        tracks.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)
    return tracks


def _assert_contract(events):
    """Every track's duration events are time-ordered with balanced B/E
    nesting; every flow id resolves to one well-formed chain."""
    for key, track in _by_track(events).items():
        depth, last_ts = 0, None
        for ev in track:
            if ev["ph"] not in ("B", "E", "X"):
                continue
            assert isinstance(ev["ts"], (int, float)), ev
            if last_ts is not None:
                assert ev["ts"] >= last_ts - 1e-6, (
                    f"track {key}: ts went backwards at {ev}")
            last_ts = ev["ts"]
            if ev["ph"] == "B":
                depth += 1
            elif ev["ph"] == "E":
                depth -= 1
                assert depth >= 0, f"track {key}: E without B at {ev}"
        assert depth == 0, f"track {key}: {depth} unclosed B slices"
    _assert_flows_well_formed(events)


def _flow_chains(events):
    chains = {}
    for ev in events:
        if ev.get("cat") == "flow":
            chains.setdefault(ev.get("id"), []).append(ev)
    for chain in chains.values():
        chain.sort(key=lambda e: e["ts"])
    return chains


def _assert_flows_well_formed(events):
    for fid, chain in _flow_chains(events).items():
        phases = [ev["ph"] for ev in chain]
        assert phases.count("s") == 1, f"flow {fid}: {phases}"
        assert phases[0] == "s", f"flow {fid} does not start with s"
        assert phases.count("f") <= 1
        finished = [ev for ev in chain
                    if ev.get("args", {}).get("milestone") == "finished"]
        if finished and chain[-1] is finished[-1]:
            assert phases[-1] == "f", f"flow {fid}: {phases}"
            assert chain[-1].get("bp") == "e"
        for ev in chain[1:-1]:
            assert ev["ph"] == "t", f"flow {fid}: {phases}"


# -- unit: flow normalization over raw event soup -----------------------------
def test_normalize_flows_rewrites_raw_chains():
    """A hand-off pair (or a stitched router+replica merge) contributes
    several raw s/f under one id; normalization leaves exactly one s,
    one f (terminal finished), t between."""
    def flow(ph, ts, milestone, **extra):
        ev = {"ph": ph, "cat": "flow", "id": "abc", "ts": ts,
              "args": {"milestone": milestone}}
        ev.update(extra)
        return ev

    events = [flow("f", 30.0, "finished", bp="e"),
              flow("s", 10.0, "enqueued"),
              flow("s", 18.0, "enqueued"),      # the decode half's raw s
              flow("t", 15.0, "admitted"),
              flow("f", 25.0, "finished", bp="e"),  # prefill half's raw f
              {"ph": "X", "name": "bystander", "ts": 1.0, "dur": 2.0}]
    TimelineExporter._normalize_flows(events)
    _assert_flows_well_formed(events)
    chain = _flow_chains(events)["abc"]
    assert [ev["ph"] for ev in chain] == ["s", "t", "t", "t", "f"]
    assert chain[-1]["ts"] == 30.0 and chain[-1]["bp"] == "e"
    assert events[-1]["ph"] == "X"  # non-flow events untouched


def test_normalize_flows_without_terminal_keeps_last_as_t():
    events = [{"ph": "s", "cat": "flow", "id": "x", "ts": 1.0,
               "args": {"milestone": "enqueued"}},
              {"ph": "f", "cat": "flow", "id": "x", "ts": 2.0, "bp": "e",
               "args": {"milestone": "admitted"}}]
    TimelineExporter._normalize_flows(events)
    # an in-flight request never gets a bogus f: the chain stays open
    assert [ev["ph"] for ev in events] == ["s", "t"]
    assert "bp" not in events[1]


# -- engine-driven export -----------------------------------------------------
def test_export_contract_and_segment_sum_identity():
    """The acceptance identity on a real run: every step slice's segment
    children tile it, reproducing the ledger's segments==wall sum."""
    recorder = FlightRecorder(capacity=32)
    eng = _engine(flight_recorder=recorder)
    exporter = TimelineExporter(eng, process_name="unit")
    eng.start()
    try:
        request = eng.submit([1, 2, 3], max_new_tokens=12)
        assert len(request.result(timeout_s=120)) == 12
    finally:
        eng.stop()
    payload = exporter.export()
    events = payload["traceEvents"]
    assert payload["events_total"] == len(events) > 0
    assert payload["anchor"]["wall0"] > 0
    assert payload["anchor"]["mono0"] > 0
    assert payload["clock_domain"] == "monotonic_us"
    _assert_contract(events)
    # track metadata: the real thread names, the ownership contract
    names = {ev["args"]["name"] for ev in events
             if ev.get("ph") == "M" and ev["name"] == "thread_name"}
    assert "llm-engine" in names and "llm-finisher" in names
    loop_meta = next(ev for ev in events
                     if ev.get("ph") == "M"
                     and ev.get("args", {}).get("name") == "llm-engine")
    assert loop_meta["args"]["loop_only"], "ownership contract missing"
    # the sum identity, read back from the rendered slices
    steps = [ev for ev in events if ev.get("cat") == "step"
             and ev["ph"] == "B"]
    assert steps, "no step slices rendered"
    segments = [ev for ev in events if ev.get("cat") == "segment"
                and ev["ph"] == "B"]
    by_ts = {}
    for seg in segments:
        by_ts.setdefault(seg["tid"], []).append(seg)
    for step in steps:
        children = [seg for seg in by_ts.get(step["tid"], [])
                    if step["ts"] <= seg["ts"]
                    < step["ts"] + step["args"]["wall_s"] * 1e6]
        total = sum(seg["args"]["seconds"] for seg in children)
        assert total == pytest.approx(step["args"]["wall_s"],
                                      rel=0.05, abs=1e-4), step
    # device busy intervals rendered as async pairs
    assert any(ev.get("cat") == "device" and ev["ph"] == "b"
               for ev in events)
    # the finished request's flow chain resolved s→…→f
    chains = _flow_chains(events)
    assert chains, "no request flow events"
    done = [c for c in chains.values()
            if c[-1].get("args", {}).get("milestone") == "finished"]
    assert done, "finished request produced no terminal flow event"
    # export counter rode along
    assert exporter.exports_total == 1


def test_export_steps_window_narrows_and_is_safe_reentrant():
    eng = _engine()
    exporter = TimelineExporter(eng, max_steps=4)
    eng.start()
    try:
        eng.generate([1, 2, 3], max_new_tokens=10)
    finally:
        eng.stop()
    wide = exporter.export(steps=128)
    narrow = exporter.export(steps=2)
    assert narrow["steps_window"] == 2
    n_steps = len([ev for ev in narrow["traceEvents"]
                   if ev.get("cat") == "step" and ev["ph"] == "B"])
    w_steps = len([ev for ev in wide["traceEvents"]
                   if ev.get("cat") == "step" and ev["ph"] == "B"])
    assert n_steps <= 2 < w_steps
    assert exporter.exports_total == 2


def test_compile_hook_chains_and_captures():
    eng = _engine()
    seen = []
    eng.executor.on_compile = lambda name, s: seen.append((name, s))
    exporter = TimelineExporter(eng)
    exporter.note_compile("prefill_16", 0.25)
    eng.executor.on_compile("decode_1", 0.125)  # through the chained hook
    payload = exporter.export()
    compiles = [ev for ev in payload["traceEvents"]
                if ev.get("cat") == "compile"]
    names = {ev["name"] for ev in compiles}
    assert "compile:prefill_16" in names and "compile:decode_1" in names
    for ev in compiles:
        assert ev["ph"] == "X" and ev["dur"] > 0
    assert seen == [("decode_1", 0.125)], "prior hook lost by chaining"


# -- /debug/timeline over HTTP ------------------------------------------------
def test_debug_timeline_route_e2e():
    app = App(config=MockConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0", "LOG_LEVEL": "ERROR",
        "TIMELINE_STEPS": "64"}))
    eng = _engine()
    exporter = app.enable_timeline(eng)
    assert exporter is eng.timeline
    assert exporter.max_steps == 64
    prof = app.enable_hostprof(eng)
    assert prof is eng.hostprof and prof.running
    eng.start()
    app.start()
    try:
        eng.generate([1, 2, 3], max_new_tokens=8)
        base = f"http://127.0.0.1:{app.http_port}"
        with urllib.request.urlopen(base + "/debug/timeline?steps=8",
                                    timeout=30) as resp:
            payload = json.loads(resp.read().decode())["data"]
        assert payload["steps_window"] == 8
        assert payload["traceEvents"]
        _assert_contract(payload["traceEvents"])
        with urllib.request.urlopen(base + "/debug/hostprof",
                                    timeout=30) as resp:
            snap = json.loads(resp.read().decode())["data"]
        assert snap["running"] is True and snap["samples_total"] >= 0
        with urllib.request.urlopen(base + "/debug/hostprof?collapsed=1",
                                    timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
    finally:
        eng.stop()
        app.shutdown()
    assert not prof.running, "shutdown hook did not stop the sampler"


def test_hostprof_disabled_by_nonpositive_hz():
    app = App(config=MockConfig({"HTTP_PORT": "0", "METRICS_PORT": "0",
                                 "HOSTPROF_HZ": "0",
                                 "LOG_LEVEL": "ERROR"}))
    assert app.enable_hostprof() is None


def test_register_timeline_metrics_idempotent():
    from gofr_tpu.metrics import Manager

    m = Manager()
    register_timeline_metrics(m)
    register_timeline_metrics(m)
    assert m.get("app_tpu_timeline_exports_total") is not None


# -- fleet stitching: the pure core -------------------------------------------
def _replica_payload(trace_id, wall0=1000.0, mono0=100.0):
    """A minimal well-formed /debug/timeline payload: one step slice and
    a full request flow, monotonic-µs domain with the anchor pair."""
    def ev(ph, ts_mono, **extra):
        base = {"ph": ph, "pid": 1, "tid": 1, "ts": ts_mono * 1e6}
        base.update(extra)
        return base

    return {
        "anchor": {"wall0": wall0, "mono0": mono0},
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
             "ts": 0, "args": {"name": "llm-server"}},
            ev("B", 100.5, name="step:decode", cat="step",
               args={"wall_s": 0.1}),
            ev("E", 100.6),
            ev("s", 100.45, cat="flow", id=trace_id, name="request",
               args={"milestone": "enqueued"}),
            ev("t", 100.5, cat="flow", id=trace_id, name="request",
               args={"milestone": "admitted"}),
            ev("f", 100.62, cat="flow", id=trace_id, name="request",
               bp="e", args={"milestone": "finished"}),
        ],
    }


def _journey(trace_id):
    summary = {"id": 7, "trace_id": trace_id, "outcome": "ok"}
    hops = [
        {"hop": "route", "actor": "router", "t_start": 1000.40,
         "t_end": 1000.41, "replica": "r0", "outcome": "committed"},
        {"hop": "stream", "actor": "router", "t_start": 1000.45,
         "t_end": 1000.70, "chunks": 3},
        {"hop": "finish", "actor": "router", "t_start": 1000.70,
         "t_end": 1000.70, "outcome": "ok"},
    ]
    return summary, hops


def test_stitch_aligns_clocks_and_joins_flows_across_pids():
    trace_id = "ab" * 16
    summary, hops = _journey(trace_id)
    stitched = stitch_payloads({"r0": _replica_payload(trace_id)},
                               journey=summary, hops=hops,
                               trace_id=trace_id)
    assert stitched["complete"] is True and stitched["missing"] == []
    assert stitched["pids"] == {"r0": 2}
    assert stitched["clock_domain"] == "wall_us"
    events = stitched["traceEvents"]
    _assert_contract(events)
    # the replica's monotonic events landed in the wall epoch: mono
    # 100.5s + (wall0-mono0)=900s shift -> wall 1000.5s
    step = next(ev for ev in events if ev.get("cat") == "step")
    assert step["pid"] == 2
    assert step["ts"] == pytest.approx(1000.5e6, abs=1e3)
    # process metadata renamed to the replica, ts untouched
    meta = next(ev for ev in events if ev.get("ph") == "M"
                and ev["pid"] == 2 and ev["name"] == "process_name")
    assert meta["args"]["name"] == "r0" and meta["ts"] == 0
    # ONE unbroken flow chain across both processes
    chain = _flow_chains(events)[trace_id]
    assert {ev["pid"] for ev in chain} == {1, 2}
    phases = [ev["ph"] for ev in chain]
    assert phases[0] == "s" and phases[-1] == "f"
    assert phases.count("s") == 1 and phases.count("f") == 1
    # the router's route attempt precedes the replica's enqueue: the
    # chain ORIGINATES at the router after the wall alignment
    assert chain[0]["pid"] == 1


def test_stitch_degrades_anchorless_replica_to_missing():
    trace_id = "cd" * 16
    summary, hops = _journey(trace_id)
    bad = _replica_payload(trace_id)
    del bad["anchor"]
    stitched = stitch_payloads(
        {"r0": _replica_payload(trace_id), "r1": bad},
        journey=summary, hops=hops, trace_id=trace_id)
    assert stitched["missing"] == ["r1"]
    assert stitched["complete"] is False
    assert stitched["pids"] == {"r0": 2}
    assert all(ev["pid"] != 3 for ev in stitched["traceEvents"])


def test_align_replica_requires_the_anchor_pair():
    events, ok = align_replica({"traceEvents": [{"ph": "X", "ts": 1}]},
                               pid=5, name="r9")
    assert ok is False and events == []


def test_router_events_mark_terminal_hop_finished():
    summary, hops = _journey("ef" * 16)
    events = router_events(summary, hops)
    flows = [ev for ev in events if ev.get("cat") == "flow"]
    milestones = [ev["args"]["milestone"] for ev in flows]
    assert milestones == ["route", "finished"]
    slices = [ev for ev in events if ev["ph"] == "X"]
    assert [ev["name"] for ev in slices] == ["route", "stream", "finish"]


# -- acceptance e2e: a real replica behind the real router --------------------
def _load(example, alias):
    path = os.path.join(EXAMPLES, example, "main.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.mark.slow  # two real engines + router; the timeline lane runs it
def test_fleet_timeline_stitches_disagg_replica_e2e():
    """DISAGG_MODE=both replica behind the real router: one request's
    stitched trace is multi-pid (router + replica), the replica's two
    engine halves render their own track blocks, and the cross-process
    flow chain for the journey's trace id is unbroken."""
    llm = _load("llm-server", "timeline_llm_server")
    replica = llm.build_app(config=MockConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0", "TPU_PLATFORM": "cpu",
        "MODEL_PRESET": "debug", "WARMUP": "false", "MAX_BATCH": "4",
        "MAX_SEQ_LEN": "64", "PREFILL_BUCKETS": "8,16", "PAGED": "true",
        "PAGE_SIZE": "8", "REQUEST_TIMEOUT": "300", "LOG_LEVEL": "ERROR",
        "INCIDENT_AUTOPSY": "false", "DISAGG_MODE": "both",
        "APP_NAME": "r0"}))
    replica.start()
    router = _load("router", "timeline_router").build_app(
        config=MockConfig({
            "HTTP_PORT": "0", "METRICS_PORT": "0", "APP_NAME": "router",
            "REQUEST_TIMEOUT": "300", "LOG_LEVEL": "ERROR",
            "FLEET_PROBE_S": "0.2",
            "FLEET_REPLICAS": f"r0=http://127.0.0.1:{replica.http_port}",
            "INCIDENT_DIR": os.path.join(
                os.environ.get("TMPDIR", "/tmp"), "timeline_incidents")}))
    router.start()
    base = f"http://127.0.0.1:{router.http_port}"
    trace = f"{0xfaded:032x}"
    try:
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt": "stitch me", "max_tokens": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{trace}-00f067aa0ba902b7-01"},
            method="POST")
        with urllib.request.urlopen(req, timeout=300) as resp:
            events = [json.loads(line.strip()[6:]) for line in resp
                      if line.strip().startswith(b"data: ")]
        assert events[-1].get("done") is True

        with urllib.request.urlopen(
                base + f"/debug/fleet/timeline/{trace}",
                timeout=60) as resp:
            stitched = json.loads(resp.read().decode())["data"]
        assert stitched["complete"] is True, stitched["missing"]
        assert stitched["trace_id"] == trace
        assert stitched["pids"] == {"r0": 2}
        trace_events = stitched["traceEvents"]
        _assert_contract(trace_events)
        pids = {ev["pid"] for ev in trace_events}
        assert pids == {1, 2}, f"not multi-process: {pids}"
        # the DISAGG both replica rendered both engine halves' tracks
        names = {ev["args"]["name"] for ev in trace_events
                 if ev.get("ph") == "M" and ev["name"] == "thread_name"}
        assert any(n.startswith("prefill:") for n in names), names
        # the journey's flow chain crosses the process boundary unbroken
        chain = _flow_chains(trace_events).get(trace)
        assert chain, "no flow events for the journey's trace id"
        phases = [ev["ph"] for ev in chain]
        assert phases[0] == "s" and phases.count("s") == 1
        assert phases[-1] == "f" and phases.count("f") == 1
        assert all(ph == "t" for ph in phases[1:-1])
        assert {ev["pid"] for ev in chain} == {1, 2}

        # unknown id is a clean 404, not a stitch of nothing
        try:
            urllib.request.urlopen(base + "/debug/fleet/timeline/999999",
                                   timeout=30)
            raise AssertionError("unknown journey id did not 404")
        except urllib.error.HTTPError as err:
            assert err.code == 404
    finally:
        router.shutdown()
        replica.shutdown()
