import time

import pytest

from gofr_tpu.cron import CronParseError, Schedule


def t(minute=0, hour=0, mday=1, mon=1, wday_py=0):
    return time.struct_time((2026, mon, mday, hour, minute, 0, wday_py, 1, -1))


def test_wildcards_match_everything():
    s = Schedule("* * * * *")
    assert s.matches(t(minute=59, hour=23))


def test_exact_fields():
    s = Schedule("30 14 1 6 *")
    assert s.matches(t(minute=30, hour=14, mday=1, mon=6))
    assert not s.matches(t(minute=31, hour=14, mday=1, mon=6))


def test_steps_ranges_lists():
    s = Schedule("*/15 9-17 * * 1,3,5")
    # python tm_wday: Mon=0 -> cron Mon=1
    assert s.matches(t(minute=45, hour=9, wday_py=0))     # Monday
    assert not s.matches(t(minute=46, hour=9, wday_py=0))
    assert not s.matches(t(minute=45, hour=8, wday_py=0))
    assert not s.matches(t(minute=45, hour=9, wday_py=1))  # Tuesday


def test_sunday_is_zero():
    s = Schedule("* * * * 0")
    assert s.matches(t(wday_py=6))  # python Sunday=6 -> cron 0


def test_invalid_specs_raise():
    for bad in ("* * * *", "60 * * * *", "* 24 * * *", "a * * * *",
                "*/0 * * * *", "5-1 * * * *"):
        with pytest.raises(CronParseError):
            Schedule(bad)


def test_crontab_runs_due_job(mock_container):
    from gofr_tpu.cron import Crontab

    crontab = Crontab(mock_container)
    ran = []
    crontab.add_job("* * * * *", "always", lambda ctx: ran.append(ctx))
    crontab._tick(time.localtime())
    deadline = time.time() + 2
    while not ran and time.time() < deadline:
        time.sleep(0.01)
    assert ran, "due job did not run"
    # ctx passed to the job is a full Context with the container
    assert ran[0].container is mock_container


def test_crontab_bad_spec_raises(mock_container):
    from gofr_tpu.cron import Crontab

    with pytest.raises(CronParseError):
        Crontab(mock_container).add_job("bad spec", "x", lambda ctx: None)
