"""Cross-validation against HuggingFace transformers (the de-facto oracle).

Every other weights test round-trips through this repo's OWN safetensors
writer — that proves reader==writer, not that the importer understands real
HF checkpoints. Here the fixture is produced by `LlamaForCausalLM.save_pretrained`
itself (genuine HF tensor names, layout, rope convention), and the loaded
model is logits-matched against transformers' forward pass. This is the
closest available stand-in for "boots actual Llama-3 weights" in a
zero-egress environment: the 8B checkpoint differs from this fixture only
in shape constants, not in format or convention.

Covers the classic importer failure modes that a self-roundtrip can never
catch: q/k head permutation (HF conversion pre-permutes for rotate-half
RoPE — loading real HF weights must NOT permute again), [out,in] vs
[in,out] projection transposes, norm placement/eps, GQA head mapping, and
tied-embedding handling.

Also cross-checks the byte-level BPE tokenizer against the `tokenizers`
library (the engine under HF's tokenizer.json) on the same vocab file.

Parity anchor: the reference pins its serialization against real wire
formats rather than its own mirrors (protoc-generated stubs in the gRPC
tests, /root/reference/pkg/gofr/grpc.go:20-46); transformers plays that
role for checkpoint bytes here.
"""

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from gofr_tpu.models.llama import LlamaConfig, init_kv_cache, llama_prefill
from gofr_tpu.models.weights import load_llama_safetensors

# Small but non-degenerate: GQA (4 q-heads over 2 kv-heads), head_dim 16,
# an MLP width that is not a multiple of the hidden size, Llama-3's
# rope_theta.
DIM, LAYERS, HEADS, KV_HEADS, FFN, VOCAB = 64, 2, 4, 2, 160, 256


def _hf_model(tie: bool):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=VOCAB, hidden_size=DIM, intermediate_size=FFN,
        num_hidden_layers=LAYERS, num_attention_heads=HEADS,
        num_key_value_heads=KV_HEADS, max_position_embeddings=128,
        rms_norm_eps=1e-5, rope_theta=500000.0, attention_bias=False,
        mlp_bias=False, tie_word_embeddings=tie)
    torch.manual_seed(7)
    model = transformers.LlamaForCausalLM(hf_cfg)
    return model.to(torch.float32).eval()


def _our_cfg():
    return LlamaConfig(vocab_size=VOCAB, dim=DIM, n_layers=LAYERS,
                       n_heads=HEADS, n_kv_heads=KV_HEADS, ffn_dim=FFN,
                       max_seq_len=128, rope_theta=500000.0, rms_eps=1e-5,
                       dtype="float32")


def _our_logits(params, cfg, tokens_np):
    import jax.numpy as jnp

    tokens = jnp.asarray(tokens_np, dtype=jnp.int32)
    B, T = tokens.shape
    k, v = init_kv_cache(cfg, B, T)
    logits, _, _ = llama_prefill(params, cfg, tokens, k, v)
    return np.asarray(logits, dtype=np.float32)


@pytest.mark.parametrize("tie", [False, True], ids=["untied", "tied"])
def test_logits_match_transformers(tmp_path, tie):
    model = _hf_model(tie)
    ckpt = tmp_path / "ckpt"
    model.save_pretrained(ckpt, safe_serialization=True)

    cfg = _our_cfg()
    params = load_llama_safetensors(cfg, str(ckpt))

    rng = np.random.default_rng(11)
    tokens = rng.integers(1, VOCAB, size=(2, 24))
    with torch.no_grad():
        ref = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    got = _our_logits(params, cfg, tokens)

    assert got.shape == ref.shape
    # Both sides compute norms/softmax/logits in fp32; residual-order and
    # fusion differences leave ~1e-5 noise at this scale.
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=2e-4)


@pytest.mark.slow  # tier-1 wall-clock budget; lighter in-lane representative kept
def test_greedy_continuation_matches_transformers(tmp_path):
    """Teacher-forced parity can hide compounding drift; greedy decode is
    the serving-shaped claim: both stacks produce the same continuation."""
    import jax.numpy as jnp

    model = _hf_model(False)
    ckpt = tmp_path / "ckpt"
    model.save_pretrained(ckpt, safe_serialization=True)
    cfg = _our_cfg()
    params = load_llama_safetensors(cfg, str(ckpt))

    rng = np.random.default_rng(5)
    prompt = rng.integers(1, VOCAB, size=(1, 8))
    steps = 16

    with torch.no_grad():
        ref = model.generate(
            torch.tensor(prompt, dtype=torch.long), max_new_tokens=steps,
            do_sample=False, use_cache=True,
            pad_token_id=0).numpy()[0, prompt.shape[1]:]

    seq = jnp.asarray(prompt, dtype=jnp.int32)
    ours = []
    for _ in range(steps):
        T = seq.shape[1]
        k, v = init_kv_cache(cfg, 1, max(T, 16))
        logits, _, _ = llama_prefill(params, cfg, seq, k, v)
        nxt = int(np.asarray(logits)[0, -1].argmax())
        ours.append(nxt)
        seq = jnp.concatenate(
            [seq, jnp.asarray([[nxt]], dtype=jnp.int32)], axis=1)

    assert ours == ref.tolist()


def test_int8_quantize_on_load_matches_post_hoc_quantize(tmp_path):
    """WEIGHT_DTYPE=int8 on a real HF-written checkpoint must equal
    loading float then quantizing: the streaming per-leaf quantize path
    and quantize_weights share per-output-channel semantics bit-exactly."""
    import jax

    from gofr_tpu.models.llama import quantize_weights

    model = _hf_model(False)
    ckpt = tmp_path / "ckpt"
    model.save_pretrained(ckpt, safe_serialization=True)
    cfg = _our_cfg()

    via_load = load_llama_safetensors(cfg, str(ckpt), weight_dtype="int8")
    via_post = quantize_weights(load_llama_safetensors(cfg, str(ckpt)))

    flat_a = jax.tree_util.tree_leaves_with_path(via_load)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(via_post))
    assert len(flat_a) == len(flat_b)
    for path, leaf in flat_a:
        np.testing.assert_array_equal(
            np.asarray(leaf), np.asarray(flat_b[path]),
            err_msg=jax.tree_util.keystr(path))


def test_loader_tolerates_hf_config_artifacts(tmp_path):
    """save_pretrained writes config.json/generation_config.json next to the
    weights; directory-form loading must key off the safetensors files only."""
    model = _hf_model(False)
    ckpt = tmp_path / "ckpt"
    model.save_pretrained(ckpt, safe_serialization=True)
    names = {p.name for p in ckpt.iterdir()}
    assert "config.json" in names  # the fixture really is an HF directory
    params = load_llama_safetensors(_our_cfg(), str(ckpt))
    assert params["tok_emb"].shape == (VOCAB, DIM)


# The pre-tokenization pattern real Llama-3 tokenizer.json files declare
# (transcribed from the public release; the module must agree or every
# VOCAB_PATH deployment mis-splits).
_LLAMA3_PATTERN = (r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+"
                   r"|\p{N}{1,3}| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+"
                   r"|\s+(?!\S)|\s+")


def test_split_pattern_matches_llama3_release():
    from gofr_tpu.models import tokenizer as tok_mod

    assert tok_mod._LLAMA3_SPLIT == _LLAMA3_PATTERN


def test_tokenizer_matches_tokenizers_library(tmp_path):
    """Same tokenizer.json, our ByteLevelBPETokenizer vs HF `tokenizers`:
    identical ids on ASCII, multibyte UTF-8, and merge-heavy repetition.

    The fixture mirrors the real Llama-3 tokenizer.json structure: a
    Split(llama3-regex, isolated) pre-tokenizer feeding
    ByteLevel(use_regex=False), byte-level BPE model, specials as
    added_tokens — so from_tokenizer_json exercises the exact layout a real
    checkpoint ships."""
    tokenizers_lib = pytest.importorskip("tokenizers")
    from tokenizers import Regex, decoders, models, pre_tokenizers, trainers

    from gofr_tpu.models.tokenizer import ByteLevelBPETokenizer

    tok = tokenizers_lib.Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.Sequence([
        pre_tokenizers.Split(Regex(_LLAMA3_PATTERN), behavior="isolated"),
        pre_tokenizers.ByteLevel(add_prefix_space=False, use_regex=False),
    ])
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=384, special_tokens=["<|begin_of_text|>", "<|end_of_text|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    corpus = ["the quick brown fox jumps over the lazy dog",
              "hello world, hello tpu serving framework",
              "çok güzel ünicode — résumé naïve 日本語 テスト",
              "it's the model's 123 4567 tokens",
              "aaaa bbbb aaaa bbbb aaaa"]
    tok.train_from_iterator(corpus, trainer)
    path = tmp_path / "tokenizer.json"
    tok.save(str(path))

    ours = ByteLevelBPETokenizer.from_tokenizer_json(str(path))

    samples = ["the quick brown fox", "hello hello world", "aaaa aaaa bbbb",
               "résumé 日本語", "it's 12345 tokens", "tabs\tand\nnewlines  x",
               ""]
    for text in samples:
        ref_ids = tok.encode(text).ids
        got_ids = ours.encode(text, bos=False)
        assert list(got_ids) == list(ref_ids), (
            f"{text!r}: ours={got_ids} hf={ref_ids}")
        assert ours.decode(got_ids) == tok.decode(
            ref_ids, skip_special_tokens=False)
