import io
import json

from gofr_tpu.logging import Level, Logger, MockLogger, parse_level
from gofr_tpu.logging.remote import _extract_level


def test_level_filtering():
    logger = MockLogger(level=Level.WARN)
    logger.info("hidden")
    logger.warn("shown")
    out = logger.output()
    assert "hidden" not in out
    assert "shown" in out


def test_json_output_when_not_terminal():
    buf = io.StringIO()
    logger = Logger(level=Level.DEBUG, normal_out=buf, error_out=buf, is_terminal=False)
    logger.infof("hello %s", "world")
    record = json.loads(buf.getvalue())
    assert record["level"] == "INFO"
    assert record["message"] == "hello world"


def test_pretty_output_on_terminal():
    buf = io.StringIO()
    logger = Logger(level=Level.DEBUG, normal_out=buf, error_out=buf, is_terminal=True)
    logger.error("boom")
    assert "\x1b[31m" in buf.getvalue()  # red for ERROR


def test_error_routed_to_error_out():
    normal, err = io.StringIO(), io.StringIO()
    logger = Logger(level=Level.DEBUG, normal_out=normal, error_out=err, is_terminal=False)
    logger.info("a")
    logger.error("b")
    assert "a" in normal.getvalue() and "b" not in normal.getvalue()
    assert "b" in err.getvalue()


def test_fatal_raises_system_exit():
    logger = MockLogger()
    try:
        logger.fatal("die")
        raise AssertionError("should have exited")
    except SystemExit:
        pass


def test_parse_level():
    assert parse_level("debug") == Level.DEBUG
    assert parse_level("NOPE", Level.WARN) == Level.WARN


def test_change_level():
    logger = MockLogger(level=Level.INFO)
    logger.debug("no")
    logger.change_level(Level.DEBUG)
    logger.debug("yes")
    assert "no" not in logger.output()
    assert "yes" in logger.output()


def test_remote_level_extraction_shapes():
    assert _extract_level("DEBUG") == "DEBUG"
    assert _extract_level({"data": {"LOG_LEVEL": "WARN"}}) == "WARN"
    assert _extract_level({"data": [{"serviceName": "x",
                                     "logLevel": {"LOG_LEVEL": "ERROR"}}]}) == "ERROR"
    assert _extract_level({"nonsense": 1}) is None
