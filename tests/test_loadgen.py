"""Traffic observatory (gofr_tpu/loadgen): trace format round-trips and
version skew, capture-hook privacy, open-loop schedule fidelity under a
stalled server, scorecard math at the noise-band edges, incident-bundle
trace export, and the knee-mode forecaster cross-check against a live
debug replica.

The e2e tests boot the real examples (importlib, the journey-test
idiom) and drive them over real sockets — the open-loop generator's
whole point is that its transport is the production one.
"""

import importlib.util
import json
import os
import tempfile
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gofr_tpu.config import MockConfig
from gofr_tpu.loadgen import (OpenLoopRunner, StatusServer, TraceCapture,
                              TraceError, baseline_from_scorecard,
                              build_scorecard, compare, dump_trace,
                              events_from_incident, make_event, percentile,
                              poisson_arrivals, prompt_text, ramp_arrivals,
                              run_knee, synthesize, zipf_weights)
from gofr_tpu.loadgen.knee import _normalize_forecast
from gofr_tpu.loadgen.trace import (TRACE_VERSION, dumps_trace, load_trace,
                                    loads_trace)

pytestmark = pytest.mark.loadgen


# ---------------------------------------------------------------- trace ----
def test_trace_roundtrip_rebases_and_sorts():
    events = [make_event(t=5.0, prompt_tokens=4, seed=9, max_new=3,
                         cls="interactive", tenant="acme", session=7,
                         turn=1),
              make_event(t=3.5, prompt_tokens=2, seed=1, max_new=1)]
    text = dumps_trace(events, source="unit")
    header, loaded = loads_trace(text)
    assert header["trace_version"] == TRACE_VERSION
    assert header["source"] == "unit"
    # sorted by t and rebased so the first arrival is t=0
    assert [e["t"] for e in loaded] == [0.0, 1.5]
    assert loaded[1]["class"] == "interactive"
    assert loaded[1]["tenant"] == "acme"
    assert loaded[1]["session"] == 7


def test_trace_version_skew():
    newer = json.dumps({"trace_version": TRACE_VERSION + 1}) + "\n"
    with pytest.raises(TraceError, match="newer"):
        loads_trace(newer)
    with pytest.raises(TraceError, match="header"):
        loads_trace("")
    with pytest.raises(TraceError):
        loads_trace("not json\n")
    # same-major unknown event fields are preserved but ignored
    text = (json.dumps({"trace_version": TRACE_VERSION}) + "\n"
            + json.dumps({"t": 0.0, "prompt_tokens": 2, "seed": 1,
                          "max_new": 1, "future_field": "xyz"}) + "\n")
    _, events = loads_trace(text)
    assert events[0]["future_field"] == "xyz"


def test_trace_file_roundtrip(tmp_path):
    path = str(tmp_path / "t.jsonl")
    n = dump_trace([make_event(0.0, 3, 5, 2)], path, source="file")
    assert n == 1
    header, events = load_trace(path)
    assert header["events"] == 1 and len(events) == 1


def test_prompt_text_is_session_prefix_extension():
    turn0 = prompt_text(make_event(0, 10, seed=1, max_new=1, session=42,
                                   turn=0))
    turn1 = prompt_text(make_event(0, 16, seed=2, max_new=1, session=42,
                                   turn=1))
    assert len(turn0.split()) == 10 and len(turn1.split()) == 16
    # shared trunk grows with turn: turn-0's trunk is a prefix of turn-1's
    trunk0 = turn0.split()[:4]
    assert turn1.split()[:4] == trunk0
    # distinct seeds keep the tails distinct
    assert turn0 != prompt_text(make_event(0, 10, seed=99, max_new=1,
                                           session=42, turn=0))


# ---------------------------------------------------------------- synth ----
def test_synth_deterministic_and_shaped():
    arr = poisson_arrivals(20.0, 2.0, __import__("random").Random(3))
    assert all(0 <= t < 2.0 for t in arr)
    a = synthesize(arr, tenants=3, seed=5)
    b = synthesize(arr, tenants=3, seed=5)
    assert a == b                      # byte-identical from the seed
    assert {e["class"] for e in a} <= {"interactive", "standard", "batch"}
    assert all(e["tenant"].startswith("tenant") for e in a)
    # session reuse produced at least one multi-turn conversation
    assert any(e["turn"] > 0 for e in a)
    ramp = ramp_arrivals(1.0, 40.0, 4.0, __import__("random").Random(3))
    # a ramp densifies: the second half holds most arrivals
    assert sum(1 for t in ramp if t > 2.0) > len(ramp) / 2
    w = zipf_weights(5)
    assert abs(sum(w) - 1.0) < 1e-9 and w == sorted(w, reverse=True)


# -------------------------------------------------------------- capture ----
def test_capture_sessions_and_privacy():
    cap = TraceCapture(capacity=16, block=8)
    cap.note("hello wor" + "ld turn one", qos_class="interactive",
             tenant="acme", max_new=4)
    cap.note("hello wor" + "ld turn two longer", qos_class="interactive",
             tenant="acme", max_new=4)
    cap.note("completely different", qos_class="batch", max_new=2)
    header, events = cap.export()
    assert header["captured_total"] == 3 and len(events) == 3
    # same leading block -> same session id, turn counter advanced
    assert events[0]["session"] == events[1]["session"]
    assert (events[0]["turn"], events[1]["turn"]) == (0, 1)
    assert events[2]["session"] != events[0]["session"]
    # privacy is structural: no prompt byte in the export
    assert "hello" not in json.dumps(events)
    assert events[0]["t"] == 0.0           # rebased
    assert events[0]["prompt_tokens"] == 4


def test_capture_is_bounded_and_never_raises():
    cap = TraceCapture(capacity=4)
    for i in range(10):
        cap.note(f"prompt {i}")
    assert len(cap) == 4
    cap.note(None)                         # type: ignore[arg-type]
    assert cap.snapshot()["captured_total"] >= 10


# ------------------------------------------------------------ scorecard ----
def test_percentile_math():
    assert percentile([], 50) is None
    assert percentile([7.0], 99) == 7.0
    assert percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.5
    assert percentile([1.0, 2.0, 3.0, 4.0], 100) == 4.0


def _ok_row(cls, tenant, ttft_s, tpot_s=0.01, tokens=4):
    return {"class": cls, "tenant": tenant, "status": "ok",
            "ttft_s": ttft_s, "tpot_s": tpot_s, "tokens": tokens, "t": 0.0}


def test_scorecard_goodput_counts_offered_not_served():
    rows = [_ok_row("interactive", "a", 0.05) for _ in range(8)]
    rows += [{"class": "interactive", "tenant": "a", "status": "shed",
              "t": 0.0}] * 2
    card = build_scorecard(rows)
    cell = card["classes"]["interactive"]
    assert cell["offered"] == 10 and cell["ok"] == 8 and cell["shed"] == 2
    # shed arrivals count against goodput — shedding is not free
    assert cell["goodput"] == 0.8
    assert card["cells"]["interactive|a"]["offered"] == 10
    assert cell["slo_met"] is True and card["slo_met"] is True


def test_scorecard_objective_miss():
    rows = [_ok_row("interactive", "a", 9.0)]     # 9s TTFT
    card = build_scorecard(rows)
    assert card["slo_met"] is False
    checks = card["classes"]["interactive"]["objective_checks"]
    assert any(c["metric"] == "ttft_ms_p95" and not c["met"]
               for c in checks)


def test_noise_band_edges():
    rows = [_ok_row("interactive", "a", 0.100) for _ in range(10)]
    base = baseline_from_scorecard(build_scorecard(rows))
    band = base["classes"]["interactive"]["ttft_ms_p50"]["band"]
    assert band == max(100.0 * 0.35, 150.0)       # abs floor dominates

    def run_with(ttft_ms):
        return compare(build_scorecard(
            [_ok_row("interactive", "a", ttft_ms / 1e3)
             for _ in range(10)]), base)

    assert run_with(100.0 + band)["verdict"] == "pass"     # exactly at edge
    assert run_with(100.0 + band + 1.0)["verdict"] == "regress"
    assert run_with(100.0)["verdict"] == "pass"
    # goodput regression beyond its band
    worse = [_ok_row("interactive", "a", 0.100) for _ in range(5)]
    worse += [{"class": "interactive", "tenant": "a", "status": "shed",
               "t": 0.0}] * 5
    assert compare(build_scorecard(worse), base)["verdict"] == "regress"
    # a class absent from the run is a regression, not a silent pass
    assert compare(build_scorecard([_ok_row("batch", "a", 0.1)]),
                   base)["verdict"] == "regress"


def test_compare_improve_and_slo_override():
    slow = [_ok_row("interactive", "a", 0.900) for _ in range(10)]
    base = baseline_from_scorecard(build_scorecard(slow))
    fast = [_ok_row("interactive", "a", 0.010) for _ in range(10)]
    assert compare(build_scorecard(fast), base)["verdict"] == "improve"
    # matching a baseline that itself blew the SLO is still a failure
    blown = [_ok_row("interactive", "a", 9.0) for _ in range(10)]
    blown_base = baseline_from_scorecard(build_scorecard(blown))
    assert compare(build_scorecard(blown), blown_base)["verdict"] \
        == "regress"


def test_checked_in_baseline_is_well_formed():
    """The blessed debug-fleet baseline CI scores against: every class,
    every compared metric with a positive band, and a recorded workload
    spec so it can be re-blessed reproducibly."""
    path = os.path.join(os.path.dirname(__file__), "baselines",
                        "loadgen_debug.json")
    with open(path, encoding="utf-8") as fp:
        baseline = json.load(fp)
    assert baseline["baseline_version"] == 1
    assert set(baseline["classes"]) == {"interactive", "standard", "batch"}
    for cell in baseline["classes"].values():
        for metric in ("ttft_ms_p50", "ttft_ms_p95", "goodput"):
            assert cell[metric]["band"] > 0
    assert baseline["workload"]["seed"] == 42
    # a run that exactly matches the baseline passes its own comparison
    synthetic_rows = []
    for cls, cell in baseline["classes"].items():
        ttft = cell["ttft_ms_p50"]["value"] / 1e3
        synthetic_rows += [_ok_row(cls, "t0", ttft) for _ in range(10)]
    result = compare(build_scorecard(synthetic_rows), baseline)
    assert result["verdict"] != "regress", result


# ------------------------------------------------- incident trace export ----
def test_incident_bundle_exports_as_trace():
    from gofr_tpu.tpu.incidents import IncidentManager

    bundle_rows = [
        {"id": 31, "enqueued_at": 100.0, "prompt_tokens": 12,
         "max_new_tokens": 8, "tenant": "acme"},
        {"id": 32, "enqueued_at": 100.5, "prompt_tokens": 6,
         "max_new_tokens": 4},
    ]
    events = events_from_incident({"slowest_requests": bundle_rows})
    assert [e["t"] for e in events] == [0.0, 0.5]
    assert events[0]["seed"] == 31 and events[0]["session"] == 31
    assert events[0]["tenant"] == "acme"
    assert events_from_incident({}) == []

    mgr = IncidentManager(engine=None, recorder=None,
                          dir=tempfile.mkdtemp(prefix="lg_inc_"))
    mgr._ring.append({"id": 5, "trigger": "slo_page",
                      "captured_at": 1.0,
                      "slowest_requests": bundle_rows})
    doc = mgr.export_trace(5)
    assert doc["trace_version"] == TRACE_VERSION
    assert doc["source"] == "incident:5"
    assert len(doc["events"]) == 2
    assert mgr.export_trace(999) is None
    # the export round-trips through the JSONL format
    _, loaded = loads_trace(dumps_trace(doc["events"],
                                        source=doc["source"]))
    assert len(loaded) == 2


# ---------------------------------------------------- open-loop generator ----
class _StallHandler(BaseHTTPRequestHandler):
    """Accepts, then stalls: the closed-loop failure mode on a plate."""

    stall_s = 1.5

    def do_POST(self):  # noqa: N802
        self.rfile.read(int(self.headers.get("Content-Length") or 0))
        time.sleep(self.stall_s)
        body = b'{"error": "stalled"}'
        self.send_response(503)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # noqa: A003,ANN002
        pass


class _FastSSEHandler(BaseHTTPRequestHandler):
    """Instant SSE stream: deterministic transport for generator units."""

    def do_POST(self):  # noqa: N802
        req = json.loads(
            self.rfile.read(int(self.headers.get("Content-Length") or 0)))
        n = int(req.get("max_tokens") or 1)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.end_headers()
        for _ in range(n):
            self.wfile.write(b'data: {"text": "w"}\n\n')
        done = json.dumps({"done": True, "tokens": n}).encode()
        self.wfile.write(b"data: " + done + b"\n\n")

    def log_message(self, *args):  # noqa: A003,ANN002
        pass


@pytest.fixture()
def _server_factory():
    servers = []

    def build(handler):
        srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever,
                         kwargs={"poll_interval": 0.1},
                         daemon=True).start()
        servers.append(srv)
        return f"http://127.0.0.1:{srv.server_address[1]}"

    yield build
    for srv in servers:
        srv.shutdown()
        srv.server_close()


def test_open_loop_schedule_holds_under_stalled_server(_server_factory):
    """The tentpole property: a stalled server must not slow arrivals."""
    url = _server_factory(_StallHandler)
    events = [make_event(t=i * 0.05, prompt_tokens=2, seed=i, max_new=1)
              for i in range(20)]                 # 20 arrivals over ~1s
    runner = OpenLoopRunner(url, events, timeout_s=10.0)
    runner.start()
    assert runner.wait_dispatch(timeout_s=15.0)
    arrivals = runner.arrivals()
    # every arrival fired even though NO request had completed yet, and
    # fired close to schedule (the dispatch-lag self-audit)
    assert len(arrivals) == 20
    assert max(a["lag_s"] for a in arrivals) < 0.5
    assert runner.join(timeout_s=15.0)
    rows = runner.rows()
    assert len(rows) == 20
    assert {r["status"] for r in rows} == {"shed"}     # 503 -> shed


def test_open_loop_inflight_cap_records_drops(_server_factory):
    url = _server_factory(_StallHandler)
    events = [make_event(t=i * 0.02, prompt_tokens=2, seed=i, max_new=1)
              for i in range(10)]
    runner = OpenLoopRunner(url, events, timeout_s=10.0, max_inflight=3)
    runner.start()
    assert runner.wait_dispatch(timeout_s=10.0)
    assert runner.join(timeout_s=15.0)
    rows = runner.rows()
    dropped = [r for r in rows if r["status"] == "dropped"]
    # over-cap arrivals are still recorded ON SCHEDULE, loudly
    assert len(rows) == 10 and len(dropped) == 7 == runner.dropped


def test_open_loop_records_ttft_and_headers(_server_factory):
    seen = {}

    class _Echo(_FastSSEHandler):
        def do_POST(self):  # noqa: N802
            seen["class"] = self.headers.get("X-QoS-Class")
            seen["tenant"] = self.headers.get("X-Tenant")
            super().do_POST()

    url = _server_factory(_Echo)
    events = [make_event(t=0.0, prompt_tokens=3, seed=1, max_new=4,
                         cls="interactive", tenant="acme", session=1)]
    rows = OpenLoopRunner(url, events, timeout_s=10.0).run(
        drain_timeout_s=10.0)
    assert rows[0]["status"] == "ok"
    assert rows[0]["tokens"] == 4
    assert rows[0]["ttft_s"] >= 0.0
    assert seen == {"class": "interactive", "tenant": "acme"}
    status_keys = OpenLoopRunner(url, [], timeout_s=1.0).status()
    assert {"offered_rps", "served_rps", "inflight", "outcomes",
            "worst_dispatch_lag_s"} <= set(status_keys)


def test_status_server_serves_runner(_server_factory):
    runner = OpenLoopRunner("127.0.0.1:1", [], timeout_s=1.0)
    server = StatusServer(
        runner, scorecard_fn=lambda: build_scorecard(runner.rows()))
    server.start()
    try:
        with urllib.request.urlopen(server.url + "/debug/loadgen",
                                    timeout=5) as resp:
            payload = json.loads(resp.read())
        assert payload["label"] == "loadgen"
        assert "scorecard" in payload
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(server.url + "/nope", timeout=5)
    finally:
        server.stop()


# ----------------------------------------------------------------- knee ----
def test_normalize_forecast_shapes():
    replica = {"forecast": {"rho": 0.5, "predicted_ttft_ms": 12.0,
                            "collapse_warning": False}}
    fleet = {"fleet": {"rho": 0.9, "predicted_ttft_ms_max": 80.0,
                       "replicas_needed": 3,
                       "collapse_warnings": ["r0"]}}
    assert _normalize_forecast(replica)["rho"] == 0.5
    assert _normalize_forecast(replica)["collapse_warning"] is False
    flat = _normalize_forecast(fleet)
    assert flat["collapse_warning"] is True
    assert flat["replicas_needed"] == 3
    assert flat["predicted_ttft_ms"] == 80.0
    assert _normalize_forecast(None) is None


def test_knee_agreement_logic(_server_factory):
    """A fast server + an early-warning forecast fn: the drill must
    report agreement (clean run) without any real collapse."""
    url = _server_factory(_FastSSEHandler)
    result = run_knee(url, lambda: {"rho": 0.2, "predicted_ttft_ms": 5.0,
                                    "collapse_warning": False},
                      rate0_rps=5.0, rate1_rps=15.0, seconds=2.0,
                      poll_s=0.2, drain_timeout_s=15.0,
                      request_timeout_s=10.0)
    assert result["agrees"] is True
    assert result["first_blowout_at_s"] is None
    assert result["collapse_warning_at_s"] is None
    assert result["ramp"]["arrivals"] == len(result["rows"])
    assert result["samples"], "forecast sampler never ran"


# ------------------------------------------------------- live debug e2e ----
def _load_example(name, alias):
    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        name, "main.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope="module")
def live_fleet():
    """One debug replica behind the real router, QoS + capacity on —
    shared across the e2e tests below (boot is the expensive part)."""
    llm = _load_example("llm-server", "loadgen_llm_server")
    router_mod = _load_example("router", "loadgen_router")
    replica = llm.build_app(config=MockConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0", "GRPC_PORT": "0",
        "APP_NAME": "lg-r0", "MODEL_PRESET": "debug", "PAGED": "true",
        "PAGE_SIZE": "16", "MAX_SEQ_LEN": "256", "PREFILL_BUCKETS": "16,64",
        "MAX_BATCH": "4", "WARMUP": "true", "REQUEST_TIMEOUT": "60",
        "LOG_LEVEL": "ERROR", "QOS": "true", "PUBSUB_BACKEND": "inproc",
        "CAPACITY_WINDOW_S": "4", "CAPACITY_RHO_WARN": "0.5",
        "INCIDENT_AUTOPSY": "false",
        "INCIDENT_DIR": tempfile.mkdtemp(prefix="lg_e2e_")}))
    replica.start()
    router_app = router_mod.build_app(config=MockConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0", "APP_NAME": "lg-router",
        "REQUEST_TIMEOUT": "60", "LOG_LEVEL": "ERROR",
        "FLEET_REPLICAS": f"r0=http://127.0.0.1:{replica.http_port}",
        "FLEET_PROBE_S": "0.3", "ELASTIC": "false",
        "INCIDENT_DIR": tempfile.mkdtemp(prefix="lg_e2e_inc_")}))
    router_app.start()
    yield {"router": router_app, "replica": replica,
           "base": f"http://127.0.0.1:{router_app.http_port}"}
    router_app.shutdown()
    replica.shutdown()


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        body = json.loads(resp.read().decode())
    return body.get("data", body) if isinstance(body, dict) else body


def test_e2e_capture_replay_reproduces(live_fleet):
    """The acceptance loop in miniature: open-loop run -> router capture
    -> replay the capture -> the scorecard reproduces within the band."""
    base = live_fleet["base"]
    import random as _random

    events = synthesize(poisson_arrivals(4.0, 3.0, _random.Random(2)),
                        tenants=2, sessions=4, prompt_tokens=(2, 6),
                        max_new=(2, 4), seed=2)
    rows_a = OpenLoopRunner(base, events, timeout_s=60.0).run(
        drain_timeout_s=120.0)
    assert any(r["status"] == "ok" for r in rows_a)

    doc = _get_json(base + "/debug/trace")
    captured = doc["events"]
    # the router observed (at least) everything the generator offered
    # minus transport failures; classes and tenants survived the hook
    assert len(captured) >= sum(1 for r in rows_a
                                if r["status"] not in ("error", "dropped"))
    assert any(e.get("class") for e in captured)
    assert any(e.get("tenant") for e in captured)

    rows_b = OpenLoopRunner(base, captured, timeout_s=60.0).run(
        drain_timeout_s=120.0)
    comparison = compare(build_scorecard(rows_b),
                         baseline_from_scorecard(build_scorecard(rows_a)))
    # reproduction = no per-metric drift beyond the noise band. The
    # absolute SLO objectives (slo_met) are a property of how loaded the
    # box is, not of capture/replay fidelity — both runs share that fate,
    # so they are excluded here.
    drifted = [c for c in comparison["checks"]
               if c.get("metric") != "slo_met" and c["verdict"] == "regress"]
    assert not drifted, drifted


def test_e2e_replica_trace_export(live_fleet):
    """The replica's flight recorder serves the same surface."""
    replica = live_fleet["replica"]
    doc = _get_json(f"http://127.0.0.1:{replica.http_port}/debug/trace")
    assert doc["trace_version"] == TRACE_VERSION
    assert doc["source"] == "flight_recorder"
    assert doc["events"], "recorder saw traffic but exported no events"
    assert all("prompt" not in e for e in doc["events"])


def test_e2e_knee_forecaster_cross_check(live_fleet):
    """Knee mode on a live debug replica: ramp past the knee while
    polling the capacity forecaster over the fleet rollup (sockets all
    the way down); when a blowout was measured, the collapse warning
    must have fired first."""
    base = live_fleet["base"]
    result = run_knee(
        base,
        lambda: _get_json(base + "/debug/fleet/capacity", timeout=5),
        rate0_rps=2.0, rate1_rps=25.0, seconds=6.0, poll_s=0.4,
        drain_timeout_s=120.0, request_timeout_s=60.0,
        synth_kw={"tenants": 2, "prompt_tokens": (2, 4),
                  "max_new": (3, 6)})
    assert result["samples"], "fleet capacity surface never answered"
    assert result["agrees"], result["detail"]
    # the artifact carries everything the soak gate needs
    assert {"baseline_ttft_ms", "blowout_ttft_ms", "peak_rho",
            "collapse_warning_at_s", "first_blowout_at_s",
            "status"} <= set(result)
