"""Native C++ runtime helpers (gofr_tpu/native): build, bind, parity, speed.

The toolchain (g++) is baked into this image, so these tests exercise the
real shared library; they skip rather than fail if a stripped environment
lacks it, matching the library's own graceful-degrade contract.
"""

import numpy as np
import pytest

from gofr_tpu import native
from gofr_tpu.models.tokenizer import BPETokenizer

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="no C++ toolchain / build failed")


@needs_native
def test_version():
    assert "gofr_native" in native.version()


@needs_native
def test_bpe_core_merges_in_rank_order():
    # vocab: 0:'a' 1:'b' 2:'c' 3:'ab' 4:'abc'
    core = native.BPECore([(0, 1, 3), (3, 2, 4)])
    assert core.encode([0, 1, 2]) == [4]
    assert core.encode([0, 1, 0, 1]) == [3, 3]
    assert core.encode([2, 0]) == [2, 0]
    assert core.encode([]) == []


@needs_native
def test_bpe_core_rank_priority():
    # rank 0 = (b,c)->5 must fire before rank 1 = (a,b)->3
    core = native.BPECore([(1, 2, 5), (0, 1, 3)])
    assert core.encode([0, 1, 2]) == [0, 5]


def _toy_tokenizer():
    vocab = {ch: i for i, ch in enumerate("abcdef")}
    vocab.update({"ab": 6, "cd": 7, "abcd": 8, "ef": 9, "<s>": 10, "</s>": 11})
    merges = ["a b", "c d", "ab cd", "e f"]
    return BPETokenizer(vocab, merges)


@needs_native
def test_tokenizer_native_path_active_and_matches_python():
    tok = _toy_tokenizer()
    assert tok._native is not None
    for text in ["abcdef", "abcabc", "fedcba", "aabbccddeeff", "", "abcd" * 50]:
        native_ids = tok.encode(text, bos=False)
        tok2 = _toy_tokenizer()
        tok2._native = None  # force the python string path
        assert native_ids == tok2.encode(text, bos=False), text
        assert tok.decode(native_ids) == text


@needs_native
def test_tokenizer_falls_back_on_unknown_char():
    tok = _toy_tokenizer()
    ids = tok.encode("abzab", bos=False)  # 'z' not in vocab -> python path
    # python path merges around the unknown; 'z' maps to id 0 ('a'): lossy but safe
    assert tok.decode(ids) == "abaab"


def test_tokenizer_without_native_merges_gate():
    # merged piece 'xy' missing from vocab -> native gate must decline
    vocab = {"x": 0, "y": 1}
    tok = BPETokenizer(vocab, ["x y"])
    assert tok._native is None
    assert tok.encode("xy", bos=False) == [0, 1]


@needs_native
def test_pad_batch_matches_numpy():
    rows = [[1, 2, 3], [4], [], [5, 6, 7, 8, 9]]
    out = native.pad_batch(rows, max_len=4, pad_id=-1)
    expected = np.array([[1, 2, 3, -1],
                         [4, -1, -1, -1],
                         [-1, -1, -1, -1],
                         [6, 7, 8, 9]], dtype=np.int32)  # overlong keeps tail
    np.testing.assert_array_equal(out, expected)
    assert out.dtype == np.int32


@needs_native
def test_pad_batch_empty():
    out = native.pad_batch([], max_len=4)
    assert out.shape == (0, 4)


def test_utf8_complete_prefix():
    s = "héllo…🙂".encode("utf-8")
    # full string is complete
    assert native.utf8_complete_prefix(s) == len(s)
    # chop the 4-byte emoji mid-sequence: prefix must stop before it
    cut = s[:-2]
    n = native.utf8_complete_prefix(cut)
    assert n == len(s) - 4
    cut[:n].decode("utf-8")  # must not raise
    assert native.utf8_complete_prefix(b"") == 0
    assert native.utf8_complete_prefix(b"abc") == 3


@needs_native
def test_utf8_complete_prefix_matches_python_fallback():
    import ctypes

    def py_mirror(buf: bytes) -> int:
        if not buf:
            return 0
        i = len(buf) - 1
        back = 0
        while i > 0 and (buf[i] & 0xC0) == 0x80 and back < 3:
            i -= 1
            back += 1
        lead = buf[i]
        if (lead & 0x80) == 0:
            need = 1
        elif (lead & 0xE0) == 0xC0:
            need = 2
        elif (lead & 0xF0) == 0xE0:
            need = 3
        elif (lead & 0xF8) == 0xF0:
            need = 4
        else:
            return len(buf)
        return len(buf) if i + need <= len(buf) else i

    cases = [b"abc", "é".encode()[:1], "🙂".encode()[:3], b"\xff\xfe",
             "aé🙂".encode(), "aé🙂".encode()[:-1], b"\x80\x80", b"a\xc3"]
    lib = native._load()
    for buf in cases:
        arr = (ctypes.c_uint8 * max(len(buf), 1)).from_buffer_copy(
            buf or b"\x00")
        got = lib.gn_utf8_complete_prefix(arr, len(buf))
        assert got == py_mirror(buf), buf
        # whatever we cut must decode cleanly when the tail was merely
        # incomplete (valid-prefix cases)
        if got < len(buf):
            buf[:got].decode("utf-8")


def test_propose_draft_matches_python_scan():
    """Native prompt-lookup must agree with the engine's pure-Python
    fallback on random histories."""
    import random

    from gofr_tpu import native

    if not native.available():
        pytest.skip("no C++ toolchain")

    def py_scan(history, d):
        n = 2
        if len(history) < n + 1:
            return []
        tail = history[-n:]
        for i in range(len(history) - n - 1, -1, -1):
            if history[i:i + n] == tail:
                return history[i + n: i + n + d]
        return []

    rng = random.Random(0)
    for trial in range(200):
        length = rng.randint(0, 60)
        vocab = rng.choice([2, 3, 8, 100])
        history = [rng.randrange(vocab) for _ in range(length)]
        d = rng.choice([1, 4, 8])
        assert native.propose_draft(history, d) == py_scan(history, d), \
            (history, d)
    # degenerate inputs
    assert native.propose_draft([], 4) == []
    assert native.propose_draft([1, 2], 4) == []
    assert native.propose_draft([1, 2, 3], 0) == []
