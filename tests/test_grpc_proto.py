"""gRPC over REAL protobuf wire format, with protoc-generated stubs.

The reference registers protoc-generated service stubs (grpc.go:56-60,
examples/grpc-server). Here protoc generates the message classes AT TEST
TIME (the binary is in the image) and the GenericService speaks their
binary encoding via SerializeToString/FromString — proving the server's
serializer plumbing carries protobuf, not just the JSON default.
"""

import shutil
import subprocess
import sys

import pytest

from gofr_tpu.grpcx import GenericService, GRPCClient, GRPCServer
from gofr_tpu.logging import MockLogger

PROTO = """
syntax = "proto3";
package gofrtest;
message EmbedRequest { string text = 1; int32 id = 2; }
message EmbedResponse { repeated float vector = 1; int32 id = 2; }
"""


@pytest.fixture(scope="module")
def embed_pb2(tmp_path_factory):
    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    root = tmp_path_factory.mktemp("proto")
    (root / "embed.proto").write_text(PROTO)
    subprocess.run(["protoc", f"--python_out={root}", "embed.proto"],
                   cwd=root, check=True)
    sys.path.insert(0, str(root))
    try:
        import embed_pb2 as module

        yield module
    finally:
        sys.path.remove(str(root))


class _Container:
    def __init__(self):
        self.logger = MockLogger()
        self.tracer = None
        self.metrics_manager = None

    def __getattr__(self, name):
        return None


def test_protobuf_stub_round_trip(embed_pb2):
    def embed(ctx):
        msg = ctx.request.payload                    # deserialized Message
        assert isinstance(msg, embed_pb2.EmbedRequest)
        return embed_pb2.EmbedResponse(
            vector=[float(len(msg.text)), 2.5], id=msg.id)

    service = GenericService(
        "gofrtest.Embedder", {"Embed": embed},
        serializer=lambda msg: msg.SerializeToString(),
        deserializer=embed_pb2.EmbedRequest.FromString)

    server = GRPCServer(_Container(), port=0, logger=MockLogger())
    server.register(service)
    server.start()
    try:
        client = GRPCClient(f"127.0.0.1:{server.port}")
        resp = client.call(
            "gofrtest.Embedder", "Embed",
            embed_pb2.EmbedRequest(text="hello", id=9),
            serializer=lambda msg: msg.SerializeToString(),
            deserializer=embed_pb2.EmbedResponse.FromString)
        assert isinstance(resp, embed_pb2.EmbedResponse)
        assert resp.id == 9
        assert list(resp.vector) == [5.0, 2.5]
        client.close()
    finally:
        server.stop()


def test_protobuf_wire_bytes_are_binary(embed_pb2):
    """The wire payload is protobuf binary, not JSON in disguise."""
    raw = embed_pb2.EmbedRequest(text="hi", id=3).SerializeToString()
    assert raw and not raw.strip().startswith(b"{")
    parsed = embed_pb2.EmbedRequest.FromString(raw)
    assert parsed.text == "hi" and parsed.id == 3


STREAM_PROTO = """
syntax = "proto3";
package gofrstream;
message GenRequest { string prompt = 1; int32 max_tokens = 2; }
message GenChunk { string text = 1; bool done = 2; int32 tokens = 3; }
"""


@pytest.fixture(scope="module")
def gen_pb2(tmp_path_factory):
    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    root = tmp_path_factory.mktemp("stream_proto")
    (root / "gen.proto").write_text(STREAM_PROTO)
    subprocess.run(["protoc", f"--python_out={root}", "gen.proto"],
                   cwd=root, check=True)
    sys.path.insert(0, str(root))
    try:
        import gen_pb2 as module

        yield module
    finally:
        sys.path.remove(str(root))


def test_protobuf_server_streaming(gen_pb2):
    """Server-streaming RPC over the REAL protobuf wire format: the
    handler returns an iterator, each item serializes as one stream
    message, and the client consumes them in order."""
    def generate(ctx):
        msg = ctx.request.payload
        assert isinstance(msg, gen_pb2.GenRequest)
        for i in range(msg.max_tokens):
            yield gen_pb2.GenChunk(text=f"{msg.prompt}-{i}")
        yield gen_pb2.GenChunk(done=True, tokens=msg.max_tokens)

    service = GenericService(
        "gofrstream.Generator", {},
        stream_methods={"Generate": generate},
        serializer=lambda msg: msg.SerializeToString(),
        deserializer=gen_pb2.GenRequest.FromString)

    server = GRPCServer(_Container(), port=0, logger=MockLogger())
    server.register(service)
    server.start()
    try:
        client = GRPCClient(f"127.0.0.1:{server.port}")
        chunks = list(client.stream(
            "gofrstream.Generator", "Generate",
            gen_pb2.GenRequest(prompt="tok", max_tokens=4),
            serializer=lambda msg: msg.SerializeToString(),
            deserializer=gen_pb2.GenChunk.FromString))
        assert [c.text for c in chunks[:-1]] == [f"tok-{i}" for i in range(4)]
        assert chunks[-1].done and chunks[-1].tokens == 4
        client.close()
    finally:
        server.stop()


def test_grpc_streams_a_real_generation():
    """The flagship workload over gRPC: a REAL engine generation streamed
    token-by-token through the server-streaming Generate service (the
    gRPC twin of the SSE /generate surface), token-for-token equal to
    the engine's own output."""
    import importlib.util
    import os as _os

    from gofr_tpu.models.llama import LlamaConfig, llama_init
    from gofr_tpu.tpu.engine import LLMEngine

    path = _os.path.join(_os.path.dirname(__file__), "..", "examples",
                         "llm-server", "main.py")
    spec = importlib.util.spec_from_file_location("llm_server_grpc_t", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)

    cfg = LlamaConfig.debug()
    params = llama_init(cfg, seed=0)
    engine = LLMEngine(params, cfg, n_slots=2, max_seq_len=128,
                       prefill_buckets=(8, 32), sampling_controls=True)
    engine.start()
    from gofr_tpu.models.tokenizer import ByteTokenizer

    tokenizer = ByteTokenizer()
    engine.tokenizer = tokenizer
    server = GRPCServer(_Container(), port=0, logger=MockLogger())
    server.register(module.build_generate_service(engine, tokenizer))
    server.start()
    try:
        want = tokenizer.decode(engine.submit(
            tokenizer.encode("grpc"), max_new_tokens=8,
            temperature=0.0, stop_tokens={tokenizer.EOS}).result(
                timeout_s=120))
        client = GRPCClient(f"127.0.0.1:{server.port}")
        chunks = list(client.stream(
            "llm.Generator", "Generate",
            {"prompt": "grpc", "max_tokens": 8, "temperature": 0.0},
            timeout_s=120))
        assert chunks[-1]["done"] is True
        assert chunks[-1]["tokens"] == 8
        streamed = "".join(c.get("text", "") for c in chunks[:-1])
        assert streamed == want
        # parameter parity with SSE: top_k=1 at temperature 1 must still
        # reproduce greedy (one survivor per step) — proves the gRPC
        # handler forwards sampling controls instead of dropping them
        chunks_k1 = list(client.stream(
            "llm.Generator", "Generate",
            {"prompt": "grpc", "max_tokens": 8, "temperature": 1.0,
             "top_k": 1},
            timeout_s=120))
        assert "".join(c.get("text", "") for c in chunks_k1[:-1]) == want
        client.close()
    finally:
        server.stop()
        engine.stop()


def test_grpc_validation_errors_map_to_invalid_argument():
    """ADVICE r4: client-input errors (ValueError / InvalidParam raised by
    handlers) must abort INVALID_ARGUMENT, not INTERNAL — gRPC clients
    need to tell bad requests from server faults, like the HTTP 400/500
    split. Covers unary and the lazily-raising stream path."""
    import grpc as grpc_mod

    from gofr_tpu.http.errors import InvalidParam

    def bad_unary(ctx):
        raise ValueError("empty prompt")

    def broken_unary(ctx):
        raise RuntimeError("engine on fire")

    def bad_stream(ctx):
        raise InvalidParam(["top_p"])
        yield  # pragma: no cover

    service = GenericService(
        "val.Svc", {"Bad": bad_unary, "Broken": broken_unary},
        stream_methods={"BadStream": bad_stream})
    server = GRPCServer(_Container(), port=0, logger=MockLogger())
    server.register(service)
    server.start()
    try:
        client = GRPCClient(f"127.0.0.1:{server.port}")
        with pytest.raises(grpc_mod.RpcError) as err:
            client.call("val.Svc", "Bad", {"x": 1})
        assert err.value.code() == grpc_mod.StatusCode.INVALID_ARGUMENT
        with pytest.raises(grpc_mod.RpcError) as err:
            client.call("val.Svc", "Broken", {"x": 1})
        assert err.value.code() == grpc_mod.StatusCode.INTERNAL
        with pytest.raises(grpc_mod.RpcError) as err:
            list(client.stream("val.Svc", "BadStream", {"x": 1}))
        assert err.value.code() == grpc_mod.StatusCode.INVALID_ARGUMENT
        client.close()
    finally:
        server.stop()


CS_PROTO = """
syntax = "proto3";
package gofrcs;
message Sample { int32 value = 1; string tag = 2; }
message Summary { int32 count = 1; int32 total = 2; string tags = 3; }
message Echo { string text = 1; int32 seq = 2; }
"""


@pytest.fixture(scope="module")
def cs_pb2(tmp_path_factory):
    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    root = tmp_path_factory.mktemp("cs_proto")
    (root / "cs.proto").write_text(CS_PROTO)
    subprocess.run(["protoc", f"--python_out={root}", "cs.proto"],
                   cwd=root, check=True)
    sys.path.insert(0, str(root))
    try:
        import cs_pb2 as module

        yield module
    finally:
        sys.path.remove(str(root))


def test_protobuf_client_streaming_aggregation(cs_pb2):
    """Client-streaming over the real protobuf wire: the handler consumes
    the inbound iterator (each message deserialized by the stub) and
    returns ONE aggregated response — completing the RPC-shape matrix the
    reference hosts via protoc registration (VERDICT r4 missing #4)."""
    def aggregate(ctx):
        count = total = 0
        tags = []
        for msg in ctx.request.payload:
            assert isinstance(msg, cs_pb2.Sample)
            count += 1
            total += msg.value
            tags.append(msg.tag)
        return cs_pb2.Summary(count=count, total=total, tags=",".join(tags))

    service = GenericService(
        "gofrcs.Aggregator", {},
        client_stream_methods={"Collect": aggregate},
        serializer=lambda msg: msg.SerializeToString(),
        deserializer=cs_pb2.Sample.FromString)
    server = GRPCServer(_Container(), port=0, logger=MockLogger())
    server.register(service)
    server.start()
    try:
        client = GRPCClient(f"127.0.0.1:{server.port}")
        out = client.client_stream(
            "gofrcs.Aggregator", "Collect",
            [cs_pb2.Sample(value=v, tag=t)
             for v, t in ((3, "a"), (4, "b"), (5, "c"))],
            serializer=lambda msg: msg.SerializeToString(),
            deserializer=cs_pb2.Summary.FromString)
        assert out.count == 3 and out.total == 12 and out.tags == "a,b,c"
        client.close()
    finally:
        server.stop()


def test_protobuf_bidi_echo(cs_pb2):
    """Bidi echo over the protobuf wire: one response per inbound message,
    order preserved, stream ends when the client's does."""
    def echo(ctx):
        for msg in ctx.request.payload:
            yield cs_pb2.Echo(text=msg.text.upper(), seq=msg.seq + 100)

    service = GenericService(
        "gofrcs.Echoer", {},
        bidi_methods={"Chat": echo},
        serializer=lambda msg: msg.SerializeToString(),
        deserializer=cs_pb2.Echo.FromString)
    server = GRPCServer(_Container(), port=0, logger=MockLogger())
    server.register(service)
    server.start()
    try:
        client = GRPCClient(f"127.0.0.1:{server.port}")
        outs = list(client.bidi(
            "gofrcs.Echoer", "Chat",
            [cs_pb2.Echo(text=f"m{i}", seq=i) for i in range(5)],
            serializer=lambda msg: msg.SerializeToString(),
            deserializer=cs_pb2.Echo.FromString))
        assert [(o.text, o.seq) for o in outs] == [
            (f"M{i}", i + 100) for i in range(5)]
        client.close()
    finally:
        server.stop()


def test_client_stream_validation_maps_to_invalid_argument(cs_pb2):
    """The 400-vs-500 split holds for the new shapes too."""
    import grpc as grpc_mod

    def reject(ctx):
        for _ in ctx.request.payload:
            raise ValueError("bad sample")
        return cs_pb2.Summary()

    service = GenericService(
        "gofrcs.Rejector", {},
        client_stream_methods={"Collect": reject},
        serializer=lambda msg: msg.SerializeToString(),
        deserializer=cs_pb2.Sample.FromString)
    server = GRPCServer(_Container(), port=0, logger=MockLogger())
    server.register(service)
    server.start()
    try:
        client = GRPCClient(f"127.0.0.1:{server.port}")
        with pytest.raises(grpc_mod.RpcError) as err:
            client.client_stream(
                "gofrcs.Rejector", "Collect", [cs_pb2.Sample(value=1)],
                serializer=lambda msg: msg.SerializeToString(),
                deserializer=cs_pb2.Summary.FromString)
        assert err.value.code() == grpc_mod.StatusCode.INVALID_ARGUMENT
        client.close()
    finally:
        server.stop()


def test_bidi_interleaves_with_generator_request():
    """JSON default serializers + a generator request body: the bidi
    handler's reply to message N arrives before the client produces
    message N+1 — proving genuine interleaving, not batch-then-reply."""
    import queue as queue_mod

    received = queue_mod.Queue()

    def echo(ctx):
        for msg in ctx.request.payload:
            yield {"got": msg["n"]}

    service = GenericService("inter.Svc", {}, bidi_methods={"Chat": echo})
    server = GRPCServer(_Container(), port=0, logger=MockLogger())
    server.register(service)
    server.start()
    try:
        client = GRPCClient(f"127.0.0.1:{server.port}")
        replies = []

        def requests():
            for n in range(3):
                yield {"n": n}
                # wait until the echo for n comes back before sending n+1
                replies.append(received.get(timeout=10))

        stream = client.bidi("inter.Svc", "Chat", requests())
        for item in stream:
            received.put(item)
        assert replies == [{"got": 0}, {"got": 1}, {"got": 2}]
        client.close()
    finally:
        server.stop()
