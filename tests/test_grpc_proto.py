"""gRPC over REAL protobuf wire format, with protoc-generated stubs.

The reference registers protoc-generated service stubs (grpc.go:56-60,
examples/grpc-server). Here protoc generates the message classes AT TEST
TIME (the binary is in the image) and the GenericService speaks their
binary encoding via SerializeToString/FromString — proving the server's
serializer plumbing carries protobuf, not just the JSON default.
"""

import shutil
import subprocess
import sys

import pytest

from gofr_tpu.grpcx import GenericService, GRPCClient, GRPCServer
from gofr_tpu.logging import MockLogger

PROTO = """
syntax = "proto3";
package gofrtest;
message EmbedRequest { string text = 1; int32 id = 2; }
message EmbedResponse { repeated float vector = 1; int32 id = 2; }
"""


@pytest.fixture(scope="module")
def embed_pb2(tmp_path_factory):
    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    root = tmp_path_factory.mktemp("proto")
    (root / "embed.proto").write_text(PROTO)
    subprocess.run(["protoc", f"--python_out={root}", "embed.proto"],
                   cwd=root, check=True)
    sys.path.insert(0, str(root))
    try:
        import embed_pb2 as module

        yield module
    finally:
        sys.path.remove(str(root))


class _Container:
    def __init__(self):
        self.logger = MockLogger()
        self.tracer = None
        self.metrics_manager = None

    def __getattr__(self, name):
        return None


def test_protobuf_stub_round_trip(embed_pb2):
    def embed(ctx):
        msg = ctx.request.payload                    # deserialized Message
        assert isinstance(msg, embed_pb2.EmbedRequest)
        return embed_pb2.EmbedResponse(
            vector=[float(len(msg.text)), 2.5], id=msg.id)

    service = GenericService(
        "gofrtest.Embedder", {"Embed": embed},
        serializer=lambda msg: msg.SerializeToString(),
        deserializer=embed_pb2.EmbedRequest.FromString)

    server = GRPCServer(_Container(), port=0, logger=MockLogger())
    server.register(service)
    server.start()
    try:
        client = GRPCClient(f"127.0.0.1:{server.port}")
        resp = client.call(
            "gofrtest.Embedder", "Embed",
            embed_pb2.EmbedRequest(text="hello", id=9),
            serializer=lambda msg: msg.SerializeToString(),
            deserializer=embed_pb2.EmbedResponse.FromString)
        assert isinstance(resp, embed_pb2.EmbedResponse)
        assert resp.id == 9
        assert list(resp.vector) == [5.0, 2.5]
        client.close()
    finally:
        server.stop()


def test_protobuf_wire_bytes_are_binary(embed_pb2):
    """The wire payload is protobuf binary, not JSON in disguise."""
    raw = embed_pb2.EmbedRequest(text="hi", id=3).SerializeToString()
    assert raw and not raw.strip().startswith(b"{")
    parsed = embed_pb2.EmbedRequest.FromString(raw)
    assert parsed.text == "hi" and parsed.id == 3
