"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh BEFORE jax import.

This is the CI tier from SURVEY.md §4: real compile/execute semantics with no
TPU hardware (the reference's miniredis-style fake-backend idiom), and 8
virtual devices so multi-chip sharding paths are exercised for real.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment's sitecustomize registers the real-TPU PJRT plugin and
# force-sets jax_platforms="axon,cpu" (overriding the env var above). Tests
# must never touch the single-tenant TPU tunnel — re-pin the config to cpu
# AFTER jax import; backends initialize lazily, so this wins.
import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 - plain environments have no override to undo
    pass

import pytest  # noqa: E402


@pytest.fixture()
def mock_container():
    from gofr_tpu import new_mock_container

    return new_mock_container()


@pytest.fixture()
def free_port():
    from gofr_tpu.testutil import get_free_port

    return get_free_port()
