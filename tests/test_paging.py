"""Paged KV serving: kernel numerics, allocator ledger, engine behavior.

The load-bearing assertions (VERDICT r2 missing #4 "done" criteria):
  - paged engine output == dense engine output token-for-token
  - HBM pool bytes and page usage track the SUM of live contexts, not
    max_seq x n_slots (mixed 16-token and long contexts share one pool)
  - admission defers when the pool is exhausted and resumes on free
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.ops.paged_attention import (paged_attention,
                                          paged_attention_reference,
                                          paged_write_decode,
                                          paged_write_prefill)
from gofr_tpu.tpu.engine import LLMEngine
from gofr_tpu.tpu.paging import PageAllocator, PagedLLMEngine

CFG = LlamaConfig.debug()


class MockLogger:
    def debugf(self, *a): pass
    def infof(self, *a): pass
    def warnf(self, *a): pass
    def errorf(self, *a): pass


# -- kernel -------------------------------------------------------------------
def test_paged_attention_kernel_matches_reference():
    rng = np.random.default_rng(0)
    B, H, Hkv, dh, ps, P, NP = 3, 4, 2, 16, 8, 10, 4
    q = jnp.asarray(rng.normal(size=(B, H, dh)), dtype=jnp.float32)
    k_pool = jnp.asarray(rng.normal(size=(P, Hkv, dh, ps)), dtype=jnp.float32)
    v_pool = jnp.asarray(rng.normal(size=(P, Hkv, dh, ps)), dtype=jnp.float32)
    table = jnp.asarray(rng.integers(0, P, size=(B, NP)), dtype=jnp.int32)
    lengths = jnp.asarray([5, 17, 32], dtype=jnp.int32)

    ref = paged_attention_reference(q, k_pool, v_pool, table, lengths)
    out = paged_attention(q, k_pool, v_pool, table, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_writes_round_trip():
    rng = np.random.default_rng(1)
    Hkv, dh, ps, P = 2, 16, 8, 12
    k_pool = jnp.zeros((P, Hkv, dh, ps), dtype=jnp.float32)
    v_pool = jnp.zeros_like(k_pool)

    # prefill: 11 tokens over pages [2, 3]; junk past length=11 -> garbage
    K, T = 1, 16
    kpre = jnp.asarray(rng.normal(size=(K, T, Hkv, dh)), dtype=jnp.float32)
    table = jnp.asarray([[2, 3]], dtype=jnp.int32)
    lens = jnp.asarray([11], dtype=jnp.int32)
    kp, vp = paged_write_prefill(k_pool, v_pool, kpre, kpre, table, lens)
    np.testing.assert_array_equal(np.asarray(kp[2, :, :, 5]),
                                  np.asarray(kpre[0, 5]))
    np.testing.assert_array_equal(np.asarray(kp[3, :, :, 2]),
                                  np.asarray(kpre[0, 10]))
    assert np.all(np.asarray(kp[3, :, :, 3:]) == 0)  # junk went to garbage

    # decode write at position 11 -> page 3, offset 3
    knew = jnp.asarray(rng.normal(size=(1, Hkv, dh)), dtype=jnp.float32)
    kp, vp = paged_write_decode(kp, vp, knew, knew, table,
                                jnp.asarray([11], dtype=jnp.int32))
    np.testing.assert_array_equal(np.asarray(kp[3, :, :, 3]),
                                  np.asarray(knew[0]))


# -- allocator ----------------------------------------------------------------
def test_page_allocator_ledger():
    a = PageAllocator(n_pages=9, page_size=16)
    assert a.free_pages == 8  # page 0 reserved as garbage
    assert a.garbage_page == 0
    assert 0 not in a.alloc(8)  # garbage page is never handed out
    a = PageAllocator(n_pages=9, page_size=16)
    assert a.pages_for(1) == 1 and a.pages_for(16) == 1 and a.pages_for(17) == 2
    got = a.alloc(5)
    assert len(got) == 5 and a.free_pages == 3
    assert a.alloc(4) is None          # insufficient: nothing taken
    assert a.free_pages == 3
    a.release(got[:2])
    assert a.free_pages == 5
    assert a.used_pages == 3


# -- engine -------------------------------------------------------------------
def _make_paged(**kw):
    params = llama_init(CFG, seed=0)
    defaults = dict(n_slots=4, max_seq_len=64, prefill_buckets=(8, 16),
                    page_size=8, logger=MockLogger())
    defaults.update(kw)
    eng = PagedLLMEngine(params, CFG, **defaults)
    eng.start()
    return eng


def test_paged_engine_matches_dense_engine():
    """Token-for-token parity with the dense engine under greedy decode."""
    params = llama_init(CFG, seed=0)
    prompts = [[5, 6, 7], [9, 10, 11, 12, 13, 14, 15, 16, 17], [1, 2]]

    dense = LLMEngine(params, CFG, n_slots=4, max_seq_len=64,
                      prefill_buckets=(8, 16), logger=MockLogger())
    dense.start()
    try:
        want = [dense.generate(p, max_new_tokens=8, temperature=0.0)
                for p in prompts]
    finally:
        dense.stop()

    paged = _make_paged()
    try:
        got = [paged.generate(p, max_new_tokens=8, temperature=0.0)
               for p in prompts]
    finally:
        paged.stop()
    assert got == want


def test_paged_engine_concurrent_mixed_lengths():
    """Mixed short/long contexts share the pool; usage tracks the SUM of
    live pages (a short context is NOT billed for the longest's length)."""
    eng = _make_paged(n_slots=4, max_seq_len=64, page_size=8,
                      n_pages=4 * 8 + 1)
    try:
        long_req = eng.submit(list(range(1, 15)), max_new_tokens=24,
                              temperature=0.0)   # 38 tokens -> 5 pages
        short_req = eng.submit([3, 4], max_new_tokens=4,
                               temperature=0.0)  # 6 tokens -> 1 page
        while not (long_req.generated and short_req.generated):
            time.sleep(0.01)
        # while both are live: 5 + 1 pages, not 2 x pages(max_seq)
        assert eng.allocator.used_pages == 6
        short_req.result(timeout_s=60)
        long_req.result(timeout_s=60)
        deadline = time.time() + 5
        while eng.allocator.used_pages and time.time() < deadline:
            time.sleep(0.01)
        assert eng.allocator.used_pages == 0  # everything returned
    finally:
        eng.stop()


def test_paged_pool_bytes_track_budget_not_dense_worstcase():
    """The pool is the explicit budget: sized at n_pages, independent of
    n_slots x max_seq_len."""
    eng = _make_paged(n_slots=4, max_seq_len=64, page_size=8, n_pages=9)
    try:
        dense_equiv = 2 * (CFG.n_layers * 4 * CFG.n_kv_heads * CFG.head_dim
                           * 64 * 4)  # f32 dense cache bytes at max_seq
        assert eng.pool_bytes() < dense_equiv / 3
    finally:
        eng.stop()


def test_paged_admission_defers_until_pages_free():
    """With a pool that fits ONE request's reservation, the second request
    must wait (not fail) and complete after the first releases."""
    # 6 tokens @ ps=8 -> 1 page; pool has 2 usable pages; each request
    # reserves 2 pages (2 + 4 tokens... make it explicit:
    eng = _make_paged(n_slots=4, max_seq_len=64, page_size=8,
                      n_pages=3)  # 2 usable + garbage
    try:
        r1 = eng.submit([1, 2, 3, 4, 5, 6, 7, 8], max_new_tokens=8,
                        temperature=0.0)  # 16 tokens -> 2 pages (all of them)
        r2 = eng.submit([9, 10], max_new_tokens=4,
                        temperature=0.0)  # 6 tokens -> 1 page: must wait
        out1 = r1.result(timeout_s=120)
        out2 = r2.result(timeout_s=120)
        assert len(out1) == 8 and len(out2) == 4
        # waiting was observed (metric is best-effort; ordering is the test)
        assert r2.finished_at >= r1.finished_at
    finally:
        eng.stop()


def test_paged_submit_rejects_impossible_reservation():
    """A request that could NEVER fit the pool is rejected at submit —
    deferring it would head-of-line-block all later admission forever."""
    eng = _make_paged(n_slots=2, max_seq_len=64, page_size=8, n_pages=3)
    try:
        with pytest.raises(ValueError, match="pool has only 2 usable"):
            eng.submit(list(range(1, 20)), max_new_tokens=32)  # 7 pages
        # a fitting request still serves
        assert len(eng.generate([1, 2], max_new_tokens=3)) == 3
    finally:
        eng.stop()


def test_paged_engine_span_and_budget_plan():
    """The paged engine keeps the base submit(span=) trace surface, and a
    budget plans with paged=True (no dense growth/ping-pong transient)."""
    from gofr_tpu.tracing import InMemoryExporter, Tracer

    tracer = Tracer(exporter=InMemoryExporter())
    params = llama_init(CFG, seed=0)
    eng = PagedLLMEngine(params, CFG, n_slots=2, max_seq_len=64, page_size=8,
                         prefill_buckets=(8, 16), logger=MockLogger(),
                         tracer=tracer, budget_bytes=64 << 20)
    eng.start()
    try:
        assert eng.plan is not None and eng.plan.growth_transient_bytes == 0
        span = tracer.start_span("req")
        out = eng.submit([1, 2, 3], max_new_tokens=4, span=span).result(
            timeout_s=60)
        assert len(out) == 4
        assert span.attributes["tpu.prefill_bucket"] == 8
        assert "batch.id" in span.attributes
    finally:
        eng.stop()


def test_paged_explicit_pool_must_fit_budget():
    """An explicit n_pages bypasses the plan's sizing; the constructor must
    still reject a pool that cannot fit the budget."""
    params = llama_init(CFG, seed=0)
    with pytest.raises(ValueError, match="does not fit the budget"):
        PagedLLMEngine(params, CFG, n_slots=2, max_seq_len=64, page_size=8,
                       n_pages=100_000, logger=MockLogger(),
                       budget_bytes=32 << 20)


def test_paged_engine_with_tp_mesh():
    """The paged pool is a STACKED array; mesh placement must shard its
    KV-head axis whole, not iterate it into per-layer slices (the dense
    engine's tuple placement)."""
    from gofr_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(tp=2), devices=jax.devices()[:2])
    params = llama_init(CFG, seed=0)
    eng = PagedLLMEngine(params, CFG, n_slots=2, max_seq_len=64, page_size=8,
                         prefill_buckets=(8,), mesh=mesh, logger=MockLogger())
    eng.start()
    try:
        assert hasattr(eng.k_cache, "shape")  # still one stacked array
        shard = eng.k_cache.sharding.shard_shape(eng.k_cache.shape)
        assert shard[2] == CFG.n_kv_heads // 2
        assert eng.pool_bytes() > 0
        out = eng.generate([1, 2, 3], max_new_tokens=4, temperature=0.0)
        assert len(out) == 4
    finally:
        eng.stop()


def test_paged_engine_streaming_and_stop_tokens():
    eng = _make_paged()
    try:
        req = eng.submit([1, 2, 3], max_new_tokens=16, temperature=0.0)
        toks = list(req.stream(timeout_s=60))
        assert len(toks) == 16
        want = eng.generate([1, 2, 3], max_new_tokens=16, temperature=0.0)
        assert toks == want
        stop = eng.generate([1, 2, 3], max_new_tokens=16, temperature=0.0,
                            stop_tokens={want[2]})
        assert stop == want[:3]
    finally:
        eng.stop()


@pytest.mark.slow  # tier-1 wall-clock budget; lighter in-lane representative kept
def test_paged_q8_engine_matches_paged_fp_closely():
    """INT8 paged pool: prefill is full-precision into the quantized splice
    (first token exact vs the fp paged engine); decode reads dequant-folded
    pages — near-ties may flip, bulk must agree, and pages must free."""
    import dataclasses

    cfg_q8 = dataclasses.replace(CFG, kv_dtype="int8")
    prompts = [[1, 2, 3, 4, 5], list(range(7, 40)), [9]]

    def serve(use_cfg):
        params = llama_init(CFG, seed=0)
        eng = PagedLLMEngine(params, use_cfg, page_size=16, n_slots=4,
                             max_seq_len=128, prefill_buckets=(8, 64),
                             decode_block_size=4)
        eng.start()
        try:
            reqs = [eng.submit(p, max_new_tokens=10, temperature=0.0)
                    for p in prompts]
            outs = [r.result(timeout_s=300) for r in reqs]
            import time as _t
            deadline = _t.time() + 10
            while eng.allocator.used_pages and _t.time() < deadline:
                _t.sleep(0.02)
            assert eng.allocator.used_pages == 0, "pages leaked"
            return outs
        finally:
            eng.stop()

    fp = serve(CFG)
    q8 = serve(cfg_q8)
    assert [len(t) for t in q8] == [len(t) for t in fp]
    for f, q in zip(fp, q8):
        assert f[0] == q[0]          # full-precision prefill: exact
    total = sum(len(t) for t in fp)
    agree = sum(a == b for f, q in zip(fp, q8) for a, b in zip(f, q))
    # What int8 KV dequant actually guarantees: per-(vector, axis) scales
    # bound the cache quantization error at ~0.4% of each vector's max
    # (|x - dq(x)| <= scale/2, scale = max|x|/127), which perturbs logits
    # only slightly — but on this random-weight debug model the top-2
    # logit gap is often inside that perturbation, and ONE flipped
    # near-tie argmax changes the whole autoregressive suffix for that
    # stream (divergence compounds; agreement below is positional). So
    # the hard guarantees are structural — exact first token (prefill is
    # full precision), equal lengths, bitwise determinism — and the bulk
    # agreement bound must tolerate one early flip per stream: >30%
    # catches a broken dequant path (near-zero agreement) without flaking
    # on a legitimate near-tie flip.
    assert agree / total > 0.3, f"only {agree}/{total} agree"
    assert q8 == serve(cfg_q8)       # deterministic


def test_paged_attention_int8_matches_reference():
    from gofr_tpu.ops.decode_attention import quantize_kv
    from gofr_tpu.ops.paged_attention import paged_attention_reference

    rng = np.random.default_rng(5)
    B, H, Hkv, dh, P, ps, NP = 3, 4, 2, 16, 9, 8, 4
    q = jnp.asarray(rng.normal(size=(B, H, dh)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(P, Hkv, dh, ps)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(P, Hkv, dh, ps)), dtype=jnp.float32)
    k8, ks = quantize_kv(k)     # axis=-2 (dh) -> scales [P, Hkv, ps]
    v8, vs = quantize_kv(v)
    table = jnp.asarray([[1, 2, 3, 4], [5, 6, 0, 0], [7, 8, 0, 0]],
                        dtype=jnp.int32)
    lens = jnp.asarray([29, 11, 16], dtype=jnp.int32)
    ref = paged_attention_reference(q, k8, v8, table, lens, ks, vs)
    out = paged_attention(q, k8, v8, table, lens, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)
    # close to the full-precision read too
    exact = paged_attention_reference(q, k, v, table, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(exact),
                               rtol=0.15, atol=0.15)


def test_paged_priority_no_head_of_line_inversion():
    """A small high-priority request must admit while a big low-priority
    request stays parked on page exhaustion — and the parked one still
    completes once pages free (no starvation)."""
    params = llama_init(CFG, seed=0)
    # tiny pool: 1 garbage + 6 usable pages of 8 tokens
    eng = PagedLLMEngine(params, CFG, page_size=8, n_pages=7, n_slots=2,
                         max_seq_len=64, prefill_buckets=(8, 32),
                         decode_block_size=2)
    eng.start()
    try:
        # occupy most of the pool: 30 prompt + 10 new = 5 pages
        hog = eng.submit(list(range(1, 31)), max_new_tokens=10,
                         temperature=0.0)
        deadline = time.time() + 60
        while hog.admitted_at is None and time.time() < deadline:
            time.sleep(0.005)
        # big low-priority: needs 5 pages -> parks (1 free page)
        big_low = eng.submit(list(range(1, 29)), max_new_tokens=10,
                             temperature=0.0, priority=5)
        time.sleep(0.3)
        assert big_low.admitted_at is None, "should be parked on pages"
        # small high-priority: needs 1 page -> must NOT wait behind big_low
        small_high = eng.submit([7, 7], max_new_tokens=4, temperature=0.0,
                                priority=0)
        out = small_high.result(timeout_s=120)
        assert len(out) == 4
        assert big_low.admitted_at is None or \
            small_high.admitted_at <= big_low.admitted_at
        # and the parked request eventually runs to completion
        assert len(big_low.result(timeout_s=120)) == 10
    finally:
        eng.stop()
