import os

from gofr_tpu.config import EnvFile, MockConfig


def _write(tmp_path, name, content):
    path = tmp_path / name
    path.write_text(content)
    return path


def test_env_file_loads_base(tmp_path):
    _write(tmp_path, ".env", "APP_NAME=test-app\nHTTP_PORT=8001\n# comment\nQUOTED=\"hi\"\n")
    cfg = EnvFile(str(tmp_path), environ={})
    assert cfg.get("APP_NAME") == "test-app"
    assert cfg.get_int("HTTP_PORT", 0) == 8001
    assert cfg.get("QUOTED") == "hi"
    assert cfg.get("MISSING") is None
    assert cfg.get_or_default("MISSING", "x") == "x"


def test_env_file_local_overlay(tmp_path):
    _write(tmp_path, ".env", "A=base\nB=base\n")
    _write(tmp_path, ".local.env", "B=local\n")
    cfg = EnvFile(str(tmp_path), environ={})
    assert cfg.get("A") == "base"
    assert cfg.get("B") == "local"


def test_env_file_app_env_overlay(tmp_path):
    _write(tmp_path, ".env", "A=base\n")
    _write(tmp_path, ".prod.env", "A=prod\n")
    cfg = EnvFile(str(tmp_path), environ={"APP_ENV": "prod"})
    assert cfg.get("A") == "prod"


def test_process_env_overrides_file(tmp_path):
    _write(tmp_path, ".env", "A=file\n")
    cfg = EnvFile(str(tmp_path), environ={"A": "process"})
    assert cfg.get("A") == "process"


def test_typed_getters():
    cfg = MockConfig({"I": "5", "F": "2.5", "B": "true", "BAD": "xx"})
    assert cfg.get_int("I", 0) == 5
    assert cfg.get_int("BAD", 7) == 7
    assert cfg.get_float("F", 0) == 2.5
    assert cfg.get_bool("B") is True
    assert cfg.get_bool("MISSING", True) is True
