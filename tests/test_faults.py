"""Chaos suite: fault injection, replay-after-reset, quarantine, breaker.

The crash-only contract (docs/resilience.md) under deterministic injected
failures on CPU JAX: a mid-decode device reset is INVISIBLE to clients
(streams pause, every delivered position exactly once, within the retry
budget), a poison request is quarantined instead of reset-looping the
engine, a reset storm opens the breaker (submit -> 503 DeviceLostError,
health DOWN) and a half-open probe closes it — and the fault plane itself
is provably absent (one attribute check, no route) when disarmed.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from gofr_tpu.container import STATUS_DEGRADED, STATUS_DOWN, STATUS_UP
from gofr_tpu.logging import MockLogger
from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.tpu.engine import (CacheLostError, DeviceLostError, LLMEngine)
from gofr_tpu.tpu.faults import (FaultPlane, InjectedFault,
                                 ResetStormBreaker)
from gofr_tpu.tpu.flightrecorder import FlightRecorder

CFG = LlamaConfig.debug()
PARAMS = llama_init(CFG, seed=0)


def _engine(**kw):
    defaults = dict(n_slots=8, max_seq_len=128, prefill_buckets=(16, 32),
                    decode_block_size=4, logger=MockLogger())
    defaults.update(kw)
    return LLMEngine(PARAMS, CFG, **defaults)


# -- fault plane unit behavior ------------------------------------------------
def test_fault_plane_rules_deterministic_and_bounded():
    plane = FaultPlane(plan=[{"site": "engine.decode", "nth": 3}])
    plane.hit("engine.decode")
    plane.hit("engine.decode")
    with pytest.raises(InjectedFault):
        plane.hit("engine.decode")
    plane.hit("engine.decode")  # times defaults to 1: rule exhausted
    snap = plane.snapshot()
    assert snap["hits"]["engine.decode"] == 4
    assert snap["rules"][0]["fired"] == 1
    assert snap["fired"][0]["hit"] == 3

    # delay action sleeps instead of raising
    lag = FaultPlane(plan=[{"site": "engine.sync", "action": "delay",
                            "delay_s": 0.05, "times": 1}])
    t0 = time.time()
    lag.hit("engine.sync")
    assert time.time() - t0 >= 0.04

    # probabilistic rules draw from the seeded RNG: same seed, same pattern
    def pattern(seed):
        p = FaultPlane(plan=[{"site": "s", "prob": 0.5, "times": 0}],
                       seed=seed)
        out = []
        for _ in range(64):
            try:
                p.hit("s")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    assert pattern(42) == pattern(42)
    assert pattern(42) != pattern(43)

    # malformed plans reject without arming
    with pytest.raises(ValueError):
        FaultPlane(plan=[{"site": "s", "action": "explode"}])
    with pytest.raises(ValueError):
        FaultPlane(plan=[{"site": "s", "nth": 1, "every": 2}])


def test_breaker_state_machine():
    t = [0.0]
    br = ResetStormBreaker(max_resets=2, window_s=10.0, cooldown_s=5.0,
                           clock=lambda: t[0])
    assert br.reject_for() is None and not br.blocked()
    assert br.record_reset() is False        # 1 reset: under the threshold
    t[0] = 1.0
    assert br.record_reset() is True         # 2 inside the window: OPEN
    assert br.blocked() and br.state == br.OPEN and br.state_code == 2
    assert br.reject_for() >= 0.5
    assert not br.probe_due()                # cooldown not elapsed
    t[0] = 6.5
    assert br.probe_due()                    # ONCE: open -> half_open
    assert not br.probe_due()
    assert br.reject_for() is not None       # half-open still sheds
    br.probe_failed()
    assert br.state == br.OPEN               # failed probe: fresh cooldown
    t[0] = 12.0
    assert br.probe_due()
    assert br.probe_ok() is True
    assert br.state == br.CLOSED and br.reject_for() is None

    # resets spaced wider than the window never trip
    t[0] = 100.0
    assert br.record_reset() is False
    t[0] = 200.0
    assert br.record_reset() is False

    # a reset landing while half-open goes straight back open, and the
    # stale in-flight probe's verdict is ignored
    t[0] = 300.0
    br.record_reset()
    t[0] = 300.1
    assert br.record_reset() is True
    t[0] = 306.0
    assert br.probe_due()
    assert br.record_reset() is False and br.state == br.OPEN
    assert br.probe_ok() is False
    assert br.state == br.OPEN

    # disabled breaker (max_resets=0) never opens
    off = ResetStormBreaker(max_resets=0)
    assert all(off.record_reset() is False for _ in range(10))
    assert off.reject_for() is None


# -- replay after reset -------------------------------------------------------
def test_concurrent_streams_survive_mid_decode_reset():
    """The acceptance bar: N>=8 concurrent streams ride out an injected
    mid-decode device reset with ZERO client-visible failures — every
    stream delivers exactly its budget of positions (no duplicates, no
    drops), replay events land in the flight recorder."""
    plane = FaultPlane(plan=[{"site": "engine.decode", "nth": 2,
                              "action": "raise"}], seed=7)
    eng = _engine(faults=plane, retry_budget=2)
    eng.recorder = FlightRecorder()
    eng.start()
    N, M = 8, 12
    results, reqs, errors = {}, {}, []

    def client(i):
        try:
            req = eng.submit([1 + i, 2 + i, 3 + i], max_new_tokens=M)
            reqs[i] = req
            results[i] = list(req.stream(timeout_s=120))
        except Exception as exc:  # noqa: BLE001 - the gate below
            errors.append((i, exc))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(N)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=300)
    try:
        assert not errors, errors
        for i in range(N):
            assert len(results[i]) == M, (i, len(results[i]))
        assert eng.resets_total >= 1
        assert eng.replays_total >= 1
        events = [e["event"]
                  for e in eng.recorder.snapshot()["engine_events"]]
        assert "device_reset" in events
        replayed = [i for i, req in reqs.items() if req.replays > 0]
        assert replayed, "no request ever replayed"
        detail = eng.recorder.lookup(reqs[replayed[0]].id)
        names = [e["event"] for e in detail["events"]]
        assert "replayed" in names
        assert names.count("finished") == 1  # exactly one terminal event
    finally:
        eng.stop()


def test_paged_engine_replays_and_rereserves_pages():
    """Replay over the paged pool: the reset rebuilds the allocator, the
    survivors re-reserve pages for prompt+emitted at re-admission, and no
    page leaks once every stream completes."""
    from gofr_tpu.tpu.paging import PagedLLMEngine

    plane = FaultPlane(plan=[{"site": "engine.decode", "nth": 2,
                              "action": "raise"}])
    eng = PagedLLMEngine(PARAMS, CFG, n_slots=4, max_seq_len=64,
                         prefill_buckets=(16,), decode_block_size=4,
                         page_size=8, prefix_cache=True,
                         logger=MockLogger(), faults=plane, retry_budget=2)
    eng.recorder = FlightRecorder()
    eng.start()
    shared = list(range(1, 12))
    results, errors = {}, []

    def client(i):
        try:
            req = eng.submit(shared + [40 + i], max_new_tokens=10)
            results[i] = list(req.stream(timeout_s=120))
        except Exception as exc:  # noqa: BLE001
            errors.append((i, exc))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=300)
    try:
        assert not errors, errors
        for i in range(4):
            assert len(results[i]) == 10, (i, len(results[i]))
        assert eng.resets_total >= 1 and eng.replays_total >= 1
        # no leaked pages: drop idle prefix-cache pages, then the pool
        # must be fully free
        eng.allocator.release(eng.prefix.drop_all_idle())
        assert eng.allocator.used_pages == 0
    finally:
        eng.stop()


def test_retry_budget_zero_fails_on_first_reset():
    plane = FaultPlane(plan=[{"site": "engine.decode", "nth": 1}])
    eng = _engine(faults=plane, retry_budget=0)
    eng.start()
    try:
        req = eng.submit([1, 2, 3], max_new_tokens=8)
        with pytest.raises(CacheLostError):
            list(req.stream(timeout_s=60))
        assert eng.replays_total == 0
    finally:
        eng.stop()


def test_poison_request_quarantined_without_third_reset():
    """A request that is the SOLE work in flight across two consecutive
    resets is quarantined (fails with the device error) instead of being
    granted its remaining retry budget — the engine is not reset a third
    time on its behalf."""
    plane = FaultPlane(plan=[{"site": "engine.decode", "every": 1,
                              "times": 5, "action": "raise"}])
    eng = _engine(faults=plane, retry_budget=5)
    eng.recorder = FlightRecorder()
    eng.start()
    try:
        req = eng.submit([1, 2, 3], max_new_tokens=8)
        with pytest.raises(CacheLostError):
            list(req.stream(timeout_s=120))
        assert eng.resets_total == 2, eng.resets_total
        assert eng.quarantined_total == 1
        detail = eng.recorder.lookup(req.id)
        names = [e["event"] for e in detail["events"]]
        assert "replayed" in names and "quarantined" in names
        # the engine itself survives: disarm and serve
        plane.disarm()
        assert len(eng.generate([5, 6], max_new_tokens=3)) == 3
    finally:
        eng.stop()


# -- reset-storm breaker end-to-end -------------------------------------------
def test_reset_storm_opens_breaker_then_half_open_probe_closes():
    plane = FaultPlane(plan=[{"site": "engine.decode", "every": 1,
                              "times": 2, "action": "raise"}])
    eng = _engine(n_slots=4, faults=plane, retry_budget=5,
                  reset_storm_max=2, reset_storm_window_s=60.0,
                  breaker_cooldown_s=0.4)
    eng.recorder = FlightRecorder()
    eng.start()
    try:
        # two concurrent requests so neither is sole-in-flight (no
        # quarantine): both decode dispatches fail -> 2 resets -> OPEN
        r1 = eng.submit([1, 2, 3], max_new_tokens=6)
        r2 = eng.submit([4, 5, 6], max_new_tokens=6)
        deadline = time.time() + 60
        while eng.breaker.state != "open" and time.time() < deadline:
            time.sleep(0.02)
        assert eng.breaker.state == "open"

        # open: submit sheds with the typed 503 + Retry-After hint
        with pytest.raises(DeviceLostError) as ei:
            eng.submit([7, 8], max_new_tokens=2)
        assert ei.value.status_code == 503
        assert ei.value.retry_after_s > 0
        # health reports DOWN with breaker evidence
        health = eng.health_check()
        assert health.status == STATUS_DOWN
        assert health.details["breaker"]["state"] in ("open", "half_open")

        # cooldown elapses -> the loop's half-open probe closes it (the
        # fault rules are exhausted, so the probe dispatch succeeds)
        deadline = time.time() + 60
        while eng.breaker.state != "closed" and time.time() < deadline:
            time.sleep(0.02)
        assert eng.breaker.state == "closed"

        # the interrupted requests were REPLAYED through the storm: both
        # streams complete in full once the breaker closes
        assert len(r1.result(timeout_s=120)) == 6
        assert len(r2.result(timeout_s=120)) == 6
        assert len(eng.generate([9, 10], max_new_tokens=3)) == 3
        assert eng.health_check().status == STATUS_UP

        events = [e["event"]
                  for e in eng.recorder.snapshot()["engine_events"]]
        assert "breaker_open" in events and "breaker_closed" in events
        assert "breaker_shed" in events
    finally:
        eng.stop()


def test_failed_half_open_probe_reopens():
    plane = FaultPlane(plan=[
        {"site": "engine.decode", "every": 1, "times": 2, "action": "raise"},
        # first probe fails -> re-open; second succeeds -> close
        {"site": "engine.probe", "nth": 1, "action": "raise"},
    ])
    eng = _engine(n_slots=4, faults=plane, retry_budget=5,
                  reset_storm_max=2, breaker_cooldown_s=0.2)
    eng.recorder = FlightRecorder()
    eng.start()
    try:
        r1 = eng.submit([1, 2, 3], max_new_tokens=4)
        r2 = eng.submit([4, 5, 6], max_new_tokens=4)
        deadline = time.time() + 60
        while eng.breaker.state != "closed" and time.time() < deadline:
            time.sleep(0.02)
        assert eng.breaker.state == "closed"
        assert len(r1.result(timeout_s=120)) == 4
        assert len(r2.result(timeout_s=120)) == 4
        events = [e["event"]
                  for e in eng.recorder.snapshot()["engine_events"]]
        assert "breaker_probe_failed" in events
        assert "breaker_closed" in events
    finally:
        eng.stop()


# -- other hook sites ---------------------------------------------------------
def test_health_probe_wedge_degrades_then_recovers():
    """'Wedge the health probe': the single-flight probe blocks, /health
    answers DEGRADED within its timeout, and once the wedge expires the
    next poll is healthy again."""
    from gofr_tpu.tpu.device import TPUClient

    client = TPUClient()
    client.connect()
    client.HEALTH_PROBE_TIMEOUT_S = 0.2
    assert client.health_check().status == STATUS_UP

    client.faults = FaultPlane(plan=[{"site": "device.health_probe",
                                      "action": "wedge", "delay_s": 0.6,
                                      "times": 1}])
    h = client.health_check()
    assert h.status == STATUS_DEGRADED
    assert "not answering" in h.details["error"]
    stuck = client._probe_thread
    stuck.join(timeout=10)
    assert client.health_check().status == STATUS_UP

    # a raise-action rule is a DOWN probe, not a crash
    client.faults = FaultPlane(plan=[{"site": "device.health_probe",
                                      "action": "raise", "times": 1}])
    deadline = time.time() + 10
    status = None
    while time.time() < deadline:
        status = client.health_check().status
        if status == STATUS_DOWN:
            break
        time.sleep(0.05)
    assert status == STATUS_DOWN
    client.faults = None


def test_executor_compile_latency_injection():
    import jax.numpy as jnp

    from gofr_tpu.tpu.executor import Executor

    ex = Executor()
    ex.faults = FaultPlane(plan=[{"site": "executor.compile",
                                  "action": "delay", "delay_s": 0.05,
                                  "times": 1}])
    t0 = time.time()
    program = ex.compile("lagged", lambda x: x + 1, (jnp.ones((4,)),))
    assert time.time() - t0 >= 0.04
    assert float(program(jnp.ones((4,)))[0]) == 2.0


# -- zero-overhead + HTTP gating ----------------------------------------------
def test_disarmed_components_hold_no_plane():
    """The zero-overhead contract: every hooked component defaults to
    faults=None, so the per-dispatch cost is ONE attribute check."""
    from gofr_tpu.tpu.device import TPUClient
    from gofr_tpu.tpu.executor import Executor

    eng = _engine()
    assert eng.faults is None
    assert Executor().faults is None
    assert TPUClient().faults is None
    eng.start()
    try:
        assert len(eng.generate([1, 2], max_new_tokens=3)) == 3
    finally:
        eng.stop()


def _call(port, path, method="GET", body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read().decode() or "null")
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), \
            json.loads(err.read().decode() or "null")


def _build_llm_app(extra=None):
    import importlib.util
    import os

    from gofr_tpu.config import MockConfig

    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "llm-server", "main.py")
    spec = importlib.util.spec_from_file_location(
        "example_llm_server_faults", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    conf = {"HTTP_PORT": "0", "METRICS_PORT": "0", "TPU_PLATFORM": "cpu",
            "MODEL_PRESET": "debug", "WARMUP": "false",
            "REQUEST_TIMEOUT": "120"}
    conf.update(extra or {})
    return module.build_app(config=MockConfig(conf))


def test_debug_faults_endpoint_gated_and_drives_a_drill():
    """POST /debug/faults 404s unless FAULT_INJECTION=true in config; when
    enabled, an armed drill plan injects a reset that /generate survives
    invisibly, and the snapshot shows the firing evidence."""
    # disabled (production posture): no route at all
    app = _build_llm_app()
    app.start()
    try:
        status, _, _ = _call(app.http_port, "/debug/faults", "POST",
                             {"plan": []})
        assert status in (403, 404)
        assert app.engine.faults is None
    finally:
        app.shutdown()

    # enabled: the route arms plans and the engine survives the drill
    app2 = _build_llm_app({"FAULT_INJECTION": "true",
                           "FAULT_INJECTION_SEED": "3"})
    app2.start()
    try:
        assert app2.engine.faults is not None
        status, _, body = _call(
            app2.http_port, "/debug/faults", "POST",
            {"plan": [{"site": "engine.decode", "nth": 1,
                       "action": "raise"}], "seed": 3})
        assert status == 201, body
        status, _, resp = _call(app2.http_port, "/generate", "POST",
                                {"prompt": "hello", "max_tokens": 6,
                                 "stream": False})
        assert status == 201 and resp["data"]["tokens"] == 6
        assert app2.engine.resets_total >= 1
        status, _, snap = _call(app2.http_port, "/debug/faults")
        assert status == 200
        snap = snap["data"]
        assert snap["rules"][0]["fired"] == 1
        assert snap["fired"][0]["site"] == "engine.decode"
        # /debug/engine carries the recovery evidence + breaker state
        status, _, es = _call(app2.http_port, "/debug/engine")
        assert status == 200
        es = es["data"]
        assert es["recovery"]["resets_total"] >= 1
        assert es["breaker"]["state"] == "closed"
        # a malformed plan 400s without disturbing the armed state
        status, _, _ = _call(app2.http_port, "/debug/faults", "POST",
                             {"plan": [{"site": "s", "action": "nope"}]})
        assert status == 400
    finally:
        app2.shutdown()


def test_breaker_shed_maps_to_http_503_with_retry_after():
    """An open breaker surfaces through the HTTP boundary as a real 503
    with a Retry-After header (routed through http/errors.py), never a
    bare 500 — same for the other duck-typed sheds."""
    from gofr_tpu.http.errors import ServiceUnavailable
    from gofr_tpu.http.responder import Responder
    from gofr_tpu.tpu.engine import EngineDrainingError, EngineStalledError

    for exc in (DeviceLostError(7.2), EngineDrainingError(),
                EngineStalledError(200.0),
                ServiceUnavailable("backend busy", retry_after_s=3.0)):
        response = Responder("POST").respond(None, exc)
        assert response.status == 503, type(exc).__name__
        assert int(response.headers["Retry-After"]) >= 1, type(exc).__name__

    # the llm-server routes engine sheds through ServiceUnavailable
    app = _build_llm_app()
    app.start()
    try:
        app.engine._draining = True
        status, headers, body = _call(app.http_port, "/generate", "POST",
                                      {"prompt": "hi", "max_tokens": 2,
                                       "stream": False})
        assert status == 503
        assert "Retry-After" in headers
        assert "draining" in body["error"]["message"]
        app.engine._draining = False
    finally:
        app.shutdown()
