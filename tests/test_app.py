"""End-to-end App tests: a real server on an ephemeral port, real HTTP calls.

This mirrors the reference's examples-as-integration-tests idiom
(examples/http-server/main_test.go:21-52 — boot the app, fire requests,
assert status codes, including the framework's well-known routes).
"""

import dataclasses
import json
import threading
import time

import requests

from gofr_tpu import App, MockConfig, new_mock_container
from gofr_tpu.container import Container
from gofr_tpu.http.errors import EntityNotFound
from gofr_tpu.http.responder import Stream


def make_app(extra_config=None):
    cfg = {"HTTP_PORT": "0", "METRICS_PORT": "0", "APP_NAME": "test-app",
           "KV_ENABLED": "true", "DB_PATH": ":memory:", "PUBSUB_BACKEND": "inproc"}
    cfg.update(extra_config or {})
    from gofr_tpu.logging import Level, MockLogger

    container = Container.create(MockConfig(cfg))
    container.logger = MockLogger(level=Level.ERROR)
    return App(container=container)


def test_full_request_cycle():
    app = make_app()

    @app.get("/greet")
    def greet(ctx):
        return {"message": f"hello {ctx.param('name')}"}

    @app.post("/echo")
    def echo(ctx):
        return ctx.bind()

    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        r = requests.get(f"{base}/greet?name=ada")
        assert r.status_code == 200
        assert r.json() == {"data": {"message": "hello ada"}}
        r = requests.post(f"{base}/echo", json={"a": 1})
        assert r.status_code == 201
        assert r.json()["data"] == {"a": 1}
        # well-known framework routes (main_test.go:37-38 parity)
        assert requests.get(f"{base}/.well-known/alive").json() == {"data": {"status": "UP"}}
        health = requests.get(f"{base}/.well-known/health").json()["data"]
        assert health["status"] in ("UP", "DEGRADED")
        assert "sql" in health["details"] and "kv" in health["details"]
        assert requests.get(f"{base}/nope").status_code == 404
        # metrics server exposes prometheus text
        m = requests.get(f"http://127.0.0.1:{app.metrics_port}/metrics")
        assert "app_http_response_bucket" in m.text
        assert "app_info" in m.text
    finally:
        app.shutdown()


def test_handler_error_mapping_and_timeout():
    app = make_app({"REQUEST_TIMEOUT": "0.5"})

    @app.get("/missing")
    def missing(ctx):
        raise EntityNotFound("id", "1")

    @app.get("/slow")
    def slow(ctx):
        import time

        time.sleep(5)
        return "done"

    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        assert requests.get(f"{base}/missing").status_code == 404
        r = requests.get(f"{base}/slow")  # 408 before the handler finishes (handler.go:65-75)
        assert r.status_code == 408
    finally:
        app.shutdown()


def test_handler_backpressure_503():
    """MAX_CONCURRENT_REQUESTS bounds RUNNING handlers (including
    408-abandoned ones): excess requests get a fast 503 instead of
    unbounded thread growth (VERDICT r2 weak #7)."""
    app = make_app({"REQUEST_TIMEOUT": "0.3", "MAX_CONCURRENT_REQUESTS": "2"})
    release = threading.Event()

    @app.get("/stall")
    def stall(ctx):
        release.wait(timeout=20)
        return "done"

    @app.get("/fast")
    def fast(ctx):
        return "ok"

    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        # two stalled handlers fill the cap (both 408 but keep running)
        assert requests.get(f"{base}/stall").status_code == 408
        assert requests.get(f"{base}/stall").status_code == 408
        # the cap is full: fast requests shed with 503
        r = requests.get(f"{base}/fast")
        assert r.status_code == 503
        assert "overloaded" in r.json()["error"]["message"]
        # liveness bypasses the cap: "is the process up" keeps answering
        # precisely while everything else sheds
        assert requests.get(f"{base}/.well-known/alive").status_code == 200
        # slots free once the stalled handlers actually finish
        release.set()
        deadline = time.time() + 10
        while time.time() < deadline:
            if requests.get(f"{base}/fast").status_code == 200:
                break
            time.sleep(0.1)
        assert requests.get(f"{base}/fast").status_code == 200
    finally:
        release.set()
        app.shutdown()


def test_streaming_holds_its_concurrency_slot():
    """A streaming body generates AFTER the handler thread returns; the
    concurrency slot must follow the stream's lifetime, or N streaming
    clients (the LLM workload) would hold zero slots."""
    app = make_app({"MAX_CONCURRENT_REQUESTS": "1"})
    gate = threading.Event()

    @app.get("/tokens")
    def tokens(ctx):
        def chunks():
            yield "first"
            gate.wait(timeout=20)
            yield "last"
        return Stream(chunks(), sse=True)

    @app.get("/fast")
    def fast(ctx):
        return "ok"

    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        with requests.get(f"{base}/tokens", stream=True) as r:
            lines = r.iter_lines()
            assert next(line for line in lines if line) == b"data: first"
            # the stream is mid-body: its slot is held, others shed
            assert requests.get(f"{base}/fast").status_code == 503
            gate.set()
            assert next(line for line in lines if line) == b"data: last"
        # stream finished -> slot released
        deadline = time.time() + 10
        while time.time() < deadline:
            if requests.get(f"{base}/fast").status_code == 200:
                break
            time.sleep(0.05)
        assert requests.get(f"{base}/fast").status_code == 200
    finally:
        gate.set()
        app.shutdown()


def test_streaming_sse():
    app = make_app()

    @app.get("/stream")
    def stream(ctx):
        return Stream(iter(["one", "two", "three"]), sse=True)

    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        with requests.get(f"{base}/stream", stream=True) as r:
            assert r.headers["Content-Type"] == "text/event-stream"
            events = [line for line in r.iter_lines() if line]
        assert events == [b"data: one", b"data: two", b"data: three"]
    finally:
        app.shutdown()


def test_basic_auth_integration():
    app = make_app()
    app.enable_basic_auth("user", "pass")

    @app.get("/private")
    def private(ctx):
        return "secret"

    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        assert requests.get(f"{base}/private").status_code == 401
        assert requests.get(f"{base}/private", auth=("user", "pass")).status_code == 200
    finally:
        app.shutdown()


def test_pubsub_roundtrip():
    app = make_app()
    received = []
    done = threading.Event()

    @app.subscribe("orders")
    def on_order(ctx):
        received.append(ctx.bind())
        done.set()

    @app.post("/order")
    def publish(ctx):
        ctx.publish("orders", ctx.bind())
        return "queued"

    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        requests.post(f"{base}/order", json={"id": 9})
        assert done.wait(timeout=5)
        assert received == [{"id": 9}]
    finally:
        app.shutdown()


def test_crud_generator():
    @dataclasses.dataclass
    class Book:
        id: int = 0
        title: str = ""

    app = make_app()
    app.add_rest_handlers(Book)
    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        r = requests.post(f"{base}/book", json={"id": 1, "title": "dune"})
        assert r.status_code == 201
        r = requests.get(f"{base}/book/1")
        assert r.json()["data"]["title"] == "dune"
        r = requests.put(f"{base}/book/1", json={"id": 1, "title": "dune2"})
        assert r.status_code == 200
        assert requests.get(f"{base}/book").json()["data"] == [{"id": 1, "title": "dune2"}]
        assert requests.delete(f"{base}/book/1").status_code == 204
        assert requests.get(f"{base}/book/1").status_code == 404
    finally:
        app.shutdown()


def test_mock_container_for_handler_unit_tests():
    """The reference's NewMockContainer idiom: test handlers with fake infra."""
    from gofr_tpu.context import Context
    from gofr_tpu.http.request import Request

    container = new_mock_container()
    container.kv.set("greeting", "hi")

    def handler(ctx):
        return ctx.kv.get("greeting")

    ctx = Context(request=Request("GET", "/"), container=container)
    assert handler(ctx) == "hi"


def test_profiler_endpoint(tmp_path):
    """POST answers 202 immediately (the capture runs on a daemon thread —
    an HTTP worker is never pinned for the window); GET polls to done."""
    import time as _time

    app = make_app()
    app.enable_profiler()
    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        r = requests.get(f"{base}/debug/profile")
        assert r.status_code == 200
        assert r.json()["data"]["active"] is False
        t0 = _time.time()
        r = requests.post(f"{base}/debug/profile",
                          json={"seconds": 1.0, "dir": str(tmp_path)})
        assert r.status_code == 202
        assert _time.time() - t0 < 1.0  # did NOT block for the capture
        trace_dir = r.json()["data"]["trace_dir"]
        assert trace_dir.startswith(str(tmp_path))
        import os

        assert os.path.isdir(trace_dir)  # pending dir created up front
        deadline = _time.time() + 30
        while _time.time() < deadline:
            status = requests.get(f"{base}/debug/profile").json()["data"]
            if not status["active"]:
                break
            assert status["pending_dir"] == trace_dir
            _time.sleep(0.05)
        assert status["active"] is False
        assert status["last_error"] is None
        assert status["last_dir"] == trace_dir  # xplane capture landed
        assert status["last_trigger"] == "manual"
        # monotonic-clock duration: ~the requested window, never negative
        assert 0.5 <= status["last_duration_s"] <= 30.0
    finally:
        app.shutdown()


def test_profiler_busy_answers_409(tmp_path):
    """A second POST while a capture runs maps the profiler's busy
    RuntimeError to HTTP 409 (one capture at a time: the profiler is a
    process-global singleton), and status() reports the running capture's
    trigger + monotonic age."""
    import time as _time

    app = make_app()
    app.enable_profiler()
    app.start()
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        r = requests.post(f"{base}/debug/profile",
                          json={"seconds": 2.0, "dir": str(tmp_path)})
        assert r.status_code == 202
        busy = requests.post(f"{base}/debug/profile",
                             json={"seconds": 1.0, "dir": str(tmp_path)})
        assert busy.status_code == 409
        assert "already running" in busy.json()["error"]["message"]
        status = requests.get(f"{base}/debug/profile").json()["data"]
        assert status["active"] is True
        assert status["trigger"] == "manual"
        assert status["seconds"] == 2.0
        assert status["running_for_s"] >= 0.0
        deadline = _time.time() + 30
        while _time.time() < deadline:
            status = requests.get(f"{base}/debug/profile").json()["data"]
            if not status["active"]:
                break
            _time.sleep(0.05)
        assert status["active"] is False  # leave the singleton idle
    finally:
        app.shutdown()


def test_profiler_captures_land_under_configured_profile_dir(tmp_path):
    """PROFILE_DIR is the process-wide capture root: a POST without an
    explicit dir writes under it, and status() reports paths relative to
    it (the regression: captures used to land relative to whatever cwd
    the process happened to start in)."""
    import os
    import time as _time

    from gofr_tpu.tpu import profiler as profmod

    root = str(tmp_path / "prof-root")
    app = make_app({"PROFILE_DIR": root})
    app.enable_profiler()
    try:
        assert profmod.profile_dir() == root
        app.start()
        base = f"http://127.0.0.1:{app.http_port}"
        r = requests.post(f"{base}/debug/profile", json={"seconds": 0.5})
        assert r.status_code == 202
        trace_dir = r.json()["data"]["trace_dir"]
        assert trace_dir.startswith(root)
        deadline = _time.time() + 30
        while _time.time() < deadline:
            status = requests.get(f"{base}/debug/profile").json()["data"]
            if not status["active"]:
                break
            _time.sleep(0.05)
        assert status["active"] is False
        assert status["profile_dir"] == root
        assert status["last_dir"] == trace_dir
        # the operator-facing relative form never escapes the root
        assert status["last_rel"] == os.path.relpath(trace_dir, root)
        assert not status["last_rel"].startswith("..")
    finally:
        app.shutdown()
        profmod.configure(profmod._DEFAULT_DIR)  # leave the global clean
