"""Durable file broker + gated external adapters.

Covers the Kafka-analog semantics the reference exercises against a real
broker in CI (kafka.go:100-218, subscriber.go:51-53): durable logs, committed
offsets per (topic, group), redelivery of uncommitted messages, and survival
across broker restarts (new instance over the same directory).
"""

import os
import subprocess
import sys

import pytest

from gofr_tpu.pubsub.filebroker import FileBroker


@pytest.fixture()
def broker(tmp_path):
    return FileBroker(root=str(tmp_path / "broker"))


def test_publish_subscribe_commit_order(broker):
    broker.publish("t", b"m1", key="k1")
    broker.publish("t", b"m2")
    msg = broker.subscribe("t", group="g", timeout_s=1)
    assert (msg.value, msg.key) == (b"m1", "k1")
    msg.commit()
    assert broker.subscribe("t", group="g", timeout_s=1).value == b"m2"


def test_uncommitted_redelivered_after_restart(tmp_path):
    root = str(tmp_path / "b")
    b1 = FileBroker(root=root)
    b1.publish("jobs", b"payload")
    assert b1.subscribe("jobs", group="g", timeout_s=1).value == b"payload"
    # no commit; a fresh broker instance (process restart) must redeliver
    b2 = FileBroker(root=root)
    msg = b2.subscribe("jobs", group="g", timeout_s=1)
    assert msg.value == b"payload"
    msg.commit()
    b3 = FileBroker(root=root)
    assert b3.subscribe("jobs", group="g", timeout_s=0.05) is None


def test_commit_is_durable_and_atomic(tmp_path):
    root = str(tmp_path / "b")
    b1 = FileBroker(root=root)
    for i in range(5):
        b1.publish("t", f"m{i}".encode())
    for _ in range(3):
        b1.subscribe("t", group="g", timeout_s=1).commit()
    b2 = FileBroker(root=root)
    assert b2.subscribe("t", group="g", timeout_s=1).value == b"m3"


def test_independent_groups(broker):
    broker.publish("t", b"x")
    assert broker.subscribe("t", group="g1", timeout_s=1).value == b"x"
    assert broker.subscribe("t", group="g2", timeout_s=1).value == b"x"


def test_requeue_rolls_back_to_committed(broker):
    broker.publish("t", b"a")
    broker.publish("t", b"b")
    broker.subscribe("t", group="g", timeout_s=1).commit()
    broker.subscribe("t", group="g", timeout_s=1)  # deliver b, no commit
    broker.requeue("t", group="g")
    assert broker.subscribe("t", group="g", timeout_s=1).value == b"b"


def test_timeout_returns_none(broker):
    assert broker.subscribe("empty", timeout_s=0.05) is None


def test_create_delete_topic(broker):
    broker.create_topic("t")
    assert "t" in broker.health_check().details["topics"]
    broker.delete_topic("t")
    assert "t" not in broker.health_check().details["topics"]


def test_invalid_topic_rejected(broker):
    with pytest.raises(ValueError):
        broker.publish("../escape", b"x")


def test_health_reports_offsets(broker):
    broker.publish("t", b"x")
    broker.subscribe("t", group="g", timeout_s=1).commit()
    h = broker.health_check()
    assert h.status == "UP"
    assert h.details["topics"]["t"] == 1
    assert h.details["groups"]["t/g"] == 1


def test_cross_process_publish_consume(tmp_path):
    """A second OS process publishes; this process consumes durably."""
    root = str(tmp_path / "b")
    broker = FileBroker(root=root)
    code = (
        "import sys; sys.path.insert(0, %r); "
        "from gofr_tpu.pubsub.filebroker import FileBroker; "
        "FileBroker(root=%r).publish('xp', b'from-child', key='pid')"
        % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))), root))
    subprocess.run([sys.executable, "-c", code], check=True, timeout=60)
    msg = broker.subscribe("xp", group="g", timeout_s=2)
    assert msg.value == b"from-child"
    assert msg.key == "pid"


def test_torn_tail_is_skipped_until_complete(tmp_path):
    """A half-written record at the log tail must not crash or be delivered."""
    root = str(tmp_path / "b")
    broker = FileBroker(root=root)
    broker.publish("t", b"whole")
    with open(broker._log_path("t"), "ab") as fp:
        fp.write(b"\x07\x00\x00")  # 3 bytes of a 16-byte header
    assert broker.subscribe("t", group="g", timeout_s=1).value == b"whole"
    assert broker.subscribe("t", group="g", timeout_s=0.05) is None


# -- external adapters are gated on their drivers -----------------------------
def test_kafka_adapter_gated():
    from gofr_tpu.pubsub.external import KafkaAdapter, MissingDriverError

    if "kafka" in sys.modules or _importable("kafka"):
        pytest.skip("kafka driver present; gating not applicable")
    with pytest.raises(MissingDriverError, match="kafka-python"):
        KafkaAdapter(brokers="localhost:9092")


def test_mqtt_adapter_gated():
    from gofr_tpu.pubsub.external import MissingDriverError, MQTTAdapter

    if _importable("paho.mqtt.client"):
        pytest.skip("paho driver present; gating not applicable")
    with pytest.raises(MissingDriverError, match="paho-mqtt"):
        MQTTAdapter(host="localhost")


def test_google_adapter_gated():
    from gofr_tpu.pubsub.external import GooglePubSubAdapter, MissingDriverError

    if _importable("google.cloud.pubsub_v1"):
        pytest.skip("google driver present; gating not applicable")
    with pytest.raises(MissingDriverError, match="google-cloud-pubsub"):
        GooglePubSubAdapter(project="p")


def _importable(module: str) -> bool:
    import importlib.util

    try:
        return importlib.util.find_spec(module) is not None
    except (ImportError, ModuleNotFoundError):
        return False


def test_container_wires_file_backend(tmp_path):
    from gofr_tpu.config import MockConfig
    from gofr_tpu.container import Container

    cfg = MockConfig({"PUBSUB_BACKEND": "file",
                      "PUBSUB_DIR": str(tmp_path / "ps"),
                      "METRICS_PORT": "0"})
    c = Container.create(cfg)
    assert isinstance(c.pubsub, FileBroker)
    c.pubsub.publish("t", b"hello")
    assert c.pubsub.subscribe("t", timeout_s=1).value == b"hello"


def test_container_survives_missing_kafka_driver():
    from gofr_tpu.config import MockConfig
    from gofr_tpu.container import Container

    if _importable("kafka"):
        pytest.skip("kafka driver present")
    cfg = MockConfig({"PUBSUB_BACKEND": "kafka", "METRICS_PORT": "0"})
    c = Container.create(cfg)  # boot must survive (sql.go:33-36 idiom)
    assert c.pubsub is None


# -- cross-process consumer-group claims --------------------------------------
def _write_foreign_claim(broker, topic, group, idx, pid, expires, acked=()):
    import json

    broker.create_topic(topic)
    with open(broker._lease_path(topic, group), "wb") as fp:
        fp.write(json.dumps({
            "claims": {str(idx): {"pid": pid, "iid": "foreign",
                                  "expires": expires}},
            "acked": list(acked)}).encode())


def test_live_foreign_claim_blocks_duplicate_delivery(broker):
    import time

    broker.publish("t", b"claimed-elsewhere")
    # pid 1 is always alive; its unexpired claim covers record 0
    _write_foreign_claim(broker, "t", "g", idx=0, pid=1,
                         expires=time.time() + 60)
    assert broker.subscribe("t", group="g", timeout_s=0.15) is None


def test_dead_owner_claim_is_ignored(broker):
    import time

    broker.publish("t", b"orphaned")
    _write_foreign_claim(broker, "t", "g", idx=0, pid=2 ** 22 + 12345,
                         expires=time.time() + 60)
    msg = broker.subscribe("t", group="g", timeout_s=1)
    assert msg is not None and msg.value == b"orphaned"


def test_expired_claim_is_ignored(broker):
    import time

    broker.publish("t", b"expired-claim")
    _write_foreign_claim(broker, "t", "g", idx=0, pid=1,
                         expires=time.time() - 1)
    msg = broker.subscribe("t", group="g", timeout_s=1)
    assert msg is not None and msg.value == b"expired-claim"


def test_claims_work_share_across_processes(broker):
    """A foreign live claim on record 0 leaves record 1 for this process."""
    import time

    broker.publish("t", b"m0")
    broker.publish("t", b"m1")
    _write_foreign_claim(broker, "t", "g", idx=0, pid=1,
                         expires=time.time() + 60)
    msg = broker.subscribe("t", group="g", timeout_s=1)
    assert msg.value == b"m1"


def test_acked_list_is_pruned_below_watermark(broker):
    """Stale acks (below the committed watermark) must not accumulate in the
    persisted group state forever (r1 advisor finding)."""
    for i in range(6):
        broker.publish("t", b"m%d" % i)
    for _ in range(6):
        broker.subscribe("t", group="g", timeout_s=1).commit()
    assert broker._committed("t", "g") == 6
    with open(broker._lease_path("t", "g"), "a+b") as lf:
        state = broker._read_state(lf)
    # contiguous committed prefix fully pruned; nothing lingers
    assert state.get("acked", []) == []
    # inject a stale ack below the watermark: the next commit sweeps it
    broker.publish("t", b"m6")
    msg = broker.subscribe("t", group="g", timeout_s=1)
    with open(broker._lease_path("t", "g"), "a+b") as lf:
        state = broker._read_state(lf)
        state["acked"] = [1, 2]  # stale: watermark is already past these
        broker._write_state(lf, state)
    msg.commit()
    with open(broker._lease_path("t", "g"), "a+b") as lf:
        state = broker._read_state(lf)
    assert state.get("acked", []) == []


def test_commit_cannot_skip_crashed_peers_record(broker):
    """Out-of-order commit must not advance the watermark past an unacked
    record owned by a dead peer — that record is redelivered, then the
    watermark covers both (the message-loss scenario)."""
    import time

    broker.publish("t", b"m0")
    broker.publish("t", b"m1")
    # dead peer crashed holding record 0
    _write_foreign_claim(broker, "t", "g", idx=0, pid=2 ** 22 + 99,
                         expires=time.time() + 60)
    # but our claim scan skips dead claims, so WE get record 0 first; to
    # model the race, claim record 1 while 0 looks live, then let it die
    _write_foreign_claim(broker, "t", "g", idx=0, pid=1,
                         expires=time.time() + 60)
    m1 = broker.subscribe("t", group="g", timeout_s=1)
    assert m1.value == b"m1"
    m1.commit()  # acks 1; watermark must stay at 0 (record 0 unacked)
    assert broker._committed("t", "g") == 0
    # peer's claim expires -> record 0 redelivered, commit advances to 2
    _write_foreign_claim(broker, "t", "g", idx=0, pid=1,
                         expires=time.time() - 1, acked=[1])
    m0 = broker.subscribe("t", group="g", timeout_s=1)
    assert m0.value == b"m0"
    m0.commit()
    assert broker._committed("t", "g") == 2
    assert broker.subscribe("t", group="g", timeout_s=0.05) is None
