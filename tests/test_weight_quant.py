"""INT8 weight quantization: storage, numerics, serving, capacity, TP.

Weights quantize to per-output-channel int8 (models.llama.quantize_weights /
llama_init_quantized); every matmul site routes through _mm/_embed/_head,
which switch on the weight leaf's dtype at trace time — activations quantize
per row and the dot runs int8 x int8 -> int32 (the MXU-native form), so the
weight HBM read genuinely halves instead of materializing a dequant copy.
This is the path that fits Llama-3-8B (~15 GiB bf16) on one 16 GiB v5e chip
(VERDICT r3 missing #1 / BASELINE config 4).
"""

import dataclasses

import pytest

import jax
import jax.numpy as jnp

from gofr_tpu.models.llama import (
    LlamaConfig,
    _q_matmul,
    _quantize_leaf,
    llama_forward_nocache,
    llama_init,
    llama_init_quantized,
    params_nbytes,
    quantize_weights,
)
from gofr_tpu.tpu.engine import LLMEngine

CFG = LlamaConfig.debug()
PROMPTS = [list(range(1, 9)), [7, 5, 3], list(range(20, 50)), [11]]


def _qtree():
    return quantize_weights(llama_init(CFG, seed=0))


def test_quantized_tree_structure():
    q = _qtree()
    L, D, F, V = CFG.n_layers, CFG.dim, CFG.ffn_dim, CFG.vocab_size
    H, Hkv, dh = CFG.n_heads, CFG.n_kv_heads, CFG.head_dim
    layers = q["layers"]
    for name, out_dim in [("wq", H * dh), ("wk", Hkv * dh), ("wv", Hkv * dh),
                          ("wo", D), ("w_gate", F), ("w_up", F),
                          ("w_down", D)]:
        assert layers[name].dtype == jnp.int8
        assert layers[name + "_s"].shape == (L, out_dim)
        assert layers[name + "_s"].dtype == jnp.float32
    assert q["tok_emb"].dtype == jnp.int8
    assert q["tok_emb_s"].shape == (V,)
    assert q["lm_head"].dtype == jnp.int8
    assert q["lm_head_s"].shape == (V,)
    # norms stay float (tiny, precision-critical)
    assert layers["attn_norm"].dtype != jnp.int8
    assert q["final_norm"].dtype != jnp.int8


def test_init_quantized_matches_quantize_at_load():
    """llama_init_quantized never materializes the float tree but must be
    numerically equivalent to quantizing a llama_init tree: int8 codes
    bitwise identical, scales to float-fusion tolerance (the jit fuses
    generate+quantize, so a scale may land 1 ulp off the eager path)."""
    a = _qtree()
    b = llama_init_quantized(CFG, seed=0)
    flat_a = jax.tree_util.tree_leaves_with_path(a)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(b))
    assert len(flat_a) == len(flat_b)
    for path, leaf in flat_a:
        other = flat_b[path]
        if leaf.dtype == jnp.int8:
            assert jnp.array_equal(leaf, other), f"int8 mismatch at {path}"
        else:
            assert jnp.allclose(leaf, other, rtol=1e-6), f"mismatch at {path}"


def test_quantize_consumes_input_tree():
    """quantize_weights pops float leaves as it goes — the documented
    peak-HBM contract (float tree + ONE int8 leaf, never two trees)."""
    fp = llama_init(CFG, seed=0)
    quantize_weights(fp)
    assert "tok_emb" not in fp and "lm_head" not in fp
    assert "wq" not in fp["layers"]


def test_q_matmul_close_to_dequant_reference():
    """The int8 dot + rescale matches the mathematical dequant matmul to
    activation-quantization error (~1/127 per element)."""
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 64), dtype=jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(3), (64, 128),
                          dtype=jnp.float32) * 0.1
    w8, s = _quantize_leaf(w, -2)
    ref = x @ (w8.astype(jnp.float32) * s[None, :])
    out = _q_matmul(x, w8, s)
    rel = jnp.linalg.norm(ref - out) / jnp.linalg.norm(ref)
    assert rel < 2e-2, f"relative error {rel}"


def test_logits_close_to_float_model():
    """End-to-end forward: quantized logits track the float model — the
    'logits-close test vs bf16 on the debug preset' (VERDICT r3 next #1)."""
    fp = llama_init(CFG, seed=0)
    q = _qtree()
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                              CFG.vocab_size)
    lf = llama_forward_nocache(fp, CFG, toks)
    lq = llama_forward_nocache(q, CFG, toks)
    assert lq.dtype == jnp.float32
    cos = jnp.sum(lf * lq, -1) / (jnp.linalg.norm(lf, axis=-1)
                                  * jnp.linalg.norm(lq, axis=-1))
    assert float(cos.min()) > 0.99, f"cosine {float(cos.min())}"
    agree = float((jnp.argmax(lf, -1) == jnp.argmax(lq, -1)).mean())
    assert agree > 0.8, f"top-1 agreement {agree}"


def _serve(params, cfg=CFG, **kw):
    eng = LLMEngine(params, cfg, n_slots=4, max_seq_len=128,
                    prefill_buckets=(8, 32), decode_block_size=4, **kw)
    eng.start()
    try:
        reqs = [eng.submit(p, max_new_tokens=12, temperature=0.0)
                for p in PROMPTS]
        return [r.result(timeout_s=300) for r in reqs]
    finally:
        eng.stop()


def test_engine_serves_quantized_weights():
    """The serving engine takes an int8 tree unchanged (the weights' dtype
    is the switch): full generations, deterministic, tracking the float
    engine's greedy output closely."""
    out_q = _serve(_qtree())
    assert [len(t) for t in out_q] == [12] * len(PROMPTS)
    assert out_q == _serve(_qtree())           # deterministic
    out_f = _serve(llama_init(CFG, seed=0))
    total = sum(len(t) for t in out_f)
    agree = sum(a == b for f, q in zip(out_f, out_q) for a, b in zip(f, q))
    assert agree / total > 0.5, f"only {agree}/{total} tokens agree"


def test_engine_plan_uses_actual_quantized_bytes():
    """The capacity plan must budget the MEASURED int8 tree, not the
    analytic cfg-dtype estimate (4x larger for an f32-config debug model)."""
    q = _qtree()
    eng = LLMEngine(q, CFG, n_slots=2, max_seq_len=128, prefill_buckets=(8,),
                    budget_bytes=1 << 30)
    assert eng.plan is not None
    assert eng.plan.params_bytes == params_nbytes(q)
    assert eng.plan.params_bytes < CFG.param_count() * 2


@pytest.mark.slow  # tier-1 wall-clock budget; lighter in-lane representative kept
def test_quantized_tp_mesh_matches_single_device():
    """int8 weights under a tp mesh: scale vectors shard with their weight's
    output axis (serving_param_specs(quantized=True)); the int32 dot
    accumulation is exact under the contraction split, so greedy decode
    matches the single-device quantized engine token-for-token."""
    from gofr_tpu.parallel import MeshPlan, make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    cfg = LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=8,
                      n_kv_heads=8, ffn_dim=128, max_seq_len=128,
                      dtype="float32")
    mesh = make_mesh(MeshPlan(tp=8))
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [17]]

    def serve(m):
        params = quantize_weights(llama_init(cfg, seed=0))
        eng = LLMEngine(params, cfg, n_slots=4, max_seq_len=64,
                        prefill_buckets=(8,), mesh=m)
        eng.start()
        try:
            reqs = [eng.submit(p, max_new_tokens=6, temperature=0.0)
                    for p in prompts]
            return [r.result(timeout_s=240) for r in reqs]
        finally:
            eng.stop()

    assert serve(mesh) == serve(None)


@pytest.mark.slow  # tier-1 wall-clock budget; lighter in-lane representative kept
def test_quantized_composes_with_int8_kv():
    """Weight quant (HBM for params) and KV quant (HBM for cache) are
    independent axes — both on must still serve deterministically."""
    cfg = dataclasses.replace(CFG, decode_attn="kernel", kv_dtype="int8")
    out = _serve(_qtree(), cfg=cfg)
    assert [len(t) for t in out] == [12] * len(PROMPTS)
    assert out == _serve(_qtree(), cfg=cfg)
