import pytest

from gofr_tpu.metrics import DuplicateMetric, Manager, MetricNotFound


def test_counter_roundtrip():
    m = Manager()
    m.new_counter("hits", "hit count")
    m.increment_counter("hits")
    m.increment_counter("hits", 2, path="/a")
    text = m.expose()
    assert "# TYPE hits counter" in text
    assert "hits 1.0" in text
    assert 'hits{path="/a"} 2.0' in text


def test_duplicate_registration_raises():
    m = Manager()
    m.new_counter("x", "")
    with pytest.raises(DuplicateMetric):
        m.new_counter("x", "")


def test_missing_metric_raises():
    m = Manager()
    with pytest.raises(MetricNotFound):
        m.increment_counter("nope")


def test_logger_mode_swallows_errors():
    from gofr_tpu.logging import MockLogger

    logger = MockLogger()
    m = Manager(logger=logger)
    m.increment_counter("nope")  # logged, not raised
    assert "not registered" in logger.output()


def test_gauge_and_updown():
    m = Manager()
    m.new_gauge("g", "")
    m.new_updown_counter("u", "")
    m.set_gauge("g", 42.5)
    m.delta_updown_counter("u", 3)
    m.delta_updown_counter("u", -1)
    text = m.expose()
    assert "g 42.5" in text
    assert "u 2.0" in text


def test_histogram_buckets_and_summary():
    m = Manager()
    m.new_histogram("lat", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        m.record_histogram("lat", v)
    text = m.expose()
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1.0"} 2' in text
    assert 'lat_bucket{le="10.0"} 3' in text
    assert 'lat_bucket{le="+Inf"} 4' in text
    assert "lat_count 4" in text
    assert "lat_sum 55.55" in text


def test_histogram_percentile():
    m = Manager()
    m.new_histogram("p", "", buckets=(1, 2, 4, 8))
    for v in (0.5, 1.5, 3, 7):
        m.record_histogram("p", v)
    hist = m.get("p")
    assert hist.percentile(0.5) in (1, 2)
    assert hist.percentile(1.0) == 8
